"""MPI communicators (reference src/smpi/mpi/smpi_comm.cpp) with an
mpi4py-flavored API: p2p entry points build Requests on the eager/
rendezvous engine, collectives dispatch through the algorithm selector
(coll.py)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .datatype import Datatype, payload_size
from .group import Group
from .op import MPI_SUM, Op
from .request import MPI_ANY_SOURCE, MPI_ANY_TAG, Request, Status


class Comm:
    """Communicator ids must be equal across ranks for "the same"
    communicator even though every rank builds its own Python object (all
    ranks share one process in simulation): ids are deterministic tuples
    (parent id, per-rank creation sequence on that parent, discriminator),
    relying on MPI's rule that communicator-creating calls are collective
    and issued in the same order everywhere."""

    def __init__(self, group: Group, id=None):
        self.group = group
        self.id = id if id is not None else "world"
        self._cc_seq: Dict[int, int] = {}

    def _next_cc_id(self, discriminator):
        from . import runtime
        me = runtime.this_rank()
        seq = self._cc_seq.get(me, 0)
        self._cc_seq[me] = seq + 1
        return (self.id, seq, discriminator)

    # -- introspection -----------------------------------------------------
    def rank(self) -> int:
        from . import runtime
        return self.group.rank(runtime.this_rank())

    def size(self) -> int:
        return self.group.size()

    def world_rank_of(self, group_rank: int) -> int:
        return self.group.actor(group_rank)

    def get_group(self) -> Group:
        return self.group

    # -- communicator management ------------------------------------------
    def dup(self) -> "Comm":
        return Comm(Group(list(self.group.world_ranks)),
                    self._next_cc_id("dup"))

    def create(self, group: Group) -> Optional["Comm"]:
        new = Comm(group, self._next_cc_id(tuple(group.world_ranks)))
        return new if group.rank(self.group.actor(self.rank())) >= 0 else None

    def split(self, color: int, key: int) -> Optional["Comm"]:
        """Collective over the communicator (smpi_comm.cpp::split)."""
        me = self.rank()
        mine = (color, key, me)
        all_triples = self.allgather(mine)
        new_id = self._next_cc_id(("split", color))
        if color < 0:
            return None
        members = sorted((k, r) for c, k, r in all_triples if c == color)
        return Comm(Group([self.group.actor(r) for _, r in members]), new_id)

    # -- point-to-point ----------------------------------------------------
    def send(self, buf, dest: int, tag: int = 0,
             count: Optional[int] = None,
             datatype: Optional[Datatype] = None) -> None:
        req = Request("send", buf, 1 if count is None else count, datatype, dest, tag, self)
        req.start()
        req.wait()

    def ssend(self, buf, dest: int, tag: int = 0,
              count: Optional[int] = None,
              datatype: Optional[Datatype] = None) -> None:
        req = Request("send", buf, 1 if count is None else count, datatype, dest, tag, self,
                      ssend=True)
        req.start()
        req.wait()

    def isend(self, buf, dest: int, tag: int = 0,
              count: Optional[int] = None,
              datatype: Optional[Datatype] = None) -> Request:
        req = Request("send", buf, 1 if count is None else count, datatype, dest, tag, self,
                      is_isend=True)
        return req.start()

    def recv(self, source: int = MPI_ANY_SOURCE, tag: int = MPI_ANY_TAG,
             buf=None, count: Optional[int] = None,
             datatype: Optional[Datatype] = None,
             status: Optional[Status] = None) -> Any:
        req = Request("recv", buf, 1 if count is None else count, datatype, source, tag, self)
        req.start()
        return req.wait(status)

    def irecv(self, source: int = MPI_ANY_SOURCE, tag: int = MPI_ANY_TAG,
              buf=None, count: Optional[int] = None,
              datatype: Optional[Datatype] = None) -> Request:
        req = Request("recv", buf, 1 if count is None else count, datatype, source, tag, self)
        return req.start()

    def sendrecv(self, sendbuf, dest: int, recvsource: int,
                 sendtag: int = 0, recvtag: int = MPI_ANY_TAG,
                 status: Optional[Status] = None) -> Any:
        rreq = self.irecv(recvsource, recvtag)
        sreq = self.isend(sendbuf, dest, sendtag)
        data = rreq.wait(status)
        sreq.wait()
        return data

    def iprobe(self, source: int = MPI_ANY_SOURCE,
               tag: int = MPI_ANY_TAG) -> bool:
        from . import runtime
        from .request import match_recv
        probe = Request("recv", None, 1, None, source, tag, self)
        me = runtime.this_rank_state()
        return (me.mailbox_small.iprobe(False, match_recv, probe) is not None
                or me.mailbox.iprobe(False, match_recv, probe) is not None)

    # -- collectives (dispatch through the selector) -----------------------
    def barrier(self) -> None:
        from . import coll
        coll.dispatch("barrier")(self)

    def bcast(self, obj, root: int = 0):
        from . import coll
        return coll.dispatch("bcast")(self, obj, root)

    def reduce(self, sendobj, op: Op = MPI_SUM, root: int = 0):
        from . import coll
        return coll.dispatch("reduce")(self, sendobj, op, root)

    def allreduce(self, sendobj, op: Op = MPI_SUM):
        from . import coll
        return coll.dispatch("allreduce")(self, sendobj, op)

    def gather(self, sendobj, root: int = 0):
        from . import coll
        return coll.dispatch("gather")(self, sendobj, root)

    def allgather(self, sendobj) -> List:
        from . import coll
        return coll.dispatch("allgather")(self, sendobj)

    def scatter(self, sendobjs: Optional[List], root: int = 0):
        from . import coll
        return coll.dispatch("scatter")(self, sendobjs, root)

    def alltoall(self, sendobjs: List) -> List:
        from . import coll
        return coll.dispatch("alltoall")(self, sendobjs)

    def reduce_scatter(self, sendobjs: List, op: Op = MPI_SUM):
        from . import coll
        return coll.dispatch("reduce_scatter")(self, sendobjs, op)

    def scan(self, sendobj, op: Op = MPI_SUM):
        from . import coll
        return coll.dispatch("scan")(self, sendobj, op)

    def __repr__(self):
        return f"<Comm id={self.id} size={self.size()}>"
