"""MPI communicators (reference src/smpi/mpi/smpi_comm.cpp) with an
mpi4py-flavored API: p2p entry points build Requests on the eager/
rendezvous engine, collectives dispatch through the algorithm selector
(coll.py)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .datatype import Datatype, payload_size
from .group import Group
from .op import MPI_SUM, Op
from .request import MPI_ANY_SOURCE, MPI_ANY_TAG, Request, Status


class Comm:
    """Communicator ids must be equal across ranks for "the same"
    communicator even though every rank builds its own Python object (all
    ranks share one process in simulation): ids are deterministic tuples
    (parent id, per-rank creation sequence on that parent, discriminator),
    relying on MPI's rule that communicator-creating calls are collective
    and issued in the same order everywhere."""

    def __init__(self, group: Group, id=None):
        self.group = group
        self.id = id if id is not None else "world"
        self._cc_seq: Dict[int, int] = {}

    def _next_cc_id(self, discriminator, collective: bool = True):
        """Deterministic communicator-id allocation (the role of the
        reference's collective context-id agreement).

        ``collective=True`` (dup/idup/split/create — collective over
        the WHOLE parent): one shared per-rank call counter.  Every
        parent rank issues these calls in the same order (MPI
        requirement), so the counters advance in lockstep and agree
        even when per-call arguments differ across ranks (two splits
        with different color patterns desynchronized the old
        per-discriminator counters — found by mpich3 comm_idup_comm).

        ``collective=False`` (MPI_Comm_create_group — collective only
        over the GROUP): sequence per (rank, discriminator) so
        non-participating ranks do not desynchronize; the group members
        all issue matching calls in the same order by the same MPI
        rule, scoped to the (group, tag) discriminator."""
        from . import runtime
        me = runtime.this_rank()
        key = (me, "coll") if collective else (me, discriminator)
        seq = self._cc_seq.get(key, 0)
        self._cc_seq[key] = seq + 1
        return (self.id, seq, discriminator)

    # -- introspection -----------------------------------------------------
    def rank(self) -> int:
        from . import runtime
        return self.group.rank(runtime.this_rank())

    def size(self) -> int:
        return self.group.size()

    def world_rank_of(self, group_rank: int) -> int:
        """P2P PEER resolution (InterComm points this at the remote
        group)."""
        return self.group.actor(group_rank)

    def recv_world_rank_of(self, group_rank: int) -> int:
        """SELF resolution — always the local group: a receive posts
        into the receiver's own mailbox even on an intercommunicator."""
        return self.group.actor(group_rank)

    def get_group(self) -> Group:
        return self.group

    # -- communicator management ------------------------------------------
    def dup(self) -> "Comm":
        return Comm(Group(list(self.group.world_ranks)),
                    self._next_cc_id("dup"))

    def create(self, group: Group) -> Optional["Comm"]:
        new = Comm(group, self._next_cc_id(tuple(group.world_ranks)))
        return new if group.rank(self.group.actor(self.rank())) >= 0 else None

    def create_group(self, group: Group, tag: int = 0) -> Optional["Comm"]:
        """MPI-3 MPI_Comm_create_group: collective only over `group`'s
        members — must not advance the parent-collective id counter
        (non-members never make this call)."""
        disc = ("cgrp", tuple(group.world_ranks), tag)
        new = Comm(group, self._next_cc_id(disc, collective=False))
        return new if group.rank(self.group.actor(self.rank())) >= 0 else None

    def split(self, color: int, key: int) -> Optional["Comm"]:
        """Collective over the communicator (smpi_comm.cpp::split)."""
        me = self.rank()
        mine = (color, key, me)
        all_triples = self.allgather(mine)
        new_id = self._next_cc_id(("split", color))
        if color < 0:
            return None
        members = sorted((k, r) for c, k, r in all_triples if c == color)
        return Comm(Group([self.group.actor(r) for _, r in members]), new_id)

    # -- point-to-point ----------------------------------------------------
    def send(self, buf, dest: int, tag: int = 0,
             count: Optional[int] = None,
             datatype: Optional[Datatype] = None) -> None:
        from . import instr_hooks as tr
        req = Request("send", buf, 1 if count is None else count, datatype, dest, tag, self)
        with tr.p2p_span("send", dest, tag, req) as visible:
            if visible:
                tr.send_arrow(self, dest, tag, req.size)
            req.start()
            req.wait()

    def ssend(self, buf, dest: int, tag: int = 0,
              count: Optional[int] = None,
              datatype: Optional[Datatype] = None) -> None:
        from . import instr_hooks as tr
        req = Request("send", buf, 1 if count is None else count, datatype, dest, tag, self,
                      ssend=True)
        with tr.p2p_span("send", dest, tag, req) as visible:
            if visible:
                tr.send_arrow(self, dest, tag, req.size)
            req.start()
            req.wait()

    def isend(self, buf, dest: int, tag: int = 0,
              count: Optional[int] = None,
              datatype: Optional[Datatype] = None,
              ssend: bool = False) -> Request:
        from . import instr_hooks as tr
        req = Request("send", buf, 1 if count is None else count, datatype, dest, tag, self,
                      is_isend=True, ssend=ssend)
        with tr.p2p_span("isend", dest, tag, req) as visible:
            if visible:
                tr.send_arrow(self, dest, tag, req.size)
            return req.start()

    def recv(self, source: int = MPI_ANY_SOURCE, tag: int = MPI_ANY_TAG,
             buf=None, count: Optional[int] = None,
             datatype: Optional[Datatype] = None,
             status: Optional[Status] = None) -> Any:
        from . import instr_hooks as tr
        req = Request("recv", buf, 1 if count is None else count, datatype, source, tag, self)
        with tr.p2p_span("recv", source, tag, req) as visible:
            req.start()
            result = req._wait_inner(status)
            if visible:
                tr.recv_arrow_once(req)
            return result

    def irecv(self, source: int = MPI_ANY_SOURCE, tag: int = MPI_ANY_TAG,
              buf=None, count: Optional[int] = None,
              datatype: Optional[Datatype] = None) -> Request:
        from . import instr_hooks as tr
        req = Request("recv", buf, 1 if count is None else count, datatype, source, tag, self)
        with tr.p2p_span("irecv", source, tag, req):
            return req.start()

    def sendrecv(self, sendbuf, dest: int, recvsource: int,
                 sendtag: int = 0, recvtag: int = MPI_ANY_TAG,
                 status: Optional[Status] = None) -> Any:
        rreq = self.irecv(recvsource, recvtag)
        sreq = self.isend(sendbuf, dest, sendtag)
        data = rreq.wait(status)
        sreq.wait()
        return data

    def iprobe(self, source: int = MPI_ANY_SOURCE,
               tag: int = MPI_ANY_TAG,
               status: Optional[Status] = None) -> bool:
        from . import runtime
        from .request import match_recv
        probe = Request("recv", None, 1, None, source, tag, self)
        me = runtime.this_rank_state()
        hit = (me.mailbox_small.iprobe(False, match_recv, probe) is not None
               or me.mailbox.iprobe(False, match_recv, probe) is not None)
        if hit and status is not None:
            status.source = probe.real_src
            status.tag = probe.real_tag
            status.count = probe.real_size
        if not hit:
            # busy iprobe loops must advance simulated time
            # (smpi_request.cpp::iprobe nsleeps, smpi/iprobe)
            from ..utils.config import config
            sleep = config["smpi/iprobe"]
            if sleep > 0:
                from ..s4u import this_actor
                this_actor.sleep_for(sleep)
        return hit

    # -- collectives (dispatch through the selector) -----------------------
    def barrier(self) -> None:
        from . import coll, instr_hooks as tr
        with tr.noop_span("barrier"):
            coll.dispatch("barrier")(self)

    def bcast(self, obj, root: int = 0):
        from . import coll, instr_hooks as tr
        with tr.coll_span("bcast", payload_size(obj, None), root=root):
            return coll.dispatch("bcast")(self, obj, root)

    def reduce(self, sendobj, op: Op = MPI_SUM, root: int = 0):
        from . import coll, instr_hooks as tr
        with tr.coll_span("reduce", payload_size(sendobj, None),
                          amount=0.0, root=root):
            return coll.dispatch("reduce")(self, sendobj, op, root)

    def allreduce(self, sendobj, op: Op = MPI_SUM):
        from . import coll, instr_hooks as tr
        with tr.coll_span("allreduce", payload_size(sendobj, None),
                          amount=0.0):
            return coll.dispatch("allreduce")(self, sendobj, op)

    def gather(self, sendobj, root: int = 0):
        from . import coll, instr_hooks as tr
        with tr.coll_span("gather", payload_size(sendobj, None),
                          recv_size=0, root=root):
            return coll.dispatch("gather")(self, sendobj, root)

    def allgather(self, sendobj) -> List:
        from . import coll, instr_hooks as tr
        with tr.coll_span("allgather", payload_size(sendobj, None),
                          recv_size=0):
            return coll.dispatch("allgather")(self, sendobj)

    def scatter(self, sendobjs: Optional[List], root: int = 0):
        from . import coll, instr_hooks as tr
        size = payload_size(sendobjs[0], None) if sendobjs else 0
        with tr.coll_span("scatter", size, recv_size=int(size), root=root):
            return coll.dispatch("scatter")(self, sendobjs, root)

    def alltoall(self, sendobjs: List) -> List:
        from . import coll, instr_hooks as tr
        size = payload_size(sendobjs[0], None) if sendobjs else 0
        with tr.coll_span("alltoall", size, recv_size=int(size)):
            return coll.dispatch("alltoall")(self, sendobjs)

    def reduce_scatter(self, sendobjs: List, op: Op = MPI_SUM):
        from . import coll, instr_hooks as tr
        counts = [int(payload_size(o, None)) for o in (sendobjs or [])]
        # Reference shape: "reducescatter 0 <recvcounts...> <comp> <dt>"
        # (VarCollTIData with send_size=0, comp_size riding send_type,
        # smpi_replay.cpp ReduceScatterAction).
        with tr.varcoll_span("reducescatter", send_size=0, recv_size=-1,
                             recvcounts=counts, send_type="0",
                             recv_type="6"):
            return coll.dispatch("reduce_scatter")(self, sendobjs, op)

    def scan(self, sendobj, op: Op = MPI_SUM):
        from . import coll, instr_hooks as tr
        with tr.noop_span("scan"):
            return coll.dispatch("scan")(self, sendobj, op)

    def exscan(self, sendobj, op: Op = MPI_SUM):
        """Exclusive prefix reduction; rank 0's result is None."""
        from . import coll, instr_hooks as tr
        with tr.noop_span("exscan"):
            return coll.dispatch("exscan")(self, sendobj, op)

    # -- v-variants: per-peer payloads naturally carry their own sizes
    # in the object model, so the same algorithms serve (the reference
    # needs separate *v entry points only because C buffers cannot).
    def allgatherv(self, sendobj) -> List:
        from . import instr_hooks as tr
        with tr.varcoll_span("allgatherv",
                             send_size=int(payload_size(sendobj, None)),
                             recv_size=-1, recvcounts=None):
            from . import coll
            return coll.dispatch("allgather")(self, sendobj)

    def alltoallv(self, sendobjs: List) -> List:
        from . import instr_hooks as tr
        counts = [int(payload_size(o, None)) for o in sendobjs]
        with tr.varcoll_span("alltoallv", send_size=sum(counts),
                             sendcounts=counts, recv_size=-1,
                             recvcounts=None):
            from . import coll
            return coll.dispatch("alltoall")(self, sendobjs)

    def gatherv(self, sendobj, root: int = 0):
        from . import instr_hooks as tr
        with tr.varcoll_span("gatherv", root=root,
                             send_size=int(payload_size(sendobj, None)),
                             recv_size=-1, recvcounts=None):
            from . import coll
            return coll.dispatch("gather")(self, sendobj, root)

    def scatterv(self, sendobjs: Optional[List], root: int = 0):
        from . import instr_hooks as tr
        counts = [int(payload_size(o, None)) for o in (sendobjs or [])]
        with tr.varcoll_span("scatterv", root=root, send_size=-1,
                             sendcounts=counts or None, recv_size=-1,
                             recvcounts=None):
            from . import coll
            return coll.dispatch("scatter")(self, sendobjs, root)

    # -- non-blocking collectives (smpi_nbc_impl.cpp) ----------------------
    def ibarrier(self):
        from . import nbc
        return nbc.ibarrier(self)

    def ibcast(self, obj, root: int = 0):
        from . import nbc
        return nbc.ibcast(self, obj, root)

    def ireduce(self, sendobj, op: Op = MPI_SUM, root: int = 0):
        from . import nbc
        return nbc.ireduce(self, sendobj, op, root)

    def iallreduce(self, sendobj, op: Op = MPI_SUM):
        from . import nbc
        return nbc.iallreduce(self, sendobj, op)

    def igather(self, sendobj, root: int = 0):
        from . import nbc
        return nbc.igather(self, sendobj, root)

    def iscatter(self, sendobjs, root: int = 0):
        from . import nbc
        return nbc.iscatter(self, sendobjs, root)

    def iallgather(self, sendobj):
        from . import nbc
        return nbc.iallgather(self, sendobj)

    def ialltoall(self, sendobjs):
        from . import nbc
        return nbc.ialltoall(self, sendobjs)

    def ireduce_scatter(self, sendobjs, op: Op = MPI_SUM):
        from . import nbc
        return nbc.ireduce_scatter(self, sendobjs, op)

    def iscan(self, sendobj, op: Op = MPI_SUM):
        from . import nbc
        return nbc.iscan(self, sendobj, op)

    def iexscan(self, sendobj, op: Op = MPI_SUM):
        from . import nbc
        return nbc.iexscan(self, sendobj, op)

    # -- topologies (smpi_topo.cpp) ----------------------------------------
    def cart_create(self, dims, periodic, reorder: bool = False):
        """Returns None (MPI_COMM_NULL) for ranks beyond the grid."""
        from .topo import CartTopology
        nnodes = 1
        for d in dims:
            nnodes *= d
        if self.rank() >= nnodes:
            return None
        return CartTopology(self, dims, periodic, reorder)

    def graph_create(self, index, edges, reorder: bool = False):
        from .topo import GraphTopology
        return GraphTopology(self, index, edges, reorder)

    def __repr__(self):
        return f"<Comm id={self.id} size={self.size()}>"
