"""Utility base layer (the XBT equivalent): config, logging, signals."""

from .config import config, declare_flag, ConfigError
from .log import get_category, new_category, apply_control
from .signal import Signal

__all__ = ["config", "declare_flag", "ConfigError", "get_category",
           "new_category", "apply_control", "Signal"]
