"""Typed configuration/flag registry.

TPU-native re-design of SimGrid's xbt config system
(reference: /root/reference/src/xbt/config.cpp, flag declarations in
/root/reference/src/simgrid/sg_config.cpp:258-437).  Same capabilities:
typed flags with defaults, aliases, on-set callbacks, ``--cfg=key:value``
command-line parsing and ``help-cfg`` dump — implemented as a plain Python
registry (no C++ needed host-side).
"""

from __future__ import annotations

import sys
from typing import Any, Callable, Dict, List, Optional


class ConfigError(Exception):
    pass


class _Flag:
    __slots__ = ("name", "description", "default", "value", "type", "callback",
                 "aliases", "touched")

    def __init__(self, name: str, description: str, default: Any,
                 callback: Optional[Callable[[Any], None]] = None,
                 aliases: Optional[List[str]] = None):
        self.name = name
        self.description = description
        self.default = default
        self.value = default
        self.type = type(default)
        self.callback = callback
        self.aliases = aliases or []
        # Explicit-set tracking (the reference's isdefault flag,
        # config.cpp:141,171,240): an explicit set that happens to equal the
        # default still counts as touched.
        self.touched = False


_TRUTHY = {"yes", "on", "true", "1"}
_FALSY = {"no", "off", "false", "0"}


class Config:
    """A registry of typed flags (the equivalent of simgrid's sg_cfg_*)."""

    def __init__(self) -> None:
        self._flags: Dict[str, _Flag] = {}
        self._alias: Dict[str, str] = {}

    # -- declaration ------------------------------------------------------
    def declare(self, name: str, description: str, default: Any,
                callback: Optional[Callable[[Any], None]] = None,
                aliases: Optional[List[str]] = None) -> None:
        if name in self._flags:
            # Re-declaration keeps the already-set value (mirrors the
            # reference's idempotent module registration).
            return
        flag = _Flag(name, description, default, callback, aliases)
        self._flags[name] = flag
        for a in flag.aliases:
            self._alias[a] = name

    # -- access -----------------------------------------------------------
    def _resolve(self, name: str) -> _Flag:
        name = self._alias.get(name, name)
        try:
            return self._flags[name]
        except KeyError:
            raise ConfigError(f"Unknown configuration key '{name}' "
                              f"(try help-cfg for the list)") from None

    def get(self, name: str) -> Any:
        return self._resolve(name).value

    def __getitem__(self, name: str) -> Any:
        return self.get(name)

    def set(self, name: str, value: Any) -> None:
        flag = self._resolve(name)
        if isinstance(value, str) and flag.type is not str:
            value = self._parse(flag, value)
        elif flag.type is float and isinstance(value, int):
            value = float(value)
        if not isinstance(value, flag.type) and not (flag.type is float and isinstance(value, int)):
            raise ConfigError(f"Invalid value {value!r} for flag '{flag.name}' "
                              f"of type {flag.type.__name__}")
        flag.value = value
        flag.touched = True
        if flag.callback is not None:
            flag.callback(value)

    def __setitem__(self, name: str, value: Any) -> None:
        self.set(name, value)

    def is_default(self, name: str) -> bool:
        return not self._resolve(name).touched

    def set_default(self, name: str, value: Any) -> None:
        """Change the default (and the value if never explicitly set) — the
        reference's config::set_default used by model initializers."""
        flag = self._resolve(name)
        if not flag.touched:
            self.set(name, value)        # validates the type first
            flag.touched = False         # still counts as a default
        flag.default = value

    @staticmethod
    def _parse(flag: _Flag, text: str) -> Any:
        if flag.type is bool:
            low = text.lower()
            if low in _TRUTHY:
                return True
            if low in _FALSY:
                return False
            raise ConfigError(f"Invalid boolean '{text}' for flag '{flag.name}'")
        if flag.type is int:
            return int(text)
        if flag.type is float:
            return float(text)
        return text

    # -- command line -----------------------------------------------------
    def set_from_string(self, opt: str) -> None:
        """Parse a --cfg= payload: one ``key:value``, or several
        space-separated ones (the reference accepts
        --cfg='a:x b:y c:z')."""
        from . import log as _log
        # A payload with spaces is a multi-option list ONLY if every
        # token's text before its first ':' names a DECLARED flag —
        # otherwise the whole payload is one value that happens to
        # contain spaces and colons (a path list, a URL).
        tokens = [opt]
        if " " in opt:
            parts = opt.split()
            def _known(tok: str) -> bool:
                key = tok.split(":", 1)[0].strip()
                return key in self._flags or key in self._alias
            if all(":" in t and _known(t) for t in parts):
                tokens = parts
        for token in tokens:
            if ":" not in token:
                raise ConfigError(
                    f"Invalid --cfg option '{token}', expected key:value")
            key, value = token.split(":", 1)
            self.set(key.strip(), value.strip())
            # reference simgrid::config logs every CLI change (the tesh
            # oracles pin these lines)
            _log.get_category("xbt_cfg").info(
                "Configuration change: Set '%s' to '%s'"
                % (key.strip(), value.strip()))

    def parse_argv(self, argv: List[str]) -> List[str]:
        """Consume --cfg=... / --log=... / --help-cfg from argv,
        returning the rest.  Log controls apply FIRST (like the
        reference's early log_init) so the configuration-change lines
        already use the requested layout."""
        from . import log as _log
        for arg in argv:
            if arg.startswith("--log="):
                _log.apply_control(arg[len("--log="):])
        remaining: List[str] = []
        for arg in argv:
            if arg.startswith("--cfg="):
                self.set_from_string(arg[len("--cfg="):])
            elif arg.startswith("--log="):
                pass
            elif arg == "--help-cfg":
                self.dump(sys.stdout)
            else:
                remaining.append(arg)
        return remaining

    def dump(self, out) -> None:
        for name in sorted(self._flags):
            f = self._flags[name]
            out.write(f"   {name}: {f.description} (default: {f.default!r})\n")


#: Process-wide configuration registry (mirrors simgrid_config).
config = Config()


def declare_flag(name: str, description: str, default: Any,
                 callback: Optional[Callable[[Any], None]] = None,
                 aliases: Optional[List[str]] = None) -> None:
    config.declare(name, description, default, callback, aliases)


# ---------------------------------------------------------------------------
# Core solver / kernel flags, same key names as the reference
# (sg_config.cpp:258-437, maxmin.cpp:12-14).
# ---------------------------------------------------------------------------
declare_flag("maxmin/precision",
             "Numerical precision used when updating simulation variables",
             1e-5, aliases=["maxmin/epsilon"])
declare_flag("surf/precision",
             "Numerical precision used when comparing simulated times",
             1e-5)
declare_flag("path",
             "Lookup path for inclusions in platform and deployment "
             "XML files",
             "./")
declare_flag("maxmin/concurrency-limit",
             "Maximum number of concurrent variables per resource (-1: none)",
             -1)
declare_flag("host/model", "Host model to use", "default")
declare_flag("cpu/model", "CPU model to use", "Cas01")
declare_flag("network/model", "Network model to use", "LV08")
declare_flag("storage/model", "Storage model to use", "default")
declare_flag("cpu/optim", "CPU optimization mode (Lazy/TI/Full)", "Lazy")
declare_flag("network/optim", "Network optimization mode (Lazy/Full)", "Lazy")
declare_flag("cpu/maxmin-selective-update",
             "Update the constraint set selectively for CPU", False)
declare_flag("network/maxmin-selective-update",
             "Update the constraint set selectively for network", False)
declare_flag("network/crosstraffic",
             "Model cross-traffic (bidirectional flows interfere)", True)
declare_flag("network/TCP-gamma",
             "Maximum TCP window size (bytes)", 4194304.0)
# Global defaults come from the LV08 model (sg_config.cpp:270-279); the
# plain CM02 init resets them to 1.0/1.0/0.0, SMPI/IB override weight-S
# only (network_smpi.cpp:24-31, network_ib.cpp init).
declare_flag("network/latency-factor",
             "Multiplier for link latencies", 13.01)
declare_flag("network/bandwidth-factor",
             "Multiplier for link bandwidths", 0.97)
declare_flag("network/weight-S",
             "RTT cost correction added per link (LV08: 20537)", 20537.0)
declare_flag("network/loopback-bw", "Default loopback bandwidth", 498000000.0)
declare_flag("network/mtu",
             "Packet size (bytes) for the packet-level network model",
             1500.0)
declare_flag("network/loopback-lat", "Default loopback latency", 0.000015)
declare_flag("lmm/backend",
             "Max-min solver backend: list (exact host, Python), native "
             "(exact host, C++), jax (vectorized, TPU/CPU), auto (native "
             "below lmm/jax-threshold variables, jax above)", "auto")
declare_flag("lmm/jax-threshold",
             "Minimum live variable count before 'auto' switches the solve "
             "to the JAX backend", 512)
declare_flag("lmm/dtype", "JAX solver dtype: float64 or float32", "float64")
declare_flag("lmm/layout",
             "Device solver element layout: coo (scatter/segment ops), "
             "ell (dense padded rows — accelerator-native, no scatters), "
             "auto (ell on accelerators when the graph is not too skewed)",
             "auto")
declare_flag("lmm/rounds",
             "JAX solver saturation-round strategy: global (one bottleneck "
             "level per round, the reference's sequential order) or local "
             "(fix every local-minimum constraint per round; exact because "
             "rou levels only increase, and far fewer device rounds)",
             "local")
declare_flag("lmm/compact",
             "Repack the device element list between solver chunks, "
             "dropping elements of already-fixed variables: on, off, or "
             "auto (on for the COO layout on CPU backends, where the "
             "host round-trip is free).  COO-only — combine with "
             "lmm/layout:coo on accelerators — and skipped below a few "
             "thousand elements where repacking costs more than it "
             "saves.  Bit-identical: dead elements contribute exact "
             "identities (0.0 to the scatter-adds and maxes, inf to "
             "the min-reductions)", "auto")
declare_flag("lmm/chain",
             "Device-resident active-set compaction for the ELL/vc "
             "solver path: chain jitted solve stages at halving static "
             "shapes with no host sync between them (one fetch per "
             "solve).  on, off, or auto (accelerators only — the CPU "
             "backend compacts host-side via lmm/compact instead)",
             "auto")
declare_flag("lmm/warm-start",
             "Selective-update solves on the device backend: off "
             "(legacy: re-flatten the modified constraint subset and "
             "cold-solve it each time), cold (device-resident full "
             "arrays, cold fixpoint restart every solve), on/auto "
             "(warm-started restarts: only the modified component "
             "re-enters the fixpoint, untouched components keep their "
             "previous solution — exact because the max-min solution "
             "decomposes by connected component).  Combine with "
             "network/maxmin-selective-update (or cpu/...) to get "
             "incremental device solves in mutating phases", "auto")
declare_flag("lmm/delta-upload",
             "Ship System mutations to the device-resident solver "
             "arrays as ONE indexed scatter payload per solve (bytes "
             "scale with touched slots) instead of re-uploading every "
             "dirty field wholesale: on, off, or auto (on whenever the "
             "warm-start device path serves the solve).  Off keeps "
             "per-field copy-on-write refreshes — the bench baseline "
             "and the escape hatch", "auto")
declare_flag("lmm/strict",
             "Abort on a failed device LMM solve (non-convergence, stall "
             "or non-finite rates) instead of gracefully degrading to the "
             "exact host solver for that solve", False)
declare_flag("lmm/pad",
             "Static-shape padding policy for device solver arrays: "
             "pow2 (power-of-two buckets — few XLA recompiles as a "
             "simulation's live system grows/shrinks, up to 2x padded "
             "volume) or tight (multiples of 4096 and exact ELL row "
             "widths — per-element device cost tracks the real system; "
             "right for one-shot solves of big fixed systems, wrong "
             "for hot simulation loops where every new shape is a "
             "multi-second XLA compile)", "pow2")
declare_flag("drain/fastpath",
             "Delegate pure-drain phases (every started flow past its "
             "latency phase, no deadlines, no profile event before the "
             "next completion) to the device-resident superstep "
             "executor: batches of advances run in one dispatch with "
             "event ordering preserved.  auto/on require a JAX-capable "
             "lmm/backend and at least drain/min-flows started flows; "
             "off disables the fast path", "auto")
declare_flag("drain/superstep",
             "Advances per device dispatch in the drain fast path "
             "(the K of the superstep executor; amortized host syncs "
             "are ~1/K per advance)", 16)
declare_flag("drain/min-flows",
             "Minimum started network flows before the drain fast "
             "path engages (below it the generic per-advance path is "
             "cheaper than plan bookkeeping)", 4096)
declare_flag("drain/pipeline",
             "Speculative supersteps kept in flight by the pipelined "
             "drain executors (the depth D of DrainSim/BatchDrainSim "
             "pipelining; the engine fast path keeps one token in "
             "flight whenever D > 0): while the host processes "
             "completion ring N, superstep N+1 already executes on "
             "the device, hiding the dispatch round trip.  Results "
             "are bit-identical to 0 (synchronous) — a mispredicted "
             "speculation is discarded and replayed from the "
             "committed state", 1)
declare_flag("drain/transitions",
             "Absorb recognizable actor transitions (latency wakes, "
             "new flows on existing routes, bound/weight/penalty "
             "changes, engine-driven partial advances) into a live "
             "drain plan as indexed device scatters instead of "
             "discarding it: the ArrayView mutation census becomes a "
             "resumable-vs-invalidating classifier and compute/comm "
             "alternation stays on the superstep path.  auto/on "
             "enable it whenever drain/fastpath engages; off restores "
             "the invalidate-on-any-mutation behavior", "auto")
declare_flag("faults/tape",
             "How campaign fleets realize per-replica fault schedules "
             "(parallel.campaign): on compiles each seeded "
             "FaultCampaign into a device-resident event tape the "
             "superstep drain consults between advances — link "
             "capacities flip mid-drain at exact schedule dates, "
             "bit-identical to solo Profile injection; static folds "
             "the schedule into time-averaged capacity multipliers "
             "(FaultCampaign.mean_availability, the pre-tape "
             "behavior); off ignores the fault dimension entirely",
             "on")
declare_flag("drain/done-eps",
             "Relative completion threshold of the f32 drain "
             "executor: a flow retires when its remainder falls to "
             "done-eps * size (reference sg_maxmin_precision "
             "semantics; keeps chip-precision ties in the f64 tie "
             "groups).  f64 drains use the engine's absolute "
             "maxmin*surf precision instead", 1e-4)
declare_flag("lmm/unroll",
             "Unroll the device fixpoint into straight-line XLA instead "
             "of lax.while_loop: on, off, or auto (on for accelerators — "
             "some backends lower gathers inside while_loop to serialized "
             "dynamic-slice loops; unrolled code keeps them vectorized)",
             "auto")
declare_flag("serve/batch",
             "Resident fleet width of the always-on campaign service "
             "(serving.service.CampaignService): queued scenarios "
             "fill up to this many lanes; lanes freed by completed "
             "replicas are revived mid-flight by admission batching",
             16)
declare_flag("serve/plan-cache",
             "Directory for the serving AOT plan cache "
             "(serving.plancache): compiled fleet executables are "
             "serialized here so warm restarts skip XLA tracing "
             "entirely; empty = in-memory caching only", "")
declare_flag("serve/surrogate",
             "Surrogate triage for the campaign service: on answers "
             "tight-interval queries from the ridge+conformal "
             "predictor (exact=True always bypasses), off sends every "
             "query to the device path", "on")
declare_flag("serve/surrogate-min-corpus",
             "Completed rows required before the serving surrogate "
             "makes its first fit (split-conformal calibration needs "
             "a held-out stripe)", 24)
declare_flag("serve/surrogate-rel-tol",
             "Maximum conformal-interval width, relative to the "
             "predicted clock, the surrogate will answer at; wider "
             "intervals escalate the query to exact device "
             "simulation", 0.1)
declare_flag("serve/surrogate-confidence",
             "Conformal coverage level of surrogate answers (the "
             "interval quantile over held-out absolute residuals)",
             0.9)
declare_flag("smpi/rma-fast-atomics",
             "Linearize RMA atomic reads (get/fetch_op/get_accumulate/"
             "cas) immediately at the origin when all its outstanding "
             "ops to the target have been applied — sound under the "
             "MPI_WIN_UNIFIED memory model and the kernel's atomic "
             "scheduling rounds, and removes the simulated round trip "
             "(set false for strict arrival-time application)", True)
declare_flag("contexts/stack-size", "Actor stack size (bytes)", 131072)
declare_flag("contexts/factory", "Actor context factory (thread)", "thread")
declare_flag("tracing", "Enable tracing", False)
declare_flag("tracing/filename", "Trace output file", "simgrid.trace")
declare_flag("tracing/format", "Trace format (Paje|TI)", "Paje")
declare_flag("tracing/platform", "Trace platform resources", False)
declare_flag("tracing/actor", "Trace actor behavior", False)
declare_flag("tracing/uncategorized",
             "Trace uncategorized resource usage", False)
declare_flag("tracing/smpi", "Trace SMPI ranks", False)
declare_flag("tracing/smpi/computing", "Trace SMPI computing states", False)
declare_flag("smpi/async-small-thresh",
             "Maximum size of messages sent over the eager (async) protocol",
             0)
declare_flag("smpi/send-is-detached-thresh",
             "Threshold under which MPI_Send is done in a detached manner",
             65536)
declare_flag("smpi/host-speed",
             "Speed of the host running the simulation (flop/s)", 20000.0)
declare_flag("smpi/os", "Overhead of a send (size-dependent segments)", "0:0:0:0:0")
declare_flag("smpi/or", "Overhead of a receive", "0:0:0:0:0")
declare_flag("smpi/ois", "Overhead of an isend", "0:0:0:0:0")
declare_flag("smpi/bw-factor", "Piecewise bandwidth factors size:factor;...",
             "65472:0.940694;15424:0.697866;9376:0.58729;5776:1.08739;3484:0.77493;"
             "1426:0.608902;732:0.341987;257:0.338112;0:0.812084")
declare_flag("smpi/lat-factor", "Piecewise latency factors size:factor;...",
             "65472:11.6436;15424:3.48845;9376:2.59299;5776:2.18796;3484:1.88101;"
             "1426:1.61075;732:1.9503;257:1.95341;0:2.01467")
declare_flag("smpi/IB-penalty-factors",
             "InfiniBand penalty factors beta_s;beta_e;gamma", "0.965;0.925;1.35")
declare_flag("smpi/simulate-computation",
             "Simulate the computation of the application", True)
declare_flag("smpi/cpu-threshold",
             "Minimal computation time (s) not discarded", 1e-6)
declare_flag("smpi/coll-selector", "Collective algorithm selector", "default")
declare_flag("model-check/reduction", "DPOR reduction (none|dpor)", "dpor")
declare_flag("model-check/max-depth", "Maximal exploration depth", 1000)
declare_flag("model-check/send-determinism",
             "Check send-determinism only: abort the exploration as "
             "soon as any actor's send pattern diverges (reference "
             "_sg_mc_send_determinism)", False)
declare_flag("model-check/communications-determinism",
             "Classify send- AND recv-determinism per actor over the "
             "whole exploration, aborting only when an actor loses "
             "both (reference _sg_mc_comms_determinism)", True)
declare_flag("precision-tracking/jax",
             "Tolerance used when cross-checking JAX solver results", 1e-9)
