"""Typed signals (observer pattern) used for cross-layer upcalls.

Equivalent of xbt::signal (reference: /root/reference/include/xbt/signal.hpp),
which SimGrid uses for every upward notification (e.g.
s4u::Link::on_communicate, Host::on_creation, Actor::on_termination).
Plugins subscribe to these without the core layers knowing about them.
"""

from __future__ import annotations

from typing import Callable, Generic, List, TypeVar

F = TypeVar("F", bound=Callable)


class Signal(Generic[F]):
    __slots__ = ("_slots",)

    def __init__(self) -> None:
        self._slots: List[Callable] = []

    def connect(self, fn: Callable) -> Callable:
        self._slots.append(fn)
        return fn

    def disconnect(self, fn: Callable) -> None:
        self._slots.remove(fn)

    def disconnect_all(self) -> None:
        self._slots.clear()

    def __call__(self, *args, **kwargs) -> None:
        for fn in list(self._slots):
            fn(*args, **kwargs)

    def __bool__(self) -> bool:
        return bool(self._slots)
