"""Hierarchical logging with runtime thresholds and simulated-clock layouts.

Capability-equivalent of SimGrid's XBT log (reference:
/root/reference/src/xbt/log.cpp, layouts in xbt_log_layout_format.cpp).
Categories form a dot-separated hierarchy with inherited thresholds;
``--log=cat.thresh:debug`` style controls are parsed by
:func:`apply_control`.  The default layout prints
``[host:actor:(pid) simulated_time] [category/priority] msg`` like the
reference's tesh-facing appender, so golden-output tests can pin lines.
"""

from __future__ import annotations

import sys
from typing import Callable, Dict, Optional

TRACE = 5
DEBUG = 10
VERBOSE = 15
INFO = 20
WARNING = 30
ERROR = 40
CRITICAL = 50

_LEVELS = {
    "trace": TRACE, "debug": DEBUG, "verbose": VERBOSE, "verb": VERBOSE,
    "info": INFO, "warning": WARNING, "warn": WARNING, "error": ERROR,
    "critical": CRITICAL,
}
_LEVEL_NAMES = {TRACE: "trace", DEBUG: "debug", VERBOSE: "verbose",
                INFO: "INFO", WARNING: "WARNING", ERROR: "ERROR",
                CRITICAL: "CRITICAL"}

#: hook returning the current simulated clock; installed by the engine.
clock_getter: Optional[Callable[[], float]] = None
#: hook returning "host:actor:(pid)" for the current context.
context_getter: Optional[Callable[[], str]] = None
#: () -> (pid, actor_name, host_name) for the %i/%P/%h layout codes
actor_info_getter: Optional[Callable[[], tuple]] = None

_categories: Dict[str, "Category"] = {}


class Appender:
    """Where rendered log lines go (xbt_log_appender_file.cpp): a
    standard stream (resolved at write time so redirection and pytest
    capture keep working), a file, or a size-rolling file."""

    def __init__(self, stream_name: Optional[str] = None,
                 path: Optional[str] = None, roll_bytes: int = 0):
        self._stream_name = stream_name    # "stderr" | "stdout" | None
        self._path = path
        self._roll = roll_bytes
        self._written = 0
        self._file = open(path, "w") if path is not None else None

    def _stream(self):
        if self._stream_name is not None:
            return getattr(sys, self._stream_name)
        return self._file

    def write(self, line: str) -> None:
        nbytes = len(line.encode("utf-8", errors="replace"))
        if self._roll and self._written + nbytes > self._roll:
            # rolling appender: restart the file (append_file.cpp roll)
            self._file.close()
            self._file = open(self._path, "w")
            self._written = 0
        stream = self._stream()
        stream.write(line)
        stream.flush()
        self._written += nbytes


_stderr_appender = Appender(stream_name="stderr")


def render_layout(fmt: str, category: str, level_name: str,
                  msg: str) -> str:
    """The %-pattern layout language (xbt_log_layout_format.cpp):
    %r simulated clock (width.precision honored), %c category,
    %p priority, %m message, %n newline, %e space, %a actor context,
    %i actor pid, %P actor name, %h host name, %% literal percent.
    Unknown specifiers render verbatim."""
    out = []
    i = 0
    while i < len(fmt):
        ch = fmt[i]
        if ch != "%":
            out.append(ch)
            i += 1
            continue
        # parse optional width[.precision]
        j = i + 1
        spec = ""
        while j < len(fmt) and (fmt[j].isdigit() or fmt[j] in ".-"):
            spec += fmt[j]
            j += 1
        code = fmt[j] if j < len(fmt) else "%"
        if code == "r":
            clock = clock_getter() if clock_getter else 0.0
            try:
                out.append(f"%{spec}f" % clock if spec else f"{clock:.6f}")
            except (ValueError, TypeError):
                # malformed width spec: render verbatim as documented
                out.append("%" + spec + code)
        elif code == "c":
            out.append(category)
        elif code == "p":
            out.append(level_name)
        elif code == "m":
            out.append(msg)
        elif code == "n":
            out.append("\n")
        elif code == "e":
            out.append(" ")
        elif code == "a":
            out.append(context_getter() if context_getter else "")
        elif code in "iPh":
            # before any engine exists, the context IS maestro (the
            # reference prints "maestro" for --cfg lines emitted during
            # sg_config parsing, ahead of engine construction)
            pid, aname, hname = (actor_info_getter()
                                 if actor_info_getter
                                 else (0, "maestro", ""))
            val = (str(pid) if code == "i"
                   else aname if code == "P" else hname)
            if spec:
                # printf width spec, e.g. %14P right-pads like the
                # reference's xbt_log layout (exec-waitany oracle)
                try:
                    val = ("%" + spec + "s") % val
                except (ValueError, TypeError):
                    pass
            out.append(val)
        elif code == "%":
            out.append("%")
        else:
            out.append("%" + spec + code)
        i = j + 1
    return "".join(out)


class Category:
    def __init__(self, name: str, parent: Optional["Category"]):
        self.name = name
        self.parent = parent
        self.threshold: Optional[int] = None  # None = inherit
        self.layout: Optional[str] = None     # None = inherit/default
        self.appender: Optional[Appender] = None
        self.additional: list = []            # 'add' appenders

    def effective_threshold(self) -> int:
        value = self._effective("threshold")
        return INFO if value is None else value

    def _effective(self, attr):
        cat: Optional[Category] = self
        while cat is not None:
            value = getattr(cat, attr)
            if value is not None:
                return value
            cat = cat.parent
        return None

    def is_enabled(self, level: int) -> bool:
        return level >= self.effective_threshold()

    def _emit(self, level: int, msg: str, *args) -> None:
        if not self.is_enabled(level):
            return
        if args:
            msg = msg % args
        lvl = _LEVEL_NAMES.get(level, str(level))
        fmt = self._effective("layout")
        if fmt is not None:
            line = render_layout(fmt, self.name, lvl, msg)
            if not line.endswith("\n"):
                line += "\n"
        else:
            # default layout = the reference's xbt_log_layout_simple:
            # "[host:actor:(pid) clock] [cat/level] msg" with the
            # actor part dropped for maestro (tesh oracles pin it)
            parts = []
            if actor_info_getter is not None:
                pid, aname, hname = actor_info_getter()
                if pid:
                    parts.append(f"{hname}:{aname}:({pid})")
            elif context_getter is not None:
                parts.append(context_getter())
            if clock_getter is not None:
                parts.append(f"{clock_getter():.6f}")
            prefix = f"[{' '.join(parts)}] " if parts else ""
            line = f"{prefix}[{self.name}/{lvl}] {msg}\n"
        appender = self._effective("appender") or _stderr_appender
        appender.write(line)
        cat: Optional[Category] = self
        while cat is not None:
            for extra in cat.additional:
                extra.write(line)
            cat = cat.parent

    def trace(self, msg, *a): self._emit(TRACE, msg, *a)
    def debug(self, msg, *a): self._emit(DEBUG, msg, *a)
    def verbose(self, msg, *a): self._emit(VERBOSE, msg, *a)
    def info(self, msg, *a): self._emit(INFO, msg, *a)
    def warning(self, msg, *a): self._emit(WARNING, msg, *a)
    def error(self, msg, *a): self._emit(ERROR, msg, *a)
    def critical(self, msg, *a): self._emit(CRITICAL, msg, *a)


def get_category(name: str) -> Category:
    if name in _categories:
        return _categories[name]
    parent = None
    if "." in name:
        parent = get_category(name.rsplit(".", 1)[0])
    elif name != "root":
        parent = get_category("root")
    cat = Category(name, parent)
    _categories[name] = cat
    return cat


def new_category(name: str, description: str = "") -> Category:
    return get_category(name)


def _make_appender(spec: str) -> Appender:
    """'file:PATH', 'rollfile:SIZE:PATH', or 'stderr'/'stdout'
    (xbt_log_appender_file.cpp appender syntax)."""
    if spec in ("stderr", "stdout"):
        return Appender(stream_name=spec)
    if spec.startswith("file:"):
        return Appender(path=spec[len("file:"):])
    if spec.startswith("rollfile:"):
        _, size, path = spec.split(":", 2)
        return Appender(path=path, roll_bytes=int(size))
    raise ValueError(f"Unknown appender spec {spec!r}")


def apply_control(control: str) -> None:
    """Apply ``cat.setting:value`` (space-separated list) log controls:
    thresholds (``cat.thresh:debug``), layouts (``cat.fmt:%m%n``),
    appenders (``cat.app:file:PATH``) and additional appenders
    (``cat.add:file:PATH``).

    Like the reference (log.cpp _xbt_log_parse_setting), any prefix of
    ``threshold`` of length >= 2 is accepted (``th``, ``thres``, ...);
    unknown settings raise instead of being silently dropped."""
    for token in control.split():
        if token == "no_loc":
            # reference xbt_log_control_set("no_loc"): hide source
            # locations (for tesh reproducibility); our layouts never
            # print locations, so this is accepted as a no-op
            continue
        if ":" not in token:
            raise ValueError(f"Invalid log control {token!r}: expected "
                             f"'category.setting:value'")
        key, value = token.split(":", 1)
        cat_name, _, setting = key.rpartition(".")
        if not cat_name:
            raise ValueError(f"Unknown log setting {setting!r} in {token!r}")
        if setting == "fmt":
            get_category(cat_name).layout = value
            continue
        if setting == "app":
            get_category(cat_name).appender = _make_appender(value)
            continue
        if setting == "add":
            get_category(cat_name).additional.append(_make_appender(value))
            continue
        # any prefix of 'threshold' is accepted, down to the bare 't'
        # the reference teshes use (s4u-platform-failures: surf_cpu.t)
        if not setting or not "threshold".startswith(setting):
            raise ValueError(f"Unknown log setting {setting!r} in {token!r}")
        level = _LEVELS.get(value.lower())
        if level is None:
            raise ValueError(f"Unknown log level '{value}'")
        get_category(cat_name).threshold = level
