"""Hierarchical logging with runtime thresholds and simulated-clock layouts.

Capability-equivalent of SimGrid's XBT log (reference:
/root/reference/src/xbt/log.cpp, layouts in xbt_log_layout_format.cpp).
Categories form a dot-separated hierarchy with inherited thresholds;
``--log=cat.thresh:debug`` style controls are parsed by
:func:`apply_control`.  The default layout prints
``[host:actor:(pid) simulated_time] [category/priority] msg`` like the
reference's tesh-facing appender, so golden-output tests can pin lines.
"""

from __future__ import annotations

import sys
from typing import Callable, Dict, Optional

TRACE = 5
DEBUG = 10
VERBOSE = 15
INFO = 20
WARNING = 30
ERROR = 40
CRITICAL = 50

_LEVELS = {
    "trace": TRACE, "debug": DEBUG, "verbose": VERBOSE, "verb": VERBOSE,
    "info": INFO, "warning": WARNING, "warn": WARNING, "error": ERROR,
    "critical": CRITICAL,
}
_LEVEL_NAMES = {TRACE: "trace", DEBUG: "debug", VERBOSE: "verbose",
                INFO: "INFO", WARNING: "WARNING", ERROR: "ERROR",
                CRITICAL: "CRITICAL"}

#: hook returning the current simulated clock; installed by the engine.
clock_getter: Optional[Callable[[], float]] = None
#: hook returning "host:actor:(pid)" for the current context.
context_getter: Optional[Callable[[], str]] = None

_categories: Dict[str, "Category"] = {}


class Category:
    def __init__(self, name: str, parent: Optional["Category"]):
        self.name = name
        self.parent = parent
        self.threshold: Optional[int] = None  # None = inherit

    def effective_threshold(self) -> int:
        cat: Optional[Category] = self
        while cat is not None:
            if cat.threshold is not None:
                return cat.threshold
            cat = cat.parent
        return INFO

    def is_enabled(self, level: int) -> bool:
        return level >= self.effective_threshold()

    def _emit(self, level: int, msg: str, *args) -> None:
        if not self.is_enabled(level):
            return
        if args:
            msg = msg % args
        parts = []
        if context_getter is not None:
            parts.append(context_getter())
        if clock_getter is not None:
            parts.append(f"{clock_getter():.6f}")
        prefix = f"[{' '.join(parts)}] " if parts else ""
        lvl = _LEVEL_NAMES.get(level, str(level))
        sys.stderr.write(f"{prefix}[{self.name}/{lvl}] {msg}\n")

    def trace(self, msg, *a): self._emit(TRACE, msg, *a)
    def debug(self, msg, *a): self._emit(DEBUG, msg, *a)
    def verbose(self, msg, *a): self._emit(VERBOSE, msg, *a)
    def info(self, msg, *a): self._emit(INFO, msg, *a)
    def warning(self, msg, *a): self._emit(WARNING, msg, *a)
    def error(self, msg, *a): self._emit(ERROR, msg, *a)
    def critical(self, msg, *a): self._emit(CRITICAL, msg, *a)


def get_category(name: str) -> Category:
    if name in _categories:
        return _categories[name]
    parent = None
    if "." in name:
        parent = get_category(name.rsplit(".", 1)[0])
    elif name != "root":
        parent = get_category("root")
    cat = Category(name, parent)
    _categories[name] = cat
    return cat


def new_category(name: str, description: str = "") -> Category:
    return get_category(name)


def apply_control(control: str) -> None:
    """Apply a ``cat.thresh:level`` (space-separated list) log control.

    Like the reference (log.cpp _xbt_log_parse_setting), any prefix of
    ``threshold`` of length >= 2 is accepted (``th``, ``thres``, ...);
    unknown settings raise instead of being silently dropped."""
    for token in control.split():
        if ":" not in token:
            raise ValueError(f"Invalid log control {token!r}: expected "
                             f"'category.setting:value'")
        key, value = token.split(":", 1)
        cat_name, _, setting = key.rpartition(".")
        if (not cat_name or len(setting) < 2
                or not "threshold".startswith(setting)):
            if setting in ("fmt", "app", "add"):  # layout/appender controls
                continue  # accepted but not implemented: formats are fixed
            raise ValueError(f"Unknown log setting {setting!r} in {token!r}")
        level = _LEVELS.get(value.lower())
        if level is None:
            raise ValueError(f"Unknown log level '{value}'")
        get_category(cat_name).threshold = level
