"""O(1) intrusive doubly-linked lists with deterministic ordering.

Python equivalent of the boost::intrusive lists the reference uses for every
kernel collection (e.g. maxmin.hpp element sets, Action state sets): the
push_front/push_back ordering defines deterministic iteration — and hence
event — order, so list membership lives on the objects themselves via a
per-list hook attribute.
"""

from __future__ import annotations

from typing import Any


class IntrusiveList:
    __slots__ = ("hook", "head", "tail", "size")

    def __init__(self, hook: str):
        self.hook = hook
        self.head: Any = None
        self.tail: Any = None
        self.size = 0

    def is_linked(self, obj) -> bool:
        return getattr(obj, self.hook, None) is not None

    def push_front(self, obj) -> None:
        assert getattr(obj, self.hook, None) is None
        setattr(obj, self.hook, [None, self.head])
        if self.head is not None:
            getattr(self.head, self.hook)[0] = obj
        else:
            self.tail = obj
        self.head = obj
        self.size += 1

    def push_back(self, obj) -> None:
        assert getattr(obj, self.hook, None) is None
        setattr(obj, self.hook, [self.tail, None])
        if self.tail is not None:
            getattr(self.tail, self.hook)[1] = obj
        else:
            self.head = obj
        self.tail = obj
        self.size += 1

    def remove(self, obj) -> None:
        prev, nxt = getattr(obj, self.hook)
        if prev is not None:
            getattr(prev, self.hook)[1] = nxt
        else:
            self.head = nxt
        if nxt is not None:
            getattr(nxt, self.hook)[0] = prev
        else:
            self.tail = prev
        setattr(obj, self.hook, None)
        self.size -= 1

    def pop_front(self):
        obj = self.head
        if obj is not None:
            self.remove(obj)
        return obj

    def front(self):
        return self.head

    def empty(self) -> bool:
        return self.head is None

    def __len__(self) -> int:
        return self.size

    def __iter__(self):
        # Safe against removal of the CURRENT node (the successor is
        # captured before yielding) — the same guarantee the
        # reference's ++it-before-erase idiom gives, so per-advance
        # model sweeps can traverse the live list directly instead of
        # paying an O(n) list(...) copy per advance.  Removing the
        # *successor* mid-iteration is not supported (same as the
        # reference).
        node = self.head
        while node is not None:
            nxt = getattr(node, self.hook)[1]
            yield node
            node = nxt

    def clear(self) -> None:
        node = self.head
        while node is not None:
            nxt = getattr(node, self.hook)[1]
            setattr(node, self.hook, None)
            node = nxt
        self.head = self.tail = None
        self.size = 0
