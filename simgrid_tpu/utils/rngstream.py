"""RngStream: L'Ecuyer's MRG32k3a with streams and substreams.

Capability-equivalent of the reference's vendored RngStream
(src/xbt/RngStream.c, the standard public-domain generator): the same
combined multiple-recursive generator, the same stream spacing (2^127
states apart) and substream spacing (2^76), so independent simulation
components can draw reproducible, non-overlapping random sequences.
Implemented from the published recurrences — not a translation of the
C file."""

from __future__ import annotations

from typing import List

_M1 = 4294967087.0
_M2 = 4294944443.0
_A12 = 1403580.0
_A13N = 810728.0
_A21 = 527612.0
_A23N = 1370589.0
_NORM = 2.328306549295727688e-10   # 1/(m1+1)
_TWO17 = 131072.0
_TWO53 = 9007199254740992.0

# A1^(2^76) mod m1 / A2^(2^76) mod m2: substream jump matrices;
# A1^(2^127) / A2^(2^127): stream jump matrices (standard constants of
# the generator, derivable by matrix exponentiation below).


def _mat_vec(A, s, m):
    return [sum(A[i][j] * s[j] for j in range(3)) % m for i in range(3)]


def _mat_mat(A, B, m):
    return [[sum(A[i][k] * B[k][j] for k in range(3)) % m
             for j in range(3)] for i in range(3)]


def _mat_pow2(A, e, m):
    """A^(2^e) mod m by repeated squaring (integer arithmetic)."""
    B = [row[:] for row in A]
    for _ in range(e):
        B = _mat_mat(B, B, m)
    return B


_A1 = [[0, 1, 0], [0, 0, 1], [int(-_A13N) % int(_M1), int(_A12), 0]]
_A2 = [[0, 1, 0], [0, 0, 1], [int(-_A23N) % int(_M2), int(_A21), 0]]
_A1_int = [[int(x) for x in row] for row in _A1]
_A2_int = [[int(x) for x in row] for row in _A2]
_A1_SUB = _mat_pow2(_A1_int, 76, int(_M1))
_A2_SUB = _mat_pow2(_A2_int, 76, int(_M2))
_A1_STREAM = _mat_pow2(_A1_int, 127, int(_M1))
_A2_STREAM = _mat_pow2(_A2_int, 127, int(_M2))

_DEFAULT_SEED = [12345] * 6


class RngStream:
    """One stream of the generator; successive constructions advance a
    package-level base seed by 2^127 like RngStream_CreateStream."""

    _next_seed: List[int] = list(_DEFAULT_SEED)

    def __init__(self, name: str = ""):
        self.name = name
        self._ig = list(RngStream._next_seed)   # stream initial state
        self._bg = list(self._ig)               # substream start
        self._cg = list(self._ig)               # current state
        RngStream._next_seed = (
            _mat_vec(_A1_STREAM, RngStream._next_seed[:3], int(_M1))
            + _mat_vec(_A2_STREAM, RngStream._next_seed[3:], int(_M2)))

    # -- seeding -----------------------------------------------------------
    @classmethod
    def set_package_seed(cls, seed: List[int]) -> None:
        assert len(seed) == 6
        cls._next_seed = list(int(s) for s in seed)

    def set_seed(self, seed: List[int]) -> None:
        assert len(seed) == 6
        self._ig = [int(s) for s in seed]
        self._bg = list(self._ig)
        self._cg = list(self._ig)

    # -- stream navigation (RngStream.c Reset*/Advance) -------------------
    def reset_start_stream(self) -> None:
        self._bg = list(self._ig)
        self._cg = list(self._ig)

    def reset_start_substream(self) -> None:
        self._cg = list(self._bg)

    def reset_next_substream(self) -> None:
        self._bg = (_mat_vec(_A1_SUB, self._bg[:3], int(_M1))
                    + _mat_vec(_A2_SUB, self._bg[3:], int(_M2)))
        self._cg = list(self._bg)

    # -- draws (RngStream.c U01) ------------------------------------------
    def rand_u01(self) -> float:
        s = self._cg
        p1 = (_A12 * s[1] - _A13N * s[0]) % _M1
        s[0], s[1], s[2] = s[1], s[2], p1
        p2 = (_A21 * s[5] - _A23N * s[3]) % _M2
        s[3], s[4], s[5] = s[4], s[5], p2
        # RngStream.c U01: (p1 > p2) ? (p1-p2)*norm : (p1-p2+m1)*norm —
        # equality maps to ~1-eps, not ~0.
        return (p1 - p2) * _NORM if p1 > p2 else (p1 - p2 + _M1) * _NORM

    def rand_int(self, low: int, high: int) -> int:
        return low + int(self.rand_u01() * (high - low + 1))


def _derive_seed6(seed: int) -> List[int]:
    """Expand one integer into a valid 6-component RngStream seed
    (each in [1, m-1], so neither triple can be all-zero) with a
    splitmix64-style scrambler: avalanching, and distinct inputs give
    unrelated states."""
    out: List[int] = []
    x = int(seed) & 0xFFFFFFFFFFFFFFFF
    for i in range(6):
        x = (x + 0x9E3779B97F4A7C15) & 0xFFFFFFFFFFFFFFFF
        z = x
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
        z ^= z >> 31
        m = int(_M1) if i < 3 else int(_M2)
        out.append(int(z % (m - 1)) + 1)
    return out


def seeded_stream(seed: int, name: str = "") -> RngStream:
    """An RngStream at a reproducible state derived from an integer seed.

    Unlike a plain ``RngStream()`` construction, this does NOT consume a
    slot of the package-level stream sequence: components that seed
    explicitly (fault campaigns, retry policies) stay bit-reproducible
    no matter how many implicit streams were created before them."""
    saved = list(RngStream._next_seed)
    rng = RngStream(name)
    RngStream._next_seed = saved
    rng.set_seed(_derive_seed6(seed))
    return rng
