"""Collective schedule tapes: device-resident comm DAGs.

Compiles static communication schedules (ring / recursive-doubling /
reduce-bcast allreduce, pairwise / bruck alltoall, binomial bcast —
mirroring smpi/coll.py — plus captured NAS-style phase DAGs) into the
(pred, ready, edges, exec) tape the superstep while_loop walks with
no host involvement: ops/lmm_drain.DrainSim(collective=...) solo,
ops/lmm_batch.BatchDrainSim(collective=...) for fleets.

Layering: schedule (per-rank op IR + DAG builder + generators) ->
topology (route/constraint lowering) -> tape (DeviceCollective, the
compiled arrays) -> maestro (the host-driven bit-identity oracle) ->
spec (the campaign/serving sweep dimension).
"""

from .maestro import HostMaestro
from .schedule import (CollectiveSchedule, CommRec, GENERATORS, Prog,
                       build_schedule, generate, seq_allreduce_lr,
                       seq_allreduce_rdb, seq_allreduce_redbcast,
                       seq_alltoall_bruck, seq_alltoall_pairwise,
                       seq_bcast_binomial, seq_reduce_flat)
from .spec import CollectiveSpec
from .tape import DeviceCollective
from .topology import FLAVORS, Topology

__all__ = [
    "CollectiveSchedule", "CollectiveSpec", "CommRec",
    "DeviceCollective", "FLAVORS", "GENERATORS", "HostMaestro",
    "Prog", "Topology", "build_schedule", "generate",
    "seq_allreduce_lr", "seq_allreduce_rdb", "seq_allreduce_redbcast",
    "seq_alltoall_bruck", "seq_alltoall_pairwise",
    "seq_bcast_binomial", "seq_reduce_flat",
]
