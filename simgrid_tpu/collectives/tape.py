"""Schedule -> device tape compilation.

``DeviceCollective`` lowers a :class:`~.schedule.CollectiveSchedule`
onto a :class:`~.topology.Topology`: every comm record becomes one
LMM flow slot (variable = record id), its route the element rows, and
the dependency sets become the (pred-count, successor-edge, exec-cost)
arrays the superstep while_loop walks autonomously — the full tape
row of the ISSUE: (pred, src, dst, route-slots, size, exec-cost).

Activation protocol (mirrored exactly by maestro.HostMaestro):

* records with no predecessors and no exec cost start LIVE
  (penalty 1, no activation event);
* records with predecessors start DORMANT (penalty 0, full remains,
  pred count = |preds|, ready = +inf).  When the last predecessor
  completes at clock t, the device schedules ready = t + exec_cost
  and a LATER advance lands on that date, scatters penalty 1.0 and
  logs the tagged ring entry ``id = -(1 + n_c + flow)``;
* root records WITH exec cost start dormant with ready = exec_cost —
  the compute leg of a compute/comm phase runs before the wire.

Zero-byte payloads (a barrier's b"" token) are clamped to one byte:
a zero-size flow can never cross the relative retirement threshold,
and both the tape and the host maestro apply the same clamp, so
bit-identity is unaffected.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from .schedule import CollectiveSchedule
from .topology import Topology


class DeviceCollective:
    """The compiled tape: platform arrays + DAG walk arrays."""

    __slots__ = ("schedule", "topology", "n_v", "n_c", "e_var",
                 "e_cnst", "e_w", "c_bound", "sizes", "penalty0",
                 "pred0", "ready0", "edge_src", "edge_dst", "exec_cost")

    def __init__(self, schedule: CollectiveSchedule,
                 topology: Topology,
                 exec_cost: Optional[np.ndarray] = None):
        if topology.ranks != schedule.ranks:
            raise ValueError(
                f"topology is for {topology.ranks} ranks, schedule "
                f"for {schedule.ranks}")
        self.schedule = schedule
        self.topology = topology
        recs = schedule.records
        n_v = len(recs)
        if n_v == 0:
            raise ValueError("schedule has no communications")
        self.n_v = n_v
        self.n_c = topology.n_c
        if exec_cost is None:
            ex = np.zeros(n_v)
        else:
            ex = np.asarray(exec_cost, np.float64)
            if ex.shape != (n_v,):
                raise ValueError(f"exec_cost must have one entry per "
                                 f"record ({n_v}), got {ex.shape}")
        self.exec_cost = ex

        ev, ec = [], []
        for rec in recs:
            for c in topology.route(rec.src, rec.dst):
                ev.append(rec.rid)
                ec.append(c)
        self.e_var = np.asarray(ev, np.int32)
        self.e_cnst = np.asarray(ec, np.int32)
        self.e_w = np.ones(len(ev))
        self.c_bound = np.asarray(topology.c_bound, np.float64)
        self.sizes = np.maximum(
            np.asarray([r.size for r in recs], np.float64), 1.0)

        self.pred0 = np.asarray([len(r.preds) for r in recs], np.int32)
        roots = self.pred0 == 0
        timed_root = roots & (ex > 0)
        self.penalty0 = np.where(roots & ~timed_root, 1.0, 0.0)
        self.ready0 = np.where(timed_root, ex, np.inf)
        es, ed = [], []
        for rec in recs:
            for p in sorted(r.rid for r in rec.preds):
                es.append(p)
                ed.append(rec.rid)
        if not es:
            # keep the edge arrays non-empty: a single dropped-slot
            # row (dst = n_v scatters into the drop lane)
            es, ed = [0], [n_v]
        self.edge_src = np.asarray(es, np.int32)
        self.edge_dst = np.asarray(ed, np.int32)

    @property
    def n_edges(self) -> int:
        return int(np.count_nonzero(self.edge_dst < self.n_v))

    def drain_args(self):
        """The ``collective=`` 5-tuple for DrainSim/BatchDrainSim."""
        return (self.pred0, self.ready0, self.edge_src, self.edge_dst,
                self.exec_cost)

    def make_sim(self, superstep: int = 16, pipeline: int = 0,
                 tape=None, device=None, **kw):
        """A ready-to-run tape-driven DrainSim over this collective."""
        from ..ops.lmm_drain import DrainSim
        return DrainSim(self.e_var, self.e_cnst, self.e_w,
                        self.c_bound, self.sizes, dtype=np.float64,
                        superstep=superstep, pipeline=pipeline,
                        penalty=self.penalty0, tape=tape,
                        device=device, collective=self.drain_args(),
                        **kw)

    def key(self) -> tuple:
        return ("dcoll", self.n_v, self.n_c, self.topology.key(),
                float(self.sizes.sum()), int(self.pred0.sum()))
