"""CollectiveSpec: the sweep dimension campaigns/serving put on
ScenarioSpec/ScenarioPlan.

A spec names (op, algo, ranks, topology flavor, payload) — everything
needed to regenerate the schedule and compile the tape — in the same
content-addressed style as ScenarioSpec: canonical dict form, stable
sha256 ``key()``, JSON round trip.  ``build()`` materializes the
DeviceCollective (schedule generation + topology lowering); plan
construction caches it, so fleets sweeping rank counts × algorithms ×
topologies pay one compile per distinct spec.
"""

from __future__ import annotations

import hashlib
import json
from typing import Dict, Optional

from .schedule import GENERATORS, generate
from .tape import DeviceCollective
from .topology import FLAVORS, Topology


class CollectiveSpec:
    """One collective workload: algorithm × rank count × topology."""

    __slots__ = ("op", "algo", "ranks", "topo", "payload", "bw",
                 "loop_bw", "core_bw")

    def __init__(self, op: str = "allreduce", algo: str = "rdb",
                 ranks: int = 8, topo: str = "nic",
                 payload: float = 1 << 20, bw: float = 1e9,
                 loop_bw: float = 0.0, core_bw: float = 0.0):
        if (op, algo) not in GENERATORS:
            raise ValueError(f"unknown collective {op}/{algo}; known: "
                             f"{sorted(GENERATORS)}")
        if topo not in FLAVORS:
            raise ValueError(f"unknown topology flavor {topo!r}")
        if ranks < 2:
            raise ValueError("a collective needs at least 2 ranks")
        self.op = str(op)
        self.algo = str(algo)
        self.ranks = int(ranks)
        self.topo = str(topo)
        #: payload bytes (elements for lr — see schedule.GENERATORS)
        self.payload = float(payload)
        self.bw = float(bw)
        self.loop_bw = float(loop_bw)
        self.core_bw = float(core_bw)

    # -- stable serialization / content addressing -------------------------

    def to_dict(self) -> Dict:
        return {"op": self.op, "algo": self.algo, "ranks": self.ranks,
                "topo": self.topo, "payload": self.payload,
                "bw": self.bw, "loop_bw": self.loop_bw,
                "core_bw": self.core_bw}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":"))

    @classmethod
    def from_dict(cls, d: Dict) -> "CollectiveSpec":
        return cls(op=d.get("op", "allreduce"),
                   algo=d.get("algo", "rdb"),
                   ranks=d.get("ranks", 8),
                   topo=d.get("topo", "nic"),
                   payload=d.get("payload", 1 << 20),
                   bw=d.get("bw", 1e9),
                   loop_bw=d.get("loop_bw", 0.0),
                   core_bw=d.get("core_bw", 0.0))

    @classmethod
    def from_json(cls, text: str) -> "CollectiveSpec":
        return cls.from_dict(json.loads(text))

    def key(self) -> str:
        """Stable sha256 of the collective identity (same convention
        as ScenarioSpec.key)."""
        return hashlib.sha256(self.to_json().encode("utf-8")).hexdigest()

    def label(self) -> str:
        return (f"{self.op}/{self.algo} r{self.ranks} {self.topo} "
                f"{self.payload:g}B")

    # -- materialization ---------------------------------------------------

    def topology(self) -> Topology:
        return Topology(self.ranks, self.topo, bw=self.bw,
                        loop_bw=self.loop_bw, core_bw=self.core_bw)

    def build(self, exec_cost=None) -> DeviceCollective:
        sched = generate(self.op, self.algo, self.ranks, self.payload)
        return DeviceCollective(sched, self.topology(),
                                exec_cost=exec_cost)
