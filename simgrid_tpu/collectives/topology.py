"""Topology flavors for compiled collective schedules.

A topology maps a (src, dst) rank pair to the constraint slots the
transfer's LMM variable rides — the route half of the tape record.
Three flavors cover the sweep axes the campaign layer exposes:

* ``nic``  — per-rank full-duplex NICs over a non-blocking fabric:
  route = [tx(src), rx(dst)].  The distributed-ML default (a pod's
  ICI/optical fabric is provisioned so endpoints, not the core, are
  the contended resource).
* ``star`` — per-rank NICs plus ONE shared core constraint:
  route = [tx(src), core, rx(dst)] — an oversubscribed aggregation
  switch, the adversarial case for ring-free algorithms.
* ``ring`` — R physical links; a transfer crosses every link on the
  shorter arc from src to dst (ties go clockwise).  Ring allreduce is
  contention-free here; rdb hop distances grow with the mask.

Every flavor also provisions a per-rank LOOPBACK constraint: the lr
allreduce posts a literal sendrecv-to-self (allreduce-lr.cpp:69-73)
and self-transfers must ride a dedicated resource, mirroring the
reference platform's loopback link, not the fabric.
"""

from __future__ import annotations

from typing import List

import numpy as np

FLAVORS = ("nic", "star", "ring")


class Topology:
    """Constraint layout + route function for one flavor instance."""

    __slots__ = ("flavor", "ranks", "bw", "loop_bw", "core_bw", "n_c",
                 "c_bound")

    def __init__(self, ranks: int, flavor: str = "nic",
                 bw: float = 1e9, loop_bw: float = 0.0,
                 core_bw: float = 0.0):
        if flavor not in FLAVORS:
            raise ValueError(f"unknown topology flavor {flavor!r} "
                             f"(expected one of {FLAVORS})")
        if ranks < 1:
            raise ValueError("topology needs at least one rank")
        self.flavor = flavor
        self.ranks = int(ranks)
        self.bw = float(bw)
        # loopback rides memory, not the fabric: default 4x the NIC
        self.loop_bw = float(loop_bw) if loop_bw else 4.0 * self.bw
        # star core: R/4 NICs' worth of aggregate (oversubscription 4)
        self.core_bw = (float(core_bw) if core_bw
                        else self.bw * max(self.ranks // 4, 1))
        R = self.ranks
        if flavor == "nic":
            self.n_c = 3 * R
            cb = np.full(self.n_c, self.bw)
            cb[2 * R:] = self.loop_bw
        elif flavor == "star":
            self.n_c = 3 * R + 1
            cb = np.full(self.n_c, self.bw)
            cb[2 * R] = self.core_bw
            cb[2 * R + 1:] = self.loop_bw
        else:  # ring
            self.n_c = 2 * R
            cb = np.full(self.n_c, self.bw)
            cb[R:] = self.loop_bw
        self.c_bound = cb

    def route(self, src: int, dst: int) -> List[int]:
        R = self.ranks
        if src == dst:
            if self.flavor == "nic":
                return [2 * R + src]
            if self.flavor == "star":
                return [2 * R + 1 + src]
            return [R + src]
        if self.flavor == "nic":
            return [src, R + dst]
        if self.flavor == "star":
            return [src, 2 * R, R + dst]
        # ring: walk the shorter arc, clockwise on ties; link i spans
        # rank i -> i+1 (mod R)
        cw = (dst - src) % R
        ccw = (src - dst) % R
        if cw <= ccw:
            return [(src + j) % R for j in range(cw)]
        return [(src - 1 - j) % R for j in range(ccw)]

    def key(self) -> tuple:
        return ("topo", self.flavor, self.ranks, self.bw,
                self.loop_bw, self.core_bw)
