"""Host-maestro oracle for collective tapes.

``HostMaestro`` runs the SAME compiled comm DAG as the device tape,
but the way the SMPI maestro would: all schedule bookkeeping (pred
counts, ready dates, fault cursor, the clock) lives on the HOST, and
the device is consulted once per advance for the rate fixpoint plus
once for the forced decrement — >= 2 dispatches and >= 2 fetches per
advance, with every activation and fault costing an extra scatter
upload.  That is the baseline the tape path's one-dispatch-per-K
supersteps are measured against (bench.py --stage collective), and
the bit-identity reference of check_determinism --runtime-collective.

Bit-identity is by construction, not by tolerance: the maestro replays
the exact per-advance recurrence of ops.lmm_drain._superstep_program
(has_coll arm) —

* rates from the same ``fixpoint`` program over the same device
  arrays;
* ``dt_plan = min(rem / rate)`` in f64 (elementwise IEEE division and
  min match the device reduction);
* the event peek: ``next_t = min(fault date, min(ready))``, fire iff
  ``next_t <= now + dt_plan`` (ties to the event), dt clamped to land
  exactly on the date;
* remains decremented ON DEVICE via ``_drain_forced_advance`` — the
  ``_rounded_product`` FMA-pinning detour is the one piece of advance
  math that must not be re-derived on host;
* the clock accumulated by the same compensated (Kahan) pair, one
  python-float step per advance — grouping K advances per dispatch
  leaves the recurrence unchanged, which is the whole invariant.

Event streams come out in the device's order: completions by flow
slot, then the fault entry, then activations by flow slot, all at the
advance's Kahan clock.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from ..ops import opstats
from ..ops.lmm_drain import (_MAX_ROUNDS, _ZERO_BITS,
                             _drain_forced_advance, DrainSim)
from ..ops.lmm_jax import fixpoint
from .tape import DeviceCollective


@functools.partial(jax.jit, static_argnames=("eps", "n_c", "n_v",
                                             "has_bounds"))
def _maestro_solve(e_var, e_cnst, e_w, c_bound, pen, v_bound,
                   eps: float, n_c: int, n_v: int,
                   has_bounds: bool = False):
    """One solve-to-convergence dispatch: the same fixpoint call the
    superstep body makes, minus the surrounding while_loop."""
    dtype = e_w.dtype
    out = fixpoint(e_var, e_cnst, e_w, c_bound, jnp.zeros(n_c, bool),
                   pen, v_bound, jnp.asarray(eps, dtype), n_c, n_v,
                   parallel_rounds=True, carry=None,
                   max_rounds=_MAX_ROUNDS, return_carry=True,
                   has_bounds=has_bounds, has_fatpipe=False)
    carry2 = out[4]
    return carry2[0], out[3], jnp.count_nonzero(carry2[4])


class HostMaestro:
    """Drive a DeviceCollective one advance per dispatch, host-side."""

    def __init__(self, dc: DeviceCollective, tape=None, device=None,
                 eps: float = 1e-5, done_eps: float = 1e-4):
        self.dc = dc
        self.n_v = dc.n_v
        self.n_c = dc.n_c
        self.sim = DrainSim(dc.e_var, dc.e_cnst, dc.e_w, dc.c_bound,
                            dc.sizes, dtype=np.float64, device=device,
                            eps=eps, done_eps=done_eps,
                            penalty=dc.penalty0,
                            repack_min=1 << 62)
        self.pred = dc.pred0.astype(np.int64).copy()
        self.ready = dc.ready0.astype(np.float64).copy()
        self.exec_cost = dc.exec_cost
        em = dc.edge_dst < dc.n_v          # drop the pad row
        self.edge_src = dc.edge_src[em]
        self.edge_dst = dc.edge_dst[em]
        if tape is not None and len(tape[0]):
            self.tape_t = np.asarray(tape[0], np.float64)
            self.tape_slot = np.asarray(tape[1], np.int32)
            self.tape_val = np.asarray(tape[2], np.float64)
        else:
            self.tape_t = np.zeros(0)
            self.tape_slot = np.zeros(0, np.int32)
            self.tape_val = np.zeros(0)
        self.tpos = 0
        self.t = 0.0
        self.comp = 0.0                    # Kahan compensation term
        self.events: list = []
        self.collective_events: list = []
        self.fault_events: list = []
        self.advances = 0
        self.dispatches = 0
        self.fetches = 0

    # -- one maestro advance ----------------------------------------------

    def _advance(self) -> None:
        s = self.sim
        rates_dev, rounds, n_light = _maestro_solve(
            *s._dev, s._cb, s._pen, s._vb, eps=s.eps, n_c=s.n_c,
            n_v=s.n_v, has_bounds=s.has_bounds)
        self.dispatches += 1
        opstats.bump("dispatches")
        if int(n_light):
            raise RuntimeError("maestro solve did not converge")
        rates = opstats.timed_fetch(rates_dev)
        pen = opstats.timed_fetch(s._pen)
        rem = opstats.timed_fetch(s._rem)
        self.fetches += 3

        live = pen > 0
        rate = np.where(live, rates, 0.0)
        flowing = live & (rate > 0)
        q = rem / np.where(flowing, rate, 1.0)
        dt_plan = float(np.min(np.where(flowing, q, np.inf))) \
            if len(q) else float("inf")

        next_ft = (float(self.tape_t[self.tpos])
                   if self.tpos < len(self.tape_t) else float("inf"))
        next_at = float(np.min(self.ready))
        now = self.t
        next_t = min(next_ft, next_at)
        fire = np.isfinite(next_t) and next_t <= now + dt_plan
        dt = max(next_t - now, 0.0) if fire else dt_plan
        if not np.isfinite(dt):
            raise RuntimeError(
                f"collective schedule deadlocked: "
                f"{len(self.events)}/{self.n_v} flows completed and "
                f"nothing is pending")

        s._pen, s._rem, out = _drain_forced_advance(
            s._pen, s._rem, s._thresh, rates_dev,
            jnp.asarray(dt, np.float64), _ZERO_BITS)
        self.dispatches += 1
        opstats.bump("dispatches")
        out = opstats.timed_fetch(out)
        self.fetches += 1
        done = out[1:] > 0
        self.advances += 1

        # Kahan clock, one python-float step — the same compensated
        # recurrence the superstep body runs in-dispatch
        y = dt - self.comp
        t_new = self.t + y
        self.comp = (t_new - self.t) - y
        self.t = t_new

        for fid in np.flatnonzero(done):
            self.events.append((t_new, int(fid)))

        if fire and next_ft <= next_at:          # fault entry
            slot = int(self.tape_slot[self.tpos])
            val = float(self.tape_val[self.tpos])
            s.apply_transitions({"c_bound": ([slot], [val])})
            self.dispatches += 1
            self.fault_events.append((t_new, slot))
            self.tpos += 1

        acts = np.zeros(0, np.int64)
        if fire and next_at <= next_ft:          # activations
            acts = np.flatnonzero(self.ready <= next_t)
            for fid in acts:
                self.collective_events.append((t_new, int(fid)))
            self.ready[acts] = np.inf

        # DAG walk: completions decrement successors; flows reaching
        # zero get ready = t_new + exec on a LATER advance
        if done.any():
            m = done[self.edge_src]
            pred_before = self.pred.copy()
            np.add.at(self.pred, self.edge_dst[m], -1)
            newly = (self.pred <= 0) & (pred_before > 0)
            self.ready[newly] = t_new + self.exec_cost[newly]
        if len(acts):
            s.apply_transitions(
                {"v_penalty": (acts, np.ones(len(acts)))})
            self.dispatches += 1

    def run(self, max_advances: int = 10_000_000) -> None:
        budget = max_advances
        while len(self.events) < self.n_v and budget > 0:
            self._advance()
            budget -= 1
        if len(self.events) < self.n_v:
            raise RuntimeError("maestro exceeded its advance budget")

    # oracle hooks ---------------------------------------------------------

    @property
    def clock(self):
        """(t, compensation) — compare bitwise against the tape sim's
        carried coll_clk pair."""
        return (self.t, self.comp)
