"""Static communication schedules: the comm-DAG IR behind the tapes.

A *schedule* is, per rank, the ordered list of point-to-point
operations a collective algorithm posts — the exact information the
SMPI maestro discovers one mailbox match at a time.  Compiling it
ahead of time is what lets the superstep while_loop walk the whole
collective on device (ASTRA-sim 3.0's workload-layer move): each
matched (send, recv) pair becomes ONE comm record with an explicit
predecessor set, and ops/lmm_drain's collective tape fires successor
records by indexed scatter instead of a host round trip per step.

Per-rank programs use four op shapes (blocking send/recv are emitted
as post + wait, mirroring smpi.Comm where ``send`` is Request.start()
+ wait() and ``sendrecv`` decomposes as irecv, isend, wait(recv),
wait(send)):

    ("isend", dst, tag, size, h)   ("irecv", src, tag, h)   ("wait", h)

``h`` is a per-rank handle (the post's sequence number).  Matching
follows the non-overtaking rule: per (src, dst, tag) channel, the
i-th recv posted matches the i-th send posted — the same FIFO
sequencing smpi.runtime applies to its mailboxes, and the reason one
constant tag per collective is safe (see coll.allreduce_lr's note).

Dependency construction is a per-rank *frontier* walk: a record's
predecessors are every record whose completion the posting rank (and
the receiving rank, at its own post point) had already waited on.  On
``wait`` the frontier becomes ``(frontier - rec.preds) | {rec}`` —
records implied transitively through the awaited record are pruned,
keeping the edge list near-minimal without changing reachability.

The ``seq_*`` generators below mirror smpi/coll.py's default
algorithms LINE FOR LINE (same peer formulas, same tag, same posting
order); tests/test_collectives.py proves each one equal to a schedule
captured from the real coll.py implementation running on threads.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, Tuple

# Mirrors smpi/coll.py (reference smpi/include/private.hpp COLL_TAG_*);
# kept literal so importing the schedule compiler never drags in the
# SMPI runtime.  tests/test_collectives.py asserts they stay in sync.
TAG_BCAST = -10
TAG_REDUCE = -12
TAG_ALLREDUCE = -13
TAG_ALLTOALL = -14

#: payload_size() of a non-buffer python object (dict payloads in
#: bruck/rdb-allgather, scalars) — smpi/datatype.py's fallback
_OBJ_BYTES = 8.0


class CommRec:
    """One matched point-to-point transfer: the tape row's identity
    half (src, dst, size) plus its dependency set.  ``rid`` is the
    flow slot in the compiled tape; allocation is rank-major in send
    program order, so record ids are deterministic for a given
    schedule."""

    __slots__ = ("rid", "src", "dst", "tag", "size", "preds")

    def __init__(self, rid: int, src: int, dst: int, tag: int,
                 size: float):
        self.rid = rid
        self.src = src
        self.dst = dst
        self.tag = tag
        self.size = float(size)
        self.preds: set = set()

    def key(self) -> tuple:
        return (self.src, self.dst, self.tag, self.size,
                tuple(sorted(r.rid for r in self.preds)))


class Prog:
    """Per-rank op-sequence builder (the capture shim and the direct
    generators share it, so both sides emit identical op tuples)."""

    __slots__ = ("ops", "_h")

    def __init__(self):
        self.ops: List[tuple] = []
        self._h = 0

    def isend(self, dst: int, tag: int, size: float) -> int:
        h = self._h
        self._h += 1
        self.ops.append(("isend", int(dst), int(tag), float(size), h))
        return h

    def irecv(self, src: int, tag: int) -> int:
        h = self._h
        self._h += 1
        self.ops.append(("irecv", int(src), int(tag), h))
        return h

    def wait(self, h: int) -> None:
        self.ops.append(("wait", h))

    def send(self, dst: int, tag: int, size: float) -> None:
        self.wait(self.isend(dst, tag, size))

    def recv(self, src: int, tag: int) -> None:
        self.wait(self.irecv(src, tag))

    def sendrecv(self, dst: int, src: int, size: float,
                 sendtag: int, recvtag: int) -> None:
        # mirror smpi.Comm.sendrecv: irecv first, then isend, wait the
        # recv, wait the send
        hr = self.irecv(src, recvtag)
        hs = self.isend(dst, sendtag, size)
        self.wait(hr)
        self.wait(hs)


class CollectiveSchedule:
    """A compiled schedule: the matched records (rid order) plus the
    originating per-rank programs."""

    __slots__ = ("ranks", "records", "progs")

    def __init__(self, ranks: int, records: List[CommRec],
                 progs: List[List[tuple]]):
        self.ranks = ranks
        self.records = records
        self.progs = progs

    @property
    def n_comms(self) -> int:
        return len(self.records)

    def sequence(self) -> List[tuple]:
        """(src, dst, tag, size, sorted-pred-rids) per record — the
        comparison key of the tape-vs-host parity tests."""
        return [r.key() for r in self.records]

    def edges(self) -> List[Tuple[int, int]]:
        out = []
        for rec in self.records:
            for p in sorted(r.rid for r in rec.preds):
                out.append((p, rec.rid))
        return out


def build_schedule(progs) -> CollectiveSchedule:
    """Compile per-rank programs (Prog instances or raw op lists) into
    matched records with dependency sets.

    Pass 1 allocates record ids (rank-major, send program order) and
    matches each recv against its channel's FIFO; pass 2 runs the
    per-rank frontier walk that accumulates predecessor sets.
    Unmatched ops raise — a schedule with dangling posts would
    deadlock the tape exactly like it would deadlock the maestro.
    """
    ops_per_rank = [p.ops if isinstance(p, Prog) else list(p)
                    for p in progs]
    ranks = len(ops_per_rank)
    records: List[CommRec] = []
    chan: Dict[tuple, deque] = {}
    send_rec: List[Dict[int, CommRec]] = [dict() for _ in range(ranks)]
    for r, ops in enumerate(ops_per_rank):
        for op in ops:
            if op[0] == "isend":
                _, dst, tag, size, h = op
                if not 0 <= dst < ranks:
                    raise ValueError(f"rank {r}: send to {dst} outside "
                                     f"communicator of {ranks}")
                rec = CommRec(len(records), r, dst, tag, size)
                records.append(rec)
                chan.setdefault((r, dst, tag), deque()).append(rec)
                send_rec[r][h] = rec
    recv_rec: List[Dict[int, CommRec]] = [dict() for _ in range(ranks)]
    for r, ops in enumerate(ops_per_rank):
        for op in ops:
            if op[0] == "irecv":
                _, src, tag, h = op
                q = chan.get((src, r, tag))
                if not q:
                    raise ValueError(
                        f"rank {r}: recv(src={src}, tag={tag}) has no "
                        "matching send (wildcards are not compilable)")
                recv_rec[r][h] = q.popleft()
    leftover = sum(len(chan[k]) for k in sorted(chan))
    if leftover:
        raise ValueError(f"{leftover} sends were never received")

    for r, ops in enumerate(ops_per_rank):
        frontier: set = set()
        handles = {}
        handles.update(send_rec[r])
        handles.update(recv_rec[r])
        for op in ops:
            if op[0] == "isend":
                send_rec[r][op[4]].preds |= frontier
            elif op[0] == "irecv":
                recv_rec[r][op[3]].preds |= frontier
            else:  # wait
                rec = handles.get(op[1])
                if rec is None:
                    raise ValueError(f"rank {r}: wait on unknown "
                                     f"handle {op[1]}")
                frontier = (frontier - rec.preds) | {rec}
    for rec in records:
        rec.preds.discard(rec)
    return CollectiveSchedule(ranks, records, ops_per_rank)


# ---------------------------------------------------------------------------
# direct generators — smpi/coll.py's algorithms, re-expressed as op
# emissions.  Peer formulas, tags and posting order are copied from
# the host implementations verbatim; the parity tests hold them to it.
# ---------------------------------------------------------------------------

def seq_bcast_binomial(ranks: int, nbytes: float,
                       root: int = 0) -> CollectiveSchedule:
    """coll.bcast_binomial_tree."""
    progs = [Prog() for _ in range(ranks)]
    for rank in range(ranks):
        p = progs[rank]
        relrank = (rank - root + ranks) % ranks
        mask = 1
        while mask < ranks:
            if relrank & mask:
                p.recv((rank - mask + ranks) % ranks, TAG_BCAST)
                break
            mask <<= 1
        mask >>= 1
        while mask > 0:
            if relrank + mask < ranks:
                p.send((rank + mask) % ranks, TAG_BCAST, nbytes)
            mask >>= 1
    return build_schedule(progs)


def seq_reduce_flat(ranks: int, nbytes: float,
                    root: int = 0) -> CollectiveSchedule:
    """coll.reduce_flat_ireduce (the reference default)."""
    progs = [Prog() for _ in range(ranks)]
    _emit_reduce_flat(progs, ranks, nbytes, root)
    return build_schedule(progs)


def _emit_reduce_flat(progs, ranks, nbytes, root):
    for rank in range(ranks):
        p = progs[rank]
        if rank != root:
            p.send(root, TAG_REDUCE, nbytes)
        else:
            reqs = [p.irecv(src, TAG_REDUCE) for src in range(ranks)
                    if src != root]
            for h in reqs:
                p.wait(h)


def _emit_bcast_binomial(progs, ranks, nbytes, root):
    for rank in range(ranks):
        p = progs[rank]
        relrank = (rank - root + ranks) % ranks
        mask = 1
        while mask < ranks:
            if relrank & mask:
                p.recv((rank - mask + ranks) % ranks, TAG_BCAST)
                break
            mask <<= 1
        mask >>= 1
        while mask > 0:
            if relrank + mask < ranks:
                p.send((rank + mask) % ranks, TAG_BCAST, nbytes)
            mask >>= 1


def seq_allreduce_redbcast(ranks: int, nbytes: float
                           ) -> CollectiveSchedule:
    """coll.allreduce_redbcast: reduce to 0 + bcast from 0 (the
    reference default).  Per-rank sequencing chains the two phases —
    the bcast root's sends depend on every reduce arrival."""
    progs = [Prog() for _ in range(ranks)]
    _emit_reduce_flat(progs, ranks, nbytes, 0)
    _emit_bcast_binomial(progs, ranks, nbytes, 0)
    return build_schedule(progs)


def _emit_allreduce_rdb(progs, ranks, nbytes):
    pof2 = 1
    while pof2 * 2 <= ranks:
        pof2 *= 2
    rem = ranks - pof2
    for rank in range(ranks):
        p = progs[rank]
        if rank < 2 * rem:
            if rank % 2 == 0:
                p.send(rank + 1, TAG_ALLREDUCE, nbytes)
                newrank = -1
            else:
                p.recv(rank - 1, TAG_ALLREDUCE)
                newrank = rank // 2
        else:
            newrank = rank - rem
        if newrank >= 0:
            mask = 1
            while mask < pof2:
                peer_new = newrank ^ mask
                peer = (peer_new * 2 + 1 if peer_new < rem
                        else peer_new + rem)
                p.sendrecv(peer, peer, nbytes,
                           TAG_ALLREDUCE, TAG_ALLREDUCE)
                mask <<= 1
        if rank < 2 * rem:
            if rank % 2:
                p.send(rank - 1, TAG_ALLREDUCE, nbytes)
            else:
                p.recv(rank + 1, TAG_ALLREDUCE)


def seq_allreduce_rdb(ranks: int, nbytes: float) -> CollectiveSchedule:
    """coll.allreduce_rdb (recursive doubling with non-power-of-two
    fold-in).  Every transfer ships the full ``nbytes`` payload."""
    progs = [Prog() for _ in range(ranks)]
    _emit_allreduce_rdb(progs, ranks, nbytes)
    return build_schedule(progs)


def seq_allreduce_lr(ranks: int, count_elems: int,
                     elem_bytes: float = 8.0) -> CollectiveSchedule:
    """coll.allreduce_lr: logical-ring reduce-scatter + all-gather on
    an ndarray of ``count_elems`` elements, including the observable
    quirks — the initial sendrecv-to-self copy (rides the loopback
    link) and the ``count_elems % ranks`` remainder folded by a
    recursive allreduce (which, at len < ranks, is rdb)."""
    progs = [Prog() for _ in range(ranks)]
    if count_elems < ranks:
        # the "not support" fallback (allreduce-lr.cpp:41-45)
        _emit_allreduce_rdb(progs, ranks, count_elems * elem_bytes)
        return build_schedule(progs)
    count = count_elems // ranks
    remainder = count_elems % ranks
    chunk = count * elem_bytes
    for rank in range(ranks):
        p = progs[rank]
        p.sendrecv(rank, rank, chunk, TAG_ALLREDUCE, TAG_ALLREDUCE)
        for _ in range(ranks - 1):          # reduce-scatter
            p.sendrecv((rank + 1) % ranks, (rank - 1 + ranks) % ranks,
                       chunk, TAG_ALLREDUCE, TAG_ALLREDUCE)
        for _ in range(ranks - 1):          # all-gather
            p.sendrecv((rank + 1) % ranks, (rank - 1 + ranks) % ranks,
                       chunk, TAG_ALLREDUCE, TAG_ALLREDUCE)
    if remainder:
        _emit_allreduce_rdb(progs, ranks, remainder * elem_bytes)
    return build_schedule(progs)


def seq_alltoall_pairwise(ranks: int,
                          block_bytes: float) -> CollectiveSchedule:
    """coll.alltoall_pairwise: ranks-1 shifted sendrecv steps."""
    progs = [Prog() for _ in range(ranks)]
    for rank in range(ranks):
        p = progs[rank]
        for step in range(1, ranks):
            dst = (rank + step) % ranks
            src = (rank - step + ranks) % ranks
            p.sendrecv(dst, src, block_bytes,
                       TAG_ALLTOALL, TAG_ALLTOALL)
    return build_schedule(progs)


def seq_alltoall_bruck(ranks: int) -> CollectiveSchedule:
    """coll.alltoall_bruck: log2(n) rounds shipping combined blocks.
    The combined payload is a python dict, so every transfer simulates
    at payload_size's object fallback (8 bytes) regardless of block
    size — exactly what the host implementation posts."""
    progs = [Prog() for _ in range(ranks)]
    for rank in range(ranks):
        p = progs[rank]
        pof2 = 1
        while pof2 < ranks:
            to = (rank + pof2) % ranks
            frm = (rank - pof2 + ranks) % ranks
            p.sendrecv(to, frm, _OBJ_BYTES, TAG_ALLTOALL, TAG_ALLTOALL)
            pof2 <<= 1
    return build_schedule(progs)


#: algorithm registry for CollectiveSpec / campaign sweeps: name ->
#: (generator, payload semantics).  "bytes" generators take a payload
#: byte count; "elems" (lr) takes an element count.
GENERATORS = {
    ("allreduce", "redbcast"): (seq_allreduce_redbcast, "bytes"),
    ("allreduce", "rdb"): (seq_allreduce_rdb, "bytes"),
    ("allreduce", "lr"): (seq_allreduce_lr, "elems"),
    ("alltoall", "pairwise"): (seq_alltoall_pairwise, "bytes"),
    ("alltoall", "bruck"): (seq_alltoall_bruck, None),
    ("bcast", "binomial_tree"): (seq_bcast_binomial, "bytes"),
    ("reduce", "default"): (seq_reduce_flat, "bytes"),
}


def generate(op: str, algo: str, ranks: int,
             payload: float) -> CollectiveSchedule:
    """Build the schedule for (op, algo) at ``ranks`` with ``payload``
    (bytes, or elements for lr; ignored by bruck)."""
    try:
        fn, mode = GENERATORS[(op, algo)]
    except KeyError:
        raise ValueError(f"no schedule generator for {op}/{algo}; "
                         f"known: {sorted(GENERATORS)}") from None
    if mode is None:
        return fn(ranks)
    if mode == "elems":
        return fn(ranks, int(payload))
    return fn(ranks, float(payload))
