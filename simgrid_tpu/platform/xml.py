"""Platform file loader: accepts the reference's simgrid.dtd XML files.

Parses the same tags/attributes as the reference SAX callbacks
(/root/reference/src/surf/xml/surfxml_sax_cb.cpp + sg_platf.cpp): zones
(Full/Floyd/Dijkstra/DijkstraCache/None/Vivaldi/Cluster variants), hosts
(speed pstates, core, availability/state profiles, coordinates), routers,
links (bandwidth, latency, sharing policy, profiles), routes & zoneRoutes
with link_ctn, bypass routes, clusters/cabinets, peers, traces and
trace_connect, and properties — built on xml.etree instead of generated
FleXML C.
"""

from __future__ import annotations

import os
import xml.etree.ElementTree as ET
from typing import Dict, List, Optional

from ..exceptions import ParseError
from ..kernel import profile as profile_mod
from ..ops.lmm_host import SharingPolicy
from ..routing.routed import (DijkstraZone, EmptyZone, FloydZone, FullZone,
                              VivaldiZone)
from ..routing.zone import NetPoint, NetPointType, NetZoneImpl
from .units import (parse_bandwidth, parse_size, parse_speed, parse_speeds,
                    parse_time)

_ZONE_FACTORY = {}


def register_zone_factory(routing: str, factory) -> None:
    _ZONE_FACTORY[routing] = factory


def _make_zone(engine, father, name: str, routing: str) -> NetZoneImpl:
    routing = routing or "None"
    if routing in _ZONE_FACTORY:
        return _ZONE_FACTORY[routing](engine, father, name)
    if routing == "Full":
        return FullZone(engine, father, name)
    if routing == "Floyd":
        return FloydZone(engine, father, name)
    if routing == "Dijkstra":
        return DijkstraZone(engine, father, name, cached=False)
    if routing == "DijkstraCache":
        return DijkstraZone(engine, father, name, cached=True)
    if routing == "None":
        return EmptyZone(engine, father, name)
    if routing == "Vivaldi":
        return VivaldiZone(engine, father, name)
    if routing == "Cluster":
        from ..routing.cluster import ClusterZone
        return ClusterZone(engine, father, name)
    raise ParseError(f"Unknown zone routing '{routing}'")


class PlatformLoader:
    """Builds the platform into an EngineImpl from an XML file or tree."""

    def __init__(self, engine):
        self.engine = engine
        self.base_dir = "."
        self.trace_connect_list: List[Dict[str, str]] = []

    # -- public ------------------------------------------------------------
    def load(self, path: str) -> None:
        self.base_dir = os.path.dirname(os.path.abspath(path))
        try:
            tree = ET.parse(path)
        except ET.ParseError as e:
            raise ParseError(f"{path}: {e}") from None
        root = tree.getroot()
        from .dtd import validate
        validate(root, path)
        # remember where the platform file lives: storage content files
        # resolve against it (and against the 'path' config entries)
        self.engine.platform_dir = self.base_dir
        for child in root:
            self._dispatch_toplevel(child, None)
        if self.engine.netzone_root is not None:
            self.engine.netzone_root.seal()
        self._apply_trace_connects()
        from ..kernel.engine import EngineImpl
        EngineImpl.on_platform_created()

    # -- dispatch ----------------------------------------------------------
    def _dispatch_toplevel(self, elem, zone) -> None:
        tag = elem.tag
        if tag in ("zone", "AS"):
            self._parse_zone(elem, zone)
        elif tag == "trace":
            self._parse_trace(elem)
        elif tag == "trace_connect":
            self.trace_connect_list.append(dict(elem.attrib))
        elif tag == "config":
            self._parse_config(elem)
        elif tag == "cluster":
            # a top-level <cluster> IS the platform (the DTD allows it;
            # energy_cluster.xml) — it becomes its own root zone
            self._parse_cluster(elem, zone)
        elif tag == "prop":
            pass
        else:
            raise ParseError(f"Unexpected top-level tag <{tag}>")

    def _parse_zone(self, elem, father) -> NetZoneImpl:
        name = elem.get("id")
        routing = elem.get("routing")
        zone = _make_zone(self.engine, father, name, routing)
        for child in elem:
            tag = child.tag
            if tag in ("zone", "AS"):
                self._parse_zone(child, zone)
            elif tag == "host":
                self._parse_host(child, zone)
            elif tag == "router":
                self._parse_router(child, zone)
            elif tag == "link":
                self._parse_link(child, zone)
            elif tag == "route":
                self._parse_route(child, zone, zone_route=False)
            elif tag in ("zoneRoute", "ASroute"):
                self._parse_route(child, zone, zone_route=True)
            elif tag in ("bypassRoute", "bypassZoneRoute", "bypassASroute"):
                self._parse_route(child, zone, zone_route="bypass" )
            elif tag == "cluster":
                self._parse_cluster(child, zone)
            elif tag == "cabinet":
                self._parse_cabinet(child, zone)
            elif tag == "peer":
                self._parse_peer(child, zone)
            elif tag == "prop":
                zone.properties[child.get("id")] = child.get("value")
            elif tag == "trace":
                self._parse_trace(child)
            elif tag == "trace_connect":
                self.trace_connect_list.append(dict(child.attrib))
            elif tag == "backbone":
                self._parse_backbone(child, zone)
            elif tag == "host_link":
                self._parse_host_link(child, zone)
            elif tag in ("storage_type", "storage", "mount", "disk"):
                self._parse_storage(child, zone)
            else:
                raise ParseError(f"Unexpected tag <{tag}> in zone {name}")
        return zone

    # -- entities ----------------------------------------------------------
    def _parse_host(self, elem, zone) -> None:
        from ..models.host import Host
        name = elem.get("id")
        speeds = parse_speeds(elem.get("speed"))
        core = int(elem.get("core", "1"))
        host = Host(self.engine, name)
        host.netpoint = NetPoint(self.engine, name, NetPointType.HOST, zone)
        cpu = self.engine.cpu_model.create_cpu(host, speeds, core)
        pstate = elem.get("pstate")
        if pstate:
            cpu.set_pstate(int(pstate))
        coords = elem.get("coordinates")
        if coords:
            host.netpoint.coords = [float(x) for x in coords.split()]
        # "speed_file" is the v4.1 name, "availability_file" the legacy one
        avail_file = elem.get("speed_file") or elem.get("availability_file")
        if avail_file:
            cpu.set_speed_profile(self._profile_from_file(avail_file))
        state_file = elem.get("state_file")
        if state_file:
            cpu.set_state_profile(self._profile_from_file(state_file))
        for child in elem:
            if child.tag == "prop":
                host.properties[child.get("id")] = child.get("value")
            elif child.tag == "mount":
                # <mount storageId=... name=...>: per-HOST mount table
                # (a storage can be attached to one host and mounted
                # on another — storage.xml mounts alice's Disk2 on
                # denise as 'c:')
                host.mounts[child.get("name")] = child.get("storageId")
        from ..models.host import Host as H
        H.on_creation(host)

    def _parse_router(self, elem, zone) -> None:
        name = elem.get("id")
        netpoint = NetPoint(self.engine, name, NetPointType.ROUTER, zone)
        coords = elem.get("coordinates")
        if coords:
            netpoint.coords = [float(x) for x in coords.split()]

    def _parse_link(self, elem, zone) -> None:
        name = elem.get("id")
        latency = parse_time(elem.get("latency", "0"))
        policy_str = elem.get("sharing_policy", "SHARED")
        policies = {"SHARED": SharingPolicy.SHARED,
                    "FATPIPE": SharingPolicy.FATPIPE,
                    "SPLITDUPLEX": SharingPolicy.SHARED,
                    "WIFI": SharingPolicy.WIFI}
        if policy_str not in policies:
            raise ValueError(
                f"Link {name!r}: unknown sharing_policy {policy_str!r} "
                f"(expected one of {sorted(policies)})")
        policy = policies[policy_str]
        if policy_str == "WIFI":
            # one bandwidth per modulation level, comma-separated
            # (reference sg_platf link parsing for WIFI links)
            if latency:
                raise ValueError(
                    f"Link {name!r}: latency is not modeled on WIFI "
                    "access points — refusing to drop it silently")
            bandwidths = [parse_bandwidth(b) for b in
                          elem.get("bandwidth").split(",")]
            model = self.engine.network_model
            if not hasattr(model, "create_wifi_link"):
                raise ValueError(
                    f"Link {name!r}: sharing_policy WIFI is not "
                    f"supported by the {type(model).__name__} network "
                    "model — refusing to simulate it as a wired link")
            link = model.create_wifi_link(name, bandwidths)
            self._attach_link_extras(elem, link)
            return
        bandwidth = parse_bandwidth(elem.get("bandwidth"))
        if policy_str == "SPLITDUPLEX":
            # two directed links, suffixed _UP and _DOWN (sg_platf.cpp)
            for suffix in ("_UP", "_DOWN"):
                link = self.engine.network_model.create_link(
                    name + suffix, bandwidth, latency, SharingPolicy.SHARED)
                self._attach_link_extras(elem, link)
        else:
            link = self.engine.network_model.create_link(
                name, bandwidth, latency, policy)
            self._attach_link_extras(elem, link)

    def _attach_link_extras(self, elem, link) -> None:
        bw_file = elem.get("bandwidth_file")
        if bw_file:
            link.set_bandwidth_profile(self._profile_from_file(bw_file))
        lat_file = elem.get("latency_file")
        if lat_file:
            link.set_latency_profile(self._profile_from_file(lat_file))
        state_file = elem.get("state_file")
        if state_file:
            link.set_state_profile(self._profile_from_file(state_file))
        for child in elem:
            if child.tag == "prop":
                link.properties[child.get("id")] = child.get("value")

    def _get_link(self, name: str, direction: Optional[str] = None):
        if direction in ("UP", "DOWN"):
            name = f"{name}_{direction}"
        link = self.engine.links.get(name)
        if link is None:
            raise ParseError(f"Unknown link '{name}'")
        return link

    def _parse_route(self, elem, zone, zone_route) -> None:
        src = self.engine.netpoints.get(elem.get("src"))
        dst = self.engine.netpoints.get(elem.get("dst"))
        if src is None or dst is None:
            raise ParseError(f"Route with unknown endpoint "
                             f"{elem.get('src')} -> {elem.get('dst')}")
        gw_src = gw_dst = None
        if zone_route and zone_route != "bypass" or (
                zone_route == "bypass" and elem.get("gw_src")):
            if elem.get("gw_src"):
                gw_src = self.engine.netpoints.get(elem.get("gw_src"))
                gw_dst = self.engine.netpoints.get(elem.get("gw_dst"))
        links = []
        for child in elem:
            if child.tag == "link_ctn":
                links.append(self._get_link(child.get("id"),
                                            child.get("direction")))
        symmetrical = elem.get("symmetrical", "YES").upper() in ("YES", "TRUE")
        if zone_route == "bypass":
            zone.add_bypass_route(src, dst, gw_src, gw_dst, links, False)
        else:
            zone.add_route(src, dst, gw_src, gw_dst, links, symmetrical)

    # -- aggregates --------------------------------------------------------
    def _parse_cluster(self, elem, zone) -> None:
        from ..routing.cluster import parse_cluster_tag
        parse_cluster_tag(self, elem, zone)

    def _parse_cabinet(self, elem, zone) -> None:
        from ..routing.cluster import parse_cabinet_tag
        parse_cabinet_tag(self, elem, zone)

    def _parse_peer(self, elem, zone) -> None:
        from ..routing.cluster import parse_peer_tag
        parse_peer_tag(self, elem, zone)

    def _parse_host_link(self, elem, zone) -> None:
        """<host_link id=... up=... down=...> inside a manual
        routing="Cluster" zone: attach the host's private link pair
        (sg_platf_new_hostlink, sg_platf.cpp)."""
        host_name = elem.get("id")
        host = self.engine.hosts.get(host_name)
        if host is None:
            raise ParseError(f"<host_link> references unknown host "
                             f"'{host_name}'")
        if host.netpoint.englobing_zone is not zone:
            raise ParseError(f"<host_link> host '{host_name}' does not "
                             f"belong to cluster zone '{zone.name}'")

        def link_of(attr):
            name = elem.get(attr)
            link = self.engine.links.get(name)
            if link is None:
                raise ParseError(f"<host_link> references unknown link "
                                 f"'{name}'")
            return getattr(link, "pimpl", link)

        netpoint = host.netpoint
        if netpoint.id in zone.node_rank:
            raise ParseError(f"Duplicate <host_link> for '{host_name}'")
        rank = len(zone.node_rank)
        zone.node_rank[netpoint.id] = rank
        zone.add_private_link(zone.node_pos(rank), link_of("up"),
                              link_of("down"))

    def _parse_backbone(self, elem, zone) -> None:
        name = elem.get("id")
        bandwidth = parse_bandwidth(elem.get("bandwidth"))
        latency = parse_time(elem.get("latency", "0"))
        link = self.engine.network_model.create_link(name, bandwidth, latency,
                                                     SharingPolicy.SHARED)
        zone.backbone = link

    def _parse_storage(self, elem, zone) -> None:
        from ..models.storage import parse_storage_tag
        parse_storage_tag(self, elem, zone)

    # -- traces ------------------------------------------------------------
    def _parse_trace(self, elem) -> None:
        name = elem.get("id")
        file_attr = elem.get("file")
        periodicity = float(elem.get("periodicity", "-1"))
        if file_attr:
            profile_mod.Profile.from_file(self._resolve(file_attr))
        else:
            profile_mod.Profile.from_string(name, elem.text or "", periodicity)

    def _profile_from_file(self, path: str) -> profile_mod.Profile:
        resolved = self._resolve(path)
        if resolved in profile_mod.trace_list:
            return profile_mod.trace_list[resolved]
        return profile_mod.Profile.from_file(resolved)

    def _resolve(self, path: str) -> str:
        if os.path.isabs(path):
            return path
        return os.path.join(self.base_dir, path)

    def _apply_trace_connects(self) -> None:
        for tc in self.trace_connect_list:
            trace = profile_mod.trace_list.get(tc.get("trace"))
            if trace is None:
                raise ParseError(f"Unknown trace '{tc.get('trace')}' "
                                 f"in trace_connect")
            kind = tc.get("kind", "HOST_AVAIL")
            element = tc.get("element")
            if kind in ("SPEED", "POWER"):
                self.engine.hosts[element].cpu.set_speed_profile(trace)
            elif kind == "HOST_AVAIL":
                self.engine.hosts[element].cpu.set_state_profile(trace)
            elif kind == "BANDWIDTH":
                self.engine.links[element].set_bandwidth_profile(trace)
            elif kind == "LATENCY":
                self.engine.links[element].set_latency_profile(trace)
            elif kind == "LINK_AVAIL":
                self.engine.links[element].set_state_profile(trace)
            else:
                raise ParseError(f"Unknown trace_connect kind '{kind}'")

    def _parse_config(self, elem) -> None:
        from ..utils.config import config
        for child in elem:
            if child.tag == "prop":
                config.set(child.get("id"), child.get("value"))
