"""Unit-suffixed value parsing for platform files.

Same unit grammar as the reference parser
(/root/reference/src/surf/xml/surfxml_sax_cb.cpp:138-260): SI prefixes
(k/M/G/...) on base 1000, binary prefixes (Ki/Mi/Gi/...) on base 1024;
times in w/d/h/m/s/ms/us/ns/ps; bandwidths in Bps (bytes) or bps (bits,
1 Bps = 8 bps); speeds in f/flops.
"""

from __future__ import annotations

import re
from typing import Dict

from ..exceptions import ParseError

_NUM_RE = re.compile(r"^\s*([-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)\s*(.*)$")


def _gen(units: Dict[str, float], unit: str, value: float, base: int,
         abbrev: bool) -> None:
    if base == 2:
        mult = 1024.0
        prefixes = (["Ki", "Mi", "Gi", "Ti", "Pi", "Ei", "Zi", "Yi"] if abbrev
                    else ["kibi", "mebi", "gibi", "tebi", "pebi", "exbi",
                          "zebi", "yobi"])
    else:
        mult = 1000.0
        prefixes = (["k", "M", "G", "T", "P", "E", "Z", "Y"] if abbrev
                    else ["kilo", "mega", "giga", "tera", "peta", "exa",
                          "zeta", "yotta"])
    units.setdefault(unit, value)
    for prefix in prefixes:
        value *= mult
        units.setdefault(prefix + unit, value)


_TIME_UNITS = {"w": 7 * 24 * 60 * 60.0, "d": 24 * 60 * 60.0, "h": 3600.0,
               "m": 60.0, "s": 1.0, "ms": 1e-3, "us": 1e-6, "ns": 1e-9,
               "ps": 1e-12}

_BW_UNITS: Dict[str, float] = {}
_gen(_BW_UNITS, "bps", 0.125, 2, True)
_gen(_BW_UNITS, "bps", 0.125, 10, True)
_gen(_BW_UNITS, "Bps", 1.0, 2, True)
_gen(_BW_UNITS, "Bps", 1.0, 10, True)

_SIZE_UNITS: Dict[str, float] = {}
_gen(_SIZE_UNITS, "b", 0.125, 2, True)
_gen(_SIZE_UNITS, "b", 0.125, 10, True)
_gen(_SIZE_UNITS, "B", 1.0, 2, True)
_gen(_SIZE_UNITS, "B", 1.0, 10, True)

_SPEED_UNITS: Dict[str, float] = {}
_gen(_SPEED_UNITS, "f", 1.0, 10, True)
_gen(_SPEED_UNITS, "flops", 1.0, 10, False)


def _parse(text: str, units: Dict[str, float], default_unit: str) -> float:
    m = _NUM_RE.match(text)
    if m is None:
        raise ParseError(f"Cannot parse number: {text!r}")
    value = float(m.group(1))
    unit = m.group(2).strip() or default_unit
    if unit not in units:
        raise ParseError(f"Unknown unit {unit!r} in {text!r}")
    return value * units[unit]


def parse_time(text: str) -> float:
    return _parse(text, _TIME_UNITS, "s")


def parse_bandwidth(text: str) -> float:
    return _parse(text, _BW_UNITS, "Bps")


def parse_size(text: str) -> float:
    return _parse(text, _SIZE_UNITS, "B")


def parse_speed(text: str) -> float:
    return _parse(text, _SPEED_UNITS, "f")


def parse_speeds(text: str) -> list:
    """Comma-separated pstate list."""
    return [parse_speed(part) for part in text.split(",")]
