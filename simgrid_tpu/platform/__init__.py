"""Platform descriptions: XML loader (simgrid.dtd compatible) + units."""
