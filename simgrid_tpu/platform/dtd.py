"""Structural validation of platform XML against the simgrid.dtd
content model (/root/reference/src/surf/xml/simgrid.dtd).

The reference's FleXML-generated parser hard-errors on unknown tags,
unknown attributes, missing required attributes and out-of-enum values;
silently accepting them (as a naive ElementTree walk would) lets typos
produce a silently-wrong platform.  This is the same contract as a
validating DTD parse, expressed as a data-driven walk."""

from __future__ import annotations

from typing import Dict, FrozenSet, Tuple

from ..exceptions import ParseError


def _s(*names) -> FrozenSet[str]:
    return frozenset(names)


#: tag -> (required attributes, optional attributes, allowed children)
SCHEMA: Dict[str, Tuple[FrozenSet[str], FrozenSet[str], FrozenSet[str]]] = {
    "platform": (_s(), _s("version"),
                 _s("config", "random", "include", "cluster", "cabinet",
                    "peer", "AS", "zone", "trace", "trace_connect",
                    "process", "actor")),
    "include": (_s("file"), _s(),
                _s("include", "cluster", "cabinet", "peer", "AS", "zone",
                   "trace", "trace_connect")),
    "trace": (_s("id", "periodicity"), _s("file"), _s()),
    "random": (_s("id", "min", "max", "mean", "std_deviation"),
               _s("seed", "radical", "generator"), _s()),
    "trace_connect": (_s("trace", "element"), _s("kind"), _s()),
    "AS": None,       # alias of zone, filled below
    "zone": (_s("id", "routing"),
             _s(),
             _s("prop", "AS", "zone", "host", "router", "link", "backbone",
                "route", "ASroute", "zoneRoute", "bypassRoute",
                "bypassASroute", "bypassZoneRoute", "cluster", "cabinet",
                "peer", "trace", "trace_connect", "storage",
                "storage_type", "host_link", "include")),
    "storage_type": (_s("id", "size"), _s("model", "content"),
                     _s("model_prop", "prop")),
    "storage": (_s("id", "typeId", "attach"), _s("content"), _s("prop")),
    "mount": (_s("storageId", "name"), _s(), _s()),
    "host": (_s("id", "speed"),
             _s("core", "speed_file", "availability_file", "state_file",
                "coordinates", "pstate"),
             _s("disk", "prop", "mount")),
    "disk": (_s("read_bw", "write_bw"), _s("id"), _s("prop")),
    "host_link": (_s("id", "up", "down"), _s(), _s()),
    "cluster": (_s("id", "prefix", "suffix", "radical", "speed", "bw",
                   "lat"),
                _s("core", "sharing_policy", "topology",
                   "topo_parameters", "bb_bw", "bb_lat",
                   "bb_sharing_policy", "router_id", "limiter_link",
                   "loopback_bw", "loopback_lat"),
                _s("prop")),
    "cabinet": (_s("id", "prefix", "suffix", "radical", "speed", "bw",
                   "lat"), _s(), _s()),
    "peer": (_s("id", "speed", "bw_in", "bw_out"),
             _s("lat", "coordinates", "speed_file", "availability_file",
                "state_file"), _s()),
    "router": (_s("id"), _s("coordinates"), _s()),
    "backbone": (_s("id", "bandwidth", "latency"), _s(), _s()),
    "link": (_s("id", "bandwidth"),
             _s("bandwidth_file", "latency", "latency_file", "state_file",
                "sharing_policy"), _s("prop")),
    "route": (_s("src", "dst"), _s("symmetrical"), _s("link_ctn")),
    "ASroute": (_s("src", "dst", "gw_src", "gw_dst"), _s("symmetrical"),
                _s("link_ctn")),
    "zoneRoute": (_s("src", "dst", "gw_src", "gw_dst"),
                  _s("symmetrical"), _s("link_ctn")),
    "link_ctn": (_s("id"), _s("direction"), _s()),
    "bypassRoute": (_s("src", "dst"), _s(), _s("link_ctn")),
    "bypassASroute": (_s("src", "dst", "gw_src", "gw_dst"), _s(),
                      _s("link_ctn")),
    "bypassZoneRoute": (_s("src", "dst", "gw_src", "gw_dst"), _s(),
                        _s("link_ctn")),
    "process": (_s("host", "function"),
                _s("start_time", "kill_time", "on_failure"),
                _s("argument", "prop")),
    "actor": (_s("host", "function"),
              _s("start_time", "kill_time", "on_failure"),
              _s("argument", "prop")),
    "argument": (_s("value"), _s(), _s()),
    "config": (_s(), _s("id"), _s("prop")),
    "prop": (_s("id", "value"), _s(), _s()),
    "model_prop": (_s("id", "value"), _s(), _s()),
}
SCHEMA["AS"] = SCHEMA["zone"]

#: attribute -> allowed values, where the DTD enumerates
ENUMS: Dict[Tuple[str, str], FrozenSet[str]] = {
    ("zone", "routing"): _s("Full", "Floyd", "Dijkstra", "DijkstraCache",
                            "None", "Vivaldi", "Cluster", "ClusterTorus",
                            "ClusterFatTree", "ClusterDragonfly"),
    ("cluster", "sharing_policy"): _s("SHARED", "SPLITDUPLEX",
                                      "FULLDUPLEX", "FATPIPE"),
    ("cluster", "topology"): _s("FLAT", "TORUS", "FAT_TREE", "DRAGONFLY"),
    ("cluster", "bb_sharing_policy"): _s("SHARED", "FATPIPE"),
    ("link", "sharing_policy"): _s("SHARED", "SPLITDUPLEX", "FULLDUPLEX",
                                   "FATPIPE", "WIFI"),
    ("route", "symmetrical"): _s("YES", "NO", "yes", "no"),
    ("link_ctn", "direction"): _s("UP", "DOWN", "NONE"),
    ("trace_connect", "kind"): _s("HOST_AVAIL", "SPEED", "LINK_AVAIL",
                                  "BANDWIDTH", "LATENCY"),
    ("process", "on_failure"): _s("DIE", "RESTART"),
}
ENUMS[("AS", "routing")] = ENUMS[("zone", "routing")]
for _t in ("ASroute", "zoneRoute"):
    ENUMS[(_t, "symmetrical")] = ENUMS[("route", "symmetrical")]
ENUMS[("actor", "on_failure")] = ENUMS[("process", "on_failure")]


def validate(root, path: str = "<platform>") -> None:
    """Walk the tree; raise ParseError on the first DTD violation."""
    if root.tag != "platform":
        raise ParseError(
            f"{path}: root element must be <platform>, got <{root.tag}>")
    _validate_elem(root, path, "platform")


def _validate_elem(elem, path: str, context: str) -> None:
    spec = SCHEMA.get(elem.tag)
    if spec is None:
        raise ParseError(f"{path}: unknown tag <{elem.tag}> in "
                         f"<{context}>")
    required, optional, children = spec
    attrs = set(elem.attrib)
    missing = required - attrs
    if missing:
        raise ParseError(
            f"{path}: <{elem.tag}> misses required attribute(s) "
            f"{sorted(missing)}")
    unknown = attrs - required - optional
    if unknown:
        raise ParseError(
            f"{path}: <{elem.tag}> has unknown attribute(s) "
            f"{sorted(unknown)} (allowed: "
            f"{sorted(required | optional)})")
    for (attr, allowed) in ((a, ENUMS.get((elem.tag, a)))
                            for a in attrs):
        if allowed is not None and elem.get(attr) not in allowed:
            raise ParseError(
                f"{path}: <{elem.tag} {attr}=\"{elem.get(attr)}\"> is "
                f"not one of {sorted(allowed)}")
    for child in elem:
        if child.tag not in children:
            raise ParseError(
                f"{path}: <{child.tag}> is not allowed inside "
                f"<{elem.tag}>")
        _validate_elem(child, path, elem.tag)
