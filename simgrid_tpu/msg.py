"""MSG legacy API: the task-oriented C interface as a thin shim over
s4u (reference src/msg/msg_legacy.cpp does exactly this over its own
s4u). Kept for parity with the reference's migration-era API surface;
new code should use s4u directly."""

from __future__ import annotations

from typing import Any, List, Optional

from . import s4u

OK = 0
TASK_CANCELED = 1
TRANSFER_FAILURE = 2
HOST_FAILURE = 3
TIMEOUT = 4


class Task:
    """m_task_t: computation + payload container (msg_task.cpp)."""

    def __init__(self, name: str, flops_amount: float = 0.0,
                 bytes_amount: float = 0.0, data: Any = None):
        self.name = name
        self.flops_amount = flops_amount
        self.bytes_amount = bytes_amount
        self.data = data
        self.sender: Optional[s4u.Actor] = None


def task_create(name: str, flops: float, nbytes: float,
                data: Any = None) -> Task:
    return Task(name, flops, nbytes, data)


def task_execute(task: Task) -> int:
    """MSG_task_execute."""
    s4u.this_actor.execute(task.flops_amount)
    return OK


def task_send(task: Task, mailbox: str) -> int:
    """MSG_task_send: payload is the Task itself."""
    s4u.Mailbox.by_name(mailbox).put(task, task.bytes_amount)
    return OK


def task_receive(mailbox: str, timeout: float = -1.0) -> Task:
    """MSG_task_receive (raises TimeoutException past `timeout`)."""
    return s4u.Mailbox.by_name(mailbox).get(timeout=timeout)


def task_isend(task: Task, mailbox: str):
    return s4u.Mailbox.by_name(mailbox).put_async(task,
                                                  task.bytes_amount)


def process_create(name: str, code, host, *args) -> s4u.Actor:
    """MSG_process_create."""
    if isinstance(host, str):
        host = s4u.Engine.get_instance().host_by_name(host)
    return s4u.Actor.create(name, host, code, *args)


def process_sleep(duration: float) -> int:
    s4u.this_actor.sleep_for(duration)
    return OK


def process_kill(actor: s4u.Actor) -> None:
    actor.kill()


def get_clock() -> float:
    return s4u.Engine.get_clock()


def get_host_number() -> int:
    return s4u.Engine.get_instance().get_host_count()


def hosts() -> List:
    return s4u.Engine.get_instance().get_all_hosts()


def host_by_name(name: str):
    return s4u.Engine.get_instance().host_by_name(name)


def create_environment(platform: str) -> None:
    """MSG_create_environment."""
    s4u.Engine.get_instance().load_platform(platform)


def main() -> None:
    """MSG_main."""
    s4u.Engine.get_instance().run()
