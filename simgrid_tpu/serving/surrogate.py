"""SMART-style surrogate triage: a cheap deterministic predictor over
completed campaign rows answers low-stakes runtime queries; exact
device simulation is reserved for the uncertain tail.

The model is closed-form ridge regression (numpy ``lstsq`` on the
regularized normal equations — no iterative fitting, no RNG) over
features derived from the :class:`~simgrid_tpu.parallel.campaign.
ScenarioSpec` alone, predicting the scenario's final drain clock
``t``.  Uncertainty is a SPLIT-CONFORMAL interval: a deterministic
index-striped calibration subset is held out of the fit and the
``confidence`` quantile of its absolute residuals becomes the
half-width — distribution-free coverage, no Gaussian assumption.
Both the fit and the calibration are MONDRIAN (group-conditional) on
the fault indicator: a faulted scenario's realized schedule depends
on its seed, which the features cannot see, so faulted clocks are
irreducible noise to the model — in a joint fit that noise drags the
shared weights and inflates CLEAN residuals by orders of magnitude
(one global quantile then vetoes every answer).  Fitting each group
its own weights + quantile keeps the clean family sharp: in-family
clean queries answer, faulted ones honestly escalate to the device.

Triage policy (:meth:`RuntimeSurrogate.triage`): answer only when the
model is fitted AND the conformal interval is tight relative to the
prediction (``width <= max(abs_tol, rel_tol * |t|)``); otherwise
return None and the service escalates to the device path.  Every
answer carries ``source="surrogate"`` plus its bounds downstream
(:class:`~simgrid_tpu.serving.service.ServiceResult`), so callers can
audit exactly which results were predicted rather than simulated.

The corpus seeds from ``bench_results/*.jsonl`` (rows carrying a spec
dict + final clock) and grows with every device-served result the
:class:`~simgrid_tpu.serving.service.CampaignService` completes.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..parallel.campaign import ScenarioSpec

#: feature-vector layout version (corpus rows don't store features —
#: they are re-derived from specs — but refits must stay comparable)
N_FEATURES = 11

#: index of the fault-indicator feature — the Mondrian calibration
#: group (seed-realized fault schedules are invisible to the features,
#: so faulted rows get their own conformal quantile)
FAULT_FEATURE = 8


def spec_features(spec: ScenarioSpec) -> np.ndarray:
    """Deterministic f64 feature vector of one scenario.  The dominant
    physics of a drain is work/capacity, so the leading features are
    the size/bandwidth ratio and its components; sparse maps enter
    through order-independent summaries (sorted before reduction — a
    float sum must not depend on dict insertion order)."""
    bw = max(spec.bw_scale, 1e-12)
    ls = sorted(spec.link_scale.values()) or [1.0]
    fs = sorted(spec.flow_scale.values()) or [1.0]
    mtbf = spec.fault_mtbf
    return np.array([
        1.0,
        spec.size_scale / bw,
        spec.size_scale,
        1.0 / bw,
        float(ls[0]),
        float(np.mean(ls)),
        float(np.mean(fs)),
        float(len(spec.dead_flows)),
        0.0 if mtbf is None else 1.0,
        0.0 if mtbf is None else spec.fault_horizon / max(mtbf, 1e-12),
        0.0 if mtbf is None else spec.fault_mttr / max(mtbf, 1e-12),
    ], np.float64)


class SurrogateAnswer:
    """One surrogate prediction with its conformal interval."""

    __slots__ = ("t", "lo", "hi", "confidence", "n_train")

    def __init__(self, t: float, lo: float, hi: float,
                 confidence: float, n_train: int):
        self.t = float(t)
        self.lo = float(lo)
        self.hi = float(hi)
        self.confidence = float(confidence)
        self.n_train = int(n_train)


class RuntimeSurrogate:
    """Ridge + split-conformal predictor of scenario drain clocks.

    ``min_corpus`` gates the first fit; after that the model refits
    every ``refit_every`` new observations (cheap: one 11×11 solve).
    ``rel_tol``/``abs_tol`` bound the interval width the triage will
    answer at; ``confidence`` is the conformal coverage level.
    Everything is deterministic — same corpus, same answers."""

    def __init__(self, alpha: float = 1e-3, min_corpus: int = 24,
                 rel_tol: float = 0.1, abs_tol: float = 0.0,
                 confidence: float = 0.9, refit_every: int = 8):
        self.alpha = float(alpha)
        self.min_corpus = int(min_corpus)
        self.rel_tol = float(rel_tol)
        self.abs_tol = float(abs_tol)
        self.confidence = float(confidence)
        self.refit_every = max(1, int(refit_every))
        self._X: List[np.ndarray] = []
        self._y: List[float] = []
        #: Mondrian per-group (ridge weights, conformal half-width),
        #: keyed by the query's fault-indicator group (None = that
        #: group never fit usably and escalates)
        self._models: Optional[
            Dict[bool, Optional[Tuple[np.ndarray, float]]]] = None
        self._fit_n = 0

    # -- corpus ------------------------------------------------------------

    def __len__(self) -> int:
        return len(self._y)

    def observe(self, spec: ScenarioSpec, t: float) -> None:
        """Append one completed (spec, final clock) row and refit when
        enough new rows accumulated."""
        if not math.isfinite(t):
            return
        self._X.append(spec_features(spec))
        self._y.append(float(t))
        n = len(self._y)
        if n >= self.min_corpus and n - self._fit_n >= self.refit_every:
            self.fit()

    def load_corpus(self, paths) -> int:
        """Seed the corpus from jsonl files (``bench_results/*.jsonl``
        or a service's own corpus log): any row — at top level or
        under ``payload`` — carrying a spec dict and a finite ``t`` is
        adopted.  Returns the number of rows loaded."""
        if isinstance(paths, (str, os.PathLike)):
            paths = [paths]
        loaded = 0
        for path in paths:
            if not os.path.exists(path):
                continue
            with open(path, "r") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        row = json.loads(line)
                    except ValueError:
                        continue
                    for rec in (row, row.get("payload")
                                if isinstance(row, dict) else None):
                        if not isinstance(rec, dict):
                            continue
                        spec_d = rec.get("spec")
                        t = rec.get("t")
                        if (isinstance(spec_d, dict)
                                and isinstance(t, (int, float))
                                and math.isfinite(float(t))):
                            self._X.append(spec_features(
                                ScenarioSpec.from_dict(spec_d)))
                            self._y.append(float(t))
                            loaded += 1
                            break
        if len(self._y) >= self.min_corpus:
            self.fit()
        return loaded

    # -- fitting -----------------------------------------------------------

    def fit(self) -> bool:
        """Refit one (ridge weights, conformal half-width) model PER
        fault-indicator group.  Within a group, every 4th row is the
        calibration stripe (deterministic, no RNG); the finite-sample
        conformal rank ``ceil((n_g + 1) * conf)`` of its absolute
        residuals is the half-width.  A group with too few train rows
        (< n_features) or no valid rank stays None and escalates.
        Returns True when at least one group is usable."""
        n = len(self._y)
        if n < self.min_corpus:
            return False
        X = np.stack(self._X)
        y = np.asarray(self._y, np.float64)
        faulted = X[:, FAULT_FEATURE] > 0.5
        models: Dict[bool, Optional[Tuple[np.ndarray, float]]] = {}
        for group in (False, True):
            Xg, yg = X[faulted == group], y[faulted == group]
            calib = np.arange(len(yg)) % 4 == 3
            Xt, yt = Xg[~calib], yg[~calib]
            Xc, yc = Xg[calib], yg[calib]
            if len(yt) < X.shape[1] or not len(yc):
                models[group] = None
                continue
            # ridge normal equations; lstsq for rank-deficient stripes
            A = Xt.T @ Xt + self.alpha * np.eye(X.shape[1])
            b = Xt.T @ yt
            try:
                w = np.linalg.solve(A, b)
            except np.linalg.LinAlgError:
                w = np.linalg.lstsq(A, b, rcond=None)[0]
            resid = np.sort(np.abs(Xc @ w - yc))
            rank = int(math.ceil((len(resid) + 1) * self.confidence))
            models[group] = ((w, float(resid[rank - 1]))
                             if 0 < rank <= len(resid) else None)
        if all(m is None for m in models.values()):
            return False
        self._models = models
        self._fit_n = n
        return True

    @property
    def fitted(self) -> bool:
        return (self._models is not None
                and any(m is not None for m in self._models.values()))

    # -- answering ---------------------------------------------------------

    def predict(self, spec: ScenarioSpec
                ) -> Optional[SurrogateAnswer]:
        """Point prediction + conformal interval from the query's
        GROUP model, or None before the first successful fit / when
        the query's group never accumulated enough rows."""
        if not self.fitted:
            return None
        model = self._models[spec.fault_mtbf is not None]
        if model is None:
            return None
        w, q = model
        t = float(spec_features(spec) @ w)
        return SurrogateAnswer(t, t - q, t + q,
                               self.confidence, self._fit_n)

    def triage(self, spec: ScenarioSpec
               ) -> Optional[SurrogateAnswer]:
        """The serving decision: the answer when the interval is tight
        enough to state with confidence, else None (escalate to the
        device path)."""
        ans = self.predict(spec)
        if ans is None:
            return None
        width = ans.hi - ans.lo
        tol = max(self.abs_tol, self.rel_tol * abs(ans.t))
        if ans.t <= 0 or width > tol:
            return None
        return ans
