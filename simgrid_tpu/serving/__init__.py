"""Always-on campaign serving: AOT plan cache, streaming scenario
queue with mid-flight admission, and surrogate triage.

The batch CLI (tools/campaign_run.py) re-pays platform flattening and
XLA tracing on every invocation — a non-starter for serving millions
of what-if queries.  This package turns the staged campaign layer
(``parallel.campaign``: spec → :class:`~simgrid_tpu.parallel.campaign.
ScenarioPlan` → executor) into a persistent service:

* :mod:`.plancache` — content-addressed AOT compilation cache:
  ``jit(...).lower().compile()`` once per plan key, executables kept
  resident and serialized to disk so warm restarts skip tracing
  entirely;
* :mod:`.service` — :class:`~simgrid_tpu.serving.service.
  CampaignService`: ``submit(spec) -> ticket``, live fleets with
  admission batching (arriving queries revive dead lanes between
  supersteps, bit-identical to solo runs), streaming per-replica
  results;
* :mod:`.surrogate` — SMART-style triage: a ridge predictor with
  conformal intervals answers low-stakes queries from completed-row
  history; wide-interval or ``exact=True`` queries go to the device.

Standing invariant: every device-served result — including scenarios
admitted mid-flight into a partially-drained fleet — is bit-identical
(events, fault streams, Kahan clocks) to ``ScenarioPlan.solo`` on the
same spec (``tools/check_determinism.py --runtime-serve``).

Durability (preemption-safe campaigns): ``CampaignService.
checkpoint``/``resume`` persist the fleet's superstep-boundary
committed state + ticket journal as a
:class:`~simgrid_tpu.checkpoint.FleetCheckpoint`, lanes with poisoned
scenarios are QUARANTINED with a :class:`~simgrid_tpu.ops.lmm_batch.
LaneFault` cause instead of killing the fleet, and device dispatches
run under a :class:`~simgrid_tpu.ops.lmm_batch.DispatchWatchdog`
(seeded-backoff retries, solo-path fallback on exhaustion) —
``tools/check_determinism.py --runtime-resume``.
"""

from ..checkpoint import CheckpointError, FleetCheckpoint
from ..ops.lmm_batch import (DispatchExhausted, DispatchWatchdog,
                             LaneFault)
from .plancache import CompiledPlan, PlanCache
from .service import CampaignService, ServiceResult, Ticket
from .surrogate import RuntimeSurrogate, SurrogateAnswer

__all__ = ["PlanCache", "CompiledPlan", "CampaignService",
           "ServiceResult", "Ticket", "RuntimeSurrogate",
           "SurrogateAnswer", "FleetCheckpoint", "CheckpointError",
           "LaneFault", "DispatchWatchdog", "DispatchExhausted"]
