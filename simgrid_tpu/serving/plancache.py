"""Content-addressed AOT plan cache for fleet programs.

Tracing + XLA compilation of the batched superstep program dominates a
campaign process's cold start (hundreds of ms to seconds), and the
batch CLI pays it on EVERY invocation.  This cache compiles each fleet
program once per ``(plan key, program kind, arg shapes/dtypes,
statics)`` signature via JAX's ahead-of-time path —
``jit(fn).lower(*args, **statics).compile()`` — keeps the resulting
executables resident, and (with ``cache_dir``) serializes them through
``jax.experimental.serialize_executable`` so a WARM RESTART of the
serving process loads compiled artifacts from disk and performs zero
XLA traces for repeated keys.

Keying: the plan key (``ScenarioPlan.plan_key`` — topology hash,
layout, dtype, B, superstep, pipeline, mesh, fault_mode) addresses the
scenario content; the signature appended here (concrete arg shapes +
dtypes + static kwargs + jax version + platform + device count) makes
it impossible for a stale or foreign artifact to be invoked on
mismatched inputs — any miss falls back to compiling (and a failed
deserialize/execute falls back to the plain traced jit, counted in
``plan_cache_fallbacks``, never an error).

opstats counters: ``plan_cache_hits`` (memory or disk),
``plan_cache_misses`` (fresh AOT compile), ``plan_compile_ms``
(monotonic milliseconds spent lowering+compiling — 0 on a fully warm
restart), ``plan_cache_fallbacks``.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import time
from typing import Any, Dict, Optional, Tuple

import jax

from ..ops import opstats

#: bumped when the serialized artifact layout changes
_FORMAT_VERSION = 1


def _signature(args, statics: Dict[str, Any]) -> str:
    """Shape/dtype/static signature of one concrete call — part of the
    artifact address, so an executable can only ever be invoked on
    inputs matching the ones it was compiled for."""
    parts = []
    for a in args:
        shape = getattr(a, "shape", None)
        if shape is None:
            parts.append(f"py:{type(a).__name__}:{a!r}")
        else:
            parts.append(f"{tuple(shape)}:{getattr(a, 'dtype', '?')}")
    parts.append(repr(sorted(statics.items())))
    return "|".join(parts)


class PlanCache:
    """Process-wide (and optionally on-disk) cache of AOT-compiled
    fleet executables, shared by every fleet the serving process
    builds.  ``cache_dir=None`` keeps it memory-only (still one
    compile per signature per process); with a directory, artifacts
    are pickled ``serialize_executable`` payloads and warm restarts
    deserialize instead of tracing."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or None
        if self.cache_dir:
            os.makedirs(self.cache_dir, exist_ok=True)
        self._mem: Dict[str, Any] = {}
        self._broken: Dict[str, bool] = {}
        self.hits = 0
        self.misses = 0
        self.disk_hits = 0
        self.fallbacks = 0
        self.compile_ms = 0.0

    # -- addressing --------------------------------------------------------

    def _digest(self, key: str, kind: str, sig: str) -> str:
        backend = jax.default_backend()
        blob = "\0".join([str(_FORMAT_VERSION), key, kind, sig,
                          jax.__version__, backend,
                          str(jax.device_count())])
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()

    def _path(self, digest: str) -> str:
        return os.path.join(self.cache_dir, digest + ".xplan")

    # -- executables -------------------------------------------------------

    def plan(self, key: str) -> "CompiledPlan":
        """A handle binding one plan key to this cache — what
        BatchDrainSim carries as ``plan=``."""
        return CompiledPlan(self, key)

    def _load_disk(self, digest: str):
        if not self.cache_dir:
            return None
        path = self._path(digest)
        if not os.path.exists(path):
            return None
        from jax.experimental import serialize_executable
        with open(path, "rb") as f:
            rec = pickle.load(f)
        if rec.get("format") != _FORMAT_VERSION:
            return None
        return serialize_executable.deserialize_and_load(
            rec["payload"], rec["in_tree"], rec["out_tree"])

    def _store_disk(self, digest: str, compiled) -> None:
        if not self.cache_dir:
            return
        from jax.experimental import serialize_executable
        payload, in_tree, out_tree = serialize_executable.serialize(
            compiled)
        rec = {"format": _FORMAT_VERSION, "payload": payload,
               "in_tree": in_tree, "out_tree": out_tree}
        path = self._path(digest)
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(rec, f)
        os.replace(tmp, path)

    def get_or_compile(self, key: str, kind: str, jitted_fn, args,
                       statics: Dict[str, Any]):
        """The compiled executable for one concrete call signature:
        memory hit, else disk hit (deserialize, no trace), else AOT
        compile (lower+compile, timed into ``plan_compile_ms``) and
        persist."""
        digest = self._digest(key, kind, _signature(args, statics))
        ex = self._mem.get(digest)
        if ex is not None:
            self.hits += 1
            opstats.bump("plan_cache_hits")
            return ex
        try:
            ex = self._load_disk(digest)
        except Exception:
            ex = None  # corrupt/foreign artifact: recompile below
        if ex is not None:
            self._mem[digest] = ex
            self.hits += 1
            self.disk_hits += 1
            opstats.bump("plan_cache_hits")
            opstats.bump("plan_cache_disk_hits")
            return ex
        t0 = time.perf_counter()
        ex = jitted_fn.lower(*args, **statics).compile()
        ms = (time.perf_counter() - t0) * 1e3
        self.misses += 1
        self.compile_ms += ms
        opstats.bump("plan_cache_misses")
        opstats.bump("plan_compile_ms", ms)
        self._mem[digest] = ex
        try:
            self._store_disk(digest, ex)
        except Exception:
            pass  # disk persistence is best-effort; serving continues
        return ex

    def call(self, key: str, kind: str, jitted_fn, args,
             statics: Dict[str, Any]):
        """Execute one fleet program through the cache.  Any failure in
        the AOT path (unserializable backend, stale artifact, sharding
        the executable refuses) falls back to the plain traced jit —
        correctness never depends on the cache."""
        digest = self._digest(key, kind, _signature(args, statics))
        if not self._broken.get(digest):
            try:
                ex = self.get_or_compile(key, kind, jitted_fn, args,
                                         statics)
                return ex(*args)
            except Exception:
                self._broken[digest] = True
                self._mem.pop(digest, None)
                # evict the on-disk artifact too: a restarted process
                # would deserialize the same broken executable and
                # re-fail — deleting it makes the restart RECOMPILE
                # instead (best-effort; serving continues either way)
                if self.cache_dir:
                    try:
                        os.remove(self._path(digest))
                    except OSError:
                        pass
                self.fallbacks += 1
                opstats.bump("plan_cache_fallbacks")
        return jitted_fn(*args, **statics)

    def stats(self) -> Dict[str, float]:
        return {"plan_cache_hits": self.hits,
                "plan_cache_misses": self.misses,
                "plan_cache_disk_hits": self.disk_hits,
                "plan_cache_fallbacks": self.fallbacks,
                "plan_compile_ms": self.compile_ms}


class CompiledPlan:
    """One plan key bound to a PlanCache — the ``plan=`` handle
    BatchDrainSim routes its jitted programs through."""

    __slots__ = ("cache", "key")

    def __init__(self, cache: PlanCache, key: str):
        self.cache = cache
        self.key = key

    def call(self, kind: str, jitted_fn, args,
             statics: Dict[str, Any]):
        return self.cache.call(self.key, kind, jitted_fn, args,
                               statics)
