"""The always-on campaign service: a persistent scenario queue over
live fleets with mid-flight admission and surrogate triage.

``submit(spec) -> Ticket`` enqueues one what-if query.  Low-stakes
queries (``exact=False``) are first offered to the surrogate
(:mod:`.surrogate`): a tight-interval prediction answers immediately
with ``source="surrogate"`` + conformal bounds; wide-interval queries
escalate to the device path.  ``exact=True`` always bypasses the
surrogate.

Device-path queries run on a resident :class:`~simgrid_tpu.ops.
lmm_batch.BatchDrainSim` fleet whose programs route through the AOT
plan cache (:mod:`.plancache`) — a warm restart performs zero XLA
traces.  ADMISSION BATCHING packs arriving queries into
partially-filled fleets: the service drives the fleet with
``run(between=...)`` and, between supersteps, (a) emits finished lanes
as streaming per-replica results and (b) revives dead lanes with
queued scenarios via ``admit_lane`` — an O(overrides) device scatter;
the admitted lane starts at its own k=0 with a fresh tape slot.  A
fired admission marks the fleet mutated, so in-flight pipeline
speculation discards and replays — preserving the standing invariant:
an admitted scenario's events, fault streams and Kahan clocks are
bit-identical to ``ScenarioPlan.solo`` on the same spec
(``tools/check_determinism.py --runtime-serve``).

Scenarios the live fleet cannot absorb (fault tape wider than the
fleet's reserved slots, elem_w into a shared-weight fleet) are
DEFERRED, not failed: they stay queued and the next fleet is sized for
them at birth.

The service is single-threaded and deterministic — ordering comes from
the submit order and the fleet's lockstep supersteps, never from
wall-clock races.  Wall-clock enters only as latency METADATA on
tickets (``time.perf_counter``).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

from ..ops import opstats
from ..ops.lmm_batch import AdmissionError
from ..parallel.campaign import ScenarioPlan, ScenarioSpec
from .plancache import PlanCache
from .surrogate import RuntimeSurrogate


class ServiceResult:
    """One answered query.  ``source`` is the audit field: ``"device"``
    results carry the exact event stream / clocks; ``"surrogate"``
    results carry the conformal interval they were stated at."""

    __slots__ = ("source", "t", "lo", "hi", "confidence", "events",
                 "fault_events", "advances", "error")

    def __init__(self, source: str, t: float, lo: float = None,
                 hi: float = None, confidence: float = None,
                 events=None, fault_events=None, advances: int = 0,
                 error: Optional[str] = None):
        self.source = source
        self.t = t
        self.lo = lo
        self.hi = hi
        self.confidence = confidence
        self.events = events
        self.fault_events = fault_events
        self.advances = advances
        self.error = error


class Ticket:
    """One submitted query's handle: spec, routing, and (once
    answered) the result plus submit→done latency metadata."""

    __slots__ = ("id", "spec", "exact", "status", "result", "lane",
                 "submitted_at", "done_at", "defer_reason")

    def __init__(self, tid: int, spec: ScenarioSpec, exact: bool):
        self.id = tid
        self.spec = spec
        self.exact = exact
        self.status = "queued"
        self.result: Optional[ServiceResult] = None
        self.lane: Optional[int] = None
        self.submitted_at = time.perf_counter()
        self.done_at: Optional[float] = None
        self.defer_reason: Optional[str] = None

    @property
    def latency_ms(self) -> Optional[float]:
        if self.done_at is None:
            return None
        return (self.done_at - self.submitted_at) * 1e3


class CampaignService:
    """A persistent scenario service over one :class:`ScenarioPlan`.

    ``batch`` is the resident fleet width (default: the
    ``serve/batch`` flag).  ``plan_cache`` routes fleet programs
    through AOT executables; ``surrogate`` (pass a
    :class:`RuntimeSurrogate`, or None for device-only) enables
    triage; ``corpus_log`` appends every device-served row as jsonl so
    future processes can seed their surrogate from it."""

    def __init__(self, plan: ScenarioPlan, batch: Optional[int] = None,
                 plan_cache: Optional[PlanCache] = None,
                 surrogate: Optional[RuntimeSurrogate] = None,
                 corpus_log: Optional[str] = None,
                 pipeline: Optional[int] = None, mesh=None):
        from ..utils.config import config
        self.plan = plan
        self.batch = int(config["serve/batch"] if batch is None
                         else batch)
        if self.batch <= 0:
            raise ValueError("service batch must be >= 1")
        if plan_cache is None and str(config["serve/plan-cache"]):
            plan_cache = PlanCache(str(config["serve/plan-cache"]))
        self.plan_cache = plan_cache
        self.surrogate = surrogate
        self.corpus_log = corpus_log
        self.pipeline = pipeline
        self.mesh = mesh
        self.tickets: List[Ticket] = []
        self.completed: List[Ticket] = []
        self._queue: List[Ticket] = []
        self._fleet = None
        self._lane_tickets: List[Optional[Ticket]] = []
        # service-lifetime counters (fleet counters are aggregated in
        # on retire; see counters())
        self.fleets = 0
        self.lanes_admitted = 0
        self.surrogate_answers = 0
        self.surrogate_escalations = 0
        self.deferrals = 0
        self.spec_issued = 0
        self.spec_committed = 0
        self.spec_rolled_back = 0

    # -- submission --------------------------------------------------------

    def submit(self, spec: ScenarioSpec,
               exact: bool = False) -> Ticket:
        """Enqueue one query.  Surrogate triage happens HERE — a
        tight-interval prediction answers without touching the queue;
        ``exact=True`` always bypasses it."""
        t = Ticket(len(self.tickets), spec, bool(exact))
        self.tickets.append(t)
        if not exact and self.surrogate is not None:
            ans = self.surrogate.triage(spec)
            if ans is not None:
                t.result = ServiceResult(
                    "surrogate", ans.t, lo=ans.lo, hi=ans.hi,
                    confidence=ans.confidence)
                t.status = "done"
                t.done_at = time.perf_counter()
                self.surrogate_answers += 1
                opstats.bump("surrogate_answers")
                self.completed.append(t)
                return t
            self.surrogate_escalations += 1
            opstats.bump("surrogate_escalations")
        self._queue.append(t)
        return t

    def submit_many(self, specs: Sequence[ScenarioSpec],
                    exact: bool = False) -> List[Ticket]:
        return [self.submit(s, exact=exact) for s in specs]

    def pending(self) -> int:
        return len(self._queue) + sum(
            1 for t in self._lane_tickets if t is not None)

    # -- the drive loop ----------------------------------------------------

    def _start_fleet(self) -> None:
        """Build a resident fleet from the queue head: up to ``batch``
        initial lanes, the rest of the width dead-at-birth and open
        for admission.  Capacity for LATER admissions is reserved at
        birth — tape slots sized by probing every queued faulted
        spec's schedule length, per-replica weight tables forced when
        any queued spec overrides element weights."""
        take = self._queue[:self.batch]
        del self._queue[:len(take)]
        tape_slots = 0
        need_batch_w = False
        for t in take + self._queue:
            if t.spec.fault_mtbf is not None:
                tape_slots = max(tape_slots,
                                 self.plan.tape_len(t.spec))
            if t.spec.elem_w:
                need_batch_w = True
        self._fleet = self.plan.executor(
            [t.spec for t in take], width=self.batch,
            plan_cache=self.plan_cache, tape_slots=tape_slots,
            batch_w=True if need_batch_w else None,
            pipeline=self.pipeline, mesh=self.mesh)
        self._lane_tickets = (list(take)
                              + [None] * (self.batch - len(take)))
        for b, t in enumerate(take):
            t.lane = b
        self.fleets += 1

    def _emit_completions(self, sim) -> None:
        """Stream finished lanes out as device results: feed the
        surrogate corpus, free the lane for admission."""
        for b in range(sim.B):
            t = self._lane_tickets[b]
            if t is None or sim._alive[b]:
                continue
            rep = sim.replicas[b]
            t.result = ServiceResult(
                "device", rep.t, events=list(rep.events),
                fault_events=list(rep.fault_events),
                advances=rep.advances, error=rep.error)
            t.status = "done"
            t.done_at = time.perf_counter()
            self.completed.append(t)
            self._lane_tickets[b] = None
            opstats.bump("serve_device_results")
            if rep.error is None:
                if self.surrogate is not None:
                    self.surrogate.observe(t.spec, rep.t)
                if self.corpus_log:
                    with open(self.corpus_log, "a") as f:
                        f.write(json.dumps(
                            {"spec": t.spec.to_dict(), "t": rep.t,
                             "source": "device"}) + "\n")

    def _admit(self, sim) -> bool:
        """Pack queued queries into the fleet's free (dead, emitted)
        lanes.  Scenarios the fleet cannot absorb are deferred — they
        stay queued for the next fleet, sized for them at birth."""
        admitted = False
        free = [b for b in range(sim.B)
                if self._lane_tickets[b] is None and not sim._alive[b]]
        if not free or not self._queue:
            return False
        remaining: List[Ticket] = []
        for t in self._queue:
            if not free:
                remaining.append(t)
                continue
            b = free[0]
            try:
                sim.admit_lane(b, self.plan.overrides_for(t.spec),
                               tape=self.plan.tape_for(t.spec))
            except AdmissionError as exc:
                t.defer_reason = str(exc)
                self.deferrals += 1
                remaining.append(t)
                continue
            free.pop(0)
            t.lane = b
            self._lane_tickets[b] = t
            self.lanes_admitted += 1
            admitted = True
        self._queue = remaining
        return admitted

    def _on_superstep(self, sim) -> bool:
        self._emit_completions(sim)
        return self._admit(sim)

    def _retire_fleet(self) -> None:
        sim = self._fleet
        self.spec_issued += sim.spec_issued
        self.spec_committed += sim.spec_committed
        self.spec_rolled_back += sim.spec_rolled_back
        self._fleet = None
        self._lane_tickets = []

    def drain(self) -> List[Ticket]:
        """Serve every queued query to completion and return ALL
        completed tickets so far, in completion order.  Fleets are
        recycled: one stays resident while admissions keep it fed;
        deferred (capacity-misfit) scenarios get a fresh fleet sized
        for them once the current one drains dry."""
        while self._queue or self._fleet is not None:
            if self._fleet is None:
                self._start_fleet()
            self._fleet.run(between=self._on_superstep)
            # fleet ran dry: everything alive finished and nothing
            # more could be admitted — final sweep, then retire
            self._emit_completions(self._fleet)
            self._retire_fleet()
        return list(self.completed)

    # -- introspection -----------------------------------------------------

    def counters(self) -> Dict[str, float]:
        c = {"fleets": self.fleets,
             "lanes_admitted": self.lanes_admitted,
             "surrogate_answers": self.surrogate_answers,
             "surrogate_escalations": self.surrogate_escalations,
             "deferrals": self.deferrals,
             "spec_issued": self.spec_issued,
             "spec_committed": self.spec_committed,
             "spec_rolled_back": self.spec_rolled_back}
        if self.plan_cache is not None:
            c.update(self.plan_cache.stats())
        return c
