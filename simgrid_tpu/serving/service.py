"""The always-on campaign service: a persistent scenario queue over
live fleets with mid-flight admission and surrogate triage.

``submit(spec) -> Ticket`` enqueues one what-if query.  Low-stakes
queries (``exact=False``) are first offered to the surrogate
(:mod:`.surrogate`): a tight-interval prediction answers immediately
with ``source="surrogate"`` + conformal bounds; wide-interval queries
escalate to the device path.  ``exact=True`` always bypasses the
surrogate.

Device-path queries run on a resident :class:`~simgrid_tpu.ops.
lmm_batch.BatchDrainSim` fleet whose programs route through the AOT
plan cache (:mod:`.plancache`) — a warm restart performs zero XLA
traces.  ADMISSION BATCHING packs arriving queries into
partially-filled fleets: the service drives the fleet with
``run(between=...)`` and, between supersteps, (a) emits finished lanes
as streaming per-replica results and (b) revives dead lanes with
queued scenarios via ``admit_lane`` — an O(overrides) device scatter;
the admitted lane starts at its own k=0 with a fresh tape slot.  A
fired admission marks the fleet mutated, so in-flight pipeline
speculation discards and replays — preserving the standing invariant:
an admitted scenario's events, fault streams and Kahan clocks are
bit-identical to ``ScenarioPlan.solo`` on the same spec
(``tools/check_determinism.py --runtime-serve``).

Scenarios the live fleet cannot absorb (fault tape wider than the
fleet's reserved slots, elem_w into a shared-weight fleet) are
DEFERRED, not failed: they stay queued and the next fleet is sized for
them at birth.

The service is single-threaded and deterministic — ordering comes from
the submit order and the fleet's lockstep supersteps, never from
wall-clock races.  Wall-clock enters only as latency METADATA on
tickets (``time.perf_counter``).
"""

from __future__ import annotations

import json
import time
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..checkpoint import CheckpointError, FleetCheckpoint
from ..ops import opstats
from ..ops.lmm_batch import (AdmissionError, DispatchExhausted,
                             LaneFault)
from ..parallel.campaign import ScenarioPlan, ScenarioSpec, _mesh_size
from .plancache import PlanCache
from .surrogate import RuntimeSurrogate


class _DrainHalt(Exception):
    """Internal drive-loop signal: ``drain(stop_after=N)`` reached its
    superstep budget.  Raised from the between-supersteps hook — the
    pipelined fleet driver's ``finally`` discards in-flight speculation
    on the way out, so the fleet is left at a committed collect
    boundary (exactly what a checkpoint needs)."""


class ServiceResult:
    """One answered query.  ``source`` is the audit field: ``"device"``
    results carry the exact event stream / clocks; ``"surrogate"``
    results carry the conformal interval they were stated at."""

    __slots__ = ("source", "t", "lo", "hi", "confidence", "events",
                 "fault_events", "advances", "error")

    def __init__(self, source: str, t: float, lo: float = None,
                 hi: float = None, confidence: float = None,
                 events=None, fault_events=None, advances: int = 0,
                 error: Optional[str] = None):
        self.source = source
        self.t = t
        self.lo = lo
        self.hi = hi
        self.confidence = confidence
        self.events = events
        self.fault_events = fault_events
        self.advances = advances
        self.error = error

    def to_dict(self) -> Dict:
        """JSON-ready journal form.  Scalars and event times are f64
        and CPython json round-trips f64 exactly (shortest-repr), so a
        checkpointed result stays bit-identical through save/load."""
        return {
            "source": self.source, "t": self.t, "lo": self.lo,
            "hi": self.hi, "confidence": self.confidence,
            "advances": self.advances, "error": self.error,
            "events": ([[t, int(i)] for t, i in self.events]
                       if self.events is not None else None),
            "fault_events": ([[t, int(s)]
                              for t, s in self.fault_events]
                             if self.fault_events is not None
                             else None),
        }

    @classmethod
    def from_dict(cls, d: Dict) -> "ServiceResult":
        ev = d.get("events")
        fev = d.get("fault_events")
        return cls(d["source"], d["t"], lo=d.get("lo"),
                   hi=d.get("hi"), confidence=d.get("confidence"),
                   events=([(float(t), int(i)) for t, i in ev]
                           if ev is not None else None),
                   fault_events=([(float(t), int(s))
                                  for t, s in fev]
                                 if fev is not None else None),
                   advances=int(d.get("advances", 0)),
                   error=d.get("error"))


class Ticket:
    """One submitted query's handle: spec, routing, and (once
    answered) the result plus submit→done latency metadata."""

    __slots__ = ("id", "spec", "exact", "status", "result", "lane",
                 "submitted_at", "done_at", "defer_reason", "fault",
                 "storms")

    def __init__(self, tid: int, spec: ScenarioSpec, exact: bool):
        self.id = tid
        self.spec = spec
        self.exact = exact
        self.status = "queued"
        self.result: Optional[ServiceResult] = None
        self.lane: Optional[int] = None
        self.submitted_at = time.perf_counter()
        self.done_at: Optional[float] = None
        self.defer_reason: Optional[str] = None
        #: structured quarantine cause when the lane serving this
        #: query was killed (ops.lmm_batch.LaneFault), else None
        self.fault: Optional[LaneFault] = None
        #: fleet generations that retired while this query sat
        #: deferred — the admission-storm trip counter
        self.storms = 0

    @property
    def latency_ms(self) -> Optional[float]:
        if self.done_at is None:
            return None
        return (self.done_at - self.submitted_at) * 1e3


class CampaignService:
    """A persistent scenario service over one :class:`ScenarioPlan`.

    ``batch`` is the resident fleet width (default: the
    ``serve/batch`` flag).  ``plan_cache`` routes fleet programs
    through AOT executables; ``surrogate`` (pass a
    :class:`RuntimeSurrogate`, or None for device-only) enables
    triage; ``corpus_log`` appends every device-served row as jsonl so
    future processes can seed their surrogate from it."""

    def __init__(self, plan: ScenarioPlan, batch: Optional[int] = None,
                 plan_cache: Optional[PlanCache] = None,
                 surrogate: Optional[RuntimeSurrogate] = None,
                 corpus_log: Optional[str] = None,
                 pipeline: Optional[int] = None, mesh=None,
                 watchdog=None, max_admission_retries: int = 8):
        from ..utils.config import config
        self.plan = plan
        self.batch = int(config["serve/batch"] if batch is None
                         else batch)
        if self.batch <= 0:
            raise ValueError("service batch must be >= 1")
        if plan_cache is None and str(config["serve/plan-cache"]):
            plan_cache = PlanCache(str(config["serve/plan-cache"]))
        self.plan_cache = plan_cache
        self.surrogate = surrogate
        self.corpus_log = corpus_log
        self.pipeline = pipeline
        self.mesh = mesh
        #: ops.lmm_batch.DispatchWatchdog guarding every fleet device
        #: dispatch; on retry exhaustion the service falls back to the
        #: solo host path for the affected queries (None = no guard)
        self.watchdog = watchdog
        #: fleet generations a deferred query may sit out before it is
        #: failed with an ``admission_storm`` LaneFault
        self.max_admission_retries = int(max_admission_retries)
        self.tickets: List[Ticket] = []
        self.completed: List[Ticket] = []
        self._queue: List[Ticket] = []
        self._fleet = None
        self._lane_tickets: List[Optional[Ticket]] = []
        # service-lifetime counters (fleet counters are aggregated in
        # on retire; see counters())
        self.fleets = 0
        self.lanes_admitted = 0
        self.surrogate_answers = 0
        self.surrogate_escalations = 0
        self.deferrals = 0
        self.spec_issued = 0
        self.spec_committed = 0
        self.spec_rolled_back = 0
        self.checkpoints = 0
        self.storm_failures = 0
        self.watchdog_solo_fallbacks = 0
        #: committed supersteps observed by THIS drain call (drives
        #: checkpoint cadence and stop_after)
        self.supersteps = 0
        # the device path exhausted its watchdog retries: every later
        # query routes straight to the solo host path
        self._device_broken = False
        # drain()-scoped checkpoint/halt directives
        self._halt_after = 0
        self._ckpt_every = 0
        self._ckpt_path: Optional[str] = None

    # -- submission --------------------------------------------------------

    def submit(self, spec: ScenarioSpec,
               exact: bool = False) -> Ticket:
        """Enqueue one query.  Surrogate triage happens HERE — a
        tight-interval prediction answers without touching the queue;
        ``exact=True`` always bypasses it."""
        # reject collective mismatches at the door: an admitted lane
        # would otherwise report a different workload's clocks
        self.plan._check_collective(spec)
        t = Ticket(len(self.tickets), spec, bool(exact))
        self.tickets.append(t)
        if not exact and self.surrogate is not None:
            ans = self.surrogate.triage(spec)
            if ans is not None:
                t.result = ServiceResult(
                    "surrogate", ans.t, lo=ans.lo, hi=ans.hi,
                    confidence=ans.confidence)
                t.status = "done"
                t.done_at = time.perf_counter()
                self.surrogate_answers += 1
                opstats.bump("surrogate_answers")
                self.completed.append(t)
                return t
            self.surrogate_escalations += 1
            opstats.bump("surrogate_escalations")
        self._queue.append(t)
        return t

    def submit_many(self, specs: Sequence[ScenarioSpec],
                    exact: bool = False) -> List[Ticket]:
        return [self.submit(s, exact=exact) for s in specs]

    def pending(self) -> int:
        return len(self._queue) + sum(
            1 for t in self._lane_tickets if t is not None)

    # -- the drive loop ----------------------------------------------------

    def _start_fleet(self) -> None:
        """Build a resident fleet from the queue head: up to ``batch``
        initial lanes, the rest of the width dead-at-birth and open
        for admission.  Capacity for LATER admissions is reserved at
        birth — tape slots sized by probing every queued faulted
        spec's schedule length, per-replica weight tables forced when
        any queued spec overrides element weights."""
        take = self._queue[:self.batch]
        del self._queue[:len(take)]
        tape_slots = 0
        need_batch_w = False
        for t in take + self._queue:
            if t.spec.fault_mtbf is not None:
                tape_slots = max(tape_slots,
                                 self.plan.tape_len(t.spec))
            if t.spec.elem_w:
                need_batch_w = True
        try:
            self._fleet = self.plan.executor(
                [t.spec for t in take], width=self.batch,
                plan_cache=self.plan_cache, tape_slots=tape_slots,
                batch_w=True if need_batch_w else None,
                pipeline=self.pipeline, mesh=self.mesh,
                watchdog=self.watchdog)
        except DispatchExhausted:
            # construction itself exhausted the watchdog (the very
            # first materialize dispatch can fail on a dead device):
            # nothing is in flight yet, so put the head back in queue
            # order for the solo fallback to serve
            self._queue[:0] = take
            raise
        self._lane_tickets = (list(take)
                              + [None] * (self.batch - len(take)))
        for b, t in enumerate(take):
            t.lane = b
        self.fleets += 1

    def _emit_completions(self, sim) -> None:
        """Stream finished lanes out as device results: feed the
        surrogate corpus, free the lane for admission."""
        for b in range(sim.B):
            t = self._lane_tickets[b]
            if t is None or sim._alive[b]:
                continue
            rep = sim.replicas[b]
            t.result = ServiceResult(
                "device", rep.t, events=list(rep.events),
                fault_events=list(rep.fault_events),
                advances=rep.advances, error=rep.error)
            t.fault = rep.fault
            t.status = "done"
            t.done_at = time.perf_counter()
            self.completed.append(t)
            self._lane_tickets[b] = None
            opstats.bump("serve_device_results")
            if rep.error is None:
                if self.surrogate is not None:
                    self.surrogate.observe(t.spec, rep.t)
                if self.corpus_log:
                    with open(self.corpus_log, "a") as f:
                        f.write(json.dumps(
                            {"spec": t.spec.to_dict(), "t": rep.t,
                             "source": "device"}) + "\n")

    def _admit(self, sim) -> bool:
        """Pack queued queries into the fleet's free (dead, emitted)
        lanes.  Scenarios the fleet cannot absorb are deferred — they
        stay queued for the next fleet, sized for them at birth."""
        admitted = False
        free = [b for b in range(sim.B)
                if self._lane_tickets[b] is None and not sim._alive[b]]
        if not free or not self._queue:
            return False
        remaining: List[Ticket] = []
        for t in self._queue:
            if not free:
                remaining.append(t)
                continue
            b = free[0]
            try:
                sim.admit_lane(b, self.plan.overrides_for(t.spec),
                               tape=self.plan.tape_for(t.spec))
            except AdmissionError as exc:
                t.defer_reason = str(exc)
                self.deferrals += 1
                remaining.append(t)
                continue
            free.pop(0)
            t.lane = b
            self._lane_tickets[b] = t
            self.lanes_admitted += 1
            admitted = True
        self._queue = remaining
        return admitted

    def _on_superstep(self, sim) -> bool:
        self._emit_completions(sim)
        mutated = self._admit(sim)
        # the hook runs once per COMMITTED superstep — the cadence
        # checkpoints and stop_after halts hang off that count.
        # Checkpoint before a potential halt so a stop_after aligned
        # with the cadence still lands its snapshot.
        self.supersteps += 1
        if (self._ckpt_every and self._ckpt_path
                and self.supersteps % self._ckpt_every == 0):
            self.checkpoint(self._ckpt_path)
        if self._halt_after and self.supersteps >= self._halt_after:
            raise _DrainHalt()
        return mutated

    def _retire_fleet(self) -> None:
        sim = self._fleet
        self.spec_issued += sim.spec_issued
        self.spec_committed += sim.spec_committed
        self.spec_rolled_back += sim.spec_rolled_back
        self._fleet = None
        self._lane_tickets = []
        # admission-storm trip: a query the retiring fleet kept
        # deferring normally fits the NEXT fleet (sized for it at
        # birth) — one that keeps missing across generations is failed
        # with a structured cause instead of spinning forever
        still: List[Ticket] = []
        for t in self._queue:
            if t.defer_reason is None:
                still.append(t)
                continue
            t.storms += 1
            if t.storms < self.max_admission_retries:
                still.append(t)
                continue
            detail = (f"admission deferred across {t.storms} fleet "
                      f"generations: {t.defer_reason}")
            t.fault = LaneFault("admission_storm", detail, -1)
            t.result = ServiceResult("device", 0.0, error=detail)
            t.status = "failed"
            t.done_at = time.perf_counter()
            self.completed.append(t)
            self.storm_failures += 1
            opstats.bump("lane_quarantined_admission_storm")
        self._queue = still

    def _serve_solo(self, t: Ticket,
                    fault: Optional[LaneFault] = None) -> None:
        """Answer one query on the solo host path (the bit-identity
        oracle itself, so the result is the one the device fleet would
        have produced).  Used after watchdog exhaustion."""
        res = self.plan.solo(t.spec)
        t.result = ServiceResult(
            "solo", res.t, events=list(res.events),
            fault_events=list(res.fault_events),
            advances=res.advances, error=res.error)
        t.fault = fault
        t.status = "done"
        t.done_at = time.perf_counter()
        self.completed.append(t)
        opstats.bump("serve_solo_results")
        if res.error is None:
            if self.surrogate is not None:
                self.surrogate.observe(t.spec, res.t)
            if self.corpus_log:
                with open(self.corpus_log, "a") as f:
                    f.write(json.dumps(
                        {"spec": t.spec.to_dict(), "t": res.t,
                         "source": "solo"}) + "\n")

    def _watchdog_fallback(self, exc: DispatchExhausted) -> None:
        """The device path exhausted its dispatch retries mid-fleet:
        flush the lanes that already finished as normal device
        results, re-serve the in-flight lanes' queries on the solo
        host path from scratch (bit-identical by the standing
        invariant; the ticket carries a ``watchdog`` LaneFault naming
        the exhaustion), and route every later query solo too."""
        sim = self._fleet
        self._device_broken = True
        self.watchdog_solo_fallbacks += 1
        opstats.bump("watchdog_solo_fallbacks")
        self._emit_completions(sim)
        for b in range(sim.B):
            t = self._lane_tickets[b]
            if t is None:
                continue
            self._serve_solo(t, fault=LaneFault(
                "watchdog",
                f"device dispatch watchdog exhausted: {exc}", b))
            self._lane_tickets[b] = None
        self._retire_fleet()

    def drain(self, stop_after: int = 0, checkpoint_every: int = 0,
              checkpoint_path: Optional[str] = None) -> List[Ticket]:
        """Serve every queued query to completion and return ALL
        completed tickets so far, in completion order.  Fleets are
        recycled: one stays resident while admissions keep it fed;
        deferred (capacity-misfit) scenarios get a fresh fleet sized
        for them once the current one drains dry.

        ``checkpoint_every=K`` with ``checkpoint_path`` writes a
        :class:`~simgrid_tpu.checkpoint.FleetCheckpoint` every K
        committed supersteps (overwriting — the token is replaced
        atomically).  ``stop_after=N`` halts after N committed
        supersteps — writing a final checkpoint when a path is set —
        and returns with the fleet still resident, so a later
        ``drain()`` (or a fresh process's :meth:`resume`) continues
        bit-identically.  A :class:`~simgrid_tpu.ops.lmm_batch.
        DispatchExhausted` from the watchdog retires the fleet onto
        the solo host path instead of raising."""
        self._halt_after = int(stop_after)
        self._ckpt_every = int(checkpoint_every)
        self._ckpt_path = checkpoint_path
        self.supersteps = 0
        try:
            while self._queue or self._fleet is not None:
                if self._fleet is None:
                    if self._device_broken:
                        while self._queue:
                            self._serve_solo(self._queue.pop(0))
                        break
                    try:
                        self._start_fleet()
                    except DispatchExhausted:
                        # dead before the fleet existed: no lanes in
                        # flight, so no per-ticket watchdog fault —
                        # the whole queue just routes solo
                        self._device_broken = True
                        self.watchdog_solo_fallbacks += 1
                        opstats.bump("watchdog_solo_fallbacks")
                        continue
                try:
                    self._fleet.run(between=self._on_superstep)
                except DispatchExhausted as exc:
                    self._watchdog_fallback(exc)
                    continue
                # fleet ran dry: everything alive finished and nothing
                # more could be admitted — final sweep, then retire
                self._emit_completions(self._fleet)
                self._retire_fleet()
        except _DrainHalt:
            if self._ckpt_path:
                self.checkpoint(self._ckpt_path)
        finally:
            self._halt_after = 0
            self._ckpt_every = 0
            self._ckpt_path = None
        return list(self.completed)

    # -- superstep-boundary checkpoint / deterministic resume --------------

    def _ticket_to_dict(self, t: Ticket) -> Dict:
        return {"id": t.id, "spec": t.spec.to_dict(),
                "exact": t.exact, "status": t.status, "lane": t.lane,
                "defer_reason": t.defer_reason, "storms": t.storms,
                "fault": (t.fault.to_dict() if t.fault is not None
                          else None),
                "result": (t.result.to_dict()
                           if t.result is not None else None)}

    @staticmethod
    def _ticket_from_dict(d: Dict) -> Ticket:
        t = Ticket(int(d["id"]), ScenarioSpec.from_dict(d["spec"]),
                   bool(d["exact"]))
        t.status = d["status"]
        t.lane = d["lane"]
        t.defer_reason = d["defer_reason"]
        t.storms = int(d.get("storms", 0))
        t.fault = (LaneFault.from_dict(d["fault"])
                   if d.get("fault") else None)
        t.result = (ServiceResult.from_dict(d["result"])
                    if d.get("result") else None)
        if t.status in ("done", "failed"):
            # latency metadata does not survive a process restart —
            # resumed tickets report 0, never a wall-clock lie
            t.submitted_at = t.done_at = 0.0
        return t

    def checkpoint(self, path: str) -> None:
        """Write one :class:`~simgrid_tpu.checkpoint.FleetCheckpoint`
        of the service: the plan's flattening arrays + solver config
        (the token is self-contained — :meth:`resume` needs no other
        input), the full ticket journal (queue order, completion
        order, per-ticket results with f64-exact streams, LaneFaults),
        and — when a fleet is resident — the BatchDrainSim COMMITTED
        state at the current collect boundary.  In-flight pipeline
        speculation is never persisted; resume replays it from
        committed state like a mispredict.  Call between supersteps
        only (``drain(checkpoint_every=...)`` does)."""
        t0 = time.perf_counter()
        plan = self.plan
        arrays: Dict[str, np.ndarray] = {
            "plan_e_var": plan.e_var, "plan_e_cnst": plan.e_cnst,
            "plan_e_w": plan.e_w, "plan_c_bound": plan.c_bound,
            "plan_sizes": plan.sizes,
        }
        for name in ("remains", "penalty", "v_bound"):
            a = getattr(plan, name)
            if a is not None:
                arrays["plan_" + name] = a
        token: Dict = {
            "plan": {
                "topology": plan.topology_hash(),
                "eps": plan.eps, "done_eps": plan.done_eps,
                "dtype": plan.dtype.name,
                "done_mode": plan.done_mode,
                "superstep": plan.superstep,
                "pipeline": plan.pipeline,
                "mesh": _mesh_size(plan.mesh),
                "fault_mode": plan.fault_mode,
                "link_names": (list(plan.link_names)
                               if plan.link_names is not None
                               else None),
                "collective": (plan.collective.to_dict()
                               if plan.collective is not None
                               else None),
            },
            "service": {
                "batch": self.batch,
                "pipeline": self.pipeline,
                "mesh": _mesh_size(self.mesh),
                "max_admission_retries": self.max_admission_retries,
                "device_broken": self._device_broken,
                "tickets": [self._ticket_to_dict(t)
                            for t in self.tickets],
                "queue": [t.id for t in self._queue],
                "completed": [t.id for t in self.completed],
                "lane_tickets": [t.id if t is not None else None
                                 for t in self._lane_tickets],
                "counters": {
                    "fleets": self.fleets,
                    "lanes_admitted": self.lanes_admitted,
                    "surrogate_answers": self.surrogate_answers,
                    "surrogate_escalations":
                        self.surrogate_escalations,
                    "deferrals": self.deferrals,
                    "spec_issued": self.spec_issued,
                    "spec_committed": self.spec_committed,
                    "spec_rolled_back": self.spec_rolled_back,
                    "checkpoints": self.checkpoints,
                    "storm_failures": self.storm_failures,
                    "watchdog_solo_fallbacks":
                        self.watchdog_solo_fallbacks,
                },
            },
            "fleet": None,
        }
        sim = self._fleet
        if sim is not None:
            st = sim.committed_state()
            for name, a in sorted(st["arrays"].items()):
                arrays["fleet_" + name] = a
            token["fleet"] = {
                "width": sim.B,
                "tape_width": (sim._tape_width if sim.has_tape
                               else 0),
                "batch_w": bool(sim.batch_w),
                "errors": st["errors"],
                "faults": st["faults"],
                "counters": st["counters"],
            }
        FleetCheckpoint(token, arrays).save(path)
        self.checkpoints += 1
        opstats.bump("fleet_checkpoints")
        opstats.bump("checkpoint_ms",
                     (time.perf_counter() - t0) * 1e3)

    @classmethod
    def resume(cls, path: str, plan: Optional[ScenarioPlan] = None,
               plan_cache: Optional[PlanCache] = None,
               surrogate: Optional[RuntimeSurrogate] = None,
               corpus_log: Optional[str] = None,
               watchdog=None) -> "CampaignService":
        """Rebuild a service from a :meth:`checkpoint` token and
        continue deterministically: the plan is reconstructed from the
        persisted flattening arrays (or validated against a passed
        ``plan`` via topology hash), the ticket journal is replayed
        into queue/completed order, and a resident fleet is rebuilt
        through :meth:`ScenarioPlan.executor` — hitting the AOT plan
        cache warm (same plan key, zero XLA traces) — then restored to
        the checkpointed committed state.  The continued drain's
        events, fault streams and Kahan clocks are bit-identical to
        the uninterrupted run
        (``tools/check_determinism.py --runtime-resume``).  Resuming
        never mutates the token: a double resume from the same path
        re-runs bit-identically."""
        ck = FleetCheckpoint.load(path)
        tok = ck.token
        pt = tok.get("plan")
        svc_tok = tok.get("service")
        if not isinstance(pt, dict) or not isinstance(svc_tok, dict):
            raise CheckpointError(
                f"fleet checkpoint {path!r} is missing its plan or "
                f"service section (foreign or truncated token)")
        if plan is None:
            kw = {}
            for name in ("remains", "penalty", "v_bound"):
                if "plan_" + name in ck.arrays:
                    kw[name] = ck.arrays["plan_" + name]
            if pt.get("collective"):
                kw["collective"] = pt["collective"]
            plan = ScenarioPlan(
                ck.arrays["plan_e_var"], ck.arrays["plan_e_cnst"],
                ck.arrays["plan_e_w"], ck.arrays["plan_c_bound"],
                ck.arrays["plan_sizes"],
                link_names=pt.get("link_names"),
                eps=pt["eps"], done_eps=pt["done_eps"],
                dtype=pt["dtype"], done_mode=pt["done_mode"],
                superstep=pt["superstep"], pipeline=pt["pipeline"],
                mesh=pt["mesh"] or None,
                fault_mode=pt["fault_mode"], **kw)
        if plan.topology_hash() != pt.get("topology"):
            raise CheckpointError(
                "fleet checkpoint topology hash does not match the "
                "plan it is being resumed onto — refusing a "
                "cross-plan resume")
        svc = cls(plan, batch=int(svc_tok["batch"]),
                  plan_cache=plan_cache, surrogate=surrogate,
                  corpus_log=corpus_log,
                  pipeline=svc_tok.get("pipeline"),
                  mesh=svc_tok.get("mesh") or None,
                  watchdog=watchdog,
                  max_admission_retries=int(
                      svc_tok.get("max_admission_retries", 8)))
        svc._device_broken = bool(svc_tok.get("device_broken"))
        svc.tickets = [cls._ticket_from_dict(d)
                       for d in svc_tok["tickets"]]
        by_id = {t.id: t for t in svc.tickets}
        svc._queue = [by_id[i] for i in svc_tok["queue"]]
        svc.completed = [by_id[i] for i in svc_tok["completed"]]
        c = svc_tok.get("counters") or {}
        for name in ("fleets", "lanes_admitted", "surrogate_answers",
                     "surrogate_escalations", "deferrals",
                     "spec_issued", "spec_committed",
                     "spec_rolled_back", "checkpoints",
                     "storm_failures", "watchdog_solo_fallbacks"):
            setattr(svc, name, int(c.get(name, 0)))
        ft = tok.get("fleet")
        if ft is not None:
            sim = plan.executor(
                [], width=int(ft["width"]),
                plan_cache=svc.plan_cache,
                tape_slots=int(ft["tape_width"]),
                batch_w=bool(ft["batch_w"]) or None,
                pipeline=svc.pipeline, mesh=svc.mesh,
                watchdog=watchdog)
            fleet_arrays = {name[len("fleet_"):]: a
                            for name, a in sorted(ck.arrays.items())
                            if name.startswith("fleet_")}
            try:
                sim.restore_state({"arrays": fleet_arrays,
                                   "errors": ft["errors"],
                                   "faults": ft["faults"],
                                   "counters": ft["counters"]})
            except ValueError as exc:
                raise CheckpointError(
                    f"fleet checkpoint state does not fit the "
                    f"rebuilt fleet: {exc}")
            svc._fleet = sim
            svc._lane_tickets = [
                by_id[i] if i is not None else None
                for i in svc_tok["lane_tickets"]]
        opstats.bump("fleet_resumes")
        return svc

    # -- introspection -----------------------------------------------------

    def counters(self) -> Dict[str, float]:
        c = {"fleets": self.fleets,
             "lanes_admitted": self.lanes_admitted,
             "surrogate_answers": self.surrogate_answers,
             "surrogate_escalations": self.surrogate_escalations,
             "deferrals": self.deferrals,
             "spec_issued": self.spec_issued,
             "spec_committed": self.spec_committed,
             "spec_rolled_back": self.spec_rolled_back,
             "checkpoints": self.checkpoints,
             "storm_failures": self.storm_failures,
             "watchdog_solo_fallbacks": self.watchdog_solo_fallbacks,
             "device_broken": int(self._device_broken)}
        if self.plan_cache is not None:
            c.update(self.plan_cache.stats())
        return c
