"""CPU TI model: closed-form trace integration (reference
src/surf/cpu_ti.cpp).  Instead of stepping through availability-profile
events, the cumulative integral of the speed profile is precomputed
(numpy prefix sums) and each action's finish date is solved analytically
with binary searches — O(log n) per action instead of one simulation
event per profile point, the fastest mode for traced platforms.

No LMM system is involved: actions on one CPU share it fairly by
priority, so remaining work evolves as area/(sum_priority * penalty)
with area = peak * integral of the scale profile.
"""

from __future__ import annotations

import math
from typing import List, Optional

import numpy as np

from ..kernel import profile as profile_mod
from ..kernel.resource import (ActionState, HeapType, NO_MAX_DURATION,
                               SuspendStates, UpdateAlgo)
from ..utils.config import config
from .cpu import Cpu, CpuAction, CpuModel

_EPSILON = 1e-12


class CpuTiProfile:
    """Cumulative integral of a delta-encoded speed profile
    (cpu_ti.cpp:25-40): time_points[i] / integral[i] arrays built as
    prefix sums."""

    def __init__(self, profile: profile_mod.Profile):
        times = [0.0]
        integrals = [0.0]
        t = 0.0
        acc = 0.0
        for val in profile.event_list:
            # the idx-0 placeholder has value -1 (it only stores the trace
            # begin offset); contribute its span at scale 0, not -1
            scale = val.value if val.value >= 0 else 0.0
            delta = max(val.date, 0.0)
            t += delta
            acc += delta * scale
            times.append(t)
            integrals.append(acc)
        # drop the duplicated leading point if the placeholder was empty
        self.time_points = np.asarray(times)
        self.integral = np.asarray(integrals)

    @staticmethod
    def _search(array: np.ndarray, a: float) -> int:
        """Index of the last point <= a (cpu_ti.cpp:255-261)."""
        if array[0] > a:
            return 0
        return int(np.searchsorted(array, a, side="right")) - 1

    def integrate_simple_point(self, a: float) -> float:
        ind = self._search(self.time_points, a)
        if ind >= len(self.time_points) - 1:
            return float(self.integral[-1])
        integral = float(self.integral[ind])
        frac = a - float(self.time_points[ind])
        if frac > 0:
            span = float(self.time_points[ind + 1] - self.time_points[ind])
            if span > 0:
                integral += (float(self.integral[ind + 1]
                                   - self.integral[ind]) / span) * frac
        return integral

    def integrate_simple(self, a: float, b: float) -> float:
        return self.integrate_simple_point(b) - self.integrate_simple_point(a)

    def solve_simple(self, a: float, amount: float) -> float:
        """Date at which `amount` of integral is accumulated past a
        (cpu_ti.cpp:186-196)."""
        target = self.integrate_simple_point(a) + amount
        ind = self._search(self.integral, target)
        ind = min(ind, len(self.time_points) - 2)
        time = float(self.time_points[ind])
        span_i = float(self.integral[ind + 1] - self.integral[ind])
        span_t = float(self.time_points[ind + 1] - self.time_points[ind])
        if span_i > 0:
            time += (target - float(self.integral[ind])) / (span_i / span_t)
        return time


class CpuTiTmgr:
    """Fixed-or-dynamic integration manager with periodic wrap-around
    (cpu_ti.cpp CpuTiTmgr)."""

    def __init__(self, profile: Optional[profile_mod.Profile],
                 value: float = 1.0):
        if profile is None or len(profile.event_list) <= 1:
            self.fixed = True
            self.value = (profile.event_list[0].value
                          if profile is not None and profile.event_list
                          and profile.event_list[0].value >= 0 else value)
            self.profile = None
            return
        self.fixed = False
        self.profile = CpuTiProfile(profile)
        self.last_time = float(self.profile.time_points[-1])
        self.total = self.profile.integrate_simple(0.0, self.last_time)

    def integrate(self, a: float, b: float) -> float:
        assert 0.0 <= a <= b + _EPSILON, \
            f"invalid integration interval [{a}, {b}]"
        if abs(a - b) < _EPSILON:
            return 0.0
        if self.fixed:
            return (b - a) * self.value

        lt = self.last_time
        if abs(math.ceil(a / lt) - a / lt) < _EPSILON:
            a_index = 1 + int(math.ceil(a / lt))
        else:
            a_index = int(math.ceil(a / lt))
        b_index = int(math.floor(b / lt))
        if a_index > b_index:     # same period chunk
            return self.profile.integrate_simple(a - (a_index - 1) * lt,
                                                 b - b_index * lt)
        first = self.profile.integrate_simple(a - (a_index - 1) * lt, lt)
        middle = (b_index - a_index) * self.total
        last = self.profile.integrate_simple(0.0, b - b_index * lt)
        return first + middle + last

    def solve(self, a: float, amount: float) -> float:
        if -_EPSILON < a < 0.0:
            a = 0.0
        if -_EPSILON < amount < 0.0:
            amount = 0.0
        assert a >= 0.0 and amount >= 0.0, \
            f"invalid solve parameters [a={a}, amount={amount}]"
        if amount < _EPSILON:
            return a
        if self.fixed:
            return a + amount / self.value

        quotient = int(math.floor(amount / self.total))
        reduced_amount = self.total * (amount / self.total
                                       - math.floor(amount / self.total))
        periods_before = int(math.floor(a / self.last_time))
        reduced_a = a - self.last_time * periods_before

        amount_till_end = self.integrate(reduced_a, self.last_time)
        if amount_till_end > reduced_amount:
            reduced_b = self.profile.solve_simple(reduced_a, reduced_amount)
        else:
            reduced_b = self.last_time + self.profile.solve_simple(
                0.0, reduced_amount - amount_till_end)
        return (self.last_time * periods_before
                + quotient * self.last_time + reduced_b)

    def get_power_scale(self, a: float) -> float:
        if self.fixed:
            return self.value
        reduced_a = a - math.floor(a / self.last_time) * self.last_time
        point = CpuTiProfile._search(self.profile.time_points, reduced_a)
        # scale in effect after point i is event i's value (placeholder -> 0)
        sc = self._scales()[min(point, len(self._scales()) - 1)]
        return sc

    def _scales(self):
        if not hasattr(self, "_scale_cache"):
            tp = self.profile.time_points
            it = self.profile.integral
            self._scale_cache = [
                (float(it[i + 1] - it[i]) / float(tp[i + 1] - tp[i])
                 if tp[i + 1] > tp[i] else 0.0)
                for i in range(len(tp) - 1)]
        return self._scale_cache


class CpuTiModel(CpuModel):
    """next_occurring_event: refresh finish dates of actions on modified
    cpus, then read the heap top (cpu_ti.cpp:293-310)."""

    def __init__(self, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        from ..ops.lmm_host import System
        self.set_maxmin_system(System(False))  # unused; kept for interface
        self.modified_cpus: List["CpuTi"] = []

    def create_cpu(self, host, speed_per_pstate: List[float],
                   core_count: int = 1) -> "CpuTi":
        return CpuTi(self, host, speed_per_pstate, core_count)

    def next_occurring_event(self, now: float) -> float:
        for cpu in list(self.modified_cpus):
            cpu.update_actions_finish_time(now)
        if not self.action_heap.empty():
            return self.action_heap.top_date() - now
        return -1.0

    def update_actions_state(self, now: float, delta: float) -> None:
        eps = config["surf/precision"]
        while (not self.action_heap.empty()
               and abs(self.action_heap.top_date() - now) < eps):
            action = self.action_heap.pop()
            action.finish(ActionState.FINISHED)
            action.cpu.update_remaining_amount(now)


class CpuTi(Cpu):
    """A CPU under trace integration (cpu_ti.cpp CpuTi)."""

    def __init__(self, model: CpuTiModel, host,
                 speed_per_pstate: List[float], core_count: int = 1):
        assert core_count == 1, "Multi-core not handled by the TI model"
        super().__init__(model, host, speed_per_pstate, core_count)
        self.action_set: List["CpuTiAction"] = []
        self.sum_priority = 0.0
        self.last_update = 0.0
        self.tmgr = CpuTiTmgr(None, 1.0)
        self._modified = False

    def set_speed_profile(self, profile: profile_mod.Profile) -> None:
        # The whole profile is integrated analytically: no future events
        # are scheduled for it (that is the point of the TI model).
        self.tmgr = CpuTiTmgr(profile, self.speed_scale)

    def apply_event(self, event: profile_mod.Event, value: float) -> None:
        if event is self.speed_event:
            self.update_remaining_amount(self.model.engine.now)
            self.set_modified(True)
            self.tmgr = CpuTiTmgr(None, value)
            self.speed_scale = value
        elif event is self.state_event:
            if value > 0:
                if not self.is_on():
                    self.host.turn_on()
            else:
                self.host.turn_off()
                date = self.model.engine.now
                for action in list(self.action_set):
                    if action.get_state() in (ActionState.INITED,
                                              ActionState.STARTED,
                                              ActionState.IGNORED):
                        action.finish_time = date
                        action.set_state(ActionState.FAILED)
                        self.model.action_heap.remove(action)
        else:
            raise AssertionError("Unknown event!")

    def is_used(self) -> bool:
        return bool(self.action_set)

    def set_modified(self, modified: bool) -> None:
        lst = self.model.modified_cpus
        if modified:
            if self not in lst:
                lst.append(self)
        elif self in lst:
            lst.remove(self)

    def update_actions_finish_time(self, now: float) -> None:
        # cpu_ti.cpp:407-461
        self.update_remaining_amount(now)

        self.sum_priority = 0.0
        for action in self.action_set:
            if (action.state_set is not self.model.started_action_set
                    or action.sharing_penalty <= 0
                    or action.suspended != SuspendStates.RUNNING):
                continue
            self.sum_priority += 1.0 / action.sharing_penalty

        for action in self.action_set:
            min_finish = NO_MAX_DURATION
            if action.state_set is not self.model.started_action_set:
                continue
            if (action.suspended == SuspendStates.RUNNING
                    and action.sharing_penalty > 0):
                total_area = (action.remains * self.sum_priority
                              * action.sharing_penalty) / self.speed_peak
                action.finish_time = self.tmgr.solve(now, total_area)
                if (action.max_duration != NO_MAX_DURATION
                        and action.start_time + action.max_duration
                        < action.finish_time):
                    min_finish = action.start_time + action.max_duration
                else:
                    min_finish = action.finish_time
            else:
                if action.max_duration != NO_MAX_DURATION:
                    min_finish = action.start_time + action.max_duration
            if min_finish != NO_MAX_DURATION:
                self.model.action_heap.update(action, min_finish,
                                              HeapType.UNSET)
            else:
                self.model.action_heap.remove(action)
        self.set_modified(False)

    def update_remaining_amount(self, now: float) -> None:
        # cpu_ti.cpp:474-510
        if self.last_update >= now:
            return
        area_total = self.tmgr.integrate(self.last_update, now) \
            * self.speed_peak
        for action in self.action_set:
            if (action.state_set is not self.model.started_action_set
                    or action.sharing_penalty <= 0
                    or action.suspended != SuspendStates.RUNNING
                    or action.start_time >= now):
                continue
            if 0 <= action.finish_time <= now:
                continue
            if self.sum_priority > 0:
                action.update_remains(
                    area_total / (self.sum_priority
                                  * action.sharing_penalty))
        self.last_update = now

    def execution_start(self, size: float,
                        requested_cores: int = 1) -> "CpuTiAction":
        return CpuTiAction(self, size)

    def sleep(self, duration: float) -> "CpuTiAction":
        if duration > 0:
            duration = max(duration, config["surf/precision"])
        action = CpuTiAction(self, 1.0)
        action.max_duration = duration
        action.suspended = SuspendStates.SLEEPING
        if duration == NO_MAX_DURATION:
            action.set_state(ActionState.IGNORED)
        return action


class CpuTiAction(CpuAction):
    """A TI execution: no LMM variable, finish dates solved analytically
    (cpu_ti.cpp CpuTiAction)."""

    def __init__(self, cpu: CpuTi, cost: float):
        super().__init__(cpu.model, cost, not cpu.is_on(), variable=None)
        self.cpu = cpu
        cpu.action_set.append(self)
        cpu.set_modified(True)

    def set_state(self, state: ActionState) -> None:
        super().set_state(state)
        self.cpu.set_modified(True)

    def cancel(self) -> None:
        self.set_state(ActionState.FAILED)
        self.model.action_heap.remove(self)
        self.cpu.set_modified(True)

    def suspend(self) -> None:
        if self.suspended != SuspendStates.SLEEPING:
            self.cpu.update_remaining_amount(self.model.engine.now)
            self.suspended = SuspendStates.SUSPENDED
            self.model.action_heap.remove(self)
            self.cpu.set_modified(True)

    def resume(self) -> None:
        if self.suspended != SuspendStates.SLEEPING:
            self.suspended = SuspendStates.RUNNING
            self.cpu.set_modified(True)

    def set_max_duration(self, duration: float) -> None:
        self.max_duration = duration
        self.cpu.set_modified(True)

    def set_sharing_penalty(self, penalty: float) -> None:
        self.cpu.update_remaining_amount(self.model.engine.now)
        self.sharing_penalty = penalty
        self.cpu.set_modified(True)

    def set_bound(self, bound: float) -> None:
        pass  # no rate bounds under trace integration

    def update_remains_lazy(self, now: float) -> None:
        raise AssertionError("TI actions never use the lazy LMM path")

    def destroy(self) -> None:
        if self in self.cpu.action_set:
            self.cpu.action_set.remove(self)
        self.cpu.set_modified(True)
        super().destroy()
