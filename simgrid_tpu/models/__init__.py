"""Resource models (the SURF equivalent): cpu, network, host, storage."""
