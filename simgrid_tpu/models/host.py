"""Hosts and the composed host model.

Host = CPU + network endpoint + actor list (reference src/surf/HostImpl.cpp
and s4u_Host.cpp); HostCLM03Model composes the CPU/network/storage models'
next-event minima (reference src/surf/host_clm03.cpp).
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..kernel.resource import Model, UpdateAlgo
from ..utils.signal import Signal


class Host:
    """A simulated machine."""

    on_creation = Signal()
    on_destruction = Signal()
    on_state_change = Signal()
    on_speed_change_sig = Signal()
    on_restart = Signal()        # (host, n_actors_rebooted)

    def __init__(self, engine, name: str):
        self.engine = engine
        self.name = name
        self.cpu = None                   # set by the CPU model factory
        self.netpoint = None              # routing endpoint
        self.actor_list: List = []
        self.properties: Dict[str, str] = {}
        self.mounts: Dict[str, str] = {}   # mount point -> storage id
        #: boot specs: every deployment actor + every actor that called
        #: set_auto_restart (HostImpl::actors_at_boot_)
        self.actors_at_boot: list = []
        self.storages: Dict[str, object] = {}
        self.data = None
        engine.hosts[name] = self

    def __repr__(self):
        return f"<Host {self.name}>"

    # -- state ------------------------------------------------------------
    def is_on(self) -> bool:
        return self.cpu.is_on() if self.cpu is not None else True

    def turn_on(self) -> None:
        if not self.is_on():
            self.cpu.turn_on()
            self.engine.watched_hosts.discard(self.name)
            Host.on_state_change(self)
            # autorestart actors are relaunched by the engine hook
            self.engine_on_host_restart()

    def turn_off(self) -> None:
        # reference s4u::Host::turn_off: kill every actor of the host
        if self.is_on():
            self.cpu.turn_off()
            # SIMIX watched-host semantics: a failed host whose actors
            # were killed while actions were pending joins the watched
            # set, so its recovery profile event forces a zero-length
            # re-solve even though no action uses the CPU any more
            # (surf_solve's is_used() test alone would let the engine
            # sleep past the reboot).  Sampled BEFORE the kills: they
            # cancel the very synchros that make the host "pending".
            pending = self.cpu.is_used() or any(
                actor.waiting_synchro is not None
                for actor in self.actor_list)
            for actor in list(self.actor_list):
                self.engine.maestro.kill(actor)
            if pending:
                self.engine.watched_hosts.add(self.name)
            # keep only the specs that should reboot with the host
            # (HostImpl::turn_off's remove_if)
            self.actors_at_boot = [spec for spec in self.actors_at_boot
                                   if spec.get("auto_restart")]
            Host.on_state_change(self)

    def engine_on_host_restart(self) -> None:
        # boot every recorded spec (HostImpl::turn_on)
        specs, self.actors_at_boot = self.actors_at_boot, []
        for spec in specs:
            from ..s4u.actor import Actor
            actor = Actor.create(spec["name"], self, spec["code"],
                                 *spec.get("args", ()))
            if spec.get("kill_time", -1) >= 0:
                actor.set_kill_time(spec["kill_time"])
            if spec.get("auto_restart"):
                actor.pimpl.auto_restart = True
                self.actors_at_boot.append(spec)
        Host.on_restart(self, len(specs))
        restart = getattr(self.engine, "on_host_restart", None)
        if restart is not None:
            restart(self)

    def on_speed_change(self) -> None:
        Host.on_speed_change_sig(self)

    # -- perf -------------------------------------------------------------
    def get_speed(self) -> float:
        # nominal speed of the current pstate (s4u::Host::get_speed);
        # the availability-profile factor is get_available_speed() —
        # the reference keeps them separate (s4u_Host.cpp), and the
        # platform-profile oracle pins the product decomposition
        return self.cpu.speed_per_pstate[self.cpu.pstate]

    def get_available_speed(self) -> float:
        return self.cpu.speed_scale

    def get_core_count(self) -> int:
        return self.cpu.core_count

    def get_load(self) -> float:
        return self.cpu.get_load()

    # -- pstates (s4u::Host::set_pstate & friends) ------------------------
    def set_pstate(self, index: int) -> None:
        # A SIMCALL like the reference's s4u::Host::set_pstate
        # (kernel::actor::simcall): the calling actor yields, so
        # concurrent actors' log lines interleave exactly as the
        # exec-dvfs oracle pins.  Outside any actor context the
        # simcall executes inline through the maestro pseudo-actor.
        from ..s4u.actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            self.cpu.set_pstate(index)
            sc.issuer.simcall_answer()
        issuer.simcall("host_set_pstate", handler)

    def get_pstate(self) -> int:
        return self.cpu.pstate

    def get_pstate_count(self) -> int:
        return self.cpu.get_pstate_count()

    def get_pstate_speed(self, index: int) -> float:
        assert 0 <= index < len(self.cpu.speed_per_pstate), \
            (f"Invalid pstate {index} (must be in "
             f"[0, {len(self.cpu.speed_per_pstate)})")
        return self.cpu.speed_per_pstate[index]

    # -- routing ----------------------------------------------------------
    def route_to(self, dst: "Host", links: List) -> float:
        """Fill `links` with the route to dst; returns the summed latency
        (reference s4u::Host::route_to → NetZoneImpl::get_global_route)."""
        from ..routing.zone import get_global_route
        return get_global_route(self.netpoint, dst.netpoint, links)


class HostCLM03Model(Model):
    """Composes CPU + network + storage minima (host_clm03.cpp)."""

    def __init__(self, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        engine.host_model = self

    def next_occurring_event(self, now: float) -> float:
        e = self.engine
        min_by_cpu = e.cpu_model.next_occurring_event(now)
        if e.network_model.next_occurring_event_is_idempotent():
            min_by_net = e.network_model.next_occurring_event(now)
        else:
            min_by_net = -1.0
        min_by_sto = (e.storage_model.next_occurring_event(now)
                      if e.storage_model is not None else -1.0)
        res = min_by_cpu
        if res < 0 or (0.0 <= min_by_net < res):
            res = min_by_net
        if res < 0 or (0.0 <= min_by_sto < res):
            res = min_by_sto
        return res

    def update_actions_state(self, now: float, delta: float) -> None:
        pass  # host model has no action of its own

    def execute_parallel(self, hosts, flops_amounts, bytes_amounts, rate):
        raise NotImplementedError(
            "parallel tasks need the ptask_L07 model "
            "(--cfg=host/model:ptask_L07)")
