"""CPU models: base interface + Cas01 (the default share-based model).

Semantics from the reference's src/surf/cpu_interface.cpp (CpuModel
update paths, CpuAction lazy remains) and src/surf/cpu_cas01.cpp
(constraint per core-set, one variable per execution, sleep as a
0-penalty action with max_duration, speed/state profile events).
"""

from __future__ import annotations

from typing import List, Optional

from ..kernel.resource import (Action, ActionState, HeapType, Model, Resource,
                               SuspendStates, NO_MAX_DURATION, UpdateAlgo)
from ..ops import opstats
from ..ops.lmm_host import System
from ..utils.config import config
from ..utils.signal import Signal
from ..kernel import profile as profile_mod


class CpuAction(Action):
    """An execution (or sleep) on a CPU (reference cpu_interface.cpp)."""

    on_state_change = Signal()  # used by the energy/load plugins

    def update_remains_lazy(self, now: float) -> None:
        assert self.state_set is self.model.started_action_set, \
            "You're updating an action that is not running."
        assert self.sharing_penalty > 0, \
            "You're updating an action that seems suspended."
        delta = now - self.last_update
        if self.remains > 0:
            self.update_remains(self.last_value * delta)
        self.last_update = now
        self.last_value = self.variable.value

    def set_state(self, state: ActionState) -> None:
        super().set_state(state)
        CpuAction.on_state_change(self)


class CpuModel(Model):
    """Base CPU model: lazy heap pops + full sweeps (cpu_interface.cpp)."""

    def update_actions_state_lazy(self, now: float, delta: float) -> None:
        eps = config["surf/precision"]
        while (not self.action_heap.empty()
               and abs(self.action_heap.top_date() - now) < eps):
            action = self.action_heap.pop()
            action.finish(ActionState.FINISHED)

    def update_actions_state_full(self, now: float, delta: float) -> None:
        if len(self.started_action_set):
            opstats.bump("native_advances")
        # direct IntrusiveList traversal (removal-safe for the current
        # node): no O(V) list(...) allocation per advance
        for action in self.started_action_set:
            action.update_remains(action.variable.value * delta)
            action.update_max_duration(delta)
            if ((action.get_remains_no_update() <= 0
                 and action.variable.sharing_penalty > 0)
                    or (action.max_duration != NO_MAX_DURATION
                        and action.max_duration <= 0)):
                action.finish(ActionState.FINISHED)


class Cpu(Resource):
    """A host's processor: LMM constraint of capacity core_count*speed
    (reference cpu_interface.hpp + cpu_cas01.cpp)."""

    def __init__(self, model: CpuModel, host, speed_per_pstate: List[float],
                 core_count: int = 1):
        super().__init__(model, host.name,
                         model.system.constraint_new(
                             None, core_count * speed_per_pstate[0]))
        self.constraint.id = self
        self.host = host
        self.core_count = core_count
        self.speed_per_pstate = list(speed_per_pstate)
        self.pstate = 0
        self.speed_scale = 1.0   # availability-profile factor
        self.speed_peak = speed_per_pstate[0]
        self.speed_event: Optional[profile_mod.Event] = None
        self.state_event: Optional[profile_mod.Event] = None
        host.cpu = self

    # -- dynamics ---------------------------------------------------------
    def get_speed(self) -> float:
        return self.speed_peak * self.speed_scale

    def set_pstate(self, index: int) -> None:
        assert 0 <= index < len(self.speed_per_pstate), \
            f"Invalid pstate {index} (must be in [0, {len(self.speed_per_pstate)})"
        self.pstate = index
        self.speed_peak = self.speed_per_pstate[index]
        self.on_speed_change()

    def get_pstate_count(self) -> int:
        return len(self.speed_per_pstate)

    def on_speed_change(self) -> None:
        # reference CpuCas01::on_speed_change + Cpu::on_speed_change signal
        self.model.system.update_constraint_bound(
            self.constraint, self.core_count * self.speed_scale * self.speed_peak)
        for var in list(self.constraint.iter_variables()):
            action = var.id
            if action is not None:
                self.model.system.update_variable_bound(
                    action.variable,
                    getattr(action, "requested_core", 1)
                    * self.speed_scale * self.speed_peak)
        Host_on_speed_change(self.host)

    def is_used(self) -> bool:
        return self.constraint._acs_hook is not None  # constraint_used()

    def set_speed_profile(self, profile: profile_mod.Profile) -> None:
        self.speed_event = profile.schedule(self.model.engine.future_evt_set, self)

    def set_state_profile(self, profile: profile_mod.Profile) -> None:
        self.state_event = profile.schedule(self.model.engine.future_evt_set, self)
        # a profile whose first point is (0, 0) means the host exists
        # but starts OFF: apply eagerly so a deployment parsed before
        # run() already sees it down (platform-failures: Fafard's
        # '0 0' profile + 'Cannot launch actor on failed host')
        # (delta encoding: event_list[0].date is the delay before the
        # first real point, whose value sits in event_list[1])
        if (len(profile.event_list) > 1
                and profile.event_list[0].date == 0.0
                and profile.event_list[1].value == 0.0
                and self.is_on()):
            self.host.turn_off()

    def apply_event(self, event: profile_mod.Event, value: float) -> None:
        # reference CpuCas01::apply_event
        if event is self.speed_event:
            self.speed_scale = value
            self.on_speed_change()
        elif event is self.state_event:
            if value > 0:
                if not self.is_on():
                    # CpuCas01::apply_event logs at verbose before the
                    # reboot (platform-failures tesh runs with
                    # --log=surf_cpu.t:verbose to see these)
                    from ..utils import log as _log
                    _log.get_category("surf_cpu").verbose(
                        "Restart processes on host %s" % self.host.name)
                    self.host.turn_on()
            else:
                date = self.model.engine.now
                self.host.turn_off()
                for var in list(self.constraint.iter_variables()):
                    action = var.id
                    if action is not None and action.get_state() in (
                            ActionState.INITED, ActionState.STARTED,
                            ActionState.IGNORED):
                        action.finish_time = date
                        action.failure_cause = "host"
                        action.set_state(ActionState.FAILED)
        else:
            raise AssertionError("Unknown event!")

    # -- action factories -------------------------------------------------
    def execution_start(self, size: float, requested_cores: int = 1) -> CpuAction:
        raise NotImplementedError

    def sleep(self, duration: float) -> CpuAction:
        raise NotImplementedError


class CpuCas01Model(CpuModel):
    def __init__(self, engine, algo: UpdateAlgo):
        super().__init__(engine, algo)
        select = config["cpu/maxmin-selective-update"]
        if algo == UpdateAlgo.LAZY:
            assert select or config.is_default("cpu/maxmin-selective-update"), \
                "You cannot disable cpu selective update with lazy updates"
            select = True
        self.set_maxmin_system(System(select))
        if select and algo != UpdateAlgo.LAZY:
            # FULL-mode never drains the modified-actions list (see
            # NetworkCm02Model): selective bookkeeping here feeds the
            # warm-started device solve only
            self.system.modified_actions = None

    def create_cpu(self, host, speed_per_pstate: List[float],
                   core_count: int = 1) -> "CpuCas01":
        return CpuCas01(self, host, speed_per_pstate, core_count)


class CpuCas01(Cpu):
    def execution_start(self, size: float, requested_cores: int = 1) -> CpuAction:
        return CpuCas01Action(self.model, size, not self.is_on(),
                              self.speed_scale * self.speed_peak,
                              self.constraint, requested_cores)

    def sleep(self, duration: float) -> CpuAction:
        # reference CpuCas01::sleep (cpu_cas01.cpp:178-205)
        if duration > 0:
            duration = max(duration, config["surf/precision"])
        action = CpuCas01Action(self.model, 1.0, not self.is_on(),
                                self.speed_scale * self.speed_peak,
                                self.constraint)
        action.max_duration = duration
        action.suspended = SuspendStates.SLEEPING
        if duration == NO_MAX_DURATION:
            action.set_state(ActionState.IGNORED)
        self.model.system.update_variable_penalty(action.variable, 0.0)
        if self.model.is_lazy():
            self.model.action_heap.remove(action)
            # weight-0 variables are invisible to the solver: make sure the
            # max_duration is (re)considered at the next share computation
            if not action.in_modified_set and self.model.system.modified_actions is not None:
                action.in_modified_set = True
                self.model.system.modified_actions.insert(0, action)
        return action


class CpuCas01Action(CpuAction):
    def __init__(self, model: CpuModel, cost: float, failed: bool,
                 speed: float, constraint, requested_core: int = 1):
        variable = model.system.variable_new(
            None, 1.0 / requested_core, requested_core * speed, 1)
        super().__init__(model, cost, failed, variable)
        variable.id = self
        self.requested_core = requested_core
        if model.is_lazy():
            self.set_last_update()
        model.system.expand(constraint, variable, 1.0)


def Host_on_speed_change(host) -> None:
    """Hook point for plugins; the s4u layer connects its signal here."""
    if hasattr(host, "on_speed_change"):
        host.on_speed_change()
