"""Model registry: named model tables + --cfg selection.

Equivalent of the reference's model description tables
(src/surf/surf_interface.cpp:56-116) and the surf_*_model_init_* functions:
models are picked by the host/model, cpu/model, network/model,
storage/model flags.  New backends (e.g. a fully device-resident solver)
register here the same way the reference registered LMM_TPU candidates.
"""

from __future__ import annotations

from typing import Callable, Dict

from ..kernel.resource import UpdateAlgo
from ..utils.config import config
from .cpu import CpuCas01Model
from .host import HostCLM03Model
from .network import (NetworkCm02Model, NetworkConstantModel)
from .storage import StorageN11Model

network_models: Dict[str, Callable] = {}
cpu_models: Dict[str, Callable] = {}
host_models: Dict[str, Callable] = {}
storage_models: Dict[str, Callable] = {}


def _register_defaults() -> None:
    def init_lv08(engine):
        config.set_default("network/latency-factor", 13.01)
        config.set_default("network/bandwidth-factor", 0.97)
        config.set_default("network/weight-S", 20537.0)
        return NetworkCm02Model(engine)

    def init_cm02(engine):
        config.set_default("network/latency-factor", 1.0)
        config.set_default("network/bandwidth-factor", 1.0)
        config.set_default("network/weight-S", 0.0)
        return NetworkCm02Model(engine)

    def init_smpi(engine):
        from .network_smpi import NetworkSmpiModel
        return NetworkSmpiModel(engine)

    def init_ib(engine):
        from .network_ib import NetworkIBModel
        return NetworkIBModel(engine)

    def init_packet(engine):
        from .network_packet import NetworkPacketModel
        return NetworkPacketModel(engine)

    network_models.update({
        "LV08": init_lv08,
        "CM02": init_cm02,
        "SMPI": init_smpi,
        "IB": init_ib,
        "Constant": NetworkConstantModel,
        # the ns-3 role: packet-level co-simulation, embedded natively
        "Packet": init_packet,
    })

    def init_cas01(engine):
        algo = (UpdateAlgo.LAZY if config["cpu/optim"] == "Lazy"
                else UpdateAlgo.FULL)
        if config["cpu/optim"] == "TI":
            from .cpu_ti import CpuTiModel
            return CpuTiModel(engine)
        return CpuCas01Model(engine, algo)

    cpu_models["Cas01"] = init_cas01
    host_models["default"] = HostCLM03Model
    # 'compound' = separate cpu+network models composed by the host
    # model — exactly what HostCLM03Model does (sg_config.cpp treats
    # default as compound when cpu/network are set explicitly)
    host_models["compound"] = HostCLM03Model
    storage_models["default"] = StorageN11Model


_register_defaults()


def setup_models(engine) -> None:
    """Instantiate the configured models in the reference's creation order
    (host first so its wake-up sweep runs first, then cpu, then network)."""
    host_model_name = config["host/model"]
    if host_model_name == "ptask_L07":
        from .ptask_l07 import HostL07Model
        from ..utils import log as _log
        # surf_host_model_init_ptask_L07 announces the switch on
        # xbt_cfg (ptask_L07.cpp:21; energy-exec.tesh pins the line)
        _log.get_category("xbt_cfg").info(
            "Switching to the L07 model to handle parallel tasks.")
        HostL07Model(engine)
        return
    host_models[host_model_name](engine)
    engine.cpu_model = cpu_models[config["cpu/model"]](engine)
    network_models[config["network/model"]](engine)  # sets engine.network_model
    engine.storage_model = storage_models[config["storage/model"]](engine)
