"""InfiniBand contention model (reference src/surf/network_ib.cpp, after
Vienne's PhD measurements): each host tracks its active outgoing and
incoming comms; whenever one starts or ends, penalty factors are
recomputed over the affected connected component and applied as variable
bound updates in the LMM system."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..kernel.resource import ActionState
from ..utils.config import config, declare_flag
from .network import LinkImpl, NetworkAction
from .network_smpi import NetworkSmpiModel

declare_flag("smpi/IB-penalty-factors",
             "Correction factor to communications using Infiniband model "
             "with contention (default value based on Stampede cluster "
             "profiling)", "0.965;0.925;1.35")


class _ActiveComm:
    __slots__ = ("action", "destination", "init_rate")

    def __init__(self, action, destination):
        self.action = action
        self.destination = destination
        self.init_rate = -1.0


class _IBNode:
    __slots__ = ("id", "active_comms_up", "active_comms_down",
                 "nb_active_comms_down")

    def __init__(self, id_: int):
        self.id = id_
        self.active_comms_up: List[_ActiveComm] = []
        self.active_comms_down: Dict["_IBNode", int] = {}
        self.nb_active_comms_down = 0


class NetworkIBModel(NetworkSmpiModel):
    def __init__(self, engine):
        super().__init__(engine)
        parts = config["smpi/IB-penalty-factors"].split(";")
        assert len(parts) == 3, \
            "smpi/IB-penalty-factors must have 3 ';'-separated values"
        self.Be, self.Bs, self.ys = (float(p) for p in parts)
        self.active_nodes: Dict[str, _IBNode] = {}
        self.active_comms: Dict[NetworkAction,
                                Tuple[_IBNode, _IBNode]] = {}

        from .host import Host
        model = self

        def register(host) -> _IBNode:
            node = model.active_nodes.get(host.name)
            if node is None:
                node = _IBNode(len(model.active_nodes))
                model.active_nodes[host.name] = node
            return node

        def on_host_creation(host):
            register(host)

        # Engine-scoped subscriptions: auto-disconnected on engine
        # teardown, so stale IB models never fire into later engines.
        engine.connect_signal(Host.on_creation, on_host_creation)

        def on_communicate(action, src, dst):
            # reference IB_action_init_callback (network_ib.cpp:44-53);
            # hosts created before the model (or by paths that don't fire
            # on_creation) are registered lazily.
            a_src = register(src)
            a_dst = register(dst)
            model.active_comms[action] = (a_src, a_dst)
            model.update_IB_factors(action, a_src, a_dst, remove=False)
        engine.connect_signal(LinkImpl.on_communicate, on_communicate)

        def on_state_change(action):
            # reference IB_action_state_changed_callback (:28-42)
            if action.get_state() != ActionState.FINISHED:
                return
            pair = model.active_comms.pop(action, None)
            if pair is not None:
                model.update_IB_factors(action, pair[0], pair[1],
                                        remove=True)
        engine.connect_signal(NetworkAction.on_state_change, on_state_change)

    # -- penalty machinery (network_ib.cpp:115-214) -----------------------
    def compute_IB_factors(self, root: _IBNode) -> None:
        num_comm_out = len(root.active_comms_up)
        max_penalty_out = 0.0
        for comm in root.active_comms_up:
            my_penalty_out = 1.0
            if num_comm_out != 1:
                if comm.destination.nb_active_comms_down > 2:
                    my_penalty_out = num_comm_out * self.Bs * self.ys
                else:
                    my_penalty_out = num_comm_out * self.Bs
            max_penalty_out = max(max_penalty_out, my_penalty_out)

        eps = config["surf/precision"]
        for comm in root.active_comms_up:
            my_penalty_in = 1.0
            if comm.destination.nb_active_comms_down != 1:
                my_penalty_in = (comm.destination.active_comms_down[root]
                                 * self.Be
                                 * len(comm.destination.active_comms_down))
            penalty = max(my_penalty_in, max_penalty_out)

            rate_before = comm.action.variable.bound
            if comm.init_rate == -1.0:
                comm.init_rate = rate_before
            penalized_bw = (comm.init_rate / penalty if num_comm_out
                            else comm.init_rate)
            if abs(penalized_bw - rate_before) > eps:
                self.system.update_variable_bound(comm.action.variable,
                                                  penalized_bw)

    def _update_rec(self, root: _IBNode, updated: Dict[int, bool]) -> None:
        if updated.get(root.id):
            return
        self.compute_IB_factors(root)
        updated[root.id] = True
        for comm in root.active_comms_up:
            self._update_rec(comm.destination, updated)
        for node in root.active_comms_down:
            self._update_rec(node, updated)

    def update_IB_factors(self, action, src: _IBNode, dst: _IBNode,
                          remove: bool) -> None:
        if src is dst:   # local comms use the loopback
            return
        if remove:
            if dst.active_comms_down.get(src, 0) == 1:
                dst.active_comms_down.pop(src, None)
            elif src in dst.active_comms_down:
                dst.active_comms_down[src] -= 1
            dst.nb_active_comms_down -= 1
            src.active_comms_up = [c for c in src.active_comms_up
                                   if c.action is not action]
        else:
            src.active_comms_up.append(_ActiveComm(action, dst))
            dst.active_comms_down[src] = dst.active_comms_down.get(src, 0) + 1
            dst.nb_active_comms_down += 1
        self._update_rec(src, {})
