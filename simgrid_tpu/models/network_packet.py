"""Packet-level network model: the ns-3 co-simulation role, natively.

The reference can hand its flows to an embedded ns-3 simulation for
packet-accurate timing (src/surf/network_ns3.cpp), coupling the two
event loops through ``next_occurring_event_is_idempotent() == false``
(surf_c_bindings.cpp:58-77).  This model fills that role without an
external simulator: flows are segmented into MTU packets that traverse
their route store-and-forward, with per-link FIFO serialization — a
discrete-event packet simulation embedded in the model, driving the
same co-simulation hook in kernel/engine.py:surf_solve.

What it captures that the fluid models cannot: per-packet
serialization delay, pipeline fill across multi-hop routes, and
head-of-line blocking between flows sharing a link.  What it ignores
(like the reference's default ns-3 CSMA mapping): protocol dynamics
(no TCP windows, no drops — links are lossless FIFO queues).

Select with --cfg=network/model:Packet; MTU via --cfg=network/mtu.
"""

from __future__ import annotations

import heapq
import math
from typing import Dict, List, Optional

from ..kernel.resource import ActionState, UpdateAlgo
from ..ops.lmm_host import System
from ..utils.config import config
from .network import LinkImpl, NetworkAction, NetworkModel, SharingPolicy


class PacketFlow(NetworkAction):
    """One flow = a train of packets (the role of an ns-3 socket)."""

    def __init__(self, model, size: float, failed: bool, route, latency):
        super().__init__(model, size, failed)
        self.route: List[LinkImpl] = route
        self.latency = latency
        mtu = float(config["network/mtu"])
        self.n_packets = max(1, int(math.ceil(size / mtu))) if size > 0 \
            else 1
        self.packet_bytes = size / self.n_packets if size > 0 else 0.0
        self.packets_arrived = 0

    def update_remains_lazy(self, now: float) -> None:
        pass  # event-driven: remains is maintained on packet arrival


class PacketLink(LinkImpl):
    """A link with a FIFO transmit queue (lossless CSMA-like)."""

    def __init__(self, model, name: str, constraint):
        super().__init__(model, name, constraint)
        self.queue: List = []          # packets awaiting transmission
        self.busy = False

    def is_used(self) -> bool:
        return self.busy or bool(self.queue)


class _Packet:
    __slots__ = ("flow", "hop", "index")

    def __init__(self, flow: PacketFlow, index: int):
        self.flow = flow
        self.hop = 0               # position in flow.route
        self.index = index


class NetworkPacketModel(NetworkModel):
    """Store-and-forward packet simulation behind the Model interface."""

    def __init__(self, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        # LinkImpl wants a constraint; the system is never solved —
        # constraints only carry identity/bound for the s4u surface
        self.set_maxmin_system(System(selective_update=False))
        self._events: List = []    # heap of (time, seq, fn)
        self._seq = 0
        self.loopback = self.create_link(
            "__loopback__", config["network/loopback-bw"],
            config["network/loopback-lat"], SharingPolicy.FATPIPE)

    # -- event machinery ---------------------------------------------------
    def _at(self, time: float, fn) -> None:
        heapq.heappush(self._events, (time, self._seq, fn))
        self._seq += 1

    def next_occurring_event_is_idempotent(self) -> bool:
        return False

    def next_occurring_event(self, bound: float) -> float:
        """Co-simulation contract (hook in engine.surf_solve): `bound`
        is the candidate time_delta; return the delta to this model's
        next packet event if it is sooner (never later than a
        non-negative bound), or a negative value to keep the bound."""
        if not self._events:
            return -1.0
        delta = max(self._events[0][0] - self.engine.now, 0.0)
        if bound >= 0.0 and delta > bound:
            return -1.0
        return delta

    def update_actions_state(self, now: float, delta: float) -> None:
        eps = config["surf/precision"]
        while self._events and self._events[0][0] <= now + eps:
            _, _, fn = heapq.heappop(self._events)
            fn()

    # -- packet progression ------------------------------------------------
    def _enqueue(self, link: PacketLink, packet: _Packet,
                 time: float) -> None:
        link.queue.append(packet)
        if not link.busy:
            self._start_tx(link, time)

    def _start_tx(self, link: PacketLink, time: float) -> None:
        # drop queued packets of canceled/failed flows before grabbing one
        while (link.queue and
               link.queue[0].flow.get_state() is not ActionState.STARTED):
            link.queue.pop(0)
        if not link.queue:
            link.busy = False
            return
        link.busy = True
        packet = link.queue.pop(0)
        bw = link.get_bandwidth()
        tx = packet.flow.packet_bytes / bw if bw > 0 else 0.0
        done = time + tx

        def on_tx_done():
            self._start_tx(link, done)
            arrival = done + link.get_latency()
            self._at(arrival, lambda: self._arrive(packet, arrival))
        self._at(done, on_tx_done)

    def _arrive(self, packet: _Packet, time: float) -> None:
        flow = packet.flow
        if flow.get_state() is not ActionState.STARTED:
            return  # flow canceled/failed mid-transfer: drop its packets
        packet.hop += 1
        if packet.hop < len(flow.route):
            nxt = flow.route[packet.hop]
            self._at(time, lambda: self._enqueue(nxt, packet, time))
            return
        # reached the destination host (finish_time = engine.now, which
        # the event scheduler has advanced to exactly this event)
        flow.packets_arrived += 1
        flow.update_remains(flow.packet_bytes)
        if flow.packets_arrived >= flow.n_packets:
            flow.finish(ActionState.FINISHED)

    # -- Model interface ---------------------------------------------------
    def create_link(self, name: str, bandwidth: float, latency: float,
                    policy: SharingPolicy = SharingPolicy.SHARED
                    ) -> PacketLink:
        constraint = self.system.constraint_new(None, bandwidth)
        if policy == SharingPolicy.FATPIPE:
            constraint.sharing_policy = SharingPolicy.FATPIPE
        link = PacketLink(self, name, constraint)
        link.bandwidth_peak = bandwidth
        link.latency_peak = latency
        LinkImpl.on_creation(link)
        return link

    def communicate(self, src, dst, size: float,
                    rate: float) -> PacketFlow:
        route: List[LinkImpl] = []
        if src is dst:
            try:
                latency = src.route_to(dst, route)
            except AssertionError:
                route, latency = [], 0.0
            if not route and latency <= 0:
                route = [self.loopback]
                latency = self.loopback.get_latency()
        else:
            latency = src.route_to(dst, route)
        assert route or latency > 0, \
            f"No route between '{src.name}' and '{dst.name}'"

        failed = any(not link.is_on() for link in route)
        flow = PacketFlow(self, size, failed, route, latency)
        flow.rate = rate
        if not failed:
            now = self.engine.now
            # per-hop propagation is charged at each arrival; any extra
            # route latency beyond the links' own (zone gateways) is
            # charged once up front
            extra = max(latency - sum(l.get_latency() for l in route),
                        0.0)
            t0 = now + extra
            if route:
                for i in range(flow.n_packets):
                    packet = _Packet(flow, i)
                    first = route[0]
                    self._at(t0, (lambda p=packet, l=first, t=t0:
                                  self._enqueue(l, p, t)))
            else:
                # latency-only route (vivaldi-style)
                self._at(t0, lambda: self._complete_nolink(flow))
        LinkImpl.on_communicate(flow, src, dst)
        return flow

    def _complete_nolink(self, flow: PacketFlow) -> None:
        flow.packets_arrived = flow.n_packets
        flow.update_remains(flow.get_remains_no_update())
        flow.finish(ActionState.FINISHED)
