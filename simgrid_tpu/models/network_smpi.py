"""SMPI network model: CM02 with piecewise per-message-size bandwidth and
latency correction factors calibrated on MPI ping-pongs (reference
src/surf/network_smpi.cpp; factors from the IPDPS'11 SMPI paper, defaults
from sg_config.cpp:336-347)."""

from __future__ import annotations

from typing import List, Tuple

from ..utils.config import config, declare_flag
from .network import NetworkCm02Model

declare_flag("smpi/bw-factor",
             "Bandwidth factors for smpi. Format: 'threshold0:value0;...'; "
             "if size >= thresholdN return valueN.",
             "65472:0.940694;15424:0.697866;9376:0.58729;5776:1.08739;"
             "3484:0.77493;1426:0.608902;732:0.341987;257:0.338112;"
             "0:0.812084")
declare_flag("smpi/lat-factor", "Latency factors for smpi.",
             "65472:11.6436;15424:3.48845;9376:2.59299;5776:2.18796;"
             "3484:1.88101;1426:1.61075;732:1.9503;257:1.95341;0:2.01467")


def parse_size_factor(spec: str) -> List[Tuple[float, float]]:
    """'threshold:value;...' sorted ascending by threshold."""
    out = []
    for part in spec.split(";"):
        if not part:
            continue
        nums = part.split(":")
        out.append((float(nums[0]), float(nums[1])))
    out.sort(key=lambda t: t[0])
    return out


def staged_value(table: List[Tuple[float, float]], size: float) -> float:
    """The value of the last threshold below `size` (network_smpi.cpp:
    50-84 evaluation: factors apply for sizes *above* their threshold)."""
    current = 1.0
    for threshold, value in table:
        if size <= threshold:
            return current
        current = value
    return current


class NetworkSmpiModel(NetworkCm02Model):
    def __init__(self, engine):
        config.set_default("network/weight-S", 8775.0)
        super().__init__(engine)
        self._bw_factor = parse_size_factor(config["smpi/bw-factor"])
        self._lat_factor = parse_size_factor(config["smpi/lat-factor"])

    def get_bandwidth_factor(self, size: float) -> float:
        return staged_value(self._bw_factor, size)

    def get_latency_factor(self, size: float) -> float:
        return staged_value(self._lat_factor, size)

    def get_bandwidth_constraint(self, rate: float, bound: float,
                                 size: float) -> float:
        if rate < 0:
            return bound
        return min(bound, rate * self.get_bandwidth_factor(size))
