"""Network models: base interface, CM02/LV08 flow-level TCP model, constant.

Semantics from the reference's src/surf/network_interface.cpp (factor
hooks, latency accounting in next-event) and src/surf/network_cm02.cpp:
one LMM constraint per link, one variable per flow expanded on every link
of the route; LV08 corrections (latency x13.01, bandwidth x0.97, RTT
weight S=20537 added to the penalty per link); latency modeled as a
0-penalty phase ended by a 'latency hat' heap event (lazy) or per-delta
decrement (full); optional cross-traffic expands the reverse route at
weight 0.05; TCP-gamma window bound rate <= gamma/(2*RTT).
"""

from __future__ import annotations

import math
from typing import List, Optional

from ..kernel.resource import (Action, ActionState, HeapType, Model, Resource,
                               NO_MAX_DURATION, UpdateAlgo)
from ..kernel import profile as profile_mod
from ..ops import opstats
from ..ops.lmm_host import SharingPolicy, System, double_update
from ..utils.config import config
from ..utils.signal import Signal


class NetworkAction(Action):
    """A flow (reference network_interface.hpp NetworkAction)."""

    on_state_change = Signal()

    def __init__(self, model, size: float, failed: bool):
        super().__init__(model, size, failed)
        self.latency = 0.0
        self.lat_current = 0.0
        self.rate = -1.0
        # True while this running action is counted in the model's
        # latency_phase_count census (FULL-mode fast path)
        self._lat_counted = False
        # Links on the route whose bandwidth is currently 0: the flow is
        # parked (infinite penalty) while any exist.  sharing_penalty keeps
        # only the *finite* part so a later bandwidth restore can undo the
        # park without inf-inf arithmetic (C++ would NaN here).
        self.parked_links = 0
        # link name -> how many weight-S terms of this flow that link
        # carries (occurrences on the FORWARD route).  set_bandwidth
        # must adjust exactly these: the constraint also holds
        # cross-traffic flows (reverse route, weight 0.05) that carry
        # no weight-S term for this link at all, and a flow whose
        # forward and reverse routes share a link sits on the
        # constraint twice but pays the term only once.
        self.ws_links: dict = {}

    @property
    def effective_penalty(self) -> float:
        return math.inf if self.parked_links else self.sharing_penalty

    def set_state(self, state: ActionState) -> None:
        if self._lat_counted and state != ActionState.STARTED:
            # leaving the started set while still in the latency phase
            # (failure, cancel, early finish): drop it from the model's
            # latency census
            self._lat_counted = False
            self.model.latency_phase_count -= 1
        super().set_state(state)
        NetworkAction.on_state_change(self)

    def is_running(self) -> bool:
        return self.state_set is self.model.started_action_set

    def update_remains_lazy(self, now: float) -> None:
        # reference NetworkCm02Action::update_remains_lazy
        if not self.is_running():
            return
        delta = now - self.last_update
        if self.remains > 0:
            self.update_remains(self.last_value * delta)
        self.update_max_duration(delta)
        if ((self.remains <= 0 and self.variable.sharing_penalty > 0)
                or (self.max_duration != NO_MAX_DURATION
                    and self.max_duration <= 0)):
            self.finish(ActionState.FINISHED)
            self.model.action_heap.remove(self)
        self.last_update = now
        self.last_value = self.variable.value


class LinkImpl(Resource):
    """A network link (reference network_interface.cpp LinkImpl)."""

    on_creation = Signal()
    on_destruction = Signal()
    on_state_change = Signal()
    on_bandwidth_change = Signal()
    on_communicate = Signal()   # (action, src, dst)

    def __init__(self, model, name: str, constraint):
        super().__init__(model, name, constraint)
        constraint.id = self
        self.bandwidth_peak = 0.0
        self.bandwidth_scale = 1.0
        self.latency_peak = 0.0
        self.latency_scale = 1.0
        self.properties = {}
        self.bandwidth_event: Optional[profile_mod.Event] = None
        self.latency_event: Optional[profile_mod.Event] = None
        self.state_event: Optional[profile_mod.Event] = None
        model.engine.links[name] = self

    def get_bandwidth(self) -> float:
        return self.bandwidth_peak * self.bandwidth_scale

    def get_latency(self) -> float:
        return self.latency_peak * self.latency_scale

    def get_sharing_policy(self) -> SharingPolicy:
        return self.constraint.sharing_policy

    def is_used(self) -> bool:
        return self.constraint._acs_hook is not None

    def turn_on(self) -> None:
        if not self.is_on_flag:
            self.is_on_flag = True
            LinkImpl.on_state_change(self)

    def turn_off(self) -> None:
        # reference LinkImpl::turn_off + network_cm02 state event: fail all
        # actions crossing this link
        if self.is_on_flag:
            self.is_on_flag = False
            LinkImpl.on_state_change(self)
            now = self.model.engine.now
            for var in list(self.constraint.iter_variables()):
                action = var.id
                if action is not None and action.get_state() in (
                        ActionState.INITED, ActionState.STARTED,
                        ActionState.IGNORED):
                    action.finish_time = now
                    # the comm post path maps link-killed flows to
                    # LINK_FAILURE and endpoint-host kills to
                    # SRC/DST_HOST_FAILURE; the cause is recorded here
                    # because the FAILED state alone cannot tell them apart
                    action.failure_cause = "link"
                    action.set_state(ActionState.FAILED)

    def set_bandwidth_profile(self, profile: profile_mod.Profile) -> None:
        self.bandwidth_event = profile.schedule(
            self.model.engine.future_evt_set, self)

    def set_latency_profile(self, profile: profile_mod.Profile) -> None:
        self.latency_event = profile.schedule(
            self.model.engine.future_evt_set, self)

    def set_state_profile(self, profile: profile_mod.Profile) -> None:
        self.state_event = profile.schedule(
            self.model.engine.future_evt_set, self)


class NetworkModel(Model):
    """Base network model (network_interface.cpp)."""

    def __init__(self, engine, algo: UpdateAlgo):
        super().__init__(engine, algo)
        engine.network_model = self
        self.loopback: Optional[LinkImpl] = None
        #: running actions still in their latency phase (FULL mode).
        #: Maintained so next_occurring_event_full can skip its O(V)
        #: latency walk in the common all-latencies-paid drain phase.
        self.latency_phase_count = 0

    def get_latency_factor(self, size: float) -> float:
        return config["network/latency-factor"]

    def get_bandwidth_factor(self, size: float) -> float:
        return config["network/bandwidth-factor"]

    def get_bandwidth_constraint(self, rate: float, bound: float,
                                 size: float) -> float:
        return rate

    def next_occurring_event_full(self, now: float) -> float:
        # reference NetworkModel::next_occuring_event_full: account for the
        # latency phase of not-yet-flowing actions.  The walk is O(V)
        # per advance and a pure-drain phase (all latencies paid) never
        # needs it: the census counter skips it outright.
        min_res = super().next_occurring_event_full(now)
        if self.latency_phase_count:
            for action in self.started_action_set:
                if action.latency > 0:
                    min_res = action.latency if min_res < 0 \
                        else min(min_res, action.latency)
        return min_res

    def communicate(self, src, dst, size: float, rate: float) -> NetworkAction:
        raise NotImplementedError

    def create_link(self, name: str, bandwidth: float, latency: float,
                    policy: SharingPolicy = SharingPolicy.SHARED) -> LinkImpl:
        raise NotImplementedError


class NetworkCm02Model(NetworkModel):
    """The LV08/CM02 fluid model (network_cm02.cpp)."""

    def __init__(self, engine):
        algo = (UpdateAlgo.FULL if config["network/optim"] == "Full"
                else UpdateAlgo.LAZY)
        super().__init__(engine, algo)
        select = config["network/maxmin-selective-update"]
        if config["network/optim"] == "Lazy":
            assert select or config.is_default("network/maxmin-selective-update"), \
                "You cannot disable network selective update with lazy updates"
            select = True
        self.set_maxmin_system(System(select))
        if select and config["network/optim"] == "Full":
            # FULL-mode sharing recomputation never drains the
            # modified-actions list; keeping it would pin every retired
            # action forever.  Selective bookkeeping here tracks
            # constraints only — the input of the warm-started device
            # solve (ops.lmm_warm), which is what Full+selective buys:
            # mutating phases re-solve only the modified component.
            self.system.modified_actions = None
        # device-resident drain fast path (ops.drain_path): FULL-mode
        # pure-drain phases delegate batches of advances to the
        # superstep executor; a no-op until its preconditions hold
        from ..ops.drain_path import DrainFastPath
        self.drain_fastpath = DrainFastPath(self)
        self.loopback = self.create_link(
            "__loopback__", config["network/loopback-bw"],
            config["network/loopback-lat"], SharingPolicy.FATPIPE)

    def create_link(self, name: str, bandwidth: float, latency: float,
                    policy: SharingPolicy = SharingPolicy.SHARED) -> "NetworkCm02Link":
        if policy == SharingPolicy.WIFI:
            # single-rate WIFI declaration: one modulation level
            if latency:
                raise ValueError(
                    f"WIFI link {name!r}: latency is not modeled on "
                    "access points (the reference hardcodes 0, "
                    "network_cm02.cpp:385) — refusing to drop it "
                    "silently")
            return NetworkWifiLink(self, name, [bandwidth])
        return NetworkCm02Link(self, name, bandwidth, latency, policy)

    def create_wifi_link(self, name: str,
                         bandwidths: List[float]) -> "NetworkWifiLink":
        """An access-point link with one bandwidth per modulation level
        (reference NetworkCm02Model::create_link, network_cm02.cpp:93-97)."""
        return NetworkWifiLink(self, name, bandwidths)

    def update_actions_state_lazy(self, now: float, delta: float) -> None:
        eps = config["surf/precision"]
        while (not self.action_heap.empty()
               and abs(self.action_heap.top_date() - now) < eps):
            action = self.action_heap.pop()
            if action.heap_type == HeapType.LATENCY:
                # latency paid: open the flow
                self.system.update_variable_penalty(action.variable,
                                                    action.effective_penalty)
                self.action_heap.remove(action)
                action.set_last_update()
            else:
                action.finish(ActionState.FINISHED)
                self.action_heap.remove(action)

    def capture_drain_scenario(self):
        """Snapshot the CURRENT pure-drain phase for the batched
        campaign executor (parallel.campaign.Campaign.from_engine):
        flattened arrays + slot/link maps, or None when the phase is
        not a pure drain.  Gated exactly like the drain fast path —
        FULL mode with every started flow past its latency and
        unconstrained by deadlines — so a campaign can only fork from
        a state the fast path itself could serve."""
        from ..ops import drain_path
        if self.is_lazy() or self.latency_phase_count:
            return None
        return drain_path.capture_scenario(self)

    def next_occurring_event_full(self, now: float) -> float:
        dt = self.drain_fastpath.serve(now)
        if dt is not None:
            return dt
        return super().next_occurring_event_full(now)

    def update_actions_state_full(self, now: float, delta: float) -> None:
        if self.drain_fastpath.apply(now, delta):
            return
        if len(self.started_action_set):
            opstats.bump("native_advances")
        eps = config["surf/precision"]
        # direct IntrusiveList traversal (removal-safe for the current
        # node): no O(V) list(...) allocation per advance
        for action in self.started_action_set:
            deltap = delta
            if action.latency > 0:
                if action.latency > deltap:
                    action.latency = double_update(action.latency, deltap, eps)
                    deltap = 0.0
                else:
                    deltap = double_update(deltap, action.latency, eps)
                    action.latency = 0.0
                if action.latency <= 0.0:
                    if action._lat_counted:
                        action._lat_counted = False
                        self.latency_phase_count -= 1
                    if not action.is_suspended():
                        self.system.update_variable_penalty(
                            action.variable, action.effective_penalty)
            if not action.variable.get_number_of_constraint():
                # no link on the route (e.g. vivaldi): complete immediately
                action.update_remains(action.get_remains_no_update())
            action.update_remains(action.variable.value * delta)
            if action.max_duration != NO_MAX_DURATION:
                action.update_max_duration(delta)
            if ((action.get_remains_no_update() <= 0
                 and action.variable.sharing_penalty > 0)
                    or (action.max_duration != NO_MAX_DURATION
                        and action.max_duration <= 0)):
                action.finish(ActionState.FINISHED)

    def communicate(self, src, dst, size: float, rate: float) -> NetworkAction:
        # reference NetworkCm02Model::communicate (network_cm02.cpp:165-279)
        route: List[LinkImpl] = []
        if src is dst:
            # Hosts without an explicit self-route ride the default
            # loopback (the reference's cluster/smpirun fabrics declare
            # per-host loopbacks; flat platforms get the model's). The
            # lookup failure is tolerated only for the self case, and
            # an empty result (asserts stripped under -O) falls back
            # the same way.
            try:
                latency = src.route_to(dst, route)
            except AssertionError:
                route, latency = [], 0.0
            if not route and latency <= 0:
                route = [self.loopback]
                latency = self.loopback.get_latency()
        else:
            latency = src.route_to(dst, route)
        assert route or latency > 0, \
            (f"No route between '{src.name}' and '{dst.name}'")

        failed = any(not link.is_on() for link in route)
        back_route: List[LinkImpl] = []
        crosstraffic = config["network/crosstraffic"]
        if crosstraffic:
            if src is dst:
                back_route = list(route)   # self-comm: same loopback
            else:
                dst.route_to(src, back_route)
            if not failed:
                failed = any(not link.is_on() for link in back_route)

        action = NetworkAction(self, size, failed)
        action.sharing_penalty = latency
        action.latency = latency
        action.rate = rate
        if self.is_lazy():
            action.set_last_update()

        weight_s = config["network/weight-S"]
        if weight_s > 0:
            for link in route:
                bw = link.get_bandwidth()
                if bw > 0:
                    action.sharing_penalty += weight_s / bw
                else:
                    action.parked_links += 1
                action.ws_links[link.name] = \
                    action.ws_links.get(link.name, 0) + 1

        bw_factor = self.get_bandwidth_factor(size)
        bandwidth_bound = -1.0 if not route else bw_factor * route[0].get_bandwidth()
        for link in route:
            bandwidth_bound = min(bandwidth_bound,
                                  bw_factor * link.get_bandwidth())

        action.lat_current = action.latency
        action.latency *= self.get_latency_factor(size)
        action.rate = self.get_bandwidth_constraint(action.rate,
                                                    bandwidth_bound, size)
        constraints_per_variable = len(route) + len(back_route)

        if action.latency > 0:
            action.variable = self.system.variable_new(
                action, 0.0, -1.0, constraints_per_variable)
            if self.is_lazy():
                date = action.latency + action.last_update
                type_ = HeapType.NORMAL if not route else HeapType.LATENCY
                self.action_heap.insert(action, date, type_)
            elif action.state_set is self.started_action_set:
                # FULL mode latency census (skips the O(V) walk in
                # next_occurring_event_full once all latencies are paid)
                action._lat_counted = True
                self.latency_phase_count += 1
        else:
            action.variable = self.system.variable_new(
                action, 1.0, -1.0, constraints_per_variable)
            if (action.sharing_penalty <= 0 and weight_s <= 0
                    and not action.parked_links):
                # pure CM02 (weight-S 0) on a zero-latency route: the
                # variable runs at penalty 1 immediately, and the lazy
                # drain's bogus-priority skip must not ignore the
                # action or its completion never gets scheduled
                # (energy-link tesh: 25kB over the latency-0 bus).
                # Parked weight-S flows keep 0: un-parking re-adds
                # their S/bw terms from that base.
                action.sharing_penalty = 1.0

        gamma = config["network/TCP-gamma"]
        if action.rate < 0:
            self.system.update_variable_bound(
                action.variable,
                gamma / (2.0 * action.lat_current) if action.lat_current > 0
                else -1.0)
        else:
            self.system.update_variable_bound(
                action.variable,
                min(action.rate, gamma / (2.0 * action.lat_current))
                if action.lat_current > 0 else action.rate)

        for link in route:
            if link.get_sharing_policy() == SharingPolicy.WIFI:
                # WIFI constraint capacity is normalized AIRTIME (1.0);
                # a station's flow consumes airtime at 1/host_rate per
                # byte/s, so faster modulations leave more airtime for
                # the others (reference network_cm02.cpp:240-260).
                # Explicit raises (not bare asserts): user-input
                # validation must survive python -O.
                if crosstraffic:
                    raise AssertionError(
                        "Cross-traffic is not yet supported when using "
                        "WIFI. Please use --cfg=network/crosstraffic:0")
                src_rate = link.get_host_rate(src)
                dst_rate = link.get_host_rate(dst)
                if src_rate < 0 and dst_rate < 0:
                    raise AssertionError(
                        "Some stations are not associated to any access "
                        "point. Make sure to call set_host_rate on all "
                        "stations.")
                # when BOTH endpoints are stations of this AP the src
                # modulation wins — the reference's own open TODO
                # (network_cm02.cpp:249 "for the moment we use src rate")
                rate = src_rate if src_rate >= 0 else dst_rate
                self.system.expand(link.constraint, action.variable,
                                   1.0 / rate)
            else:
                self.system.expand(link.constraint, action.variable, 1.0)
        if crosstraffic:
            for link in back_route:
                self.system.expand(link.constraint, action.variable, 0.05)

        LinkImpl.on_communicate(action, src, dst)
        return action


class NetworkWifiLink(LinkImpl):
    """An 802.11 access point: the LMM constraint shares normalized
    AIRTIME (capacity 1.0 after the bandwidth factor), per-station
    modulation levels translate byte rates into airtime weights at
    expand time (reference NetworkWifiLink, network_cm02.hpp:56-80,
    network_cm02.cpp:383-420).  Stations associate with
    set_host_rate(host, level); level indexes the bandwidths list."""

    def __init__(self, model: NetworkCm02Model, name: str,
                 bandwidths: List[float]):
        bw_factor = config["network/bandwidth-factor"]
        # bound = bw_factor * (1/bw_factor) = exactly 1.0 of airtime
        super().__init__(model, name,
                         model.system.constraint_new(None, 1.0))
        self.constraint.id = self
        self.constraint.sharing_policy = SharingPolicy.WIFI
        self.bandwidth_peak = 1.0 / bw_factor
        self.latency_peak = 0.0
        self.bandwidths = list(bandwidths)
        self.host_rates: dict = {}
        LinkImpl.on_creation(self)

    def get_sharing_policy(self) -> SharingPolicy:
        return SharingPolicy.WIFI

    def set_host_rate(self, host, rate_level: int) -> None:
        self.host_rates[host.name] = rate_level

    def get_host_rate(self, host) -> float:
        level = self.host_rates.get(host.name)
        if level is None:
            return -1.0
        assert 0 <= level < len(self.bandwidths), \
            f"Host {host.name!r} has an invalid rate {level}"
        return self.bandwidths[level] * self.bandwidth_scale

    def apply_event(self, event: profile_mod.Event, value: float) -> None:
        if event is self.state_event:
            if value > 0:
                self.turn_on()
            else:
                self.turn_off()
        else:
            raise AssertionError("Unknown event on a WIFI link!")


class NetworkCm02Link(LinkImpl):
    def __init__(self, model: NetworkCm02Model, name: str, bandwidth: float,
                 latency: float, policy: SharingPolicy):
        bw_factor = config["network/bandwidth-factor"]
        super().__init__(model, name,
                         model.system.constraint_new(None, bw_factor * bandwidth))
        self.constraint.id = self
        self.bandwidth_peak = bandwidth
        self.latency_peak = latency
        if policy == SharingPolicy.FATPIPE:
            self.constraint.sharing_policy = SharingPolicy.FATPIPE
        LinkImpl.on_creation(self)

    def apply_event(self, event: profile_mod.Event, value: float) -> None:
        if event is self.bandwidth_event:
            self.set_bandwidth(value)
        elif event is self.latency_event:
            self.set_latency(value)
        elif event is self.state_event:
            if value > 0:
                self.turn_on()
            else:
                self.turn_off()
        else:
            raise AssertionError("Unknown event!")

    def set_bandwidth(self, value: float) -> None:
        # reference NetworkCm02Link::set_bandwidth (network_cm02.cpp:326-349)
        old = self.bandwidth_peak * self.bandwidth_scale
        self.bandwidth_peak = value
        bw_factor = config["network/bandwidth-factor"]
        self.model.system.update_constraint_bound(
            self.constraint,
            bw_factor * self.bandwidth_peak * self.bandwidth_scale)
        LinkImpl.on_bandwidth_change(self)
        weight_s = config["network/weight-S"]
        if weight_s > 0:
            # A zero-bandwidth trace event parks the flows (infinite
            # penalty) instead of aborting; the park is tracked as a count
            # so a later restore works (delta arithmetic with inf would NaN).
            # Each flow is adjusted by its recorded number of weight-S
            # terms for THIS link (ws_links): iter_variables yields one
            # entry per element, and with cross-traffic a constraint also
            # holds reverse flows that carry no term for this link.
            seen: set = set()
            for var in list(self.constraint.iter_variables()):
                action = var.id
                if isinstance(action, NetworkAction) and id(var) not in seen:
                    seen.add(id(var))
                    n = action.ws_links.get(self.name, 0)
                    if not n:
                        continue
                    if old > 0:
                        action.sharing_penalty -= n * (weight_s / old)
                    else:
                        action.parked_links -= n
                    if value > 0:
                        action.sharing_penalty += n * (weight_s / value)
                    else:
                        action.parked_links += n
                    if not action.is_suspended():
                        self.model.system.update_variable_penalty(
                            action.variable, action.effective_penalty)

    def set_latency(self, value: float) -> None:
        # reference NetworkCm02Link::set_latency (network_cm02.cpp:351-381)
        delta = value - self.latency_peak
        self.latency_peak = value
        gamma = config["network/TCP-gamma"]
        for var in list(self.constraint.iter_variables()):
            action = var.id
            if not isinstance(action, NetworkAction):
                continue
            action.lat_current += delta
            action.sharing_penalty += delta
            lat_bound = (gamma / (2.0 * action.lat_current)
                         if action.lat_current else math.inf)
            if action.rate < 0:
                self.model.system.update_variable_bound(
                    action.variable, lat_bound)
            else:
                self.model.system.update_variable_bound(
                    action.variable, min(action.rate, lat_bound))
            if not action.is_suspended():
                self.model.system.update_variable_penalty(
                    action.variable, action.effective_penalty)


class NetworkConstantModel(NetworkModel):
    """Every communication takes a constant time (network_constant.cpp):
    the scalability baseline stripping network physics.  No links, no LMM;
    latency = network/latency-factor."""

    def __init__(self, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        self.set_maxmin_system(System(False))

    def create_link(self, name, bandwidth, latency, policy=SharingPolicy.SHARED):
        raise AssertionError(
            f"Refusing to create the link {name}: there is no link in the "
            "Constant network model (use routing='None')")

    def next_occurring_event(self, now: float) -> float:
        min_res = -1.0
        for action in self.started_action_set:
            if action.latency > 0 and (min_res < 0 or action.latency < min_res):
                min_res = action.latency
        return min_res

    def update_actions_state(self, now: float, delta: float) -> None:
        eps = config["surf/precision"]
        for action in self.started_action_set:
            if action.latency > 0:
                if action.latency > delta:
                    action.latency = double_update(action.latency, delta, eps)
                else:
                    action.latency = 0.0
            action.update_remains(action.cost * delta / action.initial_latency)
            action.update_max_duration(delta)
            if (action.get_remains_no_update() <= 0
                    or (action.max_duration != NO_MAX_DURATION
                        and action.max_duration <= 0)):
                action.finish(ActionState.FINISHED)

    def communicate(self, src, dst, size: float, rate: float) -> NetworkAction:
        action = NetworkConstantAction(self, size,
                                       config["network/latency-factor"])
        LinkImpl.on_communicate(action, src, dst)
        return action


class NetworkConstantAction(NetworkAction):
    def __init__(self, model, size: float, latency: float):
        super().__init__(model, size, False)
        self.latency = latency
        self.initial_latency = latency
        if latency <= 0.0:
            self.set_state(ActionState.FINISHED)
