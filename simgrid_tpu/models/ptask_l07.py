"""ptask L07 host model (reference src/surf/ptask_L07.cpp): parallel
tasks consuming CPU flops and link bytes *simultaneously*, solved with
the fair-bottleneck solver.  One LMM variable per parallel task spans
every involved cpu constraint (weight = flops on that host) and link
constraint (weight = summed bytes through that link)."""

from __future__ import annotations

from typing import Dict, List, Optional

from ..kernel import profile as profile_mod
from ..kernel.resource import (ActionState, Model, NO_MAX_DURATION,
                               SuspendStates, UpdateAlgo, double_update)
from ..ops.fair_bottleneck import FairBottleneck
from ..ops.lmm_host import SharingPolicy
from ..utils.config import config
from .cpu import Cpu, CpuAction, CpuModel
from .network import LinkImpl, NetworkModel


class HostL07Model(Model):
    """The composite ptask model owning the shared fair-bottleneck
    system (ptask_L07.cpp:32-45)."""

    def __init__(self, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        self.set_maxmin_system(FairBottleneck(True))
        engine.host_model = self
        engine.network_model = NetworkL07Model(self, engine)
        engine.cpu_model = CpuL07Model(self, engine)
        from .storage import StorageN11Model
        engine.storage_model = StorageN11Model(engine)

    def next_occurring_event(self, now: float) -> float:
        min_date = self.next_occurring_event_full(now)
        for action in self.started_action_set:
            if action.latency > 0 and (min_date < 0
                                       or action.latency < min_date):
                min_date = action.latency
        return min_date

    def update_actions_state(self, now: float, delta: float) -> None:
        # ptask_L07.cpp:86-134
        eps = config["surf/precision"]
        for action in self.started_action_set:
            if action.latency > 0:
                if action.latency > delta:
                    action.latency = double_update(action.latency, delta, eps)
                else:
                    action.latency = 0.0
                if action.latency <= 0.0 and not action.is_suspended():
                    action.update_bound()
                    self.system.update_variable_penalty(action.variable, 1.0)
                    action.set_last_update()
            action.update_remains(action.variable.value * delta)
            action.update_max_duration(delta)

            if ((action.get_remains_no_update() <= 0
                 and action.variable.sharing_penalty > 0)
                    or (action.max_duration != NO_MAX_DURATION
                        and action.max_duration <= 0)):
                action.finish(ActionState.FINISHED)
                continue

            # fail the action if any underlying resource is off
            for elem in action.variable.cnsts:
                resource = elem.constraint.id
                if resource is not None and not resource.is_on():
                    action.finish(ActionState.FAILED)
                    break

    def execute_parallel(self, host_list, flops_amount, bytes_amount,
                         rate: float) -> "L07Action":
        return L07Action(self, host_list, flops_amount, bytes_amount, rate)


class CpuL07Model(CpuModel):
    """CPU facet sharing the host model's fair-bottleneck system."""

    def __init__(self, host_model: HostL07Model, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        self.host_model = host_model
        self.system = host_model.system

    def create_cpu(self, host, speed_per_pstate: List[float],
                   core_count: int = 1) -> "CpuL07":
        return CpuL07(self, host, speed_per_pstate, core_count)

    def next_occurring_event(self, now: float) -> float:
        return -1.0      # the host model owns the actions

    def update_actions_state(self, now: float, delta: float) -> None:
        pass


class NetworkL07Model(NetworkModel):
    """Network facet sharing the fair-bottleneck system."""

    def __init__(self, host_model: HostL07Model, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        self.host_model = host_model
        self.system = host_model.system
        self.loopback = self.create_link(
            "__loopback__", 498000000.0, 0.000015, SharingPolicy.FATPIPE)

    def create_link(self, name: str, bandwidth: float, latency: float,
                    policy: SharingPolicy = SharingPolicy.SHARED
                    ) -> "LinkL07":
        return LinkL07(self, name, bandwidth, latency, policy)

    def communicate(self, src, dst, size: float, rate: float) -> "L07Action":
        # a 2-host ptask with only bytes (ptask_L07.cpp:211-222)
        flops = [0.0, 0.0]
        bytes_ = [0.0, size, 0.0, 0.0]   # flat [src][dst] matrix
        return self.host_model.execute_parallel([src, dst], flops, bytes_,
                                                rate)

    def next_occurring_event(self, now: float) -> float:
        return -1.0

    def update_actions_state(self, now: float, delta: float) -> None:
        pass


class CpuL07(Cpu):
    def __init__(self, model, host, speed_per_pstate, core_count=1):
        super().__init__(model, host, speed_per_pstate, core_count)
        # the L07 cpu constraint ignores multicore: the reference
        # creates it with the bare pstate speed (ptask_L07.cpp:240),
        # not core_count x speed (energy-exec ptask tesh pins this)
        model.system.update_constraint_bound(self.constraint,
                                             speed_per_pstate[0])

    def execution_start(self, size: float,
                        requested_cores: int = 1) -> "L07Action":
        flops = [size]
        return self.model.host_model.execute_parallel([self.host], flops,
                                                      None, -1.0)

    def sleep(self, duration: float) -> "L07Action":
        action = self.execution_start(1.0)
        action.set_max_duration(duration)
        action.suspended = SuspendStates.SLEEPING
        self.model.system.update_variable_penalty(action.variable, 0.0)
        return action

    def on_speed_change(self) -> None:
        self.model.system.update_constraint_bound(
            self.constraint, self.speed_scale * self.speed_peak)
        for var in list(self.constraint.iter_variables()):
            action = var.id
            if action is not None:
                self.model.system.update_variable_bound(
                    action.variable, self.speed_scale * self.speed_peak)
        # fire the host-level speed-change signal like the Cas01 cpu
        # (Cpu::on_speed_change): the energy plugin tracks pstate
        # switches through it (energy-exec ptask oracle)
        from .cpu import Host_on_speed_change
        Host_on_speed_change(self.host)


class LinkL07(LinkImpl):
    def __init__(self, model: NetworkL07Model, name: str, bandwidth: float,
                 latency: float, policy: SharingPolicy):
        super().__init__(model, name,
                         model.system.constraint_new(None, bandwidth))
        self.constraint.id = self
        self.bandwidth_peak = bandwidth
        self.latency_peak = latency
        if policy == SharingPolicy.FATPIPE:
            self.constraint.sharing_policy = SharingPolicy.FATPIPE
        LinkImpl.on_creation(self)

    def apply_event(self, event: profile_mod.Event, value: float) -> None:
        if event is self.bandwidth_event:
            self.set_bandwidth(value)
        elif event is self.latency_event:
            self.set_latency(value)
        elif event is self.state_event:
            if value > 0:
                self.turn_on()
            else:
                self.turn_off()
        else:
            raise AssertionError("Unknown event!")

    def set_bandwidth(self, value: float) -> None:
        self.bandwidth_peak = value
        LinkImpl.on_bandwidth_change(self)
        self.model.system.update_constraint_bound(
            self.constraint, self.bandwidth_peak * self.bandwidth_scale)

    def set_latency(self, value: float) -> None:
        self.latency_peak = value
        for var in list(self.constraint.iter_variables()):
            action = var.id
            if isinstance(action, L07Action):
                action.update_bound()


class L07Action(CpuAction):
    """One parallel task (ptask_L07.cpp L07Action): flops per host +
    bytes per (src, dst) pair, one variable over all constraints."""

    def __init__(self, model: HostL07Model, host_list, flops_amount,
                 bytes_amount, rate: float):
        super().__init__(model, 1.0, False)
        self.host_list = list(host_list)
        self.flops_amount = flops_amount
        self.bytes_amount = bytes_amount
        self.rate = rate
        self.set_last_update()

        n = len(self.host_list)
        used_host_nb = sum(1 for f in (flops_amount or []) if f > 0)

        latency = 0.0
        affected_links = set()
        if bytes_amount:
            for k in range(n * n):
                if bytes_amount[k] <= 0:
                    continue
                route: List[LinkImpl] = []
                lat = self.host_list[k // n].route_to(
                    self.host_list[k % n], route)
                latency = max(latency, lat)
                for link in route:
                    affected_links.add(link.name)
        link_nb = len(affected_links)

        self.latency = latency
        self.variable = model.system.variable_new(
            self, 1.0, rate if rate > 0 else -1.0, n + link_nb)
        if self.latency > 0:
            model.system.update_variable_penalty(self.variable, 0.0)

        # expand on every cpu (even 0-flop ones, to notice host failures)
        for i, host in enumerate(self.host_list):
            model.system.expand(host.cpu.constraint, self.variable,
                                flops_amount[i] if flops_amount else 0.0)

        if bytes_amount:
            for k in range(n * n):
                if bytes_amount[k] <= 0.0:
                    continue
                route = []
                self.host_list[k // n].route_to(self.host_list[k % n], route)
                for link in route:
                    model.system.expand_add(link.constraint, self.variable,
                                            bytes_amount[k])

        if link_nb + used_host_nb == 0:
            self.cost = 1.0
            self.remains = 0.0

    def update_bound(self) -> None:
        # ptask_L07.cpp:388-418
        lat_current = 0.0
        n = len(self.host_list)
        if self.bytes_amount:
            for k in range(n * n):
                if self.bytes_amount[k] > 0:
                    route: List[LinkImpl] = []
                    lat = self.host_list[k // n].route_to(
                        self.host_list[k % n], route)
                    lat_current = max(lat_current,
                                      lat * self.bytes_amount[k])
        gamma = config["network/TCP-gamma"]
        lat_bound = (gamma / (2.0 * lat_current) if lat_current > 0
                     else float("inf"))
        if self.latency <= 0.0 and self.suspended == SuspendStates.RUNNING:
            if self.rate < 0:
                self.model.system.update_variable_bound(
                    self.variable,
                    lat_bound if lat_bound != float("inf") else -1.0)
            else:
                self.model.system.update_variable_bound(
                    self.variable, min(self.rate, lat_bound))

    def update_remains_lazy(self, now: float) -> None:
        raise AssertionError("L07 runs in FULL mode only")
