"""Storage model N11: disk read/write constraints via LMM.

Semantics from the reference's src/surf/storage_n11.cpp and
StorageImpl.cpp: each storage has read/write bandwidth constraints plus a
global connection constraint; IO actions are variables expanded on the
matching constraint.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..kernel.resource import (Action, ActionState, Model, Resource,
                               NO_MAX_DURATION, UpdateAlgo)
from ..ops.lmm_host import System
from ..utils.config import config
from ..utils.signal import Signal


class StorageAction(Action):
    on_state_change = Signal()

    def __init__(self, model, cost, failed, variable, storage, io_type):
        super().__init__(model, cost, failed, variable)
        self.storage = storage
        self.io_type = io_type

    def set_state(self, state: ActionState) -> None:
        super().set_state(state)
        StorageAction.on_state_change(self)

    def update_remains_lazy(self, now: float) -> None:
        raise NotImplementedError("storage model is FULL-update only")


class StorageN11Model(Model):
    def __init__(self, engine):
        super().__init__(engine, UpdateAlgo.FULL)
        self.set_maxmin_system(System(False))
        engine.storage_model = self

    def create_storage(self, id_: str, type_id: str, content_name: str,
                       attach: str, read_bw: float, write_bw: float,
                       size: float) -> "StorageN11":
        return StorageN11(self, id_, type_id, content_name, attach,
                          read_bw, write_bw, size)

    def update_actions_state_full(self, now: float, delta: float) -> None:
        for action in self.started_action_set:
            action.update_remains(action.variable.value * delta)
            action.update_max_duration(delta)
            if ((action.get_remains_no_update() <= 0
                 and action.variable.sharing_penalty > 0)
                    or (action.max_duration != NO_MAX_DURATION
                        and action.max_duration <= 0)):
                action.finish(ActionState.FINISHED)


class StorageN11(Resource):
    """One disk: read/write constraints (storage_n11.cpp)."""

    def __init__(self, model: StorageN11Model, name: str, type_id: str,
                 content_name: str, attach: str, read_bw: float,
                 write_bw: float, size: float):
        super().__init__(model, name, model.system.constraint_new(
            None, max(read_bw, write_bw)))
        self.constraint.id = self
        self.constraint_read = model.system.constraint_new(None, read_bw)
        self.constraint_write = model.system.constraint_new(None, write_bw)
        self.type_id = type_id
        self.content_name = content_name
        self.attach = attach  # host name this disk is attached to
        self.read_bw = read_bw
        self.write_bw = write_bw
        self.size = size
        self.used_size = 0.0
        model.engine.storages[name] = self

    def is_used(self) -> bool:
        return self.constraint._acs_hook is not None

    def apply_event(self, event, value: float) -> None:
        if value > 0:
            self.turn_on()
        else:
            self.turn_off()

    def io_start(self, size: float, io_type: str) -> StorageAction:
        var = self.model.system.variable_new(None, 1.0, -1.0, 3)
        action = StorageAction(self.model, size, not self.is_on(), var,
                               self, io_type)
        var.id = action
        self.model.system.expand(self.constraint, var, 1.0)
        if io_type == "read":
            self.model.system.expand(self.constraint_read, var, 1.0)
        else:
            self.model.system.expand(self.constraint_write, var, 1.0)
        return action

    def read(self, size: float) -> StorageAction:
        return self.io_start(size, "read")

    def write(self, size: float) -> StorageAction:
        return self.io_start(size, "write")


#: registered <storage_type> declarations
_storage_types: Dict[str, dict] = {}


def parse_storage_tag(loader, elem, zone) -> None:
    """Handle <storage_type>, <storage>, <mount> platform tags
    (sg_platf.cpp storage callbacks)."""
    from ..platform.units import parse_bandwidth, parse_size

    engine = loader.engine
    if elem.tag == "storage_type":
        props = {}
        model_props = {}
        for child in elem:
            if child.tag == "prop":
                props[child.get("id")] = child.get("value")
            elif child.tag == "model_prop":
                model_props[child.get("id")] = child.get("value")
        _storage_types[elem.get("id")] = {
            "size": parse_size(elem.get("size", "0")),
            "content": elem.get("content", ""),
            "props": props,
            "model_props": model_props,
        }
    elif elem.tag == "storage":
        type_id = elem.get("typeId")
        st = _storage_types.get(type_id)
        if st is None:
            raise ValueError(f"Unknown storage type {type_id}")
        read_bw = parse_bandwidth(st["model_props"].get("Bread", "0"))
        write_bw = parse_bandwidth(st["model_props"].get("Bwrite", "0"))
        if engine.storage_model is None:
            StorageN11Model(engine)
        engine.storage_model.create_storage(
            elem.get("id"), type_id,
            elem.get("content") or st.get("content", ""),
            elem.get("attach", ""), read_bw, write_bw, st["size"])
    elif elem.tag == "mount":
        storage = engine.storages.get(elem.get("storageId"))
        if storage is not None:
            storage.mount_point = elem.get("name")
