"""Stateless safety checker with dynamic partial-order reduction.

The exploration model matches the reference's (SafetyChecker.cpp): a
program state is the set of actors with a pending (unhandled) simcall;
a transition executes one of them; DFS walks interleavings, and on
backtrack DPOR marks the latest *dependent* earlier transition for
re-interleaving (SafetyChecker.cpp:284-295). Two transitions are
dependent when they touch the same kernel object (the mc_object simcall
label — mailbox, mutex, semaphore) or the same actor, the conservative
core of the reference's request_depend (mc_request.cpp).

Where the reference snapshots the MCed process's pages to backtrack
(sosp/PageStore), this checker re-executes: the kernel is deterministic
Python, so replaying a transition prefix from a fresh engine
reconstructs the state exactly — SimGrid's own record/replay
(mc_record.cpp) promoted to the backtracking mechanism.

Timing is not explored: activities complete through zero-cost model
steps between transitions, so the checker verifies all *orderings*, not
durations (same scope as the reference's safety mode).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Set, Tuple

from ..exceptions import SimgridException
from ..utils import log as _log
from ..utils.config import config, declare_flag

_logger = _log.get_category("mc")

declare_flag("model-check/max-visited-states",
             "Maximum number of visited states (0 = unlimited)", 0)
declare_flag("model-check/visited",
             "Prune states whose signature was already explored; the "
             "value bounds the retained set (0 = pruning disabled, the "
             "reference's model-check/visited semantics)", 0)


class PropertyError(SimgridException):
    """A safety property (assertion in an actor) was violated."""

    def __init__(self, message, trace):
        super().__init__(message)
        self.trace = trace


class DeadlockError(SimgridException):
    def __init__(self, message, trace):
        super().__init__(message)
        self.trace = trace


class TerminationError(SimgridException):
    pass


def _obj_key(obj):
    """Replay-stable identity of a kernel object: transitions from
    different re-executions must compare equal, so raw object identity
    is useless (each replay rebuilds fresh objects). Mailboxes key by
    name, sync objects by their deterministic creation sequence."""
    if obj is None:
        return None
    key = getattr(obj, "mc_key", None)
    if key is not None:
        return key
    name = getattr(obj, "name", None)
    if name is not None:
        return (type(obj).__name__, name)
    return (type(obj).__name__, id(obj))  # last resort, same-session only


def _obj_keys(obj) -> frozenset:
    """A simcall may touch several kernel objects (cond_wait touches
    the condition AND the mutex); mc_object accepts a tuple for that."""
    if obj is None:
        return frozenset()
    if isinstance(obj, tuple):
        return frozenset(_obj_key(o) for o in obj if o is not None)
    return frozenset((_obj_key(obj),))


class Transition:
    """One executed scheduling decision."""

    __slots__ = ("pid", "call", "objs")

    def __init__(self, pid: int, call: str, obj):
        self.pid = pid
        self.call = call
        self.objs = _obj_keys(obj)

    def depends_on(self, other: "Transition") -> bool:
        """Conservative request_depend: same actor, or any kernel
        object touched by both (mc_request.cpp dependence core)."""
        if self.pid == other.pid:
            return True
        return bool(self.objs & other.objs)

    def __repr__(self):
        tail = " on " + "+".join(sorted(k[0] for k in self.objs)) \
            if self.objs else ""
        return f"[pid {self.pid}] {self.call}{tail}"


class _State:
    """One node of the DFS stack (reference mc::State)."""

    __slots__ = ("enabled", "todo", "done", "executed")

    def __init__(self, enabled: List[int]):
        self.enabled = list(enabled)
        self.todo: List[int] = []
        self.done: Set[int] = set()
        self.executed: Optional[Transition] = None

    def pick(self) -> Optional[int]:
        while self.todo:
            pid = self.todo.pop(0)
            if pid not in self.done:
                return pid
        return None

    def add_todo(self, pid: int) -> None:
        if pid not in self.done and pid not in self.todo:
            self.todo.append(pid)


class Session:
    """One controlled execution of the program under test.

    ``program`` builds a fresh Engine with its actors and returns it
    (or the s4u Engine wrapper); the session then drives the kernel one
    scheduling decision at a time."""

    def __init__(self, program: Callable):
        from ..s4u import Engine
        Engine._reset()
        self.violation: Optional[str] = None
        engine = program()
        self.engine = engine.pimpl if hasattr(engine, "pimpl") else engine
        # Intercept actor crashes: an uncaught exception in an actor is
        # the safety property violation (mc-failing-assert model).
        self._orig_crashed = self.engine.actor_crashed

        def record_crash(actor, exc):
            self.violation = (f"Actor {actor.name} (pid {actor.pid}) "
                              f"violated its assertion: {exc!r}")
        self.engine.actor_crashed = record_crash
        self._quiesce()

    # -- kernel driving ----------------------------------------------------
    def _run_ready_actors(self) -> None:
        """Run runnable actors until each parks at a simcall (their
        code between simcalls is invisible to other actors, so no
        interleaving is lost — same argument as smx_global.cpp's
        determinism note)."""
        engine = self.engine
        while engine.actors_to_run:
            batch = engine.actors_to_run
            engine.actors_to_run = []
            engine.context_factory.run_all(batch)

    def _quiesce(self) -> None:
        """Advance everything that needs no scheduling decision: run
        ready actors to their next simcall, fire wakes, and let started
        activities complete through (deterministic) time advances. Only
        the *ordering* of simcall handling is explored; durations run
        their deterministic course between decisions."""
        engine = self.engine
        stalls = 0
        while True:
            self._run_ready_actors()
            engine._execute_tasks()
            engine._wake_processes()
            if engine.actors_to_run:
                stalls = 0
                continue
            if engine.process_list and not self.pending_pids():
                advanced = engine.surf_solve(engine.next_timer_date())
                engine._execute_timers()
                engine._execute_tasks()
                engine._wake_processes()
                if engine.actors_to_run:
                    stalls = 0
                    continue
                if advanced < 0:
                    break        # nothing can move: deadlock leaf
                stalls += 1
                if stalls > 1000:
                    break        # profile-event churn with no progress
                continue
            break

    def pending_pids(self) -> List[int]:
        """Actors whose simcall awaits a scheduling decision: issued
        (call set) but not yet executed (handler unconsumed) — an
        already-handled blocking simcall keeps its call name until
        answered and is not a decision point."""
        return [actor.pid for actor in self.engine.process_list.values()
                if actor.simcall_.call is not None
                and actor.simcall_.handler is not None]

    def execute(self, pid: int) -> Transition:
        actor = self.engine.process_list[pid]
        sc = actor.simcall_
        transition = Transition(pid, sc.call,
                                sc.payload.get("mc_object"))
        actor.simcall_handle()
        self._quiesce()
        return transition

    def alive(self) -> bool:
        return bool(self.engine.process_list)

    def close(self) -> None:
        self.engine.actor_crashed = self._orig_crashed


class SafetyChecker:
    """DFS + DPOR over scheduling decisions (SafetyChecker.cpp:80-295).

    ``checker = SafetyChecker(program); checker.run()`` raises
    PropertyError/DeadlockError with a counterexample trace, or returns
    statistics when the full (reduced) state space is clean."""

    def __init__(self, program: Callable):
        self.program = program
        self.reduction = config["model-check/reduction"]
        assert self.reduction in ("dpor", "none"), \
            f"Unknown model-check/reduction {self.reduction!r}"
        self.max_depth = int(config["model-check/max-depth"])
        self.visited_states = 0
        self.executed_transitions = 0
        self.expanded_states = 0
        #: visited-state pruning (VisitedState.cpp): signatures of
        #: fully-seen states; bounded FIFO per model-check/visited
        self.visited_cap = int(config["model-check/visited"])
        self._seen_signatures: "OrderedDict" = OrderedDict()
        self.pruned_states = 0

    # -- subclass hooks ----------------------------------------------------
    def _make_session(self) -> Session:
        """Session factory; checker variants attach observers here."""
        return Session(self.program)

    def _on_path_complete(self, session: Session) -> None:
        """Called at every fully-executed path (leaf without live
        actors) — the comm-determinism checker compares patterns here."""

    # -- replay-based navigation ------------------------------------------
    def _replay(self, prefix: List[int]) -> Session:
        session = self._make_session()
        for pid in prefix:
            session.execute(pid)
        return session

    def run(self) -> Dict[str, int]:
        stack: List[_State] = []
        path: List[int] = []
        session = self._make_session()
        if session.violation is not None:
            raise PropertyError(session.violation, [])

        root = _State(session.pending_pids())
        self._seed_todo(root)
        stack.append(root)

        while stack:
            state = stack[-1]
            self.visited_states += 1
            cap = int(config["model-check/max-visited-states"])
            if cap > 0 and self.visited_states > cap:
                raise TerminationError(
                    f"model-check/max-visited-states ({cap}) exceeded")

            if len(stack) > self.max_depth:
                _logger.warning("/!\\ Max depth reached! /!\\")
                session = self._backtrack(stack, path)
                continue

            pid = state.pick()
            if pid is None:
                session = self._backtrack(stack, path)
                continue

            state.done.add(pid)
            self.executed_transitions += 1
            state.executed = session.execute(pid)
            path.append(pid)

            if session.violation is not None:
                raise self._with_record(
                    PropertyError(session.violation, self._trace(stack)),
                    path)

            nxt = _State(session.pending_pids())
            if not nxt.enabled:
                if session.alive():
                    raise self._with_record(DeadlockError(
                        "Deadlock: actors remain but no transition is "
                        "enabled", self._trace(stack)), path)
                self._on_path_complete(session)
            if self.visited_cap > 0 and nxt.enabled:
                # visited-state pruning (VisitedState.cpp): an already
                # fully-seen signature is not re-expanded.  Like the
                # reference, combining this with DPOR trades exhaustive
                # coverage for speed; use reduction:none for the sound
                # stateful mode.
                from .state import state_signature
                sig = state_signature(session.engine)
                if sig in self._seen_signatures:
                    self.pruned_states += 1
                    stack.append(nxt)     # empty todo: backtracks next
                    continue
                self._seen_signatures[sig] = True
                while len(self._seen_signatures) > self.visited_cap:
                    self._seen_signatures.popitem(last=False)
            self._seed_todo(nxt)
            self.expanded_states += 1
            stack.append(nxt)

        _logger.info("No property violation found.")
        _logger.info("Expanded states = %d", self.expanded_states)
        _logger.info("Visited states = %d", self.visited_states)
        _logger.info("Executed transitions = %d",
                     self.executed_transitions)
        return {"expanded_states": self.expanded_states,
                "visited_states": self.visited_states,
                "executed_transitions": self.executed_transitions,
                "pruned_states": self.pruned_states}

    def _seed_todo(self, state: _State) -> None:
        """With DPOR, start from the first enabled transition only; the
        backtracking dependence analysis adds the rest on demand
        (SafetyChecker.cpp:255-260). Without reduction, try them all."""
        if not state.enabled:
            return
        if self.reduction == "dpor":
            state.add_todo(state.enabled[0])
        else:
            for pid in state.enabled:
                state.add_todo(pid)

    def _backtrack(self, stack: List[_State], path: List[int]):
        """Undo the last transition(s). For each undone transition t,
        DPOR walks the remaining stack backwards: the latest earlier
        state whose outgoing transition is dependent on t (and from a
        different actor) must also try t's actor
        (SafetyChecker.cpp:284-295); the walk stops at a transition of
        t's own actor (program order)."""
        stack.pop()                       # the exhausted leaf
        while stack:
            state = stack[-1]
            t = state.executed            # transition being undone
            state.executed = None
            if path:
                path.pop()
            if self.reduction == "dpor" and t is not None:
                for prev in reversed(stack[:-1]):
                    pt = prev.executed
                    if pt is None:
                        continue
                    if pt.pid == t.pid:
                        break
                    if t.depends_on(pt):
                        # Flanagan-Godefroid: schedule t's actor in that
                        # state if it was enabled there; otherwise every
                        # enabled actor must be tried (the actor only
                        # becomes co-enabled through one of them).
                        if t.pid in prev.enabled:
                            prev.add_todo(t.pid)
                        else:
                            for p in prev.enabled:
                                prev.add_todo(p)
                        break
            if any(p not in state.done for p in state.todo):
                return self._replay(path)
            stack.pop()
        return None

    @staticmethod
    def _with_record(err, path: List[int]):
        """Stamp the mc_record-style path ("Path = 1;2;...") on a
        counterexample, replayable via mc.record.replay()."""
        from .record import record_of
        err.record = record_of(path)
        _logger.info("Path = %s", err.record)
        return err

    def _trace(self, stack: List[_State]) -> List[str]:
        return [repr(state.executed) for state in stack
                if state.executed is not None]
