"""Mc SimGrid equivalent: a stateless safety model checker.

The reference runs the checker as a separate ptrace-ing OS process with
page-level snapshots (src/mc/Session.cpp, sosp/). This rebuild follows
SURVEY §2.6 note 5 instead: the kernel is deterministic Python, so
exploration is *stateless* — backtracking re-executes the program from
scratch and replays the recorded transition prefix (the same
record/replay SimGrid exposes as --cfg=model-check/replay, promoted to
the backtracking mechanism). Dynamic partial-order reduction prunes
commuting interleavings like SafetyChecker.cpp:284-295.
"""

from .explorer import (DeadlockError, PropertyError, SafetyChecker,
                       Session, TerminationError)

__all__ = ["SafetyChecker", "Session", "PropertyError", "DeadlockError",
           "TerminationError"]

from .comm_determinism import (CommunicationDeterminismChecker,  # noqa: E402
                               NonDeterminismError)

__all__ += ["CommunicationDeterminismChecker", "NonDeterminismError"]

from .liveness import (BuchiAutomaton, LivenessChecker,  # noqa: E402
                       LivenessError)
from .ltl import LtlSyntaxError, ltl_to_buchi, never_claim  # noqa: E402
from .record import record_of, parse_record, replay  # noqa: E402
from .state import note, state_signature  # noqa: E402

__all__ += ["BuchiAutomaton", "LivenessChecker", "LivenessError",
            "ltl_to_buchi", "never_claim", "LtlSyntaxError",
            "record_of", "parse_record", "replay", "state_signature",
            "note"]
