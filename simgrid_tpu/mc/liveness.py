"""Liveness checking: accepting-cycle search over the product of the
program and a never-claim Büchi automaton.

Reference: mc/checker/LivenessChecker.cpp — the property (the negation
of the desired LTL formula, a "never claim") is a Büchi automaton whose
atomic propositions the verified program exposes; the checker explores
the synchronous product and reports a violation when an exploration
cycle passes through an accepting automaton state (detected there by
comparing snapshot pairs on the exploration stack,
LivenessChecker.cpp:close-pair logic).  Here cycle detection compares
kernel state *signatures* (mc/state.py) instead of memory snapshots.

API:
    aut = BuchiAutomaton(
        states=["s0", "s1"], initial="s0", accepting={"s1"},
        transitions=[("s0", "s0", lambda p: True),
                     ("s0", "s1", lambda p: not p["done"]),
                     ("s1", "s1", lambda p: not p["done"])])
    LivenessChecker(program, aut, {"done": lambda engine: ...}).run()

raises LivenessError with the lasso (prefix + cycle) when the program
has an infinite run accepted by the claim.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set, Tuple

from ..exceptions import SimgridException
from ..utils import log as _log
from ..utils.config import config
from .explorer import Session, Transition

_logger = _log.get_category("mc")


class LivenessError(SimgridException):
    def __init__(self, message, prefix, cycle):
        super().__init__(message)
        self.prefix = prefix      # transitions reaching the cycle
        self.cycle = cycle        # transitions closing the lasso


class BuchiAutomaton:
    """A never claim: states, one initial, accepting set, transitions
    guarded by predicates over the proposition valuation (the xbt
    automaton of the reference, minus the LTL-to-Büchi translator —
    claims are given directly)."""

    def __init__(self, states: List[str], initial: str,
                 accepting: Set[str],
                 transitions: List[Tuple[str, str, Callable]]):
        assert initial in states
        assert set(accepting) <= set(states)
        self.states = list(states)
        self.initial = initial
        self.accepting = set(accepting)
        self.transitions = list(transitions)

    def successors(self, state: str, valuation: Dict[str, bool]):
        return [dst for src, dst, guard in self.transitions
                if src == state and guard(valuation)]


class LivenessChecker:
    """DFS over (program state, claim state) pairs; a pair revisited on
    the exploration stack with an accepting claim state inside the loop
    is an accepted infinite run (LivenessChecker.cpp:80-150)."""

    def __init__(self, program: Callable, automaton,
                 propositions: Dict[str, Callable]):
        if isinstance(automaton, str):
            # an LTL property string: check its never claim
            from .ltl import never_claim
            automaton = never_claim(automaton)
        self.program = program
        self.automaton = automaton
        self.propositions = propositions
        self.max_depth = int(config["model-check/max-depth"])
        self.visited_pairs = 0
        self.expanded_pairs = 0

    def _valuation(self, session: Session) -> Dict[str, bool]:
        return {name: bool(fn(session.engine))
                for name, fn in self.propositions.items()}

    def run(self) -> Dict[str, int]:
        from .state import state_signature
        session = Session(self.program)
        # untimed comparison: loop iterations advance the clock, which
        # must not prevent closing the lasso (reference: timing data is
        # MC_ignore'd out of liveness snapshots)
        init_sig = state_signature(session.engine, include_clock=False)
        valuation = self._valuation(session)
        for aut0 in self.automaton.successors(self.automaton.initial,
                                              valuation) or \
                [self.automaton.initial]:
            self._dfs(session, [], init_sig, aut0, [])
        _logger.info("No liveness violation found.")
        _logger.info("Visited pairs = %d", self.visited_pairs)
        return {"visited_pairs": self.visited_pairs,
                "expanded_pairs": self.expanded_pairs}

    # -- recursive DFS with replay-based backtracking ----------------------
    def _dfs(self, session: Session, path: List[int], sig, aut_state: str,
             stack: List[Tuple]):
        """`stack` holds (signature, automaton state, accepting?) of the
        current exploration branch; session IS at `path`."""
        self.visited_pairs += 1
        pair = (sig, aut_state)
        for i, (s, a, _) in enumerate(stack):
            if (s, a) == pair:
                # a cycle through stack[i:]; accepted if any pair inside
                # it (or this one) is accepting
                if any(acc for _, _, acc in stack[i:]) or \
                        aut_state in self.automaton.accepting:
                    raise LivenessError(
                        "Liveness property violated: accepting cycle "
                        f"found (claim state {aut_state})",
                        path[:i], path[i:])
                return                      # non-accepting cycle: prune
        if len(stack) >= self.max_depth:
            _logger.warning("/!\\ Liveness max depth reached /!\\")
            return
        pids = session.pending_pids()
        if not pids:
            return                          # finite run: no infinite word
        stack.append((sig, aut_state,
                      aut_state in self.automaton.accepting))
        try:
            from .state import state_signature
            for pid in pids:
                child = self._replay(path + [pid])
                self.expanded_pairs += 1
                child_sig = state_signature(child.engine,
                                            include_clock=False)
                valuation = self._valuation(child)
                for nxt in self.automaton.successors(aut_state, valuation):
                    self._dfs(child, path + [pid], child_sig, nxt, stack)
        finally:
            stack.pop()

    def _replay(self, path: List[int]) -> Session:
        session = Session(self.program)
        for pid in path:
            session.execute(pid)
        return session
