"""LTL -> Büchi translation: the never-claim front end.

The reference accepts Promela-style never claims and LTL atoms through
a lex/yacc pair (xbt/automaton/parserPromela.lex, parserPromela.yacc,
automaton.c) and evaluates the resulting xbt_automaton during liveness
checking.  Re-designed here: formulas are translated directly to a
Büchi automaton with the classic on-the-fly tableau of Gerth, Peled,
Vardi & Wolper (PSTV'95), and the generalized acceptance condition is
degeneralized with the standard counter construction, so a property can
be stated as a plain string:

    LivenessChecker(program, never_claim("[]<> progress"), props).run()

Syntax (the reference's Promela operator set):
    ap          atomic proposition (identifier, looked up in the
                checker's proposition table)
    1 / 0       true / false
    ! f         negation            X f   next
    [] f        always (G)          <> f  eventually (F)
    f U g       until               f R g / f V g   release
    f && g, f || g, f -> g, f <-> g

`ltl_to_buchi(f)` accepts exactly the infinite words satisfying f;
`never_claim(f)` is sugar for `ltl_to_buchi("!(f)")` — the automaton
the liveness checker must find empty for the property to hold.
"""

from __future__ import annotations

import itertools
import re
from typing import Dict, FrozenSet, List, Set, Tuple

from .liveness import BuchiAutomaton

__all__ = ["ltl_to_buchi", "never_claim", "LtlSyntaxError"]


class LtlSyntaxError(ValueError):
    pass


# -- parsing ---------------------------------------------------------------

_TOKEN = re.compile(r"""\s*(?:
      (?P<lbr>\()|(?P<rbr>\))
    | (?P<glob>\[\])|(?P<fin><>)
    | (?P<and>&&)|(?P<or>\|\|)
    | (?P<iff><->)|(?P<impl>->)
    | (?P<not>!)
    | (?P<ap>[A-Za-z_][A-Za-z0-9_.]*)
    | (?P<one>1)|(?P<zero>0)
)""", re.X)

_UNARY = {"glob", "fin", "not", "X"}


def _tokenize(src: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN.match(src, pos)
        if not m or m.end() == pos:
            rest = src[pos:].strip()
            if not rest:
                break
            raise LtlSyntaxError(f"cannot tokenize {rest[:20]!r}")
        pos = m.end()
        kind = m.lastgroup
        text = m.group(kind)
        if kind == "ap" and text in ("U", "R", "V", "X", "G", "F"):
            kind = {"U": "U", "R": "R", "V": "R",
                    "X": "X", "G": "glob", "F": "fin"}[text]
        out.append((kind, text))
    out.append(("eof", ""))
    return out


class _Parser:
    """Recursive descent; precedence low->high:
    <->  ->  ||  &&  U/R  unary  atom."""

    def __init__(self, src: str):
        self.toks = _tokenize(src)
        self.i = 0

    def peek(self):
        return self.toks[self.i][0]

    def eat(self, kind=None):
        k, t = self.toks[self.i]
        if kind is not None and k != kind:
            raise LtlSyntaxError(f"expected {kind}, got {t!r}")
        self.i += 1
        return k, t

    def parse(self):
        f = self.iff()
        if self.peek() != "eof":
            raise LtlSyntaxError(
                f"trailing input at {self.toks[self.i][1]!r}")
        return f

    def iff(self):
        f = self.impl()
        while self.peek() == "iff":
            self.eat()
            g = self.impl()
            f = ("iff", f, g)
        return f

    def impl(self):
        f = self.disj()
        if self.peek() == "impl":        # right-assoc
            self.eat()
            return ("impl", f, self.impl())
        return f

    def disj(self):
        f = self.conj()
        while self.peek() == "or":
            self.eat()
            f = ("or", f, self.conj())
        return f

    def conj(self):
        f = self.until()
        while self.peek() == "and":
            self.eat()
            f = ("and", f, self.until())
        return f

    def until(self):
        f = self.unary()
        if self.peek() in ("U", "R"):    # right-assoc
            kind = self.eat()[0]
            return (kind, f, self.until())
        return f

    def unary(self):
        k = self.peek()
        if k in _UNARY:
            self.eat()
            g = self.unary()
            return {"not": ("not", g), "X": ("X", g),
                    "glob": ("R", ("ff",), g),
                    "fin": ("U", ("tt",), g)}[k]
        return self.atom()

    def atom(self):
        k, t = self.eat()
        if k == "lbr":
            f = self.iff()
            self.eat("rbr")
            return f
        if k == "ap":
            return ("ap", t)
        if k == "one":
            return ("tt",)
        if k == "zero":
            return ("ff",)
        raise LtlSyntaxError(f"unexpected {t!r}")


def _nnf(f, neg=False):
    """Negation normal form over {ap, !ap, tt, ff, and, or, X, U, R}."""
    op = f[0]
    if op == "not":
        return _nnf(f[1], not neg)
    if op == "tt":
        return ("ff",) if neg else ("tt",)
    if op == "ff":
        return ("tt",) if neg else ("ff",)
    if op == "ap":
        return ("not", f) if neg else f
    if op == "impl":
        return _nnf(("or", ("not", f[1]), f[2]), neg)
    if op == "iff":
        return _nnf(("or", ("and", f[1], f[2]),
                     ("and", ("not", f[1]), ("not", f[2]))), neg)
    if op == "X":
        return ("X", _nnf(f[1], neg))
    dual = {"and": "or", "or": "and", "U": "R", "R": "U"}
    if neg:
        op = dual[op]
    return (op, _nnf(f[1], neg), _nnf(f[2], neg))


# -- GPVW tableau ----------------------------------------------------------

def _is_literal(f) -> bool:
    return f[0] in ("tt", "ff", "ap") or \
        (f[0] == "not" and f[1][0] == "ap")


def _negate_literal(f):
    if f[0] == "tt":
        return ("ff",)
    if f[0] == "ff":
        return ("tt",)
    if f[0] == "not":
        return f[1]
    return ("not", f)


class _Node:
    __slots__ = ("id", "incoming", "new", "old", "next")

    def __init__(self, nid, incoming, new, old, nxt):
        self.id = nid
        self.incoming: Set = set(incoming)
        self.new: Set = set(new)
        self.old: Set = set(old)
        self.next: Set = set(nxt)


def _expand(node: _Node, nodes: List[_Node], counter) -> None:
    if not node.new:
        for nd in nodes:
            if nd.old == node.old and nd.next == node.next:
                nd.incoming |= node.incoming
                return
        nodes.append(node)
        _expand(_Node(next(counter), {node.id}, set(node.next),
                      set(), set()), nodes, counter)
        return
    eta = node.new.pop()
    op = eta[0]
    if _is_literal(eta):
        if eta == ("ff",) or _negate_literal(eta) in node.old:
            return                        # contradiction: drop branch
        node.old.add(eta)
        _expand(node, nodes, counter)
    elif op == "and":
        node.new |= {eta[1], eta[2]} - node.old
        node.old.add(eta)
        _expand(node, nodes, counter)
    elif op == "X":
        node.old.add(eta)
        node.next.add(eta[1])
        _expand(node, nodes, counter)
    elif op in ("or", "U", "R"):
        a, b = eta[1], eta[2]
        if op == "or":
            new1, next1, new2 = {a}, set(), {b}
        elif op == "U":
            new1, next1, new2 = {a}, {eta}, {b}
        else:  # R
            new1, next1, new2 = {b}, {eta}, {a, b}
        n1 = _Node(next(counter), node.incoming,
                   node.new | (new1 - node.old),
                   node.old | {eta}, node.next | next1)
        n2 = _Node(next(counter), node.incoming,
                   node.new | (new2 - node.old),
                   node.old | {eta}, node.next)
        _expand(n1, nodes, counter)
        _expand(n2, nodes, counter)
    else:  # pragma: no cover — exhaustive over NNF operators
        raise AssertionError(f"unexpected operator {op}")


def _subformulas(f, acc: Set) -> Set:
    acc.add(f)
    if f[0] in ("and", "or", "U", "R"):
        _subformulas(f[1], acc)
        _subformulas(f[2], acc)
    elif f[0] in ("X", "not"):
        _subformulas(f[1], acc)
    return acc


def _make_guard(literals: FrozenSet):
    pos = tuple(sorted(f[1] for f in literals if f[0] == "ap"))
    neg = tuple(sorted(f[1][1] for f in literals if f[0] == "not"))

    def guard(valuation: Dict[str, bool], _pos=pos, _neg=neg) -> bool:
        return (all(valuation.get(p, False) for p in _pos)
                and not any(valuation.get(p, False) for p in _neg))
    return guard


def ltl_to_buchi(formula: str) -> BuchiAutomaton:
    """Translate an LTL formula to a BuchiAutomaton accepting exactly
    the infinite proposition sequences that satisfy it."""
    f = _nnf(_Parser(formula).parse())
    counter = itertools.count()
    nodes: List[_Node] = []
    _expand(_Node(next(counter), {"init"}, {f}, set(), set()),
            nodes, counter)

    untils = sorted(g for g in _subformulas(f, set()) if g[0] == "U")
    k = len(untils)
    fsets = [{nd.id for nd in nodes
              if u not in nd.old or u[2] in nd.old} for u in untils]

    by_id = {nd.id: nd for nd in nodes}
    guards = {nd.id: _make_guard(frozenset(
        g for g in nd.old if _is_literal(g) and g[0] != "tt"))
        for nd in nodes}

    def sname(nid, layer):
        return f"n{nid}@{layer}"

    states = ["init"]
    transitions = []
    accepting: Set[str] = set()
    layers = range(max(k, 1))
    for nd in nodes:
        for i in layers:
            states.append(sname(nd.id, i))
    if k == 0:
        # no Until obligation: every infinite run is fair ("init" can
        # never sit on a cycle, so including it is harmless)
        accepting = set(states)
    else:
        accepting = {sname(nid, 0) for nid in fsets[0]}

    def next_layer(src_id, i):
        if k == 0:
            return 0
        return (i + 1) % k if src_id in fsets[i] else i

    for nd in nodes:
        g = guards[nd.id]
        for src in nd.incoming:
            if src == "init":
                transitions.append(("init", sname(nd.id, 0), g))
            else:
                for i in layers:
                    transitions.append(
                        (sname(src, i),
                         sname(nd.id, next_layer(src, i)), g))
    return BuchiAutomaton(states=states, initial="init",
                          accepting=accepting, transitions=transitions)


def never_claim(formula: str) -> BuchiAutomaton:
    """The Büchi automaton of the NEGATED property — what the liveness
    checker must find empty for `formula` to hold on every run."""
    return ltl_to_buchi(f"!({formula})")
