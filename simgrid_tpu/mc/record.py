"""MC record/replay: textual counterexample traces.

Reference mc/mc_record.cpp: a path through the state space is encoded
as a ';'-separated list of scheduled pids, printable by the checker
("Path = 1;2;1;...") and replayable outside the checker with
--cfg=model-check/replay.  The Session replay machinery makes this a
two-liner here, exposed as a first-class tool.
"""

from __future__ import annotations

from typing import Callable, List

from .explorer import Session, Transition


def record_of(path: List[int]) -> str:
    """Encode a scheduling path ("Path = " payload of mc_record)."""
    return ";".join(str(pid) for pid in path)


def parse_record(text: str) -> List[int]:
    return [int(tok) for tok in text.split(";") if tok.strip()]


def replay(program: Callable, record: str) -> Session:
    """Re-execute `program` following the recorded scheduling decisions
    (the reference's simgrid-mc --replay): returns the driven Session
    for post-mortem inspection (the violation fires during replay just
    as it did under the checker)."""
    session = Session(program)
    transitions: List[Transition] = []
    for pid in parse_record(record):
        if pid not in session.engine.process_list:
            raise ValueError(
                f"Replay diverged: pid {pid} has no pending actor")
        transitions.append(session.execute(pid))
    session.replayed_transitions = transitions
    return session
