"""Communication-determinism checker (reference
src/mc/checker/CommunicationDeterminismChecker.cpp).

Explores scheduling interleavings like the safety checker and records
every completed communication as a pattern (mailbox, src pid, dst pid)
in per-actor order.  The first completed execution fixes the reference
patterns (initial_communications_pattern); later interleavings are
compared pattern-by-pattern and every divergence CLASSIFIES the actor
(deterministic_comm_pattern, CommunicationDeterminismChecker.cpp:118-160):

* a diverging send pattern clears that actor's send-determinism,
* a diverging receive clears its recv-determinism,
* the diff itself is kept, named like the reference's
  print_determinism_result (mailbox/src/dst difference, or a
  missing/extra communication).

Exploration then CONTINUES — the classification covers the whole
exploration — unless the configured property is already hopeless,
mirroring the reference's early exits:

* ``model-check/send-determinism``: checking send-determinism only —
  abort the moment any actor loses it;
* otherwise (communications-determinism, the default property): abort
  when some actor has lost BOTH send- and recv-determinism.

``run()`` returns the classification
(``{"send_deterministic": bool, "recv_deterministic": bool,
"per_actor": {pid: {"send": ..., "recv": ...}}, "diffs": [...]}`` —
the reference's log_state summary, .cpp:305-331).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..exceptions import SimgridException
from ..utils import log as _log
from ..utils.config import config
from .explorer import SafetyChecker, Session

_logger = _log.get_category("mc_comm_determinism")

Pattern = Tuple[str, int, int]   # (mailbox, src pid, dst pid)


def _diff_kind(ref: Optional[Pattern], got: Optional[Pattern]) -> str:
    """Name the difference like compare_comm_pattern
    (CommunicationDeterminismChecker.cpp:40-70)."""
    if ref is None:
        return "extra communication"
    if got is None:
        return "missing communication"
    if ref[0] != got[0]:
        return f"mailbox ({ref[0]!r} vs {got[0]!r})"
    if ref[1] != got[1]:
        return f"source actor ({ref[1]} vs {got[1]})"
    if ref[2] != got[2]:
        return f"destination actor ({ref[2]} vs {got[2]})"
    return "none"


class NonDeterminismError(SimgridException):
    def __init__(self, message, kind, actor, reference, observed):
        super().__init__(message)
        self.kind = kind            # "send" | "both"
        self.actor = actor
        self.reference = reference
        self.observed = observed


class CommunicationDeterminismChecker(SafetyChecker):
    """SafetyChecker + per-actor send/recv-determinism classification."""

    def __init__(self, program):
        super().__init__(program)
        self.reference_sends: Optional[Dict[int, List[Pattern]]] = None
        self.reference_recvs: Optional[Dict[int, List[Pattern]]] = None
        self.paths_checked = 0
        #: pid -> still-deterministic flags, over the WHOLE exploration
        self.send_deterministic: Dict[int, bool] = {}
        self.recv_deterministic: Dict[int, bool] = {}
        self.diffs: List[str] = []
        self._sends: Dict[int, List[Pattern]] = {}
        self._recvs: Dict[int, List[Pattern]] = {}

    def _make_session(self) -> Session:
        from ..kernel.activity import CommImpl
        session = super()._make_session()
        self._sends = {}
        self._recvs = {}

        def on_comm(comm):
            src = comm.src_actor.pid if comm.src_actor else -1
            dst = comm.dst_actor.pid if comm.dst_actor else -1
            mbox = getattr(comm, "mbox_name", "?")
            pattern = (mbox, src, dst)
            self._sends.setdefault(src, []).append(pattern)
            self._recvs.setdefault(dst, []).append(pattern)

        session.engine.connect_signal(CommImpl.on_completion, on_comm)
        return session

    @staticmethod
    def _first_diff(ref: List[Pattern], got: List[Pattern]):
        for i in range(max(len(ref), len(got))):
            r = ref[i] if i < len(ref) else None
            g = got[i] if i < len(got) else None
            if r != g:
                return i, _diff_kind(r, g)
        return None

    def _on_path_complete(self, session: Session) -> None:
        self.paths_checked += 1
        if self.reference_sends is None:
            # the first complete path defines the law
            self.reference_sends = {k: list(v)
                                    for k, v in self._sends.items()}
            self.reference_recvs = {k: list(v)
                                    for k, v in self._recvs.items()}
            for pid in set(self.reference_sends) | \
                    set(self.reference_recvs):
                self.send_deterministic.setdefault(pid, True)
                self.recv_deterministic.setdefault(pid, True)
            return

        send_only = config["model-check/send-determinism"]
        for kind, flags, refs, gots in (
                ("send", self.send_deterministic,
                 self.reference_sends, self._sends),
                ("recv", self.recv_deterministic,
                 self.reference_recvs, self._recvs)):
            for pid in set(refs) | set(gots):
                ref = refs.get(pid, [])
                got = gots.get(pid, [])
                diff = self._first_diff(ref, got)
                if diff is None:
                    continue
                if flags.get(pid, True):
                    flags[pid] = False
                    idx, why = diff
                    msg = (f"The {kind} communications pattern of the "
                           f"actor {pid} is different! ({why} at "
                           f"communication #{idx + 1})")
                    self.diffs.append(msg)
                    _logger.info("%s", msg)
                # reference early exits (deterministic_comm_pattern,
                # .cpp:139-160)
                if send_only and kind == "send":
                    _logger.info("***** Non-send-deterministic "
                                 "communications pattern *****")
                    raise NonDeterminismError(
                        f"Non-send-deterministic communications "
                        f"pattern for actor {pid}", "send", pid, ref,
                        got)
                if (not send_only
                        and config["model-check/"
                                   "communications-determinism"]
                        and not self.send_deterministic.get(pid, True)
                        and not self.recv_deterministic.get(pid, True)):
                    _logger.info("***** Non-deterministic communications "
                                 "pattern *****")
                    raise NonDeterminismError(
                        f"Non-deterministic communications pattern for "
                        f"actor {pid} (neither send- nor "
                        f"recv-deterministic)", "both", pid, ref, got)

    def classification(self) -> Dict:
        """The reference's log_state summary (.cpp:305-331)."""
        send_ok = all(self.send_deterministic.values())
        recv_ok = all(self.recv_deterministic.values())
        _logger.info("Send-deterministic : %s", "Yes" if send_ok else "No")
        _logger.info("Recv-deterministic : %s", "Yes" if recv_ok else "No")
        return {
            "send_deterministic": send_ok,
            "recv_deterministic": recv_ok,
            "per_actor": {
                pid: {"send": self.send_deterministic.get(pid, True),
                      "recv": self.recv_deterministic.get(pid, True)}
                for pid in set(self.send_deterministic)
                | set(self.recv_deterministic)},
            "diffs": list(self.diffs),
            "paths_checked": self.paths_checked,
        }

    def run(self) -> Dict:
        super().run()
        return self.classification()
