"""Communication-determinism checker (reference
src/mc/checker/CommunicationDeterminismChecker.cpp).

Explores scheduling interleavings like the safety checker and records
every completed communication as a pattern (mailbox, src pid, dst pid)
in per-actor order. The first completed execution fixes the reference
patterns (initial_communications_pattern); any later interleaving whose
per-actor sequences differ makes the application non-send-deterministic
and/or non-recv-deterministic — the MPI message-race detector (an
MPI_ANY_SOURCE whose match depends on scheduling, etc.)."""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..exceptions import SimgridException
from ..utils import log as _log
from .explorer import SafetyChecker, Session

_logger = _log.get_category("mc_comm_determinism")

Pattern = Tuple[str, int, int]   # (mailbox, src pid, dst pid)


class NonDeterminismError(SimgridException):
    def __init__(self, message, kind, actor, reference, observed):
        super().__init__(message)
        self.kind = kind            # "send" | "recv"
        self.actor = actor
        self.reference = reference
        self.observed = observed


class CommunicationDeterminismChecker(SafetyChecker):
    """SafetyChecker + per-path communication-pattern comparison."""

    def __init__(self, program):
        super().__init__(program)
        self.reference_sends: Optional[Dict[int, List[Pattern]]] = None
        self.reference_recvs: Optional[Dict[int, List[Pattern]]] = None
        self.paths_checked = 0
        self._sends: Dict[int, List[Pattern]] = {}
        self._recvs: Dict[int, List[Pattern]] = {}

    def _make_session(self) -> Session:
        from ..kernel.activity import CommImpl
        session = super()._make_session()
        self._sends = {}
        self._recvs = {}

        def on_comm(comm):
            src = comm.src_actor.pid if comm.src_actor else -1
            dst = comm.dst_actor.pid if comm.dst_actor else -1
            mbox = getattr(comm, "mbox_name", "?")
            pattern = (mbox, src, dst)
            self._sends.setdefault(src, []).append(pattern)
            self._recvs.setdefault(dst, []).append(pattern)

        session.engine.connect_signal(CommImpl.on_completion, on_comm)
        return session

    def _on_path_complete(self, session: Session) -> None:
        self.paths_checked += 1
        if self.reference_sends is None:
            # compare_comm_pattern: the first path defines the law
            self.reference_sends = {k: list(v)
                                    for k, v in self._sends.items()}
            self.reference_recvs = {k: list(v)
                                    for k, v in self._recvs.items()}
            return
        for pid in set(self.reference_sends) | set(self._sends):
            ref = self.reference_sends.get(pid, [])
            got = self._sends.get(pid, [])
            if got != ref:
                _logger.info("***** Non-send-deterministic communications "
                             "pattern *****")
                raise NonDeterminismError(
                    f"Non-send-deterministic communications pattern for "
                    f"actor {pid}", "send", pid, ref, got)
        for pid in set(self.reference_recvs) | set(self._recvs):
            ref = self.reference_recvs.get(pid, [])
            got = self._recvs.get(pid, [])
            if got != ref:
                _logger.info("***** Non-recv-deterministic communications "
                             "pattern *****")
                raise NonDeterminismError(
                    f"Non-recv-deterministic communications pattern for "
                    f"actor {pid}", "recv", pid, ref, got)
