"""Hashable kernel-state signatures: the snapshot layer of the checker.

The reference backtracks and compares states through byte-level page
snapshots (mc/sosp/PageStore.hpp:62-97, Snapshot.cpp) because it
checks arbitrary C programs.  This kernel is deterministic Python, so a
state is fully characterized by a structural serialization of the
scheduler-visible objects — the "snapshots = hashable state dicts"
redesign (SURVEY §2.6 note 5).  Signatures power:

* visited-state pruning in the safety checker (VisitedState.cpp role);
* cycle detection for the liveness checker (LivenessChecker.cpp pairs).

Scope (mirrors the reference's MC_ignore design): the signature covers
*scheduler-visible* state — actors + pending simcalls + activity
queues + sync objects + clock (the reference's snapshots ignore timing
data via MC_ignore; pass include_clock=False for the same untimed
comparison, which the liveness checker needs to close loops whose
iterations advance simulated time).  Actor-local Python state
(counters, flags inside the actor function) is NOT visible — where it
affects future behavior, the actor must surface it with mc.note(key,
value), the explicit-state analog of the reference snapshotting the
application heap.
"""

from __future__ import annotations

from typing import Tuple


def _actor_sig(actor) -> Tuple:
    sc = actor.simcall_
    objs = sc.payload.get("mc_object") if sc.payload else None
    from .explorer import _obj_keys
    waiting = actor.waiting_synchro
    return (
        actor.pid,
        actor.name,
        bool(actor.suspended),
        sc.call,
        tuple(sorted(_obj_keys(objs))),
        sc.handler is not None,
        type(waiting).__name__ if waiting is not None else None,
    )


def _comm_sig(comm) -> Tuple:
    return (
        comm.type.name if hasattr(comm.type, "name") else str(comm.type),
        comm.src_actor.pid if comm.src_actor is not None else None,
        comm.dst_actor.pid if comm.dst_actor is not None else None,
        float(comm.size),
        bool(comm.detached),
        comm.state.name if hasattr(comm.state, "name") else str(comm.state),
    )


def _sync_sig(obj) -> Tuple:
    kind = type(obj).__name__
    if kind == "MutexImpl":
        return (obj.mc_key, bool(obj.locked),
                obj.owner.pid if obj.owner is not None else None,
                tuple(sc.issuer.pid for sc in obj.sleeping))
    if kind == "SemImpl":
        return (obj.mc_key, int(obj.value),
                tuple(sc.issuer.pid for sc in obj.sleeping))
    # ConditionVariableImpl
    return (obj.mc_key, tuple(sc.issuer.pid for sc in obj.sleeping))


def note(key, value) -> None:
    """Record actor-local state the model checker must distinguish
    (loop counters, mode flags): included in every signature under the
    calling actor's pid.  The explicit-state substitute for the
    reference's application-heap snapshot."""
    from ..s4u.actor import _current_impl
    impl = _current_impl()
    impl.engine.mc_notes[(impl.pid, key)] = value


def state_signature(engine, include_clock: bool = True) -> Tuple:
    """Deterministic, hashable signature of the kernel state."""
    actors = tuple(_actor_sig(a)
                   for _, a in sorted(engine.process_list.items()))
    mailboxes = []
    for name in sorted(engine.mailboxes):
        mbox = engine.mailboxes[name]
        if not mbox.comm_queue and not mbox.done_comm_queue:
            continue
        mailboxes.append((name,
                          tuple(_comm_sig(c) for c in mbox.comm_queue),
                          tuple(_comm_sig(c)
                                for c in mbox.done_comm_queue)))
    syncs = []
    for ref in engine.mc_sync_objects:
        obj = ref()
        if obj is not None:
            syncs.append(_sync_sig(obj))
    notes = tuple(sorted(engine.mc_notes.items()))
    return (round(engine.now, 9) if include_clock else None,
            actors, tuple(mailboxes), tuple(syncs), notes)
