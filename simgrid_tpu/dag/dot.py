"""GraphViz DOT workflow loader (reference sd_dotloader.cpp).

A node's ``size`` attribute is the flops of a sequential computation
task (or, with ``sequential=False``, the total work of an Amdahl
parallel task whose serial fraction is the ``alpha`` attribute); an
edge's ``size`` is the bytes of an end-to-end transfer task named
``src->dst`` spliced between the two nodes — a missing or non-positive
size makes the edge a plain control dependency
(sd_dotloader.cpp:155-178).  Nodes named ``root``/``end`` are
synthesized when absent; every source task gains a dependency from
``root`` and every sink a dependency to ``end`` (:187-199).  With
``schedule=True`` the ``performer``/``order`` attributes place each
task on a host, serialising same-performer tasks (:204-229); an
incomplete schedule is ignored with a warning and the load returns
None, as does a cyclic graph (:231-236).

The reference parses via libcgraph; this is a self-contained parser of
the DOT subset those files use (node/edge statements with optional
``[k="v"]`` attribute lists, ``//``, ``/* */`` and ``#`` comments,
quoted identifiers, ``a->b->c`` chains).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..utils import log as _log
from .task import Task, TaskState

_logger = _log.get_category("sd_dotparse")

_TOKEN = re.compile(
    r'\s*(?:"((?:[^"\\]|\\.)*)"'
    r'|((?:[A-Za-z0-9_.+]|-(?!>))+)'   # bare id; "-" only when not "->"
    r'|(->|[\[\]{};=,]))')


def _tokenize(text: str) -> List[str]:
    # strip comments first (none of the quoted attrs here span lines)
    text = re.sub(r"/\*.*?\*/", " ", text, flags=re.S)
    text = re.sub(r"(//|#)[^\n]*", " ", text)
    out, pos = [], 0
    while pos < len(text):
        m = _TOKEN.match(text, pos)
        if m is None:
            if text[pos:].strip():
                raise ValueError(f"DOT parse error at: {text[pos:pos+30]!r}")
            break
        if m.group(1) is not None:
            out.append(m.group(1).replace('\\"', '"'))
        elif m.group(2) is not None:
            out.append(m.group(2))
        else:
            out.append(m.group(3))
        pos = m.end()
    return out


def _parse(path: str):
    """-> (ordered node names, {name: attrs}, [(src, dst, attrs)])."""
    toks = _tokenize(open(path).read())
    i = 0
    # skip to the opening brace: [strict] (di)graph [name] {
    while i < len(toks) and toks[i] != "{":
        i += 1
    i += 1
    names: List[str] = []
    node_attrs: Dict[str, dict] = {}
    edges: List[Tuple[str, str, dict]] = []

    def see(name: str) -> None:
        if name not in node_attrs:
            names.append(name)
            node_attrs[name] = {}

    def attr_list() -> dict:
        nonlocal i
        attrs = {}
        while i < len(toks) and toks[i] == "[":
            i += 1
            while toks[i] != "]":
                k = toks[i]
                if toks[i + 1] == "=":
                    attrs[k] = toks[i + 2]
                    i += 3
                else:
                    attrs[k] = ""
                    i += 1
                if toks[i] == ",":
                    i += 1
            i += 1
        return attrs

    while i < len(toks) and toks[i] != "}":
        if toks[i] == ";":
            i += 1
            continue
        head = toks[i]
        i += 1
        if head in ("graph", "node", "edge") and i < len(toks) \
                and toks[i] == "[":
            attr_list()            # default-attr statements: ignored
            continue
        chain = [head]
        while i < len(toks) and toks[i] == "->":
            chain.append(toks[i + 1])
            i += 2
        attrs = attr_list()
        for name in chain:
            see(name)
        if len(chain) == 1:
            node_attrs[head].update(attrs)
        else:
            for src, dst in zip(chain, chain[1:]):
                edges.append((src, dst, attrs))
    return names, node_attrs, edges


def _atof(value: Optional[str]) -> float:
    try:
        return float(value)
    except (TypeError, ValueError):
        return 0.0          # C atof on a missing/empty attribute


def load_dot(path: str, sequential: bool = True, schedule: bool = False,
             hosts=None) -> Optional[List[Task]]:
    """SD_dotload / SD_PTG_dotload(sequential=False) /
    SD_dotload_with_sched(schedule=True, hosts=engine hosts)."""
    names, node_attrs, edge_list = _parse(path)

    def make_comp(name: str, attrs: dict) -> Task:
        amount = _atof(attrs.get("size"))
        if sequential:
            return Task.create_comp_seq(name, amount)
        return Task.create_comp_par_amdahl(name, amount,
                                           _atof(attrs.get("alpha")))

    jobs: Dict[str, Task] = {}
    result: List[Task] = []
    computers: Dict[str, List[Optional[Task]]] = {}
    schedule_success = True
    for name in names:
        attrs = node_attrs[name]
        task = make_comp(name, attrs)
        jobs[name] = task
        if name not in ("root", "end"):
            result.append(task)
        if sequential and schedule and schedule_success:
            performer = int(attrs.get("performer") or -1)
            order = int(attrs.get("order") or -1)
            if performer < 0 or order < 0 or (
                    hosts is not None and performer >= len(hosts)):
                _logger.verbose(
                    "The schedule is ignored, task '%s' can not be "
                    "scheduled on %d hosts", name, performer)
                schedule_success = False
                continue
            slots = computers.setdefault(str(performer), [])
            if order < len(slots) and slots[order] not in (None, task):
                _logger.verbose(
                    "Task '%s' wants to start on performer '%s' at the "
                    "same position '%s' as task '%s'",
                    slots[order].name, performer, order, name)
                schedule_success = False
                continue
            slots.extend([None] * (order + 1 - len(slots)))
            slots[order] = task

    root = jobs.get("root") or make_comp("root", {})
    root.state = TaskState.SCHEDULABLE
    result.insert(0, root)
    end = jobs.get("end") or make_comp("end", {})
    jobs.setdefault("root", root)
    jobs.setdefault("end", end)

    for src_name, dst_name, attrs in edge_list:
        src, dst = jobs[src_name], jobs[dst_name]
        size = _atof(attrs.get("size"))
        if size > 0:
            name = f"{src_name}->{dst_name}"
            if any(t.name == name for t in result):
                _logger.warning("Task '%s' is defined more than once", name)
                continue
            transfer = Task.create_comm_e2e(name, size)
            transfer.depends_on(src)
            dst.depends_on(transfer)
            result.append(transfer)
        else:
            dst.depends_on(src)

    result.append(end)

    # connect entry tasks to root and exit tasks to end (:187-199)
    for task in result:
        if not task.predecessors and task is not root:
            task.depends_on(root)
        if not task.successors and task is not end:
            end.depends_on(task)

    if schedule:
        if not schedule_success:
            _logger.warning("The scheduling is ignored")
            return None
        assert hosts is not None, "schedule=True needs the platform hosts"
        for performer, slots in computers.items():
            previous = None
            for task in slots:
                if task is None:
                    continue
                if previous is not None \
                        and previous not in task.predecessors:
                    task.depends_on(previous)
                task.schedule([hosts[int(performer)]])
                previous = task

    if not _acyclic(result):
        _logger.error("The DOT described in %s is not a DAG. It contains "
                      "a cycle.", path.rsplit("/", 1)[-1])
        return None
    return result


def _acyclic(tasks: List[Task]) -> bool:
    indeg = {id(t): len(t.predecessors) for t in tasks}
    queue = [t for t in tasks if indeg[id(t)] == 0]
    seen = 0
    while queue:
        task = queue.pop()
        seen += 1
        for nxt in task.successors:
            indeg[id(nxt)] -= 1
            if indeg[id(nxt)] == 0:
                queue.append(nxt)
    return seen == len(tasks)
