"""The DAG simulation loop (reference SD_simulate, sd_global.cpp):
start every runnable scheduled task as a kernel-model action, advance
surf time, and on completion release the dependents — no actors
involved."""

from __future__ import annotations

from typing import Dict, List, Optional

from ..utils import log as _log
from .task import Task, TaskKind, TaskState

_logger = _log.get_category("sd")


class DagEngine:
    """Drives a set of DAG tasks over an s4u Engine's platform."""

    def __init__(self, engine):
        self.engine = engine.pimpl if hasattr(engine, "pimpl") else engine
        self.tasks: List[Task] = []
        self._running: Dict[int, Task] = {}

    def add(self, *tasks: Task) -> None:
        self.tasks.extend(tasks)

    # -- execution ---------------------------------------------------------
    def _start(self, task: Task) -> None:
        e = self.engine
        task.state = TaskState.RUNNING
        task.start_time = e.now
        if task.kind == TaskKind.COMM_E2E:
            src, dst = task.hosts
            action = e.network_model.communicate(src, dst,
                                                 task.bytes_amount, -1.0)
        elif task.kind == TaskKind.COMP_PAR_AMDAHL:
            # One execution per host; the task completes when all do.
            # Modeled as the max share on one action per host; for
            # simplicity the amounts are equal, so one representative
            # action per host tracked jointly.
            actions = [host.cpu.execution_start(fl, 1)
                       for host, fl in zip(task.hosts,
                                           task.flops_amounts)]
            task._action = actions
            for a in actions:
                self._running[id(a)] = task
            return
        else:
            host = task.hosts[0]
            action = host.cpu.execution_start(task.flops_amounts[0], 1)
        task._action = action
        self._running[id(action)] = task

    def _collect_finished(self) -> List[Task]:
        done = []
        for model in self.engine.models:
            action = model.extract_done_action()
            while action is not None:
                task = self._running.pop(id(action), None)
                if task is not None:
                    if isinstance(task._action, list):
                        task._action.remove(action)
                        if not task._action:
                            done.append(task)
                    else:
                        done.append(task)
                # No actor holds a reference: release the LMM variable
                # now or the dead action keeps consuming its resource's
                # share forever.
                action.unref()
                action = model.extract_done_action()
            action = model.extract_failed_action()
            while action is not None:
                task = self._running.pop(id(action), None)
                if task is not None:
                    task.state = TaskState.FAILED
                action.unref()
                action = model.extract_failed_action()
        return done

    def simulate(self, until: float = -1.0) -> List[Task]:
        """SD_simulate: run until every scheduled task completed (or
        `until`); returns the tasks completed during the call."""
        e = self.engine
        completed: List[Task] = []

        def launch_ready():
            started = 0
            for task in self.tasks:
                if task.state == TaskState.SCHEDULED and task.is_ready():
                    task.state = TaskState.RUNNABLE
                if task.state == TaskState.RUNNABLE:
                    self._start(task)
                    started += 1
            return started

        launch_ready()
        while self._running:
            delta = e.surf_solve(until if until > 0 else -1.0)
            if delta < 0:
                break
            for task in self._collect_finished():
                task.state = TaskState.DONE
                task.finish_time = e.now
                completed.append(task)
                _logger.debug("Task '%s' done at %f", task.name, e.now)
            launch_ready()
            if until > 0 and e.now >= until:
                break
        return completed

    @property
    def clock(self) -> float:
        return self.engine.now

    # -- introspection -----------------------------------------------------
    def schedulable_tasks(self) -> List[Task]:
        return [t for t in self.tasks
                if t.state == TaskState.NOT_SCHEDULED and t.is_ready()]

    def makespan(self) -> float:
        return max((t.finish_time for t in self.tasks
                    if t.state == TaskState.DONE), default=0.0)
