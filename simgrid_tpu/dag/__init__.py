"""SimDag equivalent: DAG scheduling without actors.

Reference: src/simdag/ — typed tasks (sequential computation,
end-to-end communication) with dependencies, scheduled onto hosts and
executed directly as kernel-model actions (the reference's SimDag layer
has no actors either: SD_simulate drives surf directly,
sd_global.cpp). Includes the Pegasus DAX workflow loader
(sd_daxloader.cpp) with the same conventions: runtimes scaled by the
assumed 4.2 GFlops reference machine, per-file transfer tasks named
parent_file_child, synthetic root/end tasks.
"""

from .task import Task, TaskKind, TaskState
from .engine import DagEngine
from .dax import load_dax
from .dot import load_dot

__all__ = ["Task", "TaskKind", "TaskState", "DagEngine", "load_dax",
           "load_dot"]
