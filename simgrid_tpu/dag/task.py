"""DAG tasks (reference src/simdag/sd_task.cpp)."""

from __future__ import annotations

from enum import Enum
from typing import List, Optional


class TaskKind(Enum):
    NOT_TYPED = 0
    COMP_SEQ = 1        # sequential computation (flops)
    COMM_E2E = 2        # end-to-end transfer (bytes)
    COMP_PAR_AMDAHL = 3  # parallel computation with serial fraction


class TaskState(Enum):
    NOT_SCHEDULED = 0
    SCHEDULABLE = 1     # dependencies satisfied, awaiting scheduling
    SCHEDULED = 2
    RUNNABLE = 3        # scheduled + dependencies satisfied
    RUNNING = 4
    DONE = 5
    FAILED = 6


class Task:
    """A node of the DAG (SD_task_t)."""

    def __init__(self, name: str, amount: float,
                 kind: TaskKind = TaskKind.NOT_TYPED, data=None):
        self.name = name
        self.amount = amount
        self.kind = kind
        self.data = data
        self.state = TaskState.NOT_SCHEDULED
        self.predecessors: List["Task"] = []
        self.successors: List["Task"] = []
        self.hosts: List = []
        self.flops_amounts: List[float] = []
        self.bytes_amount: float = 0.0
        self.alpha = 0.0              # Amdahl serial fraction
        self.start_time = -1.0
        self.finish_time = -1.0
        self._unsatisfied = 0
        self._action = None

    # -- constructors (simgrid/simdag.h:104-107) --------------------------
    @staticmethod
    def create_comp_seq(name: str, amount: float, data=None) -> "Task":
        return Task(name, amount, TaskKind.COMP_SEQ, data)

    @staticmethod
    def create_comm_e2e(name: str, amount: float, data=None) -> "Task":
        return Task(name, amount, TaskKind.COMM_E2E, data)

    @staticmethod
    def create_comp_par_amdahl(name: str, amount: float, alpha: float,
                               data=None) -> "Task":
        task = Task(name, amount, TaskKind.COMP_PAR_AMDAHL, data)
        task.alpha = alpha
        return task

    # -- dependencies (sd_task.cpp SD_task_dependency_add) ---------------
    def depends_on(self, other: "Task") -> None:
        """other -> self ordering."""
        assert self not in other.successors, \
            f"Dependency {other.name} -> {self.name} already exists"
        other.successors.append(self)
        self.predecessors.append(other)

    @staticmethod
    def dependency_add(src: "Task", dst: "Task") -> None:
        dst.depends_on(src)

    # -- scheduling (SD_task_schedule / schedulev) ------------------------
    def schedule(self, hosts, flops_amounts=None,
                 bytes_amount: Optional[float] = None) -> None:
        assert self.state in (TaskState.NOT_SCHEDULED,
                              TaskState.SCHEDULABLE), \
            f"Task {self.name} cannot be scheduled in state {self.state}"
        self.hosts = list(hosts)
        if self.kind == TaskKind.COMP_SEQ:
            assert len(self.hosts) == 1
            self.flops_amounts = list(flops_amounts) if flops_amounts \
                else [self.amount]
        elif self.kind == TaskKind.COMM_E2E:
            assert len(self.hosts) == 2
            self.bytes_amount = bytes_amount if bytes_amount is not None \
                else self.amount
        elif self.kind == TaskKind.COMP_PAR_AMDAHL:
            n = len(self.hosts)
            share = self.amount * (self.alpha + (1 - self.alpha) / n)
            self.flops_amounts = [share] * n
        self.state = TaskState.SCHEDULED

    def is_ready(self) -> bool:
        return all(p.state == TaskState.DONE for p in self.predecessors)

    def __repr__(self):
        return (f"<Task {self.name} {self.kind.name} {self.state.name} "
                f"amount={self.amount:g}>")
