"""Pegasus DAX workflow loader (reference sd_daxloader.cpp).

Jobs become sequential computation tasks with flops = runtime x 4.2e9
(the reference assumes timings from a 4.2 GFlops machine,
sd_daxloader.cpp:252). Every file becomes one end-to-end transfer task
per (producer, consumer) pair, named parent_file_child (:210
uniq_transfer_task_name); files no job produces come from the synthetic
`root` task, files no job consumes feed the synthetic `end` task
(:164-183). The result is verified acyclic."""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Dict, List

from ..exceptions import ParseError
from ..utils import log as _log
from .task import Task, TaskKind, TaskState

_logger = _log.get_category("sd_daxparse")

#: flops per unit of DAX "runtime" (sd_daxloader.cpp:252)
RUNTIME_SCALE = 4_200_000_000.0


def _local(tag: str) -> str:
    return tag.rsplit("}", 1)[-1]


def load_dax(path: str) -> List[Task]:
    try:
        tree = ET.parse(path)
    except ET.ParseError as exc:
        raise ParseError(f"{path}: {exc}") from None

    root_task = Task.create_comp_seq("root", 0.0)
    root_task.state = TaskState.SCHEDULABLE
    end_task = Task.create_comp_seq("end", 0.0)

    jobs: Dict[str, Task] = {}
    file_sizes: Dict[str, float] = {}
    producers: Dict[str, List[Task]] = {}
    consumers: Dict[str, List[Task]] = {}
    file_io: Dict[int, List[int]] = {}   # id(task) -> [n_in, n_out]

    for job in tree.getroot():
        if _local(job.tag) != "job":
            continue
        job_id = job.get("id")
        name = f"{job_id}@{job.get('name', '')}"
        runtime = float(job.get("runtime")) * RUNTIME_SCALE
        task = Task.create_comp_seq(name, runtime)
        jobs[job_id] = task
        file_io[id(task)] = [0, 0]
        for uses in job:
            if _local(uses.tag) != "uses":
                continue
            fname = uses.get("file")
            size = float(uses.get("size", 0))
            if fname in file_sizes and file_sizes[fname] != size:
                _logger.warning("Ignore file %s size redefinition from %.0f"
                                " to %.0f", fname, file_sizes[fname], size)
            else:
                file_sizes[fname] = size
            if uses.get("link") == "input":
                consumers.setdefault(fname, []).append(task)
                file_io[id(task)][0] += 1
            else:
                producers.setdefault(fname, []).append(task)
                file_io[id(task)][1] += 1

    # <child ref><parent ref/></child>: control dependencies.
    for child in tree.getroot():
        if _local(child.tag) != "child":
            continue
        child_task = jobs[child.get("ref")]
        for parent in child:
            if _local(parent.tag) == "parent":
                child_task.depends_on(jobs[parent.get("ref")])

    # Files: one transfer task per (producer, consumer) pair; files
    # nobody produces come from root, files nobody consumes go to end
    # (sd_daxloader.cpp:164-200).
    transfers: List[Task] = []

    def add_transfer(producer: Task, fname: str, consumer: Task) -> None:
        transfer = Task.create_comm_e2e(
            f"{producer.name}_{fname}_{consumer.name}", file_sizes[fname])
        transfer.depends_on(producer)
        consumer.depends_on(transfer)
        transfers.append(transfer)

    for fname in file_sizes:
        prods = producers.get(fname, [])
        cons = consumers.get(fname, [])
        if not prods:
            for consumer in cons:
                add_transfer(root_task, fname, consumer)
        if not cons:
            for producer in prods:
                add_transfer(producer, fname, end_task)
        for producer in prods:
            for consumer in cons:
                if producer is consumer:
                    _logger.warning(
                        "File %s is produced and consumed by task %s. "
                        "This loop dependency will prevent the execution "
                        "of the task.", fname, producer.name)
                add_transfer(producer, fname, consumer)

    # Jobs touching no files hook directly to root/end
    # (sd_daxloader.cpp:216-222).
    for task in jobs.values():
        n_in, n_out = file_io[id(task)]
        if n_in == 0:
            task.depends_on(root_task)
        if n_out == 0:
            end_task.depends_on(task)

    tasks = [root_task] + list(jobs.values()) + transfers + [end_task]
    _check_acyclic(tasks)
    return tasks


def _check_acyclic(tasks: List[Task]) -> None:
    """Kahn's algorithm over the built DAG (acyclic_graph_detail)."""
    indeg = {id(t): len(t.predecessors) for t in tasks}
    queue = [t for t in tasks if indeg[id(t)] == 0]
    seen = 0
    while queue:
        task = queue.pop()
        seen += 1
        for succ in task.successors:
            indeg[id(succ)] -= 1
            if indeg[id(succ)] == 0:
                queue.append(succ)
    if seen != len(tasks):
        raise ParseError("The loaded DAX workflow is not a DAG "
                         "(cycle detected)")
