"""Paje trace core: typed container hierarchy, event buffer, sinks.

Re-implements the reference's instrumentation data model
(src/instr/instr_paje_{types,containers,events,header,trace}.cpp) in
host Python: a tree of trace *types* (container/state/variable/link/
event), a tree of *containers* mirroring the platform, and timestamped
events buffered in nondecreasing order and flushed whenever simulated
time advances (TRACE_paje_dump_buffer, instr_paje_trace.cpp:47-70).

The same event stream doubles as the TI (time-independent) trace writer
(instr_private.hpp:35-41): in TI mode StateEvents carrying TIData are
written as replayable action lines to per-rank files instead.
"""

from __future__ import annotations

import bisect
import os
from typing import Dict, List, Optional, TextIO

# e_event_type (instr_paje_events.hpp:19-38) — the numeric codes used in
# both the header %EventDef lines and the event records.
PAJE_DefineContainerType = 0
PAJE_DefineVariableType = 1
PAJE_DefineStateType = 2
PAJE_DefineEventType = 3
PAJE_DefineLinkType = 4
PAJE_DefineEntityValue = 5
PAJE_CreateContainer = 6
PAJE_DestroyContainer = 7
PAJE_SetVariable = 8
PAJE_AddVariable = 9
PAJE_SubVariable = 10
PAJE_SetState = 11
PAJE_PushState = 12
PAJE_PopState = 13
PAJE_ResetState = 14
PAJE_StartLink = 15
PAJE_EndLink = 16
PAJE_NewEvent = 17

PAJE_FORMAT = "Paje"
TI_FORMAT = "TI"


def _fmt_time(t: float, precision: int = 9) -> str:
    return f"{t:.{precision}f}"


class EntityValue:
    """A named value of a state/event type (instr_paje_values.cpp)."""

    def __init__(self, trace: "Trace", name: str, color: str,
                 father: "Type"):
        self.id = trace.new_id()
        self.name = name
        self.color = color
        self.father = father
        if trace.format == PAJE_FORMAT:
            line = (f"{PAJE_DefineEntityValue} {self.id} "
                    f"{father.id} {name}")
            if color:
                line += f' "{color}"'
            trace.write_line(line)


class Type:
    """A node of the trace type tree (instr_paje_types.cpp)."""

    def __init__(self, trace: "Trace", kind: int, name: str,
                 father: Optional["Type"], color: str = "",
                 source: Optional["Type"] = None,
                 dest: Optional["Type"] = None):
        self.trace = trace
        self.kind = kind
        self.name = name
        self.father = father
        self.color = color
        self.children: Dict[str, "Type"] = {}
        self.values: Dict[str, EntityValue] = {}
        self.id = trace.new_id()
        if father is not None:
            father.children[name] = self
            self._log_definition(source, dest)

    def _log_definition(self, source, dest) -> None:
        if self.trace.format != PAJE_FORMAT:
            return
        if self.kind == PAJE_DefineLinkType:
            self.trace.write_line(
                f"{self.kind} {self.id} {self.father.id} {source.id} "
                f"{dest.id} {self.name}")
        else:
            line = f"{self.kind} {self.id} {self.father.id} {self.name}"
            if self.color:
                line += f' "{self.color}"'
            self.trace.write_line(line)

    # -- child type factories (Type::by_name_or_create) -------------------
    def container_type(self, name: str) -> "Type":
        return self.children.get(name) or Type(
            self.trace, PAJE_DefineContainerType, name, self)

    def state_type(self, name: str) -> "Type":
        return self.children.get(name) or Type(
            self.trace, PAJE_DefineStateType, name, self)

    def variable_type(self, name: str, color: str = "") -> "Type":
        return self.children.get(name) or Type(
            self.trace, PAJE_DefineVariableType, name, self, color=color)

    def event_type(self, name: str) -> "Type":
        return self.children.get(name) or Type(
            self.trace, PAJE_DefineEventType, name, self)

    def link_type(self, name: str, source: "Type", dest: "Type") -> "Type":
        full = f"{name}-{source.id}-{dest.id}"
        return self.children.get(full) or Type(
            self.trace, PAJE_DefineLinkType, full, self,
            source=source, dest=dest)

    def value(self, name: str, color: str = "") -> EntityValue:
        val = self.values.get(name)
        if val is None:
            val = EntityValue(self.trace, name, color, self)
            self.values[name] = val
        return val


class Container:
    """A node of the container tree (instr_paje_containers.cpp)."""

    def __init__(self, trace: "Trace", name: str, type_name: str,
                 father: Optional["Container"]):
        self.trace = trace
        self.name = name
        self.father = father
        self.children: Dict[str, "Container"] = {}
        if father is None:
            self.type = Type(trace, PAJE_DefineContainerType, "0", None)
            self.id = "0"
            trace.root_container = self
        else:
            self.type = father.type.container_type(type_name)
            self.id = str(trace.new_id())
            father.children[name] = self
        trace.containers_by_name[name] = self
        self._log_creation()

    def _log_creation(self) -> None:
        t = self.trace
        if t.format == PAJE_FORMAT:
            if self.father is not None:
                t.write_line(
                    f"{PAJE_CreateContainer} {_fmt_time(t.clock())} "
                    f"{self.id} {self.type.id} {self.father.id} "
                    f'"{self._display_name()}"')
        elif t.format == TI_FORMAT and self.type.name == "MPI":
            # Only MPI rank containers produce replayable TI files.
            t.open_ti_file(self)

    def _display_name(self) -> str:
        # rank-N containers are renamed to the 0-based rank in the trace
        # (instr_paje_containers.cpp Container::log_creation).
        return self.name

    def remove_from_parent(self) -> None:
        t = self.trace
        for child in list(self.children.values()):
            child.remove_from_parent()
        if t.format == PAJE_FORMAT and self.father is not None:
            t.flush(force=True)
            t.write_line(f"{PAJE_DestroyContainer} {_fmt_time(t.clock())} "
                         f"{self.type.id} {self.id}")
        elif t.format == TI_FORMAT:
            t.close_ti_file(self)
        if self.father is not None:
            self.father.children.pop(self.name, None)
        t.containers_by_name.pop(self.name, None)

    def child(self, name: str, type_name: str) -> "Container":
        return self.children.get(name) or Container(
            self.trace, name, type_name, self)


class PajeEvent:
    """A buffered timestamped event (instr_paje_events.cpp)."""

    __slots__ = ("event_type", "timestamp", "type", "container", "tail")

    def __init__(self, trace: "Trace", container: Container, type_: Type,
                 event_type: int, tail: str = "", timestamp=None):
        self.event_type = event_type
        self.timestamp = trace.clock() if timestamp is None else timestamp
        self.type = type_
        self.container = container
        self.tail = tail
        trace.insert_into_buffer(self)

    def render(self, precision: int) -> str:
        line = (f"{self.event_type} {_fmt_time(self.timestamp, precision)} "
                f"{self.type.id} {self.container.id}")
        if self.tail:
            line += f" {self.tail}"
        return line


class TIEvent:
    """A TI-mode action line routed to its rank's trace file; buffered in
    the same stream as Paje events to keep flush ordering uniform."""

    __slots__ = ("timestamp", "container", "line", "event_type")

    def __init__(self, trace: "Trace", container: Container, line: str,
                 timestamp=None):
        self.event_type = -1
        self.timestamp = trace.clock() if timestamp is None else timestamp
        self.container = container
        self.line = line
        trace.insert_into_buffer(self)


class Trace:
    """One tracing session: output file(s), type/container trees, buffer.

    Owned by the engine that started tracing; `flush()` is wired to the
    engine's time-advance signal so events with timestamps at or before
    the new simulated NOW hit the file in order, exactly when the
    reference calls TRACE_paje_dump_buffer (surf_c_bindings.cpp:148).
    """

    def __init__(self, filename: str, fmt: str, clock_getter,
                 precision: int = 9, display_sizes: bool = False):
        self.format = fmt
        self.filename = filename
        self.clock = clock_getter
        self.precision = precision
        self.display_sizes = display_sizes
        self._next_id = 0
        self.containers_by_name: Dict[str, Container] = {}
        self.root_container: Optional[Container] = None
        self._buffer: List = []
        self._keys: List[float] = []  # timestamps, for bisect insertion
        self.ti_files: Dict[str, TextIO] = {}
        self.file: Optional[TextIO] = open(filename, "w")
        if fmt == PAJE_FORMAT:
            self._write_header()

    # -- ids ---------------------------------------------------------------
    def new_id(self) -> int:
        self._next_id += 1
        return self._next_id

    # -- raw write ---------------------------------------------------------
    def write_line(self, line: str) -> None:
        self.file.write(line + "\n")

    def comment(self, text: str) -> None:
        self.write_line(f"# {text}")

    # -- header (instr_paje_header.cpp; non-basic, sizes optional) --------
    def _write_header(self) -> None:
        w = self.write_line
        defs = [
            ("PajeDefineContainerType", PAJE_DefineContainerType,
             ["Alias string", "Type string", "Name string"]),
            ("PajeDefineVariableType", PAJE_DefineVariableType,
             ["Alias string", "Type string", "Name string", "Color color"]),
            ("PajeDefineStateType", PAJE_DefineStateType,
             ["Alias string", "Type string", "Name string"]),
            ("PajeDefineEventType", PAJE_DefineEventType,
             ["Alias string", "Type string", "Name string"]),
            ("PajeDefineLinkType", PAJE_DefineLinkType,
             ["Alias string", "Type string", "StartContainerType string",
              "EndContainerType string", "Name string"]),
            ("PajeDefineEntityValue", PAJE_DefineEntityValue,
             ["Alias string", "Type string", "Name string", "Color color"]),
            ("PajeCreateContainer", PAJE_CreateContainer,
             ["Time date", "Alias string", "Type string",
              "Container string", "Name string"]),
            ("PajeDestroyContainer", PAJE_DestroyContainer,
             ["Time date", "Type string", "Name string"]),
            ("PajeSetVariable", PAJE_SetVariable,
             ["Time date", "Type string", "Container string",
              "Value double"]),
            ("PajeAddVariable", PAJE_AddVariable,
             ["Time date", "Type string", "Container string",
              "Value double"]),
            ("PajeSubVariable", PAJE_SubVariable,
             ["Time date", "Type string", "Container string",
              "Value double"]),
            ("PajeSetState", PAJE_SetState,
             ["Time date", "Type string", "Container string",
              "Value string"]),
            ("PajePushState", PAJE_PushState,
             ["Time date", "Type string", "Container string",
              "Value string"]
             + (["Size int"] if self.display_sizes else [])),
            ("PajePopState", PAJE_PopState,
             ["Time date", "Type string", "Container string"]),
            ("PajeResetState", PAJE_ResetState,
             ["Time date", "Type string", "Container string"]),
            ("PajeStartLink", PAJE_StartLink,
             ["Time date", "Type string", "Container string",
              "Value string", "StartContainer string", "Key string"]
             + (["Size int"] if self.display_sizes else [])),
            ("PajeEndLink", PAJE_EndLink,
             ["Time date", "Type string", "Container string",
              "Value string", "EndContainer string", "Key string"]),
            ("PajeNewEvent", PAJE_NewEvent,
             ["Time date", "Type string", "Container string",
              "Value string"]),
        ]
        for name, code, fields in defs:
            w(f"%EventDef {name} {code}")
            for field in fields:
                w(f"%       {field}")
            w("%EndEventDef")

    # -- TI per-rank files -------------------------------------------------
    def open_ti_file(self, container: Container) -> None:
        folder = self.filename + "_files"
        os.makedirs(folder, exist_ok=True)
        path = os.path.join(folder, f"{container.name}.txt")
        self.ti_files[container.name] = open(path, "w")
        # The master trace file lists the per-rank files (what
        # smpi_replay consumes as the trace-file list).
        self.write_line(path)

    def close_ti_file(self, container: Container) -> None:
        if container.name in self.ti_files:
            # Flush while the file is still registered — pending events
            # for this rank must land before the handle goes away.
            self.flush(force=True)
            self.ti_files.pop(container.name).close()

    # -- buffer (insert_into_buffer, instr_paje_trace.cpp:76-100) ---------
    def insert_into_buffer(self, event) -> None:
        pos = bisect.bisect_right(self._keys, event.timestamp)
        self._keys.insert(pos, event.timestamp)
        self._buffer.insert(pos, event)

    def flush(self, up_to: Optional[float] = None, force: bool = False
              ) -> None:
        """Dump buffered events with timestamp <= up_to (all if force)."""
        if force or up_to is None:
            n = len(self._buffer)
        else:
            n = bisect.bisect_right(self._keys, up_to)
        for event in self._buffer[:n]:
            self._print(event)
        del self._buffer[:n]
        del self._keys[:n]

    def _print(self, event) -> None:
        if isinstance(event, TIEvent):
            f = self.ti_files.get(event.container.name)
            if f is not None:
                f.write(event.line + "\n")
        elif self.format == PAJE_FORMAT:
            self.write_line(event.render(self.precision))

    def close(self) -> None:
        self.flush(force=True)
        if self.root_container is not None:
            self.root_container.remove_from_parent()
            self.root_container = None
        self.flush(force=True)
        for f in self.ti_files.values():
            f.close()
        self.ti_files.clear()
        if self.file is not None:
            self.file.close()
            self.file = None
