"""Jedule output: DAG schedule visualization XML (reference
src/instr/jedule/): platform topology + one event per completed task
with its host set and start/end times, loadable by the Jedule
visualizer."""

from __future__ import annotations

from xml.sax.saxutils import quoteattr


def dump_jedule(dag_engine, path: str) -> None:
    """Write the schedule of a completed DagEngine run
    (jedule_sd_binding.cpp jedule_sd_dump)."""
    engine = dag_engine.engine
    lines = ['<?xml version="1.0"?>', "<jedule>", "  <jedule_meta>",
             '    <prop key="description" value="simgrid_tpu jedule"/>',
             "  </jedule_meta>", "  <platform>",
             '    <container name="root">']
    for host in engine.hosts.values():
        lines.append(f'      <resource name={quoteattr(host.name)} '
                     f'type="host"/>')
    lines += ["    </container>", "  </platform>", "  <events>"]
    for task in dag_engine.tasks:
        if task.finish_time < 0:
            continue
        hosts = " ".join(h.name for h in task.hosts)
        lines.append(
            f'    <event name={quoteattr(task.name)} '
            f'start="{task.start_time:.9f}" end="{task.finish_time:.9f}" '
            f'resources={quoteattr(hosts)} '
            f'type="{task.kind.name.lower()}"/>')
    lines += ["  </events>", "</jedule>"]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
