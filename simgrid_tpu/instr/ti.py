"""TI (time-independent) action payloads attached to SMPI state events.

Mirrors the reference's TIData class family (instr_private.hpp:42-190):
each SMPI call carries one of these; in TI trace mode its `print()`
becomes the replayable action line (consumed by smpi.replay), in Paje
mode `display_size()` is appended to the PushState event when
tracing/smpi/display-sizes is on.
"""

from __future__ import annotations

from typing import List, Optional


def _num(x: float) -> str:
    """Render like C++ ostream<<double: ints stay bare."""
    f = float(x)
    return str(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


class TIData:
    def __init__(self, name: str):
        self.name = name

    def print(self) -> str:
        return self.name

    def display_size(self) -> str:
        return "NA"


class NoOpTIData(TIData):
    """init, finalize, test, wait, barrier."""


class CpuTIData(TIData):
    """compute, sleep (instr_private.hpp:106-116)."""

    def __init__(self, name: str, amount: float):
        super().__init__(name)
        self.amount = amount

    def print(self) -> str:
        return f"{self.name} {_num(self.amount)}"

    def display_size(self) -> str:
        return _num(self.amount)


class Pt2PtTIData(TIData):
    """send, isend, recv, irecv (instr_private.hpp:118-134)."""

    def __init__(self, name: str, endpoint: int, size: int, tag: int,
                 datatype: str = ""):
        super().__init__(name)
        self.endpoint = endpoint
        self.size = size
        self.tag = tag
        self.datatype = datatype

    def print(self) -> str:
        return (f"{self.name} {self.endpoint} {self.tag} "
                f"{self.size} {self.datatype}")

    def display_size(self) -> str:
        return str(self.size)


class WaitTIData(TIData):
    """wait carries the (src, dst, tag) of the awaited request
    (WaitTIData in instr_smpi.hpp)."""

    def __init__(self, src: int, dst: int, tag: int):
        super().__init__("wait")
        self.src, self.dst, self.tag = src, dst, tag

    def print(self) -> str:
        return f"wait {self.src} {self.dst} {self.tag}"


class CollTIData(TIData):
    """bcast, reduce, allreduce, gather, scatter, allgather, alltoall
    (instr_private.hpp:136-158)."""

    def __init__(self, name: str, root: int, amount: float, send_size: int,
                 recv_size: int, send_type: str = "", recv_type: str = ""):
        super().__init__(name)
        self.root = root
        self.amount = amount
        self.send_size = send_size
        self.recv_size = recv_size
        self.send_type = send_type
        self.recv_type = recv_type

    def print(self) -> str:
        parts = [self.name, str(self.send_size)]
        if self.recv_size >= 0:
            parts.append(str(self.recv_size))
        if self.amount >= 0.0:
            parts.append(_num(self.amount))
        if self.root > 0 or (self.root == 0 and self.send_type):
            parts.append(str(self.root))
        parts.append(f"{self.send_type} {self.recv_type}")
        return " ".join(parts)

    def display_size(self) -> str:
        return str(self.send_size)


class VarCollTIData(TIData):
    """gatherv, scatterv, allgatherv, alltoallv, reducescatter
    (instr_private.hpp:160-190)."""

    def __init__(self, name: str, root: int, send_size: int,
                 sendcounts: Optional[List[int]], recv_size: int,
                 recvcounts: Optional[List[int]], send_type: str = "",
                 recv_type: str = ""):
        super().__init__(name)
        self.root = root
        self.send_size = send_size
        self.sendcounts = sendcounts
        self.recv_size = recv_size
        self.recvcounts = recvcounts
        self.send_type = send_type
        self.recv_type = recv_type

    def print(self) -> str:
        parts = [self.name]
        if self.send_size >= 0:
            parts.append(str(self.send_size))
        if self.sendcounts is not None:
            parts.extend(str(c) for c in self.sendcounts)
        if self.recv_size >= 0:
            parts.append(str(self.recv_size))
        if self.recvcounts is not None:
            parts.extend(str(c) for c in self.recvcounts)
        if self.root > 0 or (self.root == 0 and self.send_type):
            parts.append(str(self.root))
        parts.append(f"{self.send_type} {self.recv_type}")
        return " ".join(parts)

    def display_size(self) -> str:
        return str(self.send_size if self.send_size > 0 else self.recv_size)
