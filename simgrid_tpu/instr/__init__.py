"""Instrumentation: Paje + TI trace sinks over the kernel's signals.

The reference hooks its tracing into the kernel via xbt::signal
callbacks and flushes the event buffer on every time advance
(surf_c_bindings.cpp:148 -> instr_paje_trace.cpp:47); this package does
the same over the Python kernel's engine-scoped signals. Enable with
--cfg=tracing:yes (+ tracing/platform, tracing/actor,
tracing/uncategorized, tracing/smpi, tracing/filename, tracing/format).

TPU note: tracing is a pure host-side sink — it observes the event loop,
never the device solve, so enabling it does not perturb the jitted LMM
path (device steps are surfaced via jax.profiler annotations instead).
"""

from __future__ import annotations

import hashlib
from typing import Dict, Optional

from ..utils.config import config, declare_flag
from . import ti
from .paje import (Container, PAJE_EndLink, PAJE_PopState, PAJE_PushState,
                   PAJE_SetVariable, PAJE_StartLink, PajeEvent, TIEvent,
                   Trace, TI_FORMAT, PAJE_FORMAT)

declare_flag("tracing/precision",
             "Numerical precision used when timestamping events", 9)
declare_flag("tracing/smpi/display-sizes",
             "Add message size information to the SMPI states/links", False)
declare_flag("tracing/smpi/grouped",
             "Group MPI rank containers under their host container", True)

# Known state colors (instr_smpi.cpp:30-80); others are hash-derived.
_COLORS = {
    "computing": "0 1 1",
    "sleeping": "0 0.5 0.5",
    "MPI_STATE": "",
}

_trace: Optional[Trace] = None
_rank_hosts: Dict[int, object] = {}
_link_keys: Dict[str, list] = {}
_link_key_counter = 0


def find_color(name: str) -> str:
    color = _COLORS.get(name)
    if color is None:
        h = hashlib.md5(name.encode()).digest()
        color = (f"{h[0] / 255:.3f} {h[1] / 255:.3f} {h[2] / 255:.3f}")
        _COLORS[name] = color
    return color


def is_enabled() -> bool:
    return _trace is not None


def trace() -> Trace:
    return _trace


def container(name: str) -> Container:
    return _trace.containers_by_name[name]


# ---------------------------------------------------------------------------
# Session lifecycle
# ---------------------------------------------------------------------------

def start(engine_impl) -> None:
    """TRACE_start equivalent (instr_config.cpp): open the sink, build
    the platform container tree, wire flush + plugin signals."""
    global _trace
    if _trace is not None or not config["tracing"]:
        return
    fmt = TI_FORMAT if config["tracing/format"].upper() == "TI" \
        else PAJE_FORMAT
    _trace = Trace(config["tracing/filename"], fmt,
                   clock_getter=lambda: engine_impl.now,
                   precision=config["tracing/precision"],
                   display_sizes=config["tracing/smpi/display-sizes"])

    root = Container(_trace, engine_impl.netzone_root.name
                     if engine_impl.netzone_root else "root", "", None)

    if fmt == PAJE_FORMAT:
        # Platform/actor containers only make sense for visualization;
        # in TI mode only MPI rank containers get (replayable) files.
        if config["tracing/platform"] or config["tracing/uncategorized"]:
            _build_platform_containers(engine_impl, root)
        if config["tracing/uncategorized"]:
            _wire_utilization(engine_impl)
        if config["tracing/actor"]:
            _wire_actors(engine_impl)

    from ..kernel.engine import EngineImpl
    engine_impl.connect_signal(EngineImpl.on_time_advance,
                               lambda delta: _trace and _trace.flush(
                                   up_to=engine_impl.now))
    engine_impl.connect_signal(EngineImpl.on_simulation_end, stop)


def stop() -> None:
    global _trace
    if _trace is not None:
        _trace.close()
        _trace = None
    _rank_hosts.clear()
    _link_keys.clear()


# ---------------------------------------------------------------------------
# Platform containers (instr_platform.cpp)
# ---------------------------------------------------------------------------

def _build_platform_containers(engine_impl, root: Container) -> None:
    def walk(zone, father: Container, level: int):
        cont = father if zone.netpoint.name == father.name else \
            father.child(zone.name, f"L{level}")
        for host in zone.get_hosts():
            hc = cont.child(host.name, "HOST")
            hc.type.variable_type("power", "1 0 0")
        for child in zone.children:
            walk(child, cont, level + 1)

    walk(engine_impl.netzone_root, root, 1)
    # Links live at the root level container of their zone; give each a
    # container + bandwidth/latency variables (instr_platform.cpp).
    for link in engine_impl.links.values():
        lc = root.child(link.name, "LINK")
        lc.type.variable_type("bandwidth", "1 1 1")
        lc.type.variable_type("latency", "1 1 1")
        bw_type = lc.type.children["bandwidth"]
        lat_type = lc.type.children["latency"]
        PajeEvent(_trace, lc, bw_type, PAJE_SetVariable,
                  tail=_fmt_val(link.get_bandwidth()), timestamp=0.0)
        PajeEvent(_trace, lc, lat_type, PAJE_SetVariable,
                  tail=_fmt_val(link.get_latency()), timestamp=0.0)
    for host_cont_name, host in engine_impl.hosts.items():
        cont = _trace.containers_by_name.get(host_cont_name)
        if cont is not None:
            PajeEvent(_trace, cont, cont.type.children["power"],
                      PAJE_SetVariable, tail=_fmt_val(host.get_speed()),
                      timestamp=0.0)


def _fmt_val(v: float) -> str:
    return f"{v:f}" if v == int(v) else repr(v)


# ---------------------------------------------------------------------------
# Uncategorized resource utilization (instr_resource_utilization.cpp)
# ---------------------------------------------------------------------------

def _wire_utilization(engine_impl) -> None:
    from ..kernel.engine import EngineImpl
    last: Dict[str, float] = {}

    def on_advance(delta: float) -> None:
        if _trace is None:
            return
        start_t = engine_impl.now - delta
        for link in engine_impl.links.values():
            cont = _trace.containers_by_name.get(link.name)
            if cont is None:
                continue
            usage = _cnst_usage(link)
            if last.get(link.name) != usage:
                vt = cont.type.variable_type("bandwidth_used", "0.5 0 0")
                PajeEvent(_trace, cont, vt, PAJE_SetVariable,
                          tail=_fmt_val(usage), timestamp=start_t)
                last[link.name] = usage
        for host in engine_impl.hosts.values():
            cont = _trace.containers_by_name.get(host.name)
            if cont is None:
                continue
            usage = _cnst_usage(host.cpu)
            key = "cpu!" + host.name
            if last.get(key) != usage:
                vt = cont.type.variable_type("power_used", "0.5 0 0")
                PajeEvent(_trace, cont, vt, PAJE_SetVariable,
                          tail=_fmt_val(usage), timestamp=start_t)
                last[key] = usage

    engine_impl.connect_signal(EngineImpl.on_time_advance, on_advance)

    # Per-action utilization on every action state change
    # (instr_platform.cpp instr_action_on_state_change + the UNCAT
    # debug lines of instr_resource_utilization.cpp:22, which the
    # exec-ptask tesh pins at --log=instr_resource.t:debug)
    from ..models.cpu import Cpu, CpuAction
    from ..models.network import NetworkAction
    from ..utils import log as _xlog
    res_log = _xlog.get_category("instr_resource")

    def on_action_state_change(action, *_):
        var = getattr(action, "variable", None)
        if var is None or _trace is None:
            return
        now = engine_impl.now
        since = getattr(action, "last_update", 0.0)
        for elem in var.cnsts:
            value = elem.consumption_weight * var.value
            if not value:
                continue
            resource = elem.constraint.id
            if isinstance(resource, Cpu):
                kind, rname, vname = ("HOST", resource.host.name,
                                      "speed_used")
            else:
                rname = getattr(resource, "name", None)
                kind, vname = "LINK", "bandwidth_used"
            if rname is None or rname not in _trace.containers_by_name:
                continue
            # lazy args: the disabled-debug path must stay ~free
            res_log.debug("UNCAT %s [%f - %f] %s %s %f", kind, since,
                          now, rname, vname, value)

    engine_impl.connect_signal(CpuAction.on_state_change,
                               on_action_state_change)
    engine_impl.connect_signal(NetworkAction.on_state_change,
                               on_action_state_change)


def _cnst_usage(resource) -> float:
    cnst = getattr(resource, "constraint", None)
    if cnst is None:
        return 0.0
    return sum(e.consumption_weight * e.variable.value
               for e in cnst.enabled_element_set
               if e.consumption_weight > 0)


# ---------------------------------------------------------------------------
# Actor tracing (instr_platform.cpp actor signal hooks)
# ---------------------------------------------------------------------------

def _wire_actors(engine_impl) -> None:
    from ..kernel.actor import ActorImpl
    from ..s4u.actor import Actor

    def actor_container(actor_impl) -> Optional[Container]:
        return _trace.containers_by_name.get(
            f"{actor_impl.name}-{actor_impl.pid}")

    def on_creation(actor_impl) -> None:
        if _trace is None or actor_impl.host is None:
            return
        father = _trace.containers_by_name.get(actor_impl.host.name,
                                               _trace.root_container)
        cont = father.child(f"{actor_impl.name}-{actor_impl.pid}", "ACTOR")
        st = cont.type.state_type("ACTOR_STATE")
        for name in ("suspend", "sleep", "receive", "send", "execute"):
            st.value(name, find_color(name))

    def push(actor_impl, state: str) -> None:
        cont = _trace and actor_container(actor_impl)
        if cont:
            st = cont.type.state_type("ACTOR_STATE")
            ev = PajeEvent(_trace, cont, st, PAJE_PushState)
            ev.tail = str(st.value(state).id)

    def pop(actor_impl) -> None:
        cont = _trace and actor_container(actor_impl)
        if cont:
            PajeEvent(_trace, cont,
                      cont.type.state_type("ACTOR_STATE"), PAJE_PopState)

    def on_destruction(actor_impl) -> None:
        cont = _trace and actor_container(actor_impl)
        if cont:
            cont.remove_from_parent()

    engine_impl.connect_signal(ActorImpl.on_creation, on_creation)
    engine_impl.connect_signal(ActorImpl.on_termination, on_destruction)
    engine_impl.connect_signal(Actor.on_suspend,
                               lambda a: a and push(a.pimpl, "suspend"))
    engine_impl.connect_signal(Actor.on_resume,
                               lambda a: a and pop(a.pimpl))
    engine_impl.connect_signal(Actor.on_sleep,
                               lambda a: a and push(a.pimpl, "sleep"))
    engine_impl.connect_signal(Actor.on_wake_up,
                               lambda a: a and pop(a.pimpl))


# ---------------------------------------------------------------------------
# SMPI tracing (instr_smpi.cpp)
# ---------------------------------------------------------------------------

def smpi_enabled() -> bool:
    return _trace is not None and config["tracing/smpi"]


def _rank_name(rank: int, instance: str = "main") -> str:
    # Multi-instance jobs each restart ranks at 0: the instance name
    # disambiguates containers (main keeps the reference's bare
    # "rank-N" so traces stay interchangeable).
    return f"rank-{rank}" if instance == "main" else \
        f"{instance}#rank-{rank}"


def _rank_container(rank: int, instance: str = "main") -> Container:
    return _trace.containers_by_name[_rank_name(rank, instance)]


def smpi_init(rank: int, host, instance: str = "main") -> None:
    """TRACE_smpi_init + setup_container (instr_smpi.cpp:139-168);
    idempotent so arrows can pre-create a peer's container."""
    name = _rank_name(rank, instance)
    if not smpi_enabled() or name in _trace.containers_by_name:
        return
    father = _trace.root_container
    if config["tracing/smpi/grouped"]:
        father = _trace.containers_by_name.get(host.name, father)
    cont = father.child(name, "MPI")
    st = cont.type.state_type("MPI_STATE")
    if config["tracing/smpi/computing"]:
        st.value("computing", find_color("computing"))
    # The pt2pt link type lives on the root type, rank -> rank.
    _trace.root_container.type.link_type("MPI_LINK", cont.type, cont.type)


def smpi_finalize(rank: int, instance: str = "main") -> None:
    if smpi_enabled():
        _rank_container(rank, instance).remove_from_parent()


def smpi_in(rank: int, op_name: str, extra: ti.TIData,
            ti_line: bool = True, instance: str = "main") -> None:
    """TRACE_smpi_comm_in: push the MPI call state; in TI mode emit the
    replayable action line instead (instr_paje_events.cpp StateEvent).
    ti_line=False marks calls the TI/replay grammar does not support
    (waitany etc., instr_paje_events.cpp:110 comment)."""
    if not smpi_enabled():
        return
    cont = _rank_container(rank, instance)
    if _trace.format == TI_FORMAT:
        if ti_line:
            TIEvent(_trace, cont, f"{rank} {extra.print()}")
        return
    st = cont.type.state_type("MPI_STATE")
    ev = PajeEvent(_trace, cont, st, PAJE_PushState)
    ev.tail = str(st.value(op_name, find_color(op_name)).id)
    if _trace.display_sizes:
        ev.tail += f" {extra.display_size()}"


def smpi_out(rank: int, instance: str = "main") -> None:
    if not smpi_enabled():
        return
    if _trace.format == TI_FORMAT:
        return
    cont = _rank_container(rank, instance)
    PajeEvent(_trace, cont, cont.type.state_type("MPI_STATE"),
              PAJE_PopState)


def smpi_computing_in(rank: int, amount: float) -> None:
    if smpi_enabled() and config["tracing/smpi/computing"]:
        smpi_in(rank, "computing", ti.CpuTIData("compute", amount))


def smpi_computing_out(rank: int) -> None:
    if smpi_enabled() and config["tracing/smpi/computing"]:
        smpi_out(rank)


def _pt2pt_key(src: int, dst: int, tag: int, send: int) -> str:
    """Matching key generation for pt2pt link arrows
    (instr_smpi.cpp:105-137): the first side to reach the rendezvous
    mints the key, the other pops it."""
    global _link_key_counter
    aux = f"{src}#{dst}#{tag}#{1 - send}"
    queue = _link_keys.get(aux)
    if queue:
        key = queue.pop(0)
        if not queue:
            del _link_keys[aux]
        return key
    _link_key_counter += 1
    key = f"{src}_{dst}_{tag}_{_link_key_counter}"
    _link_keys.setdefault(f"{src}#{dst}#{tag}#{send}", []).append(key)
    return key


def smpi_send(rank: int, src: int, dst: int, tag: int, size: int,
              instance: str = "main") -> None:
    """TRACE_smpi_send: StartLink arrow from the sender."""
    if not smpi_enabled() or _trace.format == TI_FORMAT:
        return
    src_key = src if instance == "main" else f"{instance}.{src}"
    dst_key = dst if instance == "main" else f"{instance}.{dst}"
    key = _pt2pt_key(src_key, dst_key, tag, send=1)
    root = _trace.root_container
    lt = root.type.link_type("MPI_LINK",
                             _rank_container(src, instance).type,
                             _rank_container(dst, instance).type)
    ev = PajeEvent(_trace, root, lt, PAJE_StartLink,
                   tail=f"PTP {_rank_container(src, instance).id} {key}")
    if _trace.display_sizes:
        ev.tail += f" {size}"


def smpi_recv(rank_src: int, rank_dst: int, tag: int,
              instance: str = "main") -> None:
    """TRACE_smpi_recv: EndLink arrow at the receiver."""
    if not smpi_enabled() or _trace.format == TI_FORMAT:
        return
    src_key = rank_src if instance == "main" else f"{instance}.{rank_src}"
    dst_key = rank_dst if instance == "main" else f"{instance}.{rank_dst}"
    key = _pt2pt_key(src_key, dst_key, tag, send=0)
    root = _trace.root_container
    lt = root.type.link_type("MPI_LINK",
                             _rank_container(rank_src, instance).type,
                             _rank_container(rank_dst, instance).type)
    PajeEvent(_trace, root, lt, PAJE_EndLink,
              tail=f"PTP {_rank_container(rank_dst, instance).id} {key}")
