"""Fair-bottleneck solver for parallel tasks (reference
src/kernel/lmm/fair_bottleneck.cpp).

Unlike max-min (which weighs each variable's total usage), the
fair-bottleneck fixpoint repeatedly grants every live variable the
largest equal increment its tightest constraint allows: each round every
constraint splits its remaining capacity evenly over its live variables,
each variable takes the minimum offer across its constraints (and its
own bound), and exhausted constraints retire with their variables."""

from __future__ import annotations

from .lmm_host import SharingPolicy, System, double_update
from ..utils.config import config


class FairBottleneck(System):
    """An LMM system solved with bottleneck_solve instead of the max-min
    fixpoint (make_new_fair_bottleneck_system equivalent)."""

    def solve(self) -> None:
        if not self.modified:
            return
        self.solve_count += 1
        self.bottleneck_solve()

    def bottleneck_solve(self) -> None:
        eps = config["maxmin/precision"]

        # Init: live variables have a positive penalty and at least one
        # weighted element (fair_bottleneck.cpp:28-51).
        var_list = []
        for var in self.variable_set:
            var.value = 0.0
            if var.sharing_penalty > 0.0 and any(
                    e.consumption_weight != 0.0 for e in var.cnsts):
                var_list.append(var)
            elif var.sharing_penalty > 0.0:
                var.value = 1.0

        cnst_list = list(self.active_constraint_set)
        for cnst in cnst_list:
            cnst.remaining = cnst.bound
            cnst.usage = 0.0

        in_var_list = set(id(v) for v in var_list)

        while var_list:
            # Offer per constraint: remaining / #live variables (FATPIPE
            # offers its full remaining to each).
            next_cnst_list = []
            for cnst in cnst_list:
                nb = sum(1 for e in cnst.enabled_element_set
                         if e.consumption_weight > 0
                         and id(e.variable) in in_var_list)
                if nb > 0 and cnst.sharing_policy == SharingPolicy.FATPIPE:
                    nb = 1
                if nb == 0:
                    cnst.remaining = 0.0
                    cnst.usage = 0.0
                else:
                    cnst.usage = cnst.remaining / nb
                    next_cnst_list.append(cnst)
            cnst_list = next_cnst_list

            # Every live variable takes its minimal offer.
            still = []
            for var in var_list:
                min_inc = float("inf")
                for elem in var.cnsts:
                    if elem.consumption_weight > 0:
                        min_inc = min(min_inc,
                                      elem.constraint.usage
                                      / elem.consumption_weight)
                if var.bound > 0:
                    min_inc = min(min_inc, var.bound - var.value)
                var.mu = min_inc
                var.value += min_inc
                if var.value == var.bound:
                    in_var_list.discard(id(var))
                else:
                    still.append(var)
            var_list = still

            # Charge the increments; retire exhausted constraints and
            # their variables.
            next_cnst_list = []
            for cnst in cnst_list:
                if cnst.sharing_policy != SharingPolicy.FATPIPE:
                    for elem in cnst.enabled_element_set:
                        cnst.remaining = double_update(
                            cnst.remaining,
                            elem.consumption_weight * elem.variable.mu, eps)
                else:
                    for elem in cnst.enabled_element_set:
                        cnst.usage = min(cnst.usage,
                                         elem.consumption_weight
                                         * elem.variable.mu)
                    cnst.remaining = double_update(cnst.remaining,
                                                   cnst.usage, eps)
                if cnst.remaining <= 0.0:
                    for elem in cnst.enabled_element_set:
                        if (elem.consumption_weight > 0
                                and id(elem.variable) in in_var_list):
                            in_var_list.discard(id(elem.variable))
                    var_list = [v for v in var_list
                                if id(v) in in_var_list]
                else:
                    next_cnst_list.append(cnst)
            cnst_list = next_cnst_list

        self.modified = True
        if self.selective_update_active:
            self.remove_all_modified_set()
