"""Warm-started selective device solves (TPU-native incremental path).

The reference's ``network/maxmin-selective-update`` re-solves only the
constraints reachable from a mutation (src/kernel/lmm/maxmin.cpp) — its
soundness argument is that the max-min solution decomposes by connected
component: fixing a variable only ever changes the remaining/usage of
constraints in its own component, so untouched components keep their
exact previous solution.  This module carries that discipline onto the
device backend end to end:

* **Device-resident masters + delta uploads** — the flattened solver
  arrays (ops.lmm_view masters) stay resident on device; each solve
  ships one indexed scatter payload holding only the slots the System
  mutated since the last solve (``ArrayView.consume``), so upload cost
  scales with the number of touched slots, not field size.  On the
  tunneled accelerator, where every host->device transfer costs
  150-500 ms regardless of size, this turns a mutating solve's ~7
  MB-sized uploads into one small indexed one.

* **Warm-started modified-component fixpoint restarts** — the previous
  solve's ``(v_value, v_fixed, remaining, usage)`` ride the device
  between solves.  The next solve re-initializes ONLY the slots of the
  modified component (``modified_constraint_set``, already closed
  under shared enabled variables by ``System.update_modified_set``):
  modified constraints get ``remaining = bound`` and a recomputed
  ``usage0``, their variables are unfixed, and everything else is
  masked fixed/dark.  The fixpoint then iterates only the modified
  component, cutting rounds from O(level depth of the whole system) to
  O(level depth of the delta).  Because every per-round reduction in
  the fixpoint (segment sums/maxes/mins over a constraint's elements
  or a variable's constraints) is component-local, the values computed
  for the modified component are bit-identical to a cold full solve of
  the same arrays.

Carry invalidation is exact by construction (the hard part):

* slot renumbering or reallocation (``ArrayView._compact``, bucket
  growth) bumps ``layout_epoch`` -> full re-upload + cold restart;
* any dirty slot that is NOT invisible and NOT inside the modified
  component (a constraint-closure hole: sharing-policy flips, mixing
  in host-backend solves that consumed the modified set, positive->
  positive penalty writes) -> cold restart;
* a live element crossing the component boundary (modified variable
  with an element in an unmodified constraint) -> cold restart;
* dtype alternation keeps independent per-dtype masters/carries, each
  with its own dirty-index consumer, so f64 engine solves and f32
  accelerator solves can interleave without cross-poisoning;
* drain-fast-path retirements (``expected_frees``) skip the plan
  version bump but still mark dirty indices, so the masters see the
  zeroed weights and the closure check sees the retired slots.

Solves that cannot be warmed fall back to a cold full solve of the
same device-resident arrays — always available, always exact.

Runs that selected the ELL layout (``lmm/layout:ell``, or auto on an
accelerator) are served from device-resident ELL masters maintained
incrementally alongside the COO ones: the view's element slots are
append-only within a layout epoch (``on_expand`` always allocates at
``n_elem``; only ``_compact`` renumbers, and that bumps the epoch), so
a new element's lane is simply ``fill[row]++`` on both the cv and vc
tables — the same lane the stable-sort ``ell_from_arrays`` build would
assign, which keeps the row-reduction order (and therefore every
usage sum's rounding) bit-identical to a fresh conversion.  Dead lanes
(zeroed weights from freed variables) contribute exact identities to
the row reductions until the next epoch rebuild.  A row overflowing
its padded width forces a host rebuild of the tables (rare: widths are
pow2-bucketed).  Only when the COO->ELL conversion itself is refused
(width/fill caps — the same caps the plain solve path applies) does
the solve drop to the COO masters, counted in ``warm_ell_fallbacks``;
the plain path serves COO for those systems too, so the layouts stay
consistent.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..utils.config import config
from . import opstats
from .lmm_jax import (_ELL_MAX_FILL, _ELL_MAX_WIDTH, _MAX_ROUNDS, _bucket,
                      _default_chunk, _default_platform, _solve_ell_chunk,
                      _solve_kernel_chunk, use_local_rounds)

_FIELDS = ("e_var", "e_cnst", "e_w", "c_bound", "c_fatpipe",
           "v_penalty", "v_bound")
_CAST_FIELDS = ("e_w", "c_bound", "v_penalty", "v_bound")


def _warm_mode() -> str:
    mode = config["lmm/warm-start"]
    if mode not in ("auto", "on", "cold", "off"):
        raise ValueError(f"Unknown lmm/warm-start {mode!r} "
                         "(expected auto, on, cold or off)")
    return mode


def _delta_enabled() -> bool:
    mode = config["lmm/delta-upload"]
    if mode not in ("auto", "on", "off"):
        raise ValueError(f"Unknown lmm/delta-upload {mode!r} "
                         "(expected auto, on or off)")
    return mode != "off"


def _ell_selected() -> bool:
    """True when the run's layout choice resolves to ELL (explicit, or
    auto on an accelerator) — the layout the warm carry cannot serve."""
    layout = config["lmm/layout"]
    return layout == "ell" or (layout == "auto"
                               and _default_platform() != "cpu")


@functools.partial(jax.jit, static_argnames=("layout",))
def _apply_deltas(payload, e_var, e_cnst, e_w, c_bound, c_fatpipe,
                  v_penalty, v_bound, layout: Tuple):
    """Apply one fused delta payload to the device masters.

    ``payload`` is a single f64 vector holding, per dirty field,
    ``n`` slot indices followed by ``n`` new values (int32 slots and
    bools are exact in f64); ``layout`` is the static
    ``(field_index, offset, n)`` table.  ONE host->device transfer
    per solve, then pure on-device scatters — ``arr.at[idx].set``
    with the padding slots repeating the first (index, value) pair,
    so duplicate writes all carry the same value and the scatter is
    deterministic."""
    masters = [e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty, v_bound]
    for fi, off, n in layout:
        idx = payload[off:off + n].astype(jnp.int32)
        vals = payload[off + n:off + 2 * n].astype(masters[fi].dtype)
        masters[fi] = masters[fi].at[idx].set(vals)
    return tuple(masters)


@jax.jit
def _apply_deltas_ell(cv_var, cv_w, cv_valid, vc_cnst, vc_valid, vc_w,
                      cv_idx, cv_v, cv_wv, vc_idx, vc_c, vc_wv):
    """Scatter one element-delta batch into the ELL masters.  Indices
    are flattened (row * width + lane); the padding entries repeat the
    first (index, value) pair so duplicate writes agree and the scatter
    stays deterministic (the _apply_deltas discipline)."""
    shp_c, shp_v = cv_var.shape, vc_cnst.shape
    cv_var = cv_var.reshape(-1).at[cv_idx].set(cv_v).reshape(shp_c)
    cv_w = cv_w.reshape(-1).at[cv_idx].set(cv_wv).reshape(shp_c)
    cv_valid = cv_valid.reshape(-1).at[cv_idx].set(cv_wv > 0).reshape(shp_c)
    vc_cnst = vc_cnst.reshape(-1).at[vc_idx].set(vc_c).reshape(shp_v)
    vc_w = vc_w.reshape(-1).at[vc_idx].set(vc_wv).reshape(shp_v)
    vc_valid = vc_valid.reshape(-1).at[vc_idx].set(vc_wv > 0).reshape(shp_v)
    return cv_var, cv_w, cv_valid, vc_cnst, vc_valid, vc_w


@functools.partial(jax.jit, static_argnames=("eps",))
def _warm_init_ell(cv_var, cv_w, cv_valid, c_bound, c_fatpipe, v_penalty,
                   prev_value, prev_remaining, prev_usage, prev_cv_live,
                   mc_idx, eps: float):
    """ELL analog of `_warm_init`: cold-start expressions (mirroring
    `fixpoint_ell`'s None-carry init, row reductions included) for the
    modified component, previous solution masked fixed/dark elsewhere.
    The extra carry leg is `cv_live`: modified rows are re-derived from
    the warm v_fixed (identical to the cold expression there — every
    live element of a modified row belongs to a modified variable by
    the component-closure checks), untouched rows keep the previous
    converged mask."""
    dtype = cv_w.dtype
    n_c = c_bound.shape[0]
    n_v = v_penalty.shape[0]
    eps_t = jnp.asarray(eps, dtype)

    c_mod = jnp.zeros(n_c, bool).at[mc_idx].set(True)
    live = cv_valid & (cv_w > 0)
    v_mod = jnp.zeros(n_v, bool).at[cv_var].max(live & c_mod[:, None])
    has_live_elem = jnp.zeros(n_v, bool).at[cv_var].max(live)

    v_enabled = v_penalty > 0
    cv_evalid = cv_valid & jnp.take(v_enabled, cv_var)
    safe_pen = jnp.where(v_enabled, v_penalty, 1.0)
    cv_upen = jnp.where(cv_evalid, cv_w / jnp.take(safe_pen, cv_var), 0.0)
    usage_sum = cv_upen.sum(axis=1)
    usage_max = cv_upen.max(axis=1, initial=0.0)
    usage0 = jnp.where(c_fatpipe, usage_max, usage_sum)

    v_value0 = jnp.where(jnp.isfinite(v_penalty), v_penalty, 0.0) * 0.0
    keep_prev = ~v_mod & v_enabled & has_live_elem
    v_value = jnp.where(keep_prev, prev_value, v_value0)
    v_fixed = jnp.where(v_mod, v_penalty < 0, True)
    remaining = jnp.where(c_mod, c_bound, prev_remaining)
    usage = jnp.where(c_mod, usage0, prev_usage)
    light = c_mod & (c_bound > c_bound * eps_t) & (usage0 > 0)
    cv_live = jnp.where(c_mod[:, None],
                        cv_evalid & ~jnp.take(v_fixed, cv_var),
                        prev_cv_live)
    return (v_value, v_fixed, remaining, usage, light,
            jnp.array(0, jnp.int32), cv_live)


@functools.partial(jax.jit, static_argnames=("eps",))
def _warm_init(e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty,
               prev_value, prev_remaining, prev_usage, mc_idx,
               eps: float):
    """Build the fixpoint carry for a modified-component restart.

    Modified slots get exactly the cold-start initialization (same
    expressions as ``fixpoint``'s None-carry init, so the component's
    round arithmetic is bit-identical to a cold full solve); untouched
    slots keep the previous solution, masked fixed/dark so the loop
    never revisits them."""
    dtype = e_w.dtype
    n_c = c_bound.shape[0]
    n_v = v_penalty.shape[0]
    eps_t = jnp.asarray(eps, dtype)

    c_mod = jnp.zeros(n_c, bool).at[mc_idx].set(True)
    e_live = e_w > 0
    v_mod = jnp.zeros(n_v, bool).at[e_var].max(
        e_live & jnp.take(c_mod, e_cnst))
    has_live_elem = jnp.zeros(n_v, bool).at[e_var].max(e_live)

    v_enabled = v_penalty > 0
    e_valid = e_live & jnp.take(v_enabled, e_var)
    safe_pen = jnp.where(v_enabled, v_penalty, 1.0)
    e_upen = jnp.where(e_valid, e_w / jnp.take(safe_pen, e_var), 0.0)
    usage_sum = jnp.zeros(n_c, dtype).at[e_cnst].add(e_upen)
    usage_max = jnp.zeros(n_c, dtype).at[e_cnst].max(e_upen)
    usage0 = jnp.where(c_fatpipe, usage_max, usage_sum)

    v_value0 = jnp.where(jnp.isfinite(v_penalty), v_penalty, 0.0) * 0.0
    # untouched slots keep the previous value only where one exists to
    # keep (enabled with a live element); recycled/ghost slots get the
    # cold init so the returned vector matches a cold full solve
    keep_prev = ~v_mod & v_enabled & has_live_elem
    v_value = jnp.where(keep_prev, prev_value, v_value0)
    v_fixed = jnp.where(v_mod, v_penalty < 0, True)
    remaining = jnp.where(c_mod, c_bound, prev_remaining)
    usage = jnp.where(c_mod, usage0, prev_usage)
    light = c_mod & (c_bound > c_bound * eps_t) & (usage0 > 0)
    return (v_value, v_fixed, remaining, usage, light,
            jnp.array(0, jnp.int32))


class _DtypeState:
    """Per-solve-dtype device residency: masters, carry, validity tags."""

    __slots__ = ("masters", "shapes", "epoch", "carry", "meta",
                 "ell", "ell_shape", "ell_n", "cv_lane", "vc_lane",
                 "cv_fill", "vc_fill")

    def __init__(self):
        self.masters = None        # tuple of device arrays, _FIELDS order
        self.shapes = None         # (E, C, V) padded lengths
        self.epoch = -1            # view.layout_epoch the masters track
        self.carry = None          # converged fixpoint state, or None
        self.meta = None           # (eps, parallel, layout, shape) of it
        # ELL residency (lmm/layout:ell runs): the six 2D tables plus
        # the host lane maps that let element deltas land as scatters
        self.ell = None            # (cv_var, cv_w, cv_valid,
        #                             vc_cnst, vc_valid, vc_w) on device
        self.ell_shape = None      # (C, Wc, V, Wv)
        self.ell_n = 0             # element slots placed so far
        self.cv_lane = None        # per-element lane in its cv row
        self.vc_lane = None        # per-element lane in its vc row
        self.cv_fill = None        # per-constraint occupied lane count
        self.vc_fill = None        # per-variable occupied lane count


class WarmSolver:
    """Device-resident incremental solver attached to one System."""

    def __init__(self, system):
        self.system = system
        system.warm_solver = self
        self._states: Dict[np.dtype, _DtypeState] = {}
        # observability (read by tests, tools and bench)
        self.solves = 0
        self.warm_solves = 0
        self.cold_solves = 0
        self.warm_ell_fallbacks = 0
        self.carry_invalidations = 0
        self.last_rounds = 0
        self.last_mode = ""
        self.last_layout = ""
        self.last_upload_bytes = 0
        self.last_dirty_slots = 0

    # -- carry management --------------------------------------------------

    def invalidate(self) -> None:
        """Drop every carried fixpoint state (masters stay resident).
        Called when a solve happened outside this solver (host-exact
        fallback) so stale values can never seed a warm restart."""
        for dt in sorted(self._states, key=str):
            st = self._states[dt]
            if st.carry is not None:
                self.carry_invalidations += 1
            st.carry = None

    # -- upload ------------------------------------------------------------

    def _cast(self, view, field: str, key):
        src = getattr(view, field)
        return src.astype(key) if field in _CAST_FIELDS else src

    def _upload_full(self, st: _DtypeState, view, key) -> None:
        arrays = [self._cast(view, f, key) for f in _FIELDS]
        nbytes = sum(a.nbytes for a in arrays)
        st.masters = tuple(jax.device_put(a) for a in arrays)
        st.shapes = (len(view.e_var), len(view.c_bound),
                     len(view.v_penalty))
        st.epoch = view.layout_epoch
        st.ell = None              # element slots may have renumbered
        opstats.bump("uploaded_bytes_full", nbytes)
        self.last_upload_bytes += nbytes

    def _upload_delta(self, st: _DtypeState, view, key, dirty) -> int:
        """Apply per-index mutations to the device masters; returns the
        number of dirty slots shipped.  Fields whose index identity was
        lost (dirty is True) are re-shipped whole and poison the carry
        (handled by the caller via the returned sentinel -1)."""
        true_fields = [f for f in _FIELDS if dirty[f] is True]
        if true_fields:
            masters = list(st.masters)
            for f in true_fields:
                arr = self._cast(view, f, key)
                masters[_FIELDS.index(f)] = jax.device_put(arr)
                opstats.bump("uploaded_bytes_full", arr.nbytes)
                self.last_upload_bytes += arr.nbytes
            st.masters = tuple(masters)

        idx_fields = [(f, sorted(dirty[f])) for f in _FIELDS
                      if dirty[f] is not True and dirty[f]]
        n_slots = sum(len(ix) for _, ix in idx_fields)
        if idx_fields:
            if _delta_enabled():
                layout = []
                chunks = []
                off = 0
                for f, ix in idx_fields:
                    n = _bucket(len(ix), floor=8)
                    idx = np.empty(n, np.float64)
                    vals = np.empty(n, np.float64)
                    idx[:len(ix)] = ix
                    idx[len(ix):] = ix[0]
                    src = getattr(view, f)
                    vals[:len(ix)] = src[ix]
                    vals[len(ix):] = src[ix[0]]
                    layout.append((_FIELDS.index(f), off, n))
                    chunks.append(idx)
                    chunks.append(vals)
                    off += 2 * n
                payload = np.concatenate(chunks)
                st.masters = _apply_deltas(jax.device_put(payload),
                                           *st.masters,
                                           layout=tuple(layout))
                opstats.bump("uploaded_bytes_delta", payload.nbytes)
                self.last_upload_bytes += payload.nbytes
            else:
                # whole-field refresh of only the fields that changed
                # (the copy-on-write snapshot discipline, kept as the
                # escape hatch and as the bench's full-upload baseline)
                masters = list(st.masters)
                for f, _ in idx_fields:
                    arr = self._cast(view, f, key)
                    masters[_FIELDS.index(f)] = jax.device_put(arr)
                    opstats.bump("uploaded_bytes_full", arr.nbytes)
                    self.last_upload_bytes += arr.nbytes
                st.masters = tuple(masters)
        if true_fields:
            return -1
        return n_slots

    # -- ELL residency -----------------------------------------------------

    def _build_ell(self, st: _DtypeState, view, key) -> bool:
        """Host rebuild of the ELL masters + lane maps from the view
        (same widths, caps and stable element-index lane order as
        `ell_from_arrays`, so the row-reduction rounding matches the
        plain solve path's conversion).  Returns False when the caps
        refuse the conversion — COO serves those systems everywhere."""
        E = view.n_elem
        e_var = view.e_var[:E].astype(np.int64)
        e_cnst = view.e_cnst[:E].astype(np.int64)
        e_w = view.e_w[:E]
        C, V = len(view.c_bound), len(view.v_penalty)
        c_deg = np.bincount(e_cnst, minlength=C)
        v_deg = np.bincount(e_var, minlength=V)
        wc = int(c_deg.max()) if E else 1
        wv = int(v_deg.max()) if E else 1
        if wc > _ELL_MAX_WIDTH or wv > _ELL_MAX_WIDTH:
            st.ell = None
            return False
        Wc = _bucket(max(wc, 1), floor=4)
        Wv = _bucket(max(wv, 1), floor=4)
        if E and (C * Wc + V * Wv) > _ELL_MAX_FILL * 2 * E:
            st.ell = None
            return False

        slots_total = len(view.e_var)
        cv_lane = np.full(slots_total, -1, np.int32)
        vc_lane = np.full(slots_total, -1, np.int32)
        cv_var = np.zeros((C, Wc), np.int32)
        cv_w = np.zeros((C, Wc), key)
        cv_valid = np.zeros((C, Wc), bool)
        vc_cnst = np.zeros((V, Wv), np.int32)
        vc_valid = np.zeros((V, Wv), bool)
        vc_w = np.zeros((V, Wv), key)
        ew = e_w.astype(key)

        def row_slots(keys, n_rows):
            order = np.argsort(keys, kind="stable")
            sorted_keys = keys[order]
            group_start = np.searchsorted(sorted_keys, np.arange(n_rows))
            lanes = np.arange(E, dtype=np.int64) - group_start[sorted_keys]
            return order, sorted_keys, lanes

        if E:
            order, rows, lanes = row_slots(e_cnst, C)
            cv_lane[order] = lanes
            cv_var[rows, lanes] = e_var[order]
            cv_w[rows, lanes] = ew[order]
            cv_valid[rows, lanes] = ew[order] > 0
            order, rows, lanes = row_slots(e_var, V)
            vc_lane[order] = lanes
            vc_cnst[rows, lanes] = e_cnst[order]
            vc_w[rows, lanes] = ew[order]
            vc_valid[rows, lanes] = ew[order] > 0

        arrays = (cv_var, cv_w, cv_valid, vc_cnst, vc_valid, vc_w)
        nbytes = sum(a.nbytes for a in arrays)
        st.ell = tuple(jax.device_put(a) for a in arrays)
        st.ell_shape = (C, Wc, V, Wv)
        st.ell_n = E
        st.cv_lane, st.vc_lane = cv_lane, vc_lane
        st.cv_fill = c_deg.astype(np.int32)
        st.vc_fill = v_deg.astype(np.int32)
        opstats.bump("uploaded_bytes_full", nbytes)
        self.last_upload_bytes += nbytes
        return True

    def _sync_ell(self, st: _DtypeState, view, key, dirty) -> bool:
        """Scatter the element dirt into the resident ELL tables.  New
        elements (append-only within an epoch) take lane ``fill[row]++``
        on each side — the lane a fresh stable-order build would assign.
        Returns False when a row overflows its padded width (rebuild)."""
        e_dirty = sorted(dirty["e_var"] | dirty["e_cnst"] | dirty["e_w"])
        if not e_dirty:
            return True
        C, Wc, V, Wv = st.ell_shape
        if (len(view.c_bound) != C or len(view.v_penalty) != V
                or len(view.e_var) != len(st.cv_lane)):
            return False           # row/slot table growth: rebuild
        cv_idx: list = []
        cv_v: list = []
        cv_wv: list = []
        vc_idx: list = []
        vc_c: list = []
        vc_wv: list = []
        for i in e_dirty:
            v = int(view.e_var[i])
            c = int(view.e_cnst[i])
            w = float(view.e_w[i])
            if i >= st.ell_n:
                lane_c = int(st.cv_fill[c])
                lane_v = int(st.vc_fill[v])
                if lane_c >= Wc or lane_v >= Wv:
                    return False
                st.cv_fill[c] = lane_c + 1
                st.vc_fill[v] = lane_v + 1
                st.cv_lane[i] = lane_c
                st.vc_lane[i] = lane_v
            else:
                lane_c = int(st.cv_lane[i])
                lane_v = int(st.vc_lane[i])
                if lane_c < 0 or lane_v < 0:
                    return False
            cv_idx.append(c * Wc + lane_c)
            cv_v.append(v)
            cv_wv.append(w)
            vc_idx.append(v * Wv + lane_v)
            vc_c.append(c)
            vc_wv.append(w)
        st.ell_n = max(st.ell_n, e_dirty[-1] + 1)

        n = _bucket(len(cv_idx), floor=8)
        pads = []
        for src, dt in ((cv_idx, np.int32), (cv_v, np.int32),
                        (cv_wv, key), (vc_idx, np.int32),
                        (vc_c, np.int32), (vc_wv, key)):
            a = np.empty(n, dt)
            a[:len(src)] = src
            a[len(src):] = src[0]
            pads.append(a)
        st.ell = _apply_deltas_ell(*st.ell, *pads)
        nbytes = sum(a.nbytes for a in pads)
        opstats.bump("uploaded_bytes_delta", nbytes)
        self.last_upload_bytes += nbytes
        return True

    def _ensure_ell(self, st: _DtypeState, view, key, dirty) -> bool:
        """Bring the ELL masters up to date with the view; returns True
        when the solve can be served in the ELL layout."""
        if st.ell is not None and dirty is not None \
                and not any(dirty[f] is True
                            for f in ("e_var", "e_cnst", "e_w")):
            if self._sync_ell(st, view, key, dirty):
                return True
        # missing, stale or overflowed: rebuild from the view (the
        # carry's cv_live leg is lane-addressed, so a rebuild means a
        # cold restart — enforced via the meta shape tag)
        return self._build_ell(st, view, key)

    # -- carry validity ----------------------------------------------------

    def _delta_in_component(self, view, dirty, c_mod, v_mod,
                            has_live_elem, has_live_c) -> bool:
        """Every slot mutated since the carry must be either inside the
        modified component or invisible to the solve (zero weight, no
        live element) — otherwise the carried values of some untouched
        slot are stale and only a cold restart is exact."""
        e_dirty = dirty["e_var"] | dirty["e_cnst"] | dirty["e_w"]
        if e_dirty:
            ei = np.fromiter(e_dirty, np.int64, len(e_dirty))
            if not np.all(c_mod[view.e_cnst[ei]] | (view.e_w[ei] == 0.0)):
                return False
        v_dirty = dirty["v_penalty"] | dirty["v_bound"]
        if v_dirty:
            vi = np.fromiter(v_dirty, np.int64, len(v_dirty))
            visible = (view.v_penalty[vi] > 0) & has_live_elem[vi]
            if not np.all(v_mod[vi] | ~visible):
                return False
        c_dirty = dirty["c_bound"] | dirty["c_fatpipe"]
        if c_dirty:
            ci = np.fromiter(c_dirty, np.int64, len(c_dirty))
            if not np.all(c_mod[ci] | ~has_live_c[ci]):
                return False
        return True

    # -- solve -------------------------------------------------------------

    def solve(self, view, cnst_list, dtype, eps: float, warm: bool):
        """Solve the System with the given modified constraints;
        returns host (values, remaining, usage) at view slot numbering.
        Raises RuntimeError on non-convergence/stall/non-finite rates
        (the caller degrades to the exact host solver)."""
        key = np.dtype(dtype)
        view.maybe_compact()
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = _DtypeState()
        dirty = view.consume(f"warm:{key}")
        shapes = (len(view.e_var), len(view.c_bound), len(view.v_penalty))

        self.last_upload_bytes = 0
        self.last_dirty_slots = 0
        full = (dirty is None or st.masters is None
                or st.epoch != view.layout_epoch or st.shapes != shapes)
        if full:
            self._upload_full(st, view, key)
            st.carry = None
        else:
            n_slots = self._upload_delta(st, view, key, dirty)
            if n_slots < 0:
                st.carry = None
            else:
                self.last_dirty_slots = n_slots

        # ELL runs are served from the resident ELL masters (lane maps
        # keep them delta-maintained alongside the COO ones).  Only a
        # conversion the width/fill caps refuse drops to COO — the
        # plain solve path serves COO for those systems too, so the
        # layout stays what the run would get anywhere; the residual
        # gap is counted (opstats `warm_ell_fallbacks`).
        use_ell = False
        if _ell_selected():
            use_ell = self._ensure_ell(st, view, key,
                                       None if full else dirty)
            if not use_ell:
                self.warm_ell_fallbacks += 1
                opstats.bump("warm_ell_fallbacks")
        self.last_layout = "ell" if use_ell else "coo"

        eps_f = float(eps)
        parallel = use_local_rounds()
        # the carry is layout-addressed (the ELL leg's cv_live lives at
        # (row, lane)), so a layout or table-shape flip cold-restarts
        meta = (eps_f, parallel, "ell" if use_ell else "coo",
                st.ell_shape if use_ell else None)
        mc = np.fromiter((c._view_slot for c in cnst_list), np.int64,
                         len(cnst_list))

        carry0 = None
        if warm and st.carry is not None and st.meta == meta:
            c_mod = np.zeros(shapes[1], bool)
            c_mod[mc] = True
            live = view.e_w > 0
            en = view.v_penalty > 0
            v_mod = np.zeros(shapes[2], bool)
            v_mod[view.e_var[live & c_mod[view.e_cnst]]] = True
            has_live_elem = np.zeros(shapes[2], bool)
            has_live_elem[view.e_var[live]] = True
            has_live_c = np.zeros(shapes[1], bool)
            has_live_c[view.e_cnst[live & en[view.e_var]]] = True
            # component-closure boundary: a live enabled variable of
            # the modified component must not touch any unmodified
            # constraint, or a cold solve could fix it at that
            # constraint's level while the warm solve cannot
            boundary_ok = not np.any(live & en[view.e_var]
                                     & v_mod[view.e_var]
                                     & ~c_mod[view.e_cnst])
            if boundary_ok and self._delta_in_component(
                    view, dirty, c_mod, v_mod, has_live_elem, has_live_c):
                n_mc = _bucket(len(mc), floor=8)
                mc_pad = np.empty(n_mc, np.int32)
                mc_pad[:len(mc)] = mc
                mc_pad[len(mc):] = mc[0]
                mc_dev = jax.device_put(mc_pad)
                opstats.bump("uploaded_bytes_delta", mc_pad.nbytes)
                self.last_upload_bytes += mc_pad.nbytes
                prev = st.carry
                if use_ell:
                    carry0 = _warm_init_ell(
                        st.ell[0], st.ell[1], st.ell[2],
                        st.masters[3], st.masters[4], st.masters[5],
                        prev[0], prev[2], prev[3], prev[6],
                        mc_dev, eps=eps_f)
                else:
                    carry0 = _warm_init(*st.masters[:6], prev[0],
                                        prev[2], prev[3], mc_dev,
                                        eps=eps_f)

        st.carry = None   # poisoned until this solve converges
        values, remaining, usage, rounds, out = self._run_chunks(
            st, carry0, eps_f, parallel, shapes, view, use_ell)
        st.carry = out
        st.meta = meta

        self.solves += 1
        self.last_rounds = rounds
        self.last_mode = "warm" if carry0 is not None else "cold"
        if carry0 is not None:
            self.warm_solves += 1
            opstats.bump("warm_solves")
            # a warm restart whose entire delta is constraint-bound
            # flips is the fault-injection signature (link capacities
            # changed, topology didn't) — counted separately so fault
            # sweeps can see their re-solves ride the warm path
            if dirty is not None and all(
                    f == "c_bound" or not dirty[f]
                    for f in sorted(dirty)) \
                    and dirty.get("c_bound"):
                opstats.bump("warm_bound_restarts")
        else:
            self.cold_solves += 1
            opstats.bump("cold_solves")
        opstats.bump("solves")
        opstats.bump("fixpoint_rounds", rounds)
        return values, remaining, usage

    def _run_chunks(self, st: _DtypeState, carry, eps_f: float,
                    parallel: bool, shapes, view, use_ell: bool = False):
        """Bounded-round dispatch loop with host convergence checks
        between chunks; one device->host transfer per chunk (the
        solve_arrays discipline, minus host-side compaction, which
        would detach the carry from the resident masters)."""
        E, n_c, n_v = shapes
        chunk = _default_chunk()
        if _default_platform() != "cpu" and E >= 1 << 20:
            chunk = min(chunk, 32)
        has_bounds = bool(np.any((view.v_bound > 0)
                                 & (view.v_penalty > 0)))
        has_fatpipe = bool(view.c_fatpipe.any())

        prev_progress = None
        while True:
            if use_ell:
                values, remaining, usage, rounds, carry = _solve_ell_chunk(
                    st.ell[0], st.ell[1], st.ell[2], st.ell[3],
                    st.ell[4], st.masters[3], st.masters[4],
                    st.masters[5], st.masters[6], st.ell[5], carry,
                    eps=eps_f, parallel_rounds=parallel, chunk=chunk,
                    unroll=False, has_bounds=has_bounds,
                    has_fatpipe=has_fatpipe)
            else:
                values, remaining, usage, rounds, carry = \
                    _solve_kernel_chunk(
                        *st.masters, carry, eps=eps_f, n_c=n_c, n_v=n_v,
                        parallel_rounds=parallel, chunk=chunk,
                        unroll=False, has_bounds=has_bounds,
                        has_fatpipe=has_fatpipe)
            opstats.bump("dispatches")
            rdt = values.dtype
            fetched = np.asarray(jnp.concatenate([
                jnp.stack([rounds.astype(rdt),
                           jnp.count_nonzero(carry[4]).astype(rdt),
                           jnp.count_nonzero(carry[1]).astype(rdt)]),
                values, remaining.astype(rdt), usage.astype(rdt)]))
            rounds, n_light, n_fixed = (int(fetched[0]), int(fetched[1]),
                                        int(fetched[2]))
            if n_light == 0:
                values = fetched[3:3 + n_v]
                remaining = fetched[3 + n_v:3 + n_v + n_c]
                usage = fetched[3 + n_v + n_c:3 + n_v + 2 * n_c]
                break
            if rounds >= _MAX_ROUNDS:
                raise RuntimeError(
                    f"LMM warm solve did not converge within "
                    f"{_MAX_ROUNDS} saturation rounds ({n_c} constraint "
                    f"slots, {n_v} variable slots, {n_light} still "
                    f"active); check maxmin/precision vs the system's "
                    f"magnitudes")
            progress = (n_light, n_fixed)
            if progress == prev_progress:
                raise RuntimeError(
                    f"LMM warm solve stalled after {rounds} rounds: "
                    f"{n_light} active constraints and {n_fixed} fixed "
                    f"variables unchanged over {chunk} rounds; the "
                    f"system does not converge at eps={eps_f} in "
                    f"{np.dtype(fetched.dtype).name} precision")
            prev_progress = progress
        if not np.all(np.isfinite(values)):
            raise RuntimeError(
                "LMM warm solve returned non-finite rates "
                f"({n_c} constraint slots, {n_v} variable slots)")
        return values, remaining, usage, rounds, carry


def solve_selective(system, dtype, eps: float) -> bool:
    """Device entry for selective-update systems: serve the solve from
    the warm solver (device-resident masters + modified-component
    restart).  Returns False when ``lmm/warm-start:off`` asks for the
    legacy re-flatten path instead.

    Host side-effects mirror the list solver's selective init pass
    (maxmin.cpp:509-539) exactly like the legacy path: values of the
    modified constraints' enabled variables are reset, their actions
    flagged modified for lazy model updates, and only the modified
    constraints' variables/remaining/usage are written back — the
    reference's selective-update contract."""
    mode = _warm_mode()
    if mode == "off":
        return False
    view = system.array_view
    if view is None:
        from .lmm_view import ArrayView
        view = ArrayView(system)
    solver = system.warm_solver
    if solver is None:
        solver = WarmSolver(system)

    cnst_list = list(system.modified_constraint_set)
    for cnst in cnst_list:
        for elem in cnst.enabled_element_set:
            elem.variable.value = 0.0
    if system.modified_actions is not None:
        # zero-bound constraints' actions are reported too, matching
        # the legacy paths (park support, see Model lazy path)
        for cnst in cnst_list:
            for elem in cnst.enabled_element_set:
                if elem.consumption_weight > 0:
                    system.flag_action_modified(elem.variable.id)

    if cnst_list:
        values, remaining, usage = solver.solve(
            view, cnst_list, dtype, eps, warm=mode in ("auto", "on"))
        for cnst in cnst_list:
            ci = cnst._view_slot
            cnst.remaining = float(remaining[ci])
            cnst.usage = float(usage[ci])
            for elem in cnst.enabled_element_set:
                elem.variable.value = \
                    float(values[elem.variable._view_slot])

    system.modified = False
    system.remove_all_modified_set()
    return True
