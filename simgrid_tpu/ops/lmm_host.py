"""Exact host-side max-min fairness solver (the determinism oracle).

Re-implements the *semantics* of SimGrid's lmm::System saturate-bottleneck
algorithm — reference behavior studied from
/root/reference/src/kernel/lmm/maxmin.{hpp,cpp} (solve fixpoint at
maxmin.cpp:502-693, concurrency limits at maxmin.hpp:104-129, epsilon
helpers at src/surf/surf_interface.hpp:34-55) — with the same list
orderings, tie-breaking and ``double_update`` clamping so event ordering is
bit-identical to the reference.  This solver is the correctness oracle for
the vectorized JAX/TPU backend (:mod:`simgrid_tpu.ops.lmm_jax`) and the
fast path for small systems where a device round-trip would dominate.

The problem solved: maximize the minimum of ``penalty_i * rho_i`` subject to
``sum_i (w_ij * rho_i) <= C_j`` for every SHARED constraint j (``max_i``
instead of the sum for FATPIPE constraints), plus per-variable upper bounds.
The algorithm repeatedly saturates the bottleneck constraint (smallest
remaining/usage ratio), freezing the variables it feeds.
"""

from __future__ import annotations

import sys
from enum import Enum
from typing import Any, Callable, List, Optional

from ..utils.config import config


class SharingPolicy(Enum):
    SHARED = 0   # sum of consumptions bounded
    FATPIPE = 1  # max of consumptions bounded
    WIFI = 2


INT_MAX = sys.maxsize


# -- float helpers with explicit precision (surf_interface.hpp:34-55) -------

def double_update(value: float, delta: float, precision: float) -> float:
    value -= delta
    if value < precision:
        value = 0.0
    return value


def double_positive(value: float, precision: float) -> bool:
    return value > precision


def double_equals(a: float, b: float, precision: float) -> bool:
    return abs(a - b) < precision


# The reference keeps elements/variables/constraints in boost::intrusive
# lists whose push_front/push_back ordering defines the deterministic
# iteration (and hence floating-point accumulation) order; see
# utils/intrusive.py for the Python equivalent.
from ..utils.intrusive import IntrusiveList


class Element:
    """One (variable, constraint) incidence with its consumption weight."""

    __slots__ = ("consumption_weight", "constraint", "variable",
                 "_enabled_hook", "_disabled_hook", "_active_hook",
                 "_view_eslot")

    def __init__(self, constraint: "Constraint", variable: "Variable",
                 consumption_weight: float):
        self.consumption_weight = consumption_weight
        self.constraint = constraint
        self.variable = variable
        self._enabled_hook = None
        self._disabled_hook = None
        self._active_hook = None

    def get_concurrency(self) -> int:
        # weight < 1 (e.g. cross-traffic at 0.05) does not count toward the
        # constraint's concurrency (maxmin.cpp:30-40).
        return 1 if self.consumption_weight >= 1 else 0

    def decrease_concurrency(self) -> None:
        self.constraint.concurrency_current -= self.get_concurrency()

    def increase_concurrency(self) -> None:
        cnst = self.constraint
        cnst.concurrency_current += self.get_concurrency()
        if cnst.concurrency_current > cnst.concurrency_maximum:
            cnst.concurrency_maximum = cnst.concurrency_current

    def make_active(self) -> None:
        if self._active_hook is None:
            self.constraint.active_element_set.push_front(self)

    def make_inactive(self) -> None:
        if self._active_hook is not None:
            self.constraint.active_element_set.remove(self)


class Constraint:
    """A bounded resource: ``sum/max of w*rho <= bound``."""

    __slots__ = ("bound", "id", "rank", "remaining", "usage",
                 "concurrency_limit", "concurrency_current",
                 "concurrency_maximum", "_sharing_policy",
                 "enabled_element_set", "disabled_element_set",
                 "active_element_set", "_cs_hook", "_acs_hook", "_mcs_hook",
                 "_light_idx", "jax_slot", "_view_slot", "_system",
                 "_waiters")

    def __init__(self, system: "System", id_obj, bound: float):
        self._system = system
        self.bound = bound
        self.id = id_obj
        self.rank = system._next_cnst_rank
        system._next_cnst_rank += 1
        self.remaining = 0.0
        self.usage = 0.0
        self.concurrency_limit = config["maxmin/concurrency-limit"]
        self.concurrency_current = 0
        self.concurrency_maximum = 0
        self._sharing_policy = SharingPolicy.SHARED
        self.enabled_element_set = IntrusiveList("_enabled_hook")
        self.disabled_element_set = IntrusiveList("_disabled_hook")
        self.active_element_set = IntrusiveList("_active_hook")
        self._cs_hook = None
        self._acs_hook = None
        self._mcs_hook = None
        self._light_idx = -1
        self.jax_slot = -1  # stable slot in the flattened device arrays
        #: staged variables whose cached blocker is this constraint
        #: (insertion-ordered dict used as an ordered set)
        self._waiters: dict = {}

    @property
    def sharing_policy(self) -> "SharingPolicy":
        return self._sharing_policy

    @sharing_policy.setter
    def sharing_policy(self, policy: "SharingPolicy") -> None:
        # models assign the policy directly after constraint_new; route
        # the write through the ArrayView so a FATPIPE link created
        # after the view exists is solved with max-sharing, not sum
        self._sharing_policy = policy
        view = self._system.array_view
        if view is not None:
            view.on_policy(self)

    # concurrency ---------------------------------------------------------
    def get_concurrency_limit(self) -> int:
        return self.concurrency_limit

    def set_concurrency_limit(self, limit: int) -> None:
        assert limit < 0 or self.concurrency_maximum <= limit
        self.concurrency_limit = limit
        # A raised limit frees slack without an on_disabled_var event:
        # probe our registered waiters now (failed probes re-register on
        # their real blocker).  The reference would wake them at the
        # next disabled-list scan — same outcome, earlier instant.
        for var in list(self._waiters.values()):
            if var.staged_penalty > 0 and var.can_enable():
                self._system.enable_var(var)

    def get_concurrency_slack(self) -> int:
        if self.concurrency_limit < 0:
            return INT_MAX
        return self.concurrency_limit - self.concurrency_current

    # introspection -------------------------------------------------------
    def get_usage(self) -> float:
        """Load of the resource: sum (or max for FATPIPE) of w*value."""
        result = 0.0
        if self.sharing_policy != SharingPolicy.FATPIPE:
            for elem in self.enabled_element_set:
                if elem.consumption_weight > 0:
                    result += elem.consumption_weight * elem.variable.value
        else:
            for elem in self.enabled_element_set:
                if elem.consumption_weight > 0:
                    result = max(result, elem.consumption_weight * elem.variable.value)
        return result

    def get_variable_amount(self) -> int:
        return sum(1 for e in self.enabled_element_set if e.consumption_weight > 0)

    def iter_variables(self):
        for elem in self.enabled_element_set:
            yield elem.variable
        for elem in self.disabled_element_set:
            yield elem.variable


class Variable:
    """One consumer (an Action's rate): solved value is ``rho``."""

    __slots__ = ("id", "rank", "cnsts", "sharing_penalty", "staged_penalty",
                 "bound", "concurrency_share", "value", "visited", "mu",
                 "_vs_hook", "_svs_hook", "jax_slot", "_view_slot",
                 "_by_cnst", "_blocker")

    def __init__(self, system: "System", id_obj, sharing_penalty: float,
                 bound: float):
        self.id = id_obj
        self.rank = system._next_var_rank
        system._next_var_rank += 1
        self.cnsts: List[Element] = []
        #: constraint-id -> [elements]: O(1) lookup for expand's
        #: current-share scan and expand_add's edge search — a linear
        #: var.cnsts walk per element made huge-class bench construction
        #: (384 elements/var) quadratic per variable
        self._by_cnst: dict = {}
        #: the first constraint whose slack blocked can_enable — while
        #: its slack stays below our share, later wake-up probes answer
        #: 'no' in O(1) instead of rescanning all 384 bench elements,
        #: and on_disabled_var probes only its own registered waiters
        #: (the staged-variable wake-up walk was quadratic without it)
        self._blocker = None
        self.sharing_penalty = sharing_penalty
        self.staged_penalty = 0.0
        self.bound = bound
        self.concurrency_share = 1
        self.value = 0.0
        self.visited = system._visited_counter - 1
        self.mu = 0.0
        self._vs_hook = None
        self._svs_hook = None
        self.jax_slot = -1

    def set_concurrency_share(self, value: int) -> None:
        self.concurrency_share = value

    def get_value(self) -> float:
        return self.value

    def get_bound(self) -> float:
        return self.bound

    def get_min_concurrency_slack(self) -> int:
        minslack = INT_MAX
        for elem in self.cnsts:
            slack = elem.constraint.get_concurrency_slack()
            if slack < minslack:
                if slack == 0:
                    return 0
                minslack = slack
        return minslack

    def set_blocker(self, cnst) -> None:
        """(Re)register this staged variable as waiting on `cnst`; the
        wake-up scan (System.on_disabled_var) probes only registered
        waiters."""
        old = self._blocker
        if old is cnst:
            return
        if old is not None:
            old._waiters.pop(id(self), None)
        self._blocker = cnst
        if cnst is not None:
            cnst._waiters[id(self)] = self

    def can_enable(self) -> bool:
        # Early-exit slack scan (vs the reference's full
        # get_min_concurrency_slack): the first constraint below the
        # required share answers 'no', and it is cached as the blocker
        # so the next probe is O(1) until that constraint frees
        # capacity — keeps dense bench-protocol construction from
        # going quadratic in staged variables.
        if self.staged_penalty <= 0:
            return False
        share = self.concurrency_share
        blocker = self._blocker
        if (blocker is not None
                and blocker.get_concurrency_slack() < share):
            return False
        for elem in self.cnsts:
            if elem.constraint.get_concurrency_slack() < share:
                self.set_blocker(elem.constraint)
                return False
        self.set_blocker(None)
        return True

    def get_constraint(self, num: int) -> Optional[Constraint]:
        return self.cnsts[num].constraint if num < len(self.cnsts) else None

    def get_constraint_weight(self, num: int) -> float:
        return self.cnsts[num].consumption_weight

    def get_number_of_constraint(self) -> int:
        return len(self.cnsts)


class _LightEntry:
    __slots__ = ("cnst", "remaining_over_usage")

    def __init__(self, cnst: Constraint, rou: float):
        self.cnst = cnst
        self.remaining_over_usage = rou


class System:
    """The max-min fairness system: constraint/variable graph + solve().

    ``solve()`` dispatches between the exact list-based fixpoint below and
    the vectorized JAX backend (see :mod:`simgrid_tpu.ops.lmm_jax`)
    according to ``lmm/backend`` / ``lmm/jax-threshold``.
    """

    def __init__(self, selective_update: bool = False):
        self.selective_update_active = selective_update
        self.modified = False
        self._visited_counter = 1
        self._next_var_rank = 1
        self._next_cnst_rank = 1
        self.variable_set = IntrusiveList("_vs_hook")
        self.constraint_set = IntrusiveList("_cs_hook")
        self.active_constraint_set = IntrusiveList("_acs_hook")
        self.modified_constraint_set = IntrusiveList("_mcs_hook")
        self.saturated_variable_set = IntrusiveList("_svs_hook")
        # Actions whose variable value changed in the last solve; consumed
        # by lazy model updates (Action::ModifiedSet analog).
        self.modified_actions: Optional[List[Any]] = [] if selective_update else None
        self.solve_fn: Optional[Callable[["System"], None]] = None
        self.solve_count = 0
        #: incrementally-maintained flat arrays (ops.lmm_view.ArrayView),
        #: created lazily by the device backend; hooks below keep it in
        #: sync with every graph mutation
        self.array_view = None
        #: device-resident incremental solver (ops.lmm_warm.WarmSolver),
        #: created lazily on the first selective device solve
        self.warm_solver = None

    def flag_action_modified(self, action) -> None:
        """Report one action's rate as changed by the current solve
        (idempotent; the shared idiom of every solve backend)."""
        if (self.modified_actions is not None and action is not None
                and not getattr(action, "in_modified_set", False)):
            action.in_modified_set = True
            self.modified_actions.append(action)

    def drain_modified_actions(self) -> List[Any]:
        """Pop the actions whose rate changed in the last solve (the
        Action::ModifiedSet analog consumed by lazy model updates), clearing
        their membership flag so later solves can re-report them."""
        actions = self.modified_actions or []
        for action in actions:
            action.in_modified_set = False
        self.modified_actions = [] if self.selective_update_active else None
        return actions

    # -- graph construction ----------------------------------------------
    def constraint_new(self, id_obj, bound: float) -> Constraint:
        cnst = Constraint(self, id_obj, bound)
        self.constraint_set.push_back(cnst)
        if self.array_view is not None:
            self.array_view.on_new_cnst(cnst)
        return cnst

    def variable_new(self, id_obj, sharing_penalty: float,
                     bound: float = -1.0,
                     number_of_constraints: int = 1) -> Variable:
        var = Variable(self, id_obj, sharing_penalty, bound)
        if sharing_penalty > 0:
            self.variable_set.push_front(var)
        else:
            self.variable_set.push_back(var)
        if self.array_view is not None:
            self.array_view.on_new_var(var)
        return var

    def variable_free(self, var: Variable) -> None:
        self.variable_set.remove(var)
        if var._svs_hook is not None:
            self.saturated_variable_set.remove(var)
        self._var_free(var)

    def variable_free_all(self) -> None:
        while not self.variable_set.empty():
            self.variable_free(self.variable_set.front())

    def _var_free(self, var: Variable) -> None:
        self.modified = True
        if self.array_view is not None:
            self.array_view.on_var_free(var)
        if var.cnsts:
            self.update_modified_set(var.cnsts[0].constraint)
        for elem in var.cnsts:
            if var.sharing_penalty > 0:
                elem.decrease_concurrency()
            if elem._enabled_hook is not None:
                elem.constraint.enabled_element_set.remove(elem)
            if elem._disabled_hook is not None:
                elem.constraint.disabled_element_set.remove(elem)
            if elem._active_hook is not None:
                elem.constraint.active_element_set.remove(elem)
            nelements = (len(elem.constraint.enabled_element_set)
                         + len(elem.constraint.disabled_element_set))
            if nelements == 0:
                self.make_constraint_inactive(elem.constraint)
            else:
                self.on_disabled_var(elem.constraint)
        var.set_blocker(None)
        var.cnsts.clear()
        var._by_cnst.clear()

    def cnst_free(self, cnst: Constraint) -> None:
        self.make_constraint_inactive(cnst)
        self.constraint_set.remove(cnst)
        if self.array_view is not None:
            self.array_view.on_cnst_free(cnst)

    def expand(self, cnst: Constraint, var: Variable,
               consumption_weight: float) -> None:
        """Add (or stage) the var->cnst edge (maxmin.cpp:234-285 behavior)."""
        self.modified = True

        current_share = 0
        if var.concurrency_share > 1:
            for elem in var._by_cnst.get(id(cnst), ()):
                if elem._enabled_hook is not None:
                    current_share += elem.get_concurrency()

        if (var.sharing_penalty > 0
                and var.concurrency_share - current_share > cnst.get_concurrency_slack()):
            penalty = var.sharing_penalty
            self.disable_var(var)
            for elem in var.cnsts:
                self.on_disabled_var(elem.constraint)
            consumption_weight = 0
            var.staged_penalty = penalty
            assert not var.sharing_penalty
            # a failed can_enable registers the real blocker; on the
            # (rare) success, conservatively wait on the trigger
            if var.can_enable():
                var.set_blocker(cnst)

        elem = Element(cnst, var, consumption_weight)
        var.cnsts.append(elem)
        var._by_cnst.setdefault(id(cnst), []).append(elem)

        if var.sharing_penalty:
            cnst.enabled_element_set.push_front(elem)
            elem.increase_concurrency()
        else:
            cnst.disabled_element_set.push_back(elem)
        if self.array_view is not None:
            self.array_view.on_expand(elem)

        if not self.selective_update_active:
            self.make_constraint_active(cnst)
        elif elem.consumption_weight > 0 or var.sharing_penalty > 0:
            self.make_constraint_active(cnst)
            self.update_modified_set(cnst)
            if len(var.cnsts) > 1:
                self.update_modified_set(var.cnsts[0].constraint)

    def expand_add(self, cnst: Constraint, var: Variable, value: float) -> None:
        """Add value to an existing edge's weight (max for FATPIPE)."""
        self.modified = True
        edge = var._by_cnst.get(id(cnst))
        elem = edge[0] if edge else None
        if elem is not None:
            if var.sharing_penalty:
                elem.decrease_concurrency()
            if cnst.sharing_policy != SharingPolicy.FATPIPE:
                elem.consumption_weight += value
            else:
                elem.consumption_weight = max(elem.consumption_weight, value)
            if self.array_view is not None:
                self.array_view.on_weight(elem)
            if var.sharing_penalty:
                if cnst.get_concurrency_slack() < elem.get_concurrency():
                    penalty = var.sharing_penalty
                    self.disable_var(var)
                    for elem2 in var.cnsts:
                        self.on_disabled_var(elem2.constraint)
                    var.staged_penalty = penalty
                    assert not var.sharing_penalty
                    if var.can_enable():
                        var.set_blocker(cnst)
                elem.increase_concurrency()
            self.update_modified_set(cnst)
        else:
            self.expand(cnst, var, value)

    # -- active/modified bookkeeping --------------------------------------
    def make_constraint_active(self, cnst: Constraint) -> None:
        if cnst._acs_hook is None:
            self.active_constraint_set.push_back(cnst)

    def make_constraint_inactive(self, cnst: Constraint) -> None:
        if cnst._acs_hook is not None:
            self.active_constraint_set.remove(cnst)
        if cnst._mcs_hook is not None:
            self.modified_constraint_set.remove(cnst)

    def update_modified_set(self, cnst: Constraint) -> None:
        if self.selective_update_active and cnst._mcs_hook is None:
            self.modified_constraint_set.push_back(cnst)
            self._update_modified_set_rec(cnst)

    def _update_modified_set_rec(self, cnst: Constraint) -> None:
        # Depth-first propagation with the exact recursion order of the
        # reference (maxmin.cpp:898-913) — the modified-set order is the
        # selective solve's constraint order, so it must match — but driven
        # by an explicit generator stack so 100k-flow chains cannot
        # overflow Python's recursion limit.
        def visit(c: Constraint):
            for elem in c.enabled_element_set:
                var = elem.variable
                for elem2 in var.cnsts:
                    if var.visited == self._visited_counter:
                        break
                    c2 = elem2.constraint
                    if c2 is not c and c2._mcs_hook is None:
                        self.modified_constraint_set.push_back(c2)
                        yield c2
                var.visited = self._visited_counter

        stack = [visit(cnst)]
        while stack:
            child = next(stack[-1], None)
            if child is None:
                stack.pop()
            else:
                stack.append(visit(child))

    def remove_all_modified_set(self) -> None:
        self._visited_counter += 1
        if self._visited_counter == 1:
            for var in self.variable_set:
                var.visited = 0
        self.modified_constraint_set.clear()

    # -- enable/disable/staging (concurrency limits) ----------------------
    def enable_var(self, var: Variable) -> None:
        var.set_blocker(None)
        var.sharing_penalty = var.staged_penalty
        var.staged_penalty = 0
        if self.array_view is not None:
            self.array_view.on_penalty(var)
        self.variable_set.remove(var)
        self.variable_set.push_front(var)
        for elem in var.cnsts:
            elem.constraint.disabled_element_set.remove(elem)
            elem.constraint.enabled_element_set.push_front(elem)
            elem.increase_concurrency()
        if var.cnsts:
            self.update_modified_set(var.cnsts[0].constraint)

    def disable_var(self, var: Variable) -> None:
        assert not var.staged_penalty, "Staged penalty should have been cleared"
        self.variable_set.remove(var)
        self.variable_set.push_back(var)
        if var.cnsts:
            self.update_modified_set(var.cnsts[0].constraint)
        for elem in var.cnsts:
            elem.constraint.enabled_element_set.remove(elem)
            elem.constraint.disabled_element_set.push_back(elem)
            if elem._active_hook is not None:
                elem.constraint.active_element_set.remove(elem)
            elem.decrease_concurrency()
        var.sharing_penalty = 0.0
        var.staged_penalty = 0.0
        var.value = 0.0
        if self.array_view is not None:
            self.array_view.on_penalty(var)

    def on_disabled_var(self, cnst: Constraint) -> None:
        """Wake staged variables when `cnst` frees concurrency slack.

        The reference walks the whole disabled element list with a full
        slack scan per candidate (maxmin.cpp on_disabled_var) — O(list)
        per wake-up and quadratic over a churny run.  Here every staged
        variable is registered on ONE currently-blocking constraint
        (Variable.set_blocker), so the scan probes exactly the
        candidates this constraint was blocking, in registration order.
        Candidates blocked elsewhere cannot become enableable from this
        constraint's slack release, so skipping them is
        behavior-preserving; the probe order within one scan is
        registration order rather than the reference's disabled-list
        order (observable only when several waiters compete for the
        same freed slack — documented divergence)."""
        if cnst.get_concurrency_limit() < 0:
            return
        if not cnst._waiters:
            return
        for var in list(cnst._waiters.values()):
            if cnst.concurrency_current == cnst.get_concurrency_limit():
                break
            if var.staged_penalty > 0 and var.can_enable():
                self.enable_var(var)

    # -- runtime updates ---------------------------------------------------
    def update_variable_penalty(self, var: Variable, penalty: float) -> None:
        assert penalty >= 0, "Variable penalty should not be negative!"
        if penalty == var.sharing_penalty:
            return
        enabling_var = penalty > 0 and var.sharing_penalty <= 0
        disabling_var = penalty <= 0 and var.sharing_penalty > 0
        self.modified = True
        if enabling_var:
            var.staged_penalty = penalty
            minslack = var.get_min_concurrency_slack()
            if minslack < var.concurrency_share:
                # minslack < share guarantees the scan fails; run it
                # for its blocker-registration side effect
                var.can_enable()
                return
            self.enable_var(var)
        elif disabling_var:
            self.disable_var(var)
        else:
            var.sharing_penalty = penalty
            if self.array_view is not None:
                self.array_view.on_penalty(var)

    def update_variable_bound(self, var: Variable, bound: float) -> None:
        self.modified = True
        var.bound = bound
        if self.array_view is not None:
            self.array_view.on_vbound(var)
        if var.cnsts:
            self.update_modified_set(var.cnsts[0].constraint)

    def update_constraint_bound(self, cnst: Constraint, bound: float) -> None:
        self.modified = True
        self.update_modified_set(cnst)
        cnst.bound = bound
        if self.array_view is not None:
            self.array_view.on_cbound(cnst)

    # -- solve -------------------------------------------------------------
    def solve(self) -> None:
        if not self.modified:
            return
        self.solve_count += 1
        if self.solve_fn is not None:
            self.solve_fn(self)
            return
        self.solve_exact()

    def solve_exact(self) -> None:
        if self.selective_update_active:
            self._solve_list(list(self.modified_constraint_set))
        else:
            self._solve_list(list(self.active_constraint_set))

    def _solve_list(self, cnst_list: List[Constraint]) -> None:
        eps = config["maxmin/precision"]
        min_usage = -1.0
        min_bound = -1.0

        # Reset the value of every enabled variable of the touched portion.
        for cnst in cnst_list:
            for elem in cnst.enabled_element_set:
                elem.variable.value = 0.0

        light: List[_LightEntry] = []
        saturated_constraints: List[int] = []

        for cnst in cnst_list:
            cnst.remaining = cnst.bound
            if not double_positive(cnst.remaining, cnst.bound * eps):
                # Zero-capacity constraint: its flows get rate 0 this round.
                # Unlike the reference (maxmin.cpp:523-525), still report the
                # actions as modified so the lazy model drops their stale
                # completion dates (park support, see Model lazy path).
                for elem in cnst.enabled_element_set:
                    if elem.consumption_weight > 0:
                        self.flag_action_modified(elem.variable.id)
                continue
            cnst.usage = 0.0
            for elem in cnst.enabled_element_set:
                if elem.consumption_weight > 0:
                    w = elem.consumption_weight / elem.variable.sharing_penalty
                    if cnst.sharing_policy != SharingPolicy.FATPIPE:
                        cnst.usage += w
                    elif cnst.usage < w:
                        cnst.usage = w
                    elem.make_active()
                    self.flag_action_modified(elem.variable.id)
            if cnst.usage > 0:
                rou = cnst.remaining / cnst.usage
                entry = _LightEntry(cnst, rou)
                cnst._light_idx = len(light)
                light.append(entry)
                min_usage, saturated_constraints = self._saturated_constraints_update(
                    rou, len(light) - 1, saturated_constraints, min_usage)

        self._saturated_variable_set_update(light, saturated_constraints)

        light_num = len(light)
        while True:
            var_list = self.saturated_variable_set
            for var in var_list:
                if var.bound > 0 and var.bound * var.sharing_penalty < min_usage:
                    if min_bound < 0:
                        min_bound = var.bound * var.sharing_penalty
                    else:
                        min_bound = min(min_bound, var.bound * var.sharing_penalty)

            while not var_list.empty():
                var = var_list.front()
                if min_bound < 0:
                    var.value = min_usage / var.sharing_penalty
                else:
                    if double_equals(min_bound, var.bound * var.sharing_penalty, eps):
                        var.value = var.bound
                    else:
                        var_list.remove(var)
                        continue

                for elem in var.cnsts:
                    cnst = elem.constraint
                    if cnst.sharing_policy != SharingPolicy.FATPIPE:
                        cnst.remaining = double_update(
                            cnst.remaining, elem.consumption_weight * var.value,
                            cnst.bound * eps)
                        cnst.usage = double_update(
                            cnst.usage,
                            elem.consumption_weight / var.sharing_penalty, eps)
                        if (not double_positive(cnst.usage, eps)
                                or not double_positive(cnst.remaining, cnst.bound * eps)):
                            if cnst._light_idx >= 0:
                                idx = cnst._light_idx
                                light[idx] = light[light_num - 1]
                                light[idx].cnst._light_idx = idx
                                light_num -= 1
                                cnst._light_idx = -1
                        else:
                            if cnst._light_idx >= 0:
                                light[cnst._light_idx].remaining_over_usage = \
                                    cnst.remaining / cnst.usage
                        elem.make_inactive()
                    else:
                        # FATPIPE: recompute the max over still-unset vars.
                        cnst.usage = 0.0
                        elem.make_inactive()
                        for elem2 in cnst.enabled_element_set:
                            if elem2.variable.value > 0:
                                continue
                            if elem2.consumption_weight > 0:
                                cnst.usage = max(
                                    cnst.usage,
                                    elem2.consumption_weight / elem2.variable.sharing_penalty)
                        if (not double_positive(cnst.usage, eps)
                                or not double_positive(cnst.remaining, cnst.bound * eps)):
                            if cnst._light_idx >= 0:
                                idx = cnst._light_idx
                                light[idx] = light[light_num - 1]
                                light[idx].cnst._light_idx = idx
                                light_num -= 1
                                cnst._light_idx = -1
                        else:
                            if cnst._light_idx >= 0:
                                light[cnst._light_idx].remaining_over_usage = \
                                    cnst.remaining / cnst.usage
                var_list.remove(var)

            min_usage = -1.0
            min_bound = -1.0
            saturated_constraints = []
            for pos in range(light_num):
                min_usage, saturated_constraints = self._saturated_constraints_update(
                    light[pos].remaining_over_usage, pos, saturated_constraints,
                    min_usage)
            self._saturated_variable_set_update(light, saturated_constraints)
            if light_num <= 0:
                break

        self.modified = False
        if self.selective_update_active:
            self.remove_all_modified_set()

    @staticmethod
    def _saturated_constraints_update(usage, pos, saturated, min_usage):
        assert usage > 0, "Impossible"
        if min_usage < 0 or min_usage > usage:
            min_usage = usage
            saturated = [pos]
        elif min_usage == usage:
            saturated.append(pos)
        return min_usage, saturated

    def _saturated_variable_set_update(self, light, saturated_constraints):
        for pos in saturated_constraints:
            cnst = light[pos].cnst
            for elem in cnst.active_element_set:
                if elem.consumption_weight > 0 and elem.variable._svs_hook is None:
                    self.saturated_variable_set.push_back(elem.variable)

    # -- debugging ---------------------------------------------------------
    def print_system(self, out=sys.stderr) -> None:
        out.write("MAX-MIN ( " + " ".join(
            f"'{v.rank}'({v.sharing_penalty})" for v in self.variable_set) + " )\n")
        for cnst in self.active_constraint_set:
            op = " , " if cnst.sharing_policy == SharingPolicy.FATPIPE else " + "
            terms = op.join(
                f"{e.consumption_weight}.'{e.variable.rank}'({e.variable.value})"
                for e in cnst.enabled_element_set)
            out.write(f"\t({terms}0) <= {cnst.bound} ('{cnst.rank}')\n")
        for var in self.variable_set:
            bound = f" (<={var.bound})" if var.bound > 0 else ""
            out.write(f"'{var.rank}'({var.sharing_penalty}) : {var.value}{bound}\n")


def make_new_maxmin_system(selective_update: bool = False) -> System:
    return System(selective_update)
