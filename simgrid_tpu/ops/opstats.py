"""Process-wide performance counters for the device solve paths.

Every device-facing module reports into one flat counter table so
tools can attribute cost per simulation phase without plumbing a
context object through the solver entry points:

* ``dispatches``            — device kernel dispatches (solver chunks,
                              drain advances/supersteps, warm solves)
* ``fixpoint_rounds``       — saturation rounds executed on device
* ``uploaded_bytes_full``   — host->device bytes shipped as whole
                              arrays (fresh ``device_put``)
* ``uploaded_bytes_delta``  — host->device bytes shipped as indexed
                              scatter payloads (ops.lmm_warm)
* ``solves`` / ``warm_solves`` / ``cold_solves`` — device solve entry
                              counts (warm = carried modified-component
                              restart, cold = full re-init)

Counters only ever increase; consumers snapshot before a phase and
diff after (``snapshot``/``diff``).  Purely observational — nothing in
the solve paths reads them back.
"""

from __future__ import annotations

from typing import Dict

_counters: Dict[str, float] = {}


def bump(name: str, n=1) -> None:
    _counters[name] = _counters.get(name, 0) + n


def snapshot() -> Dict[str, float]:
    return dict(_counters)


def diff(before: Dict[str, float]) -> Dict[str, float]:
    """Counter deltas since `before` (keys with zero delta omitted)."""
    out = {}
    for k, v in _counters.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


def reset() -> None:
    _counters.clear()
