"""Process-wide performance counters for the device solve paths.

Every device-facing module reports into one flat counter table so
tools can attribute cost per simulation phase without plumbing a
context object through the solver entry points:

* ``dispatches``            — device kernel dispatches (solver chunks,
                              drain advances/supersteps, warm solves)
* ``batch_dispatches``      — dispatches that ran a whole replica
                              FLEET (ops.lmm_batch); always also
                              counted in ``dispatches``
* ``batch_replicas``        — replicas admitted into batched fleets
* ``fixpoint_rounds``       — saturation rounds executed on device
* ``uploaded_bytes_full``   — host->device bytes shipped as whole
                              arrays (fresh ``device_put``)
* ``uploaded_bytes_delta``  — host->device bytes shipped as indexed
                              scatter payloads (ops.lmm_warm) or
                              compact per-replica scenario payloads
                              (ops.lmm_batch)
* ``solves`` / ``warm_solves`` / ``cold_solves`` — device solve entry
                              counts (warm = carried modified-component
                              restart, cold = full re-init)
* ``warm_ell_fallbacks``    — selective solves that requested a warm
                              restart while the ELL layout was
                              selected: the warm carry is COO-only, so
                              the solver falls back to cold and counts
                              the gap here instead of hiding it
* ``shards``                — shard lanes admitted into mesh-sharded
                              fleets/solves (ops.lmm_batch ``mesh=``:
                              one bump of the mesh's device count per
                              sharded program set up)
* ``demux_fetches``         — per-SHARD completion-ring transfers of
                              sharded fleets: each fleet sync fetches
                              one [B/M, ·] block per device and the
                              host reassembles them in replica order
                              before the event demux
* ``replicated_upload_bytes`` — host->device bytes for fleet-SHARED
                              arrays under a mesh, counted once per
                              device copy (a pod really ships M
                              copies of the platform flattening)
* ``sharded_upload_bytes``  — host->device bytes for [B, ·]
                              per-replica payloads under a mesh: each
                              byte lands on exactly one device, so
                              this stays flat per replica as the mesh
                              grows
* ``fetches``               — device->host result transfers routed
                              through :func:`timed_fetch` (drain ring
                              fetches, batched fleet fetches; each
                              shard block of a sharded fleet counts
                              once)
* ``fetched_bytes``         — device->host bytes moved by those
                              transfers
* ``blocking_fetches``      — the subset of ``fetches`` whose device
                              computation had NOT finished when the
                              host asked (``Array.is_ready()`` false):
                              the host genuinely stalled on the tunnel
                              round trip instead of overlapping it
* ``host_block_ms``         — monotonic host milliseconds spent inside
                              fetches (``time.perf_counter`` deltas —
                              wall time the host driver was blocked on
                              device results; the overlap fraction of
                              the pipelined drain is
                              1 - host_block_ms/phase wall)
* ``donated_buffers``       — carried-state device buffers handed to
                              XLA for in-place reuse by donating
                              superstep dispatches (ops.lmm_drain /
                              ops.lmm_batch ``donate=``: one bump per
                              donated argument, so steady-state drains
                              add 2 — pen and rem — per superstep);
                              the donation win proglint's ``donation``
                              rule verifies in the lowered IR
* ``speculations_issued`` / ``speculations_committed`` /
  ``speculations_rolled_back`` — speculative supersteps dispatched
                              in-flight by the pipelined drain
                              executors, how many were committed
                              as-is, and how many were discarded
                              because processing the PRECEDING
                              completion ring mutated the system
* ``fault_tape_slots``      — fault-tape entries compiled into device
                              event tapes at sim construction
                              (ops.lmm_drain ``tape=`` / ops.lmm_batch
                              ``tapes=``: one bump per scheduled
                              failure/repair date across all lanes)
* ``fault_tape_events``     — tape events that FIRED mid-drain: the
                              superstep clamped dt to the event date,
                              scattered the new constraint bound and
                              emitted the tagged ring entry the host
                              demuxed into ``fault_events``
* ``fault_replays``         — speculative in-flight supersteps
                              discarded because the superstep they
                              chained from fired a tape event (the
                              pipelined executors treat a fire as a
                              clean-collect boundary and replay from
                              the post-fault state)
* ``warm_bound_restarts``   — warm solves whose entire dirty delta was
                              constraint-bound flips (the
                              fault-injection signature: capacities
                              changed, topology didn't); subset of
                              ``warm_solves``
* ``plan_cache_hits`` / ``plan_cache_misses`` — serving AOT plan-cache
                              lookups (serving.plancache): a hit
                              reuses a resident or disk-serialized
                              compiled executable (zero traces), a
                              miss pays one ``lower().compile()``
* ``plan_cache_disk_hits``  — the subset of hits deserialized from the
                              on-disk artifact store (warm restarts)
* ``plan_compile_ms``       — monotonic milliseconds spent AOT
                              lowering+compiling on plan-cache misses
                              (0 on a fully warm restart)
* ``plan_cache_fallbacks``  — plan-cache dispatches that fell back to
                              the plain traced jit (unserializable
                              backend / stale artifact); correctness
                              never depends on the cache
* ``lanes_admitted``        — dead fleet lanes revived mid-flight with
                              a NEW scenario by the serving admission
                              path (BatchDrainSim.admit_lane)
* ``serve_device_results``  — queries the campaign service answered
                              with exact device simulation
* ``surrogate_answers`` / ``surrogate_escalations`` — queries the
                              serving surrogate answered from its
                              conformal-interval prediction vs routed
                              to the device because the interval was
                              too wide (exact=True bypasses both)
* ``solver_fallbacks``      — device solves redone by the exact host
                              solver after a non-convergent/non-finite
                              device fixpoint (the per-stage view of
                              ``lmm_jax.get_fallback_count``'s
                              process-global int)
* ``lane_quarantined_<cause>`` — fleet lanes killed WITH a recorded
                              cause (ops.lmm_batch.LaneFault) instead
                              of poisoning the fleet: ``nan_solve``,
                              ``stall``, ``non_convergence``,
                              ``ring_overflow``, ``admission_storm``,
                              ``watchdog``
* ``fleet_checkpoints``     — superstep-boundary FleetCheckpoints
                              written by the campaign service
* ``checkpoint_ms``         — monotonic milliseconds spent building +
                              writing those checkpoints
* ``fleet_resumes``         — services rebuilt from a FleetCheckpoint
                              token (CampaignService.resume)
* ``watchdog_retries`` / ``watchdog_exhausted`` /
  ``watchdog_slow_dispatches`` — dispatch-watchdog activity: seeded-
                              backoff retries of failed device
                              dispatches, dispatches that kept failing
                              past the retry policy, and dispatches
                              that succeeded but exceeded the
                              wall-clock threshold
* ``watchdog_solo_fallbacks`` — campaign-service fallbacks onto the
                              solo host path after watchdog
                              exhaustion (affected in-flight queries
                              are re-served solo, bit-identically)
* ``serve_solo_results``    — queries the campaign service answered
                              on the solo host path (watchdog
                              fallback)
* ``native_advances``       — engine advances served by the generic
                              host sweep (models.cpu/models.network)
                              instead of a device drain plan
* ``fastpath_advances``     — engine advances fully served by the
                              device drain plan (ops.drain_path
                              serve/apply at the planned dt)
* ``drain_transitions``     — drain-plan transition absorptions: dirty
                              deltas scattered into the live device
                              state instead of invalidating the plan
* ``drain_transition_slots`` — slots touched by those scatters
* ``drain_cause_<cause>``   — drain-plan invalidation/absorption
                              causes (``partial_advance``,
                              ``transition``, ``stall``,
                              ``profile_event``, ...): one bump per
                              event, keyed by cause
* ``phase_<kind>``          — drain-plan builds keyed by the
                              classified phase kind of the system
                              snapshot (ops.drain_path.classify_phase)
* ``collective_tape_slots`` — collective-tape entries compiled into
                              device schedule tapes at sim
                              construction (collectives.tape)
* ``collective_tape_fires`` — collective tape events that FIRED
                              mid-drain (ring entries the host demuxed
                              into ``collective_events``)
* ``collective_replays``    — speculative in-flight supersteps
                              discarded because the superstep they
                              chained from fired a collective tape
                              event (mirror of ``fault_replays``)
* ``retraces``              — jit trace executions of the kernel
                              program functions (bumped at TRACE time
                              only, from inside the program body): a
                              steady-state superstep loop must keep
                              this flat — a nonzero delta on a repeat
                              run is a cache-busting retrace

Counters only ever increase; consumers snapshot before a phase and
diff after (``snapshot``/``diff``), or wrap the phase in ``scoped``.
Purely observational — nothing in the solve paths reads them back.
(``host_block_ms`` uses the monotonic ``time.perf_counter`` — never
the banned wall-clock ``time.time`` — so the determinism lint stays
clean and the timing is immune to clock steps.)

Per-stage scoping
-----------------

``scoped(name)`` brackets a phase: the yielded dict is filled with the
phase's counter *deltas* on exit and also recorded in ``stage_stats``
under ``name``.  Scopes nest (each diffs against its own entry
snapshot), so a bench process running several stages — or the batch
driver running several fleets — reports per-stage counters instead of
process-cumulative ones, and re-running a stage in the same process
can no longer double-count the previous stage's work::

    with opstats.scoped("sweep/b64") as st:
        campaign.run_batched(batch=64)
    st["dispatches"]          # this stage only
"""

from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator

import numpy as np

_counters: Dict[str, float] = {}

#: per-stage deltas recorded by ``scoped`` (last run of each stage)
stage_stats: Dict[str, Dict[str, float]] = {}


def bump(name: str, n=1) -> None:
    _counters[name] = _counters.get(name, 0) + n


def timed_fetch(arr) -> "np.ndarray":
    """Fetch one device array to host with blocking accounting: counts
    the transfer in ``fetches``, classifies it as a ``blocking_fetch``
    when the device had not finished computing it at call time
    (``is_ready()`` false — the host is about to stall on the round
    trip), and adds the monotonic milliseconds spent inside the fetch
    to ``host_block_ms``.  The pipelined drain's whole point is turning
    blocking fetches into ready ones; this is where that is measured.
    """
    ready = bool(getattr(arr, "is_ready", lambda: False)())
    t0 = time.perf_counter()
    out = np.asarray(arr)
    bump("host_block_ms", (time.perf_counter() - t0) * 1e3)
    bump("fetches")
    bump("fetched_bytes", out.nbytes)
    if not ready:
        bump("blocking_fetches")
    return out


def snapshot() -> Dict[str, float]:
    return dict(_counters)


def diff(before: Dict[str, float]) -> Dict[str, float]:
    """Counter deltas since `before` (keys with zero delta omitted)."""
    out = {}
    for k, v in _counters.items():
        d = v - before.get(k, 0)
        if d:
            out[k] = d
    return out


@contextlib.contextmanager
def scoped(name: str) -> Iterator[Dict[str, float]]:
    """Bracket a phase: yields a dict that receives the phase's counter
    deltas on exit (also kept in ``stage_stats[name]``)."""
    before = snapshot()
    stats: Dict[str, float] = {}
    stage_stats[name] = stats
    try:
        yield stats
    finally:
        stats.update(diff(before))


def get_stage(name: str) -> Dict[str, float]:
    """The recorded deltas of a completed ``scoped`` stage ({} when the
    stage never ran)."""
    return dict(stage_stats.get(name, {}))


def reset() -> None:
    """Clear every counter AND the recorded stage deltas (fresh
    process-equivalent state for tests and multi-phase tools)."""
    _counters.clear()
    stage_stats.clear()
