"""Batched multi-replica scenario executor: vmapped solve+drain across
a fleet of independent simulations in ONE device program.

The paper's hot spot — the max-min fixpoint — is already fast for one
simulation (fused/superstepped drains, warm-started selective solves),
but the north star is serving *fleets* of scenarios: Monte Carlo fault
campaigns, parameter sweeps, per-user what-ifs.  Run solo, each replica
pays its own dispatches and uploads, and on the tunneled accelerator
every host->device transfer costs 150-500 ms *regardless of size* —
exactly the shape batched inference serving amortizes (cf. ASTRA-sim
3.0 and the TPU fluid-flow framework in PAPERS.md, both of which get
their throughput from batching many independent problem instances into
one accelerator program).

This module ``vmap``s the existing kernel *programs* (the raw functions
behind ops.lmm_drain's solo jits and ops.lmm_jax's chunk kernels) over
a leading replica axis:

* **one shared platform flattening** — the COO structure (e_var,
  e_cnst) and, by default, the element weights are uploaded ONCE for
  the whole fleet; only per-replica state (bounds, remaining,
  penalties, thresholds) carries the batch axis;
* **compact scenario payloads** — per-replica scenarios are shipped as
  small override records (bandwidth/size scale factors plus sparse
  per-link and per-flow deltas) and *materialized on device*, so the
  per-replica upload cost is O(overrides), not O(system);
* **lockstep supersteps with an alive mask** — every dispatch runs up
  to K advances for every live replica; finished (or diverged)
  replicas go dark (their lane's while_loop cond is forced false, so
  the batching rule freezes their state) instead of forcing ragged
  shapes;
* **per-replica completion rings, one fetch** — each superstep's
  [B, ring] event log comes back in a single device->host transfer and
  is demultiplexed into per-replica event streams.

Determinism contract: each replica's event order AND clocks are
bit-identical to the same scenario drained solo by ops.lmm_drain's
DrainSim — the vmapped lane executes the exact same program, per-lane
reductions keep the solo element order, and per-replica clocks are
accumulated on the host in f64 exactly like the solo path
(``tools/check_determinism.py --runtime-batch`` asserts this against a
batch of 64 mixed fault/sweep scenarios).

Pod-scale sharding: ``mesh=M`` shards the REPLICA axis of the same
vmapped programs across a device mesh with ``NamedSharding(mesh,
PartitionSpec("batch"))`` — per-replica state ([B, ·] bounds, flow
state, thresholds, alive mask, payloads, completion rings) is split
into per-device blocks while the shared platform flattening (COO
structure, base arrays) is replicated.  Compact scenario payloads are
device_put under the batch sharding, so every payload byte lands on
exactly ONE device and host->device traffic stays flat as B grows with
the mesh; each superstep's completion rings come back as one fetch PER
SHARD (``demux_fetches``) and are reassembled in replica order before
the host demux, so the committed event stream is independent of the
mesh shape.  The per-lane program is untouched by partitioning (no
cross-lane math), so a sharded fleet is bit-identical to the
single-device vmapped fleet AND to solo runs
(``tools/check_determinism.py --runtime-shard``).  On CPU, validate
with ``XLA_FLAGS=--xla_force_host_platform_device_count=M``.

When B is not divisible by the mesh size the fleet is padded with
DEAD lanes (neutral overrides, alive=False from birth): the vmap
batching rule freezes them at k=0, they are excluded from the demux,
and a runtime guard asserts they produce zero completion events.
"""

from __future__ import annotations

import functools
import time
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from . import opstats
from .lmm_jax import (_MAX_ROUNDS, _solve_kernel_chunk_batched,
                      _solve_kernel_chunk_batched_fresh)
from .lmm_drain import (_FLAG_BUDGET, _FLAG_OK, _FLAG_STALLED, _ZERO_BITS,
                        _pos_group, _fused_step_program,
                        _superstep_program, _to2d)


#: the mesh axis name the replica dimension shards over
BATCH_AXIS = "batch"


class AdmissionError(RuntimeError):
    """A lane admission the fleet cannot honor within the capacity
    fixed at fleet birth: the lane is alive or out of range, the
    overrides carry ``elem_w`` entries but the fleet shares one weight
    table, or the fault tape is wider than the fleet's reserved tape
    slots.  The serving layer catches this and either defers the query
    or retires the fleet."""


class LaneFault:
    """Why one lane was QUARANTINED — killed with a recorded cause
    while the rest of the fleet kept draining.  Attached to the lane's
    :class:`ReplicaState` (and, through the serving layer, to the
    query's Ticket) so a poisoned scenario is diagnosable instead of
    silently missing.  Causes:

    * ``nan_solve``        — the superstep returned a NaN clock
                             advance (degenerate capacities/overrides);
                             the lane's ring events for that dispatch
                             are garbage and are dropped
    * ``stall``            — no flow holds bandwidth (dt not finite)
    * ``non_convergence``  — the budget rescue still could not finish
                             one advance
    * ``ring_overflow``    — the completion ring reported more events
                             than it has slots (defensive; would
                             corrupt the demux)
    * ``admission_storm``  — the serving layer gave up admitting the
                             scenario after repeated fleet generations
    * ``watchdog``         — device dispatches exhausted the retry
                             policy; the query fell back to the solo
                             host path

    Each quarantine bumps the matching ``lane_quarantined_<cause>``
    opstats counter."""

    __slots__ = ("cause", "detail", "lane", "superstep", "t")

    def __init__(self, cause: str, detail: str, lane: int,
                 superstep: int = 0, t: float = 0.0):
        self.cause = str(cause)
        self.detail = str(detail)
        self.lane = int(lane)
        self.superstep = int(superstep)
        self.t = float(t)

    def to_dict(self) -> Dict:
        return {"cause": self.cause, "detail": self.detail,
                "lane": self.lane, "superstep": self.superstep,
                "t": self.t}

    @classmethod
    def from_dict(cls, d: Dict) -> "LaneFault":
        return cls(d["cause"], d["detail"], d["lane"],
                   superstep=d.get("superstep", 0), t=d.get("t", 0.0))

    def __repr__(self) -> str:
        return (f"LaneFault(cause={self.cause!r}, lane={self.lane}, "
                f"t={self.t!r}, detail={self.detail!r})")


class DispatchExhausted(RuntimeError):
    """A device dispatch kept failing after every watchdog retry; the
    caller (serving layer) should fall back to the solo host path for
    the affected lanes instead of poisoning the whole campaign."""


class DispatchWatchdog:
    """Wall-clock guard around fleet device dispatches: bounded
    retries with seeded exponential backoff (riding the existing
    :class:`~simgrid_tpu.s4u.activity.RetryPolicy` shape) around every
    dispatch/fetch, plus a post-hoc slow-dispatch threshold.

    Retrying a fleet dispatch is SAFE: issues and fetches are pure
    functions of the committed device state (nothing commits until the
    host collect), so a re-run after a transient runtime failure is
    bit-identical.  A dispatch that still fails after
    ``policy.max_attempts`` raises :class:`DispatchExhausted`.  A
    dispatch that *succeeds* but took longer than ``timeout_s`` cannot
    be aborted mid-flight (jax calls are synchronous) — it is counted
    in ``watchdog_slow_dispatches`` so operators see the device
    degrading before it dies.

    Backoff delays use the monotonic-safe ``time.sleep`` only; the
    jitter is the RetryPolicy's SEEDED stream, so retry timing never
    introduces wall-clock entropy into the audited packages."""

    def __init__(self, policy=None, timeout_s: float = float("inf")):
        if policy is None:
            from ..s4u.activity import RetryPolicy
            policy = RetryPolicy(max_attempts=3, base_delay=0.05,
                                 multiplier=4.0, max_delay=2.0)
        self.policy = policy
        self.timeout_s = float(timeout_s)
        self.retries = 0
        self.slow_dispatches = 0
        self.exhausted = 0

    def guard(self, fn, what: str = "dispatch"):
        attempt = 1
        while True:
            t0 = time.perf_counter()
            try:
                out = fn()
            except Exception as exc:
                if attempt >= int(self.policy.max_attempts):
                    self.exhausted += 1
                    opstats.bump("watchdog_exhausted")
                    raise DispatchExhausted(
                        f"fleet {what} failed {attempt} time(s), "
                        f"retry policy exhausted: {exc}") from exc
                self.retries += 1
                opstats.bump("watchdog_retries")
                time.sleep(float(self.policy.backoff(attempt)))
                attempt += 1
                continue
            if time.perf_counter() - t0 > self.timeout_s:
                self.slow_dispatches += 1
                opstats.bump("watchdog_slow_dispatches")
            return out

    def timed(self, fn, what: str = "fetch"):
        """Wall-clock accounting WITHOUT retries — for the ring fetch,
        whose source buffer is consumed on failure (the superstep must
        be replayed from committed state, not the fetch re-run)."""
        t0 = time.perf_counter()
        out = fn()
        if time.perf_counter() - t0 > self.timeout_s:
            self.slow_dispatches += 1
            opstats.bump("watchdog_slow_dispatches")
        return out


def _pow2_bucket(n: int) -> int:
    """Smallest power of two >= n (>= 1): payload/tape widths are
    bucketed so admissions and warm restarts hit a handful of stable
    compiled shapes instead of one per width."""
    return 1 if n <= 1 else 1 << (int(n) - 1).bit_length()


def _as_mesh(mesh) -> Optional[Mesh]:
    """Normalize the ``mesh`` argument: None stays None (single-device
    vmap), an int M builds a 1-D ("batch",) mesh over the first M
    devices, a jax Mesh is used as-is (it must carry a "batch" axis)."""
    if mesh is None:
        return None
    if isinstance(mesh, Mesh):
        if BATCH_AXIS not in mesh.axis_names:
            raise ValueError(
                f"replica-sharded fleets need a {BATCH_AXIS!r} mesh "
                f"axis (got {mesh.axis_names})")
        return mesh
    n = int(mesh)
    if n <= 0:
        raise ValueError("mesh must be a positive device count")
    devices = jax.devices()
    if n > len(devices):
        raise ValueError(
            f"mesh={n} but only {len(devices)} device(s) visible "
            f"(on CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n})")
    return Mesh(np.asarray(devices[:n]), axis_names=(BATCH_AXIS,))


# ---------------------------------------------------------------------------
# Scenario overrides: compact per-replica deltas, materialized on device
# ---------------------------------------------------------------------------

class ReplicaOverrides:
    """One replica's deviation from the shared base scenario.

    Everything here is SMALL by design — a campaign's whole point is
    that per-replica upload cost must not scale with system size:

    * ``bw_scale``     — global link-capacity multiplier (sweeps);
    * ``size_scale``   — global flow-size multiplier (sweeps);
    * ``link_scale``   — sparse {constraint slot: capacity factor}
                         (fault-campaign degradations, hot-spot what-ifs);
    * ``flow_scale``   — sparse {variable slot: size factor};
    * ``dead_flows``   — variable slots absent from this replica
                         (penalty forced to 0: the flow never runs);
    * ``elem_w``       — sparse {element slot: sharing weight}: this
                         replica's element-weight deviations from the
                         shared ``e_w`` table (route-weight what-ifs,
                         per-replica QoS shares).  The fleet's [B, E]
                         weight table is materialized ON DEVICE from
                         these indexed payloads — upload bytes scale
                         with the overridden slots, never with B×E.
    """

    __slots__ = ("bw_scale", "size_scale", "link_scale", "flow_scale",
                 "dead_flows", "elem_w")

    def __init__(self, bw_scale: float = 1.0, size_scale: float = 1.0,
                 link_scale: Optional[Dict[int, float]] = None,
                 flow_scale: Optional[Dict[int, float]] = None,
                 dead_flows: Iterable[int] = (),
                 elem_w: Optional[Dict[int, float]] = None):
        if bw_scale <= 0 or size_scale <= 0:
            raise ValueError("bw_scale and size_scale must be > 0")
        self.bw_scale = float(bw_scale)
        self.size_scale = float(size_scale)
        self.link_scale = dict(link_scale or {})
        self.flow_scale = dict(flow_scale or {})
        self.dead_flows = tuple(sorted(set(int(s) for s in dead_flows)))
        self.elem_w = dict(elem_w or {})


def derive_replica_arrays(c_bound, sizes, remains, penalty,
                          ov: ReplicaOverrides):
    """HOST materialization of one replica's f64 per-replica arrays —
    the exact op-for-op mirror of the device `_materialize` kernel, so
    a solo run (ops.lmm_drain.DrainSim over these arrays) is
    bit-identical to the replica's lane in the batched program.  Keep
    the two in sync: base*global-scale first, then the sparse factors
    in sorted slot order."""
    cb = np.asarray(c_bound, np.float64) * ov.bw_scale
    for slot in sorted(ov.link_scale):
        cb[slot] *= ov.link_scale[slot]
    sz = np.asarray(sizes, np.float64) * ov.size_scale
    rem = np.asarray(remains, np.float64) * ov.size_scale
    for slot in sorted(ov.flow_scale):
        sz[slot] *= ov.flow_scale[slot]
        rem[slot] *= ov.flow_scale[slot]
    pen = np.asarray(penalty, np.float64).copy()
    for slot in ov.dead_flows:
        pen[slot] = 0.0
    return cb, sz, rem, pen


def derive_replica_ew(e_w, ov: ReplicaOverrides, dtype) -> np.ndarray:
    """HOST materialization of one replica's element weights — the
    op-for-op mirror of the device `_materialize_ew` kernel: indexed
    SET (not multiply) of the overridden slots in sorted order, then
    the dtype cast.  Exact: scatter-set carries the payload value
    bit-for-bit, so solo and batched lanes see identical weights."""
    ew = np.asarray(e_w, np.float64).copy()
    for slot in sorted(ov.elem_w):
        ew[slot] = ov.elem_w[slot]
    return ew.astype(dtype)


def _pack_overrides(specs: List[ReplicaOverrides], n_c: int, n_v: int):
    """Stack the fleet's overrides into padded payload arrays (pad
    index = out-of-range slot, dropped by the device scatters; pad
    factor = 1.0, a no-op)."""
    B = len(specs)
    sl = max(1, max(len(s.link_scale) for s in specs))
    sf = max(1, max(len(s.flow_scale) for s in specs))
    sd = max(1, max(len(s.dead_flows) for s in specs))
    bw = np.array([s.bw_scale for s in specs], np.float64)
    fs = np.array([s.size_scale for s in specs], np.float64)
    li = np.full((B, sl), n_c, np.int32)
    lf = np.ones((B, sl), np.float64)
    fi = np.full((B, sf), n_v, np.int32)
    ff = np.ones((B, sf), np.float64)
    di = np.full((B, sd), n_v, np.int32)
    for b, s in enumerate(specs):
        for j, slot in enumerate(sorted(s.link_scale)):
            li[b, j] = slot
            lf[b, j] = s.link_scale[slot]
        for j, slot in enumerate(sorted(s.flow_scale)):
            fi[b, j] = slot
            ff[b, j] = s.flow_scale[slot]
        for j, slot in enumerate(s.dead_flows):
            di[b, j] = slot
    return bw, fs, li, lf, fi, ff, di


def _pack_elem_w(specs: List[ReplicaOverrides], pad_idx: int, dtype):
    """Stack the fleet's sparse element-weight overrides into one
    padded indexed payload (pad index = out-of-range slot, dropped by
    the device scatter).  Bytes scale with the widest replica's
    override count — NEVER with B×E."""
    B = len(specs)
    se = max(1, max(len(s.elem_w) for s in specs))
    ei = np.full((B, se), pad_idx, np.int32)
    ew = np.zeros((B, se), dtype)
    for b, s in enumerate(specs):
        for j, slot in enumerate(sorted(s.elem_w)):
            ei[b, j] = slot
            ew[b, j] = s.elem_w[slot]
    return ei, ew


@jax.jit
def _materialize_ew(base_ew2, ei, ew):
    """DEVICE materialization of the fleet's [B, ·, group] element
    weights from the shared 2D table + indexed payloads: per-lane
    scatter-SET into the flattened table (pad slots drop).  Must stay
    the op-for-op mirror of derive_replica_ew."""
    flat = base_ew2.reshape(-1)

    def lane(ei_l, ew_l):
        return flat.at[ei_l].set(ew_l, mode="drop").reshape(
            base_ew2.shape)

    return jax.vmap(lane)(ei, ew)


@jax.jit
def _materialize(base_cb, base_sizes, base_rem, base_pen,
                 bw, fs, li, lf, fi, ff, di):
    """DEVICE materialization of the fleet's per-replica f64 state from
    the shared base + compact payloads: base*global-scale elementwise,
    then sparse scatter-multiplies (pad slots scatter out of range and
    drop).  Must stay the op-for-op mirror of derive_replica_arrays."""
    def lane(bw_l, fs_l, li_l, lf_l, fi_l, ff_l, di_l):
        cb = base_cb * bw_l
        cb = cb.at[li_l].multiply(lf_l, mode="drop")
        sz = base_sizes * fs_l
        rem = base_rem * fs_l
        sz = sz.at[fi_l].multiply(ff_l, mode="drop")
        rem = rem.at[fi_l].multiply(ff_l, mode="drop")
        pen = base_pen.at[di_l].set(0.0, mode="drop")
        return cb, sz, rem, pen
    return jax.vmap(lane)(bw, fs, li, lf, fi, ff, di)


@functools.partial(jax.jit, static_argnames=("done_rel",))
def _admit_lane_state(base_cb, base_sizes, base_rem, base_pen,
                      bw, fs, li, lf, fi, ff, di,
                      cb, pen, rem, thresh, b, done_eps,
                      done_rel: bool):
    """DEVICE admission of ONE lane into a live fleet: the per-lane
    `_materialize` math (f64 base*global-scale + sparse scatters), the
    threshold derivation and the f64→dtype casts, scattered into row
    ``b`` of the committed fleet state.  Must stay op-for-op identical
    to the constructor materialization so an admitted lane is
    bit-identical to the same scenario in a fresh fleet (and therefore
    to its solo run).  Upload cost is O(overrides) — the payload is
    the same compact record a fleet-birth lane ships."""
    cb64 = base_cb * bw
    cb64 = cb64.at[li].multiply(lf, mode="drop")
    sz64 = base_sizes * fs
    rem64 = base_rem * fs
    sz64 = sz64.at[fi].multiply(ff, mode="drop")
    rem64 = rem64.at[fi].multiply(ff, mode="drop")
    pen64 = base_pen.at[di].set(0.0, mode="drop")
    if done_rel:
        th64 = done_eps * sz64
    else:
        th64 = jnp.full_like(sz64, done_eps)
    dt = cb.dtype
    return (cb.at[b].set(cb64.astype(dt)),
            pen.at[b].set(pen64.astype(dt)),
            rem.at[b].set(rem64.astype(dt)),
            thresh.at[b].set(th64.astype(dt)))


@jax.jit
def _admit_lane_tape(tape_t, tape_slot, tape_val, tpos,
                     row_t, row_s, row_v, b):
    """Scatter one admitted lane's fault tape row (inf-padded to the
    fleet's tape width) and reset its cursor to 0 — the admitted lane
    starts at its own k=0 with a fresh tape slot."""
    return (tape_t.at[b].set(row_t),
            tape_slot.at[b].set(row_s),
            tape_val.at[b].set(row_v),
            tpos.at[b].set(jnp.int32(0)))


@jax.jit
def _admit_lane_coll(pred, ready, clk, row_p, row_r, b):
    """Reset one admitted lane's collective-DAG walk state to the
    schedule's birth state (fresh predecessor counts and activation
    dates, Kahan clock pair back to zero) — the lane replays the whole
    shared schedule from its own t=0."""
    return (pred.at[b].set(row_p),
            ready.at[b].set(row_r),
            clk.at[b].set(jnp.zeros(2, jnp.float64)))


@jax.jit
def _admit_lane_ew(base_ew2, ew_fleet, ei, ewv, b):
    """Re-materialize one lane's element-weight row from the shared
    base table + the admitted spec's indexed payload (scatter-SET, pad
    slots drop) — clears whatever the lane's previous occupant had."""
    lane = base_ew2.reshape(-1).at[ei].set(
        ewv, mode="drop").reshape(base_ew2.shape)
    return ew_fleet.at[b].set(lane)


# ---------------------------------------------------------------------------
# Batched kernel programs (vmapped solo programs + alive-mask gating)
# ---------------------------------------------------------------------------

def _batch_superstep_program(e_var, e_cnst, e_w, c_bound, v_bound,
                             pen, rem, thresh, ids, alive, k,
                             round_budget, zero_bits,
                             tape_t, tape_slot, tape_val, tape_pos,
                             coll_pred, coll_ready, coll_clk,
                             edge_src, edge_dst, exec_cost, t0,
                             eps: float, n_c: int, n_v: int,
                             k_max: int, group: int,
                             has_bounds: bool = False,
                             batch_w: bool = False,
                             has_tape: bool = False,
                             has_coll: bool = False):
    """One fleet superstep: the solo superstep program vmapped over the
    replica axis.  A dead lane (alive=False) gets k=0, so its outer
    while_loop cond is false on entry and the vmap batching rule
    freezes its state — finished/diverged replicas cost nothing but
    masked lanes, and their state is returned unchanged bit-for-bit.

    With ``has_tape`` each lane additionally carries its own fault
    event tape ([B, T] dates/slots/values, inf-padded), tape cursor and
    f64 base clock — sharded shard-local like every other [B, ·]
    payload, so a lane's fires never cross device boundaries.

    With ``has_coll`` each lane carries its own collective-DAG state
    (predecessor counts [B, n_v], pending-activation dates [B, n_v],
    the Kahan clock pair [B, 2]) while the schedule STRUCTURE
    (edge_src / edge_dst / exec_cost) is shared across the fleet like
    the platform — rank-count/algorithm sweeps batch scenarios that
    differ only in per-lane overrides."""
    k = jnp.asarray(k, jnp.int32)

    def lane(cb, pen_l, rem_l, th_l, alive_l, tt_l, ts_l, tv_l, tp_l,
             cp_l, cr_l, ck_l, t0_l, ew_l):
        k_l = jnp.where(alive_l, k, jnp.int32(0))
        return _superstep_program(
            e_var, e_cnst, ew_l, cb, v_bound, pen_l, rem_l, th_l, ids,
            k_l, jnp.asarray(round_budget, jnp.int32), jnp.int32(0),
            zero_bits, tt_l, ts_l, tv_l, tp_l,
            cp_l, cr_l, ck_l, edge_src, edge_dst, exec_cost, t0_l,
            eps=eps, n_c=n_c, n_v=n_v, k_max=k_max,
            group=group, has_bounds=has_bounds, has_tape=has_tape,
            has_coll=has_coll)

    return jax.vmap(lane,
                    in_axes=(0,) * 13 + (0 if batch_w else None,))(
        c_bound, pen, rem, thresh, alive, tape_t, tape_slot, tape_val,
        tape_pos, coll_pred, coll_ready, coll_clk, t0, e_w)


_BATCH_SUPERSTEP_STATICS = ("eps", "n_c", "n_v", "k_max", "group",
                            "has_bounds", "batch_w", "has_tape",
                            "has_coll")

_batch_superstep = functools.partial(
    jax.jit,
    static_argnames=_BATCH_SUPERSTEP_STATICS)(_batch_superstep_program)

#: the donating twin (see ops.lmm_drain._drain_superstep_donate):
#: committed-state fleet dispatches reuse the [B, n_v] (pen, rem)
#: buffers in place.  Dispatched under its own plan-cache kind
#: ("superstep_donate") so AOT artifacts never alias the non-donating
#: executable, and NEVER under a watchdog — a retried dispatch would
#: replay over inputs the first attempt already consumed.
_batch_superstep_donate = functools.partial(
    jax.jit, static_argnames=_BATCH_SUPERSTEP_STATICS,
    donate_argnames=("pen", "rem"))(_batch_superstep_program)


def _batch_fused_lane(e_var, e_cnst, ew_l, cb, v_bound, pen_l, rem_l,
                      th_l, carry_l, act, zero_bits, eps, n_c, n_v,
                      chunk, has_bounds):
    pen2, rem2, carry2, stats = _fused_step_program(
        e_var, e_cnst, ew_l, cb, v_bound, pen_l, rem_l, th_l, carry_l,
        zero_bits, eps=eps, n_c=n_c, n_v=n_v, chunk=chunk,
        has_bounds=has_bounds)
    sel = lambda a, b: jnp.where(act, a, b)  # noqa: E731
    if carry_l is None:
        carry_out = carry2
    else:
        carry_out = tuple(sel(n, o) for n, o in zip(carry2, carry_l))
    return (sel(pen2, pen_l), sel(rem2, rem_l), carry_out,
            jnp.where(act, stats, jnp.zeros_like(stats)))


@functools.partial(jax.jit,
                   static_argnames=("eps", "n_c", "n_v", "chunk",
                                    "has_bounds", "batch_w"))
def _batch_fused_fresh(e_var, e_cnst, e_w, c_bound, v_bound, pen, rem,
                       thresh, active, zero_bits, eps: float, n_c: int,
                       n_v: int, chunk: int, has_bounds: bool = False,
                       batch_w: bool = False):
    """Fleet fused solve+advance, fresh fixpoint start.  Inactive lanes
    still trace through the math but every output is frozen to the
    input state, so only `active` replicas advance."""
    def lane(cb, pen_l, rem_l, th_l, act, ew_l):
        return _batch_fused_lane(e_var, e_cnst, ew_l, cb, v_bound,
                                 pen_l, rem_l, th_l, None, act,
                                 zero_bits, eps, n_c, n_v, chunk,
                                 has_bounds)
    return jax.vmap(lane, in_axes=(0, 0, 0, 0, 0,
                                   0 if batch_w else None))(
        c_bound, pen, rem, thresh, active, e_w)


@functools.partial(jax.jit,
                   static_argnames=("eps", "n_c", "n_v", "chunk",
                                    "has_bounds", "batch_w"))
def _batch_fused_cont(e_var, e_cnst, e_w, c_bound, v_bound, pen, rem,
                      thresh, carry, active, zero_bits, eps: float,
                      n_c: int, n_v: int, chunk: int,
                      has_bounds: bool = False, batch_w: bool = False):
    """Continuation flavor: resume per-replica fixpoint carries (rare —
    only when a solve needs more than one chunk of rounds)."""
    def lane(cb, pen_l, rem_l, th_l, carry_l, act, ew_l):
        return _batch_fused_lane(e_var, e_cnst, ew_l, cb, v_bound,
                                 pen_l, rem_l, th_l, carry_l, act,
                                 zero_bits, eps, n_c, n_v, chunk,
                                 has_bounds)
    return jax.vmap(lane, in_axes=(0, 0, 0, 0, 0, 0,
                                   0 if batch_w else None))(
        c_bound, pen, rem, thresh, carry, active, e_w)


# ---------------------------------------------------------------------------
# Batched flattened solve (no drain): B rate queries, one program
# ---------------------------------------------------------------------------

def solve_arrays_batch(e_var, e_cnst, e_w, c_bound, c_fatpipe,
                       v_penalty, v_bound, eps: float,
                       parallel_rounds: bool = True,
                       chunk: int = 4096, device=None, mesh=None):
    """Solve B independent max-min systems sharing one COO structure in
    lockstep chunks; returns (values [B,V], remaining [B,C],
    usage [B,C], rounds [B]).

    ``e_w`` may be [E] (shared weights) or [B,E]; ``c_bound``,
    ``v_penalty``, ``v_bound`` are [B,·].  Convergence is checked once
    per chunk for the WHOLE fleet in a single [B, 3+V+2C] fetch;
    converged lanes are frozen by their own loop cond, so stragglers
    never recompute finished replicas.

    ``mesh`` (int device count or a ("batch",) jax Mesh) shards the
    replica axis across devices: shared structure replicated,
    per-replica arrays split into per-device blocks.  A ragged B is
    padded with penalty-0 lanes (they converge in zero rounds) and the
    padding is trimmed from every output, so results are bit-identical
    to the unsharded call."""
    mesh = _as_mesh(mesh)
    e_w = np.asarray(e_w)
    batch_w = e_w.ndim == 2
    dtype = e_w.dtype
    c_bound = np.asarray(c_bound, dtype)
    v_penalty = np.asarray(v_penalty, dtype)
    v_bound = np.asarray(v_bound, dtype)
    B = c_bound.shape[0]
    n_shards = int(np.prod(list(mesh.shape.values()))) if mesh else 1
    pad = (-B) % n_shards
    if pad:
        # dead padding lanes: penalty 0 everywhere, so usage0 is 0,
        # the light set starts empty and the lane converges instantly
        c_bound = np.concatenate([c_bound, c_bound[-1:].repeat(pad, 0)])
        v_penalty = np.concatenate(
            [v_penalty, np.zeros((pad,) + v_penalty.shape[1:], dtype)])
        v_bound = np.concatenate(
            [v_bound, np.full((pad,) + v_bound.shape[1:], -1.0, dtype)])
        if batch_w:
            e_w = np.concatenate([e_w, e_w[-1:].repeat(pad, 0)])
    n_c, n_v = c_bound.shape[1], v_penalty.shape[1]
    c_fatpipe = np.asarray(c_fatpipe, bool)
    has_bounds = bool(np.any((v_bound > 0) & (v_penalty > 0)))
    has_fatpipe = bool(c_fatpipe.any())
    eps_f = float(eps)

    if mesh is not None:
        bspec = NamedSharding(mesh, P(BATCH_AXIS))
        rspec = NamedSharding(mesh, P())
        put_shared = lambda a: jax.device_put(np.asarray(a), rspec)  # noqa: E731
        put_batched = lambda a: jax.device_put(np.asarray(a), bspec)  # noqa: E731
        opstats.bump("shards", n_shards)
    else:
        put_shared = put_batched = \
            lambda a: jax.device_put(np.asarray(a), device)  # noqa: E731
    shared = [put_shared(a) for a in (e_var, e_cnst)]
    fat = put_shared(c_fatpipe)
    batched = [put_batched(e_w) if batch_w else put_shared(e_w)]
    batched += [put_batched(a) for a in (c_bound, v_penalty, v_bound)]
    shared_bytes = (sum(np.asarray(a).nbytes for a in (e_var, e_cnst))
                    + c_fatpipe.nbytes
                    + (0 if batch_w else e_w.nbytes))
    batched_bytes = (sum(a.nbytes for a in (c_bound, v_penalty, v_bound))
                     + (e_w.nbytes if batch_w else 0))
    opstats.bump("uploaded_bytes_full", shared_bytes + batched_bytes)
    if mesh is not None:
        opstats.bump("replicated_upload_bytes", shared_bytes * n_shards)
        opstats.bump("sharded_upload_bytes", batched_bytes)

    carry = None
    prev_progress = None
    while True:
        if carry is None:
            out = _solve_kernel_chunk_batched_fresh(
                shared[0], shared[1], batched[0], batched[1], fat,
                batched[2], batched[3], eps=eps_f, n_c=n_c, n_v=n_v,
                parallel_rounds=parallel_rounds, chunk=chunk,
                has_bounds=has_bounds, has_fatpipe=has_fatpipe,
                batch_w=batch_w)
        else:
            out = _solve_kernel_chunk_batched(
                shared[0], shared[1], batched[0], batched[1], fat,
                batched[2], batched[3], carry, eps=eps_f, n_c=n_c,
                n_v=n_v, parallel_rounds=parallel_rounds, chunk=chunk,
                has_bounds=has_bounds, has_fatpipe=has_fatpipe,
                batch_w=batch_w)
        values, remaining, usage, rounds, carry = out
        opstats.bump("dispatches")
        opstats.bump("batch_dispatches")
        rdt = values.dtype
        fetched = np.asarray(jnp.concatenate([
            jnp.stack([rounds.astype(rdt),
                       jnp.count_nonzero(carry[4], axis=1).astype(rdt),
                       jnp.count_nonzero(carry[1], axis=1).astype(rdt)],
                      axis=1),
            values, remaining.astype(rdt), usage.astype(rdt)], axis=1))
        rounds_h = fetched[:, 0].astype(np.int64)
        n_light = fetched[:, 1].astype(np.int64)
        n_fixed = fetched[:, 2].astype(np.int64)
        if not n_light.any():
            values = fetched[:, 3:3 + n_v]
            remaining = fetched[:, 3 + n_v:3 + n_v + n_c]
            usage = fetched[:, 3 + n_v + n_c:3 + n_v + 2 * n_c]
            break
        if (rounds_h >= _MAX_ROUNDS).any():
            bad = int(np.argmax(rounds_h >= _MAX_ROUNDS))
            raise RuntimeError(
                f"LMM batch solve: replica {bad} did not converge "
                f"within {_MAX_ROUNDS} saturation rounds "
                f"({n_c} constraints, {n_v} variables, batch {B})")
        progress = (n_light.tobytes(), n_fixed.tobytes())
        if progress == prev_progress:
            bad = int(np.argmax(n_light > 0))
            raise RuntimeError(
                f"LMM batch solve stalled: replica {bad} made no "
                f"progress over {chunk} rounds ({int(n_light[bad])} "
                f"active constraints); the system does not converge "
                f"at eps={eps} in {np.dtype(dtype).name} precision")
        prev_progress = progress
    opstats.bump("fixpoint_rounds", int(rounds_h.sum()))
    if pad:
        values, remaining, usage, rounds_h = (
            values[:B], remaining[:B], usage[:B], rounds_h[:B])
    return values, remaining, usage, rounds_h


# ---------------------------------------------------------------------------
# The batched drain executor
# ---------------------------------------------------------------------------

class FleetToken:
    """One issued (possibly in-flight) fleet superstep: the batched
    mirror of ops.lmm_drain.SuperstepToken, carrying the [B, ·] flow
    state in/out plus the alive mask the dispatch ran under.  jax
    arrays are immutable, so the token is a free double-buffered
    snapshot; discarding an un-collected token is O(1)."""

    __slots__ = ("pen_in", "rem_in", "pen_out", "rem_out", "packed",
                 "k", "alive", "speculative",
                 "cb_in", "cb_out", "tpos_out", "t0_in", "t0_out",
                 "pred_out", "ready_out", "clk_out")

    def __init__(self, pen_in, rem_in, pen_out, rem_out, packed,
                 k: int, alive, speculative: bool,
                 cb_in=None, cb_out=None, tpos_out=None,
                 t0_in=None, t0_out=None,
                 pred_out=None, ready_out=None, clk_out=None):
        self.pen_in = pen_in
        self.rem_in = rem_in
        self.pen_out = pen_out
        self.rem_out = rem_out
        self.packed = packed
        self.k = k
        self.alive = alive
        self.speculative = speculative
        # fault-tape double buffers (see SuperstepToken): per-lane
        # bounds in/out, post-dispatch tape cursors, and the [B] f64
        # base clocks this dispatch started from / left behind
        self.cb_in = cb_in
        self.cb_out = cb_out
        self.tpos_out = tpos_out
        self.t0_in = t0_in
        self.t0_out = t0_out
        # collective-tape double buffers (see SuperstepToken)
        self.pred_out = pred_out
        self.ready_out = ready_out
        self.clk_out = clk_out


class ReplicaState:
    """Host-side record of one replica in a fleet."""

    __slots__ = ("index", "events", "fault_events",
                 "collective_events", "t", "advances",
                 "alive", "error", "fault")

    def __init__(self, index: int):
        self.index = index
        self.events: List[Tuple[float, int]] = []
        #: (time, constraint slot) per fired tape entry, fire order
        self.fault_events: List[Tuple[float, int]] = []
        #: (time, flow id) per fired collective activation, fire order
        self.collective_events: List[Tuple[float, int]] = []
        self.t = 0.0              # f64 master clock (host-accumulated)
        self.advances = 0
        self.alive = True
        self.error: Optional[str] = None
        #: why the lane was quarantined (None for clean completion)
        self.fault: Optional[LaneFault] = None


class BatchDrainSim:
    """Drain B scenario replicas of ONE shared platform flattening to
    completion in lockstep batched device programs.

    Constructor arguments mirror ops.lmm_drain.DrainSim — COO elements,
    constraint capacities, flow sizes — plus ``overrides``: one
    :class:`ReplicaOverrides` per replica, materialized on device from
    compact payloads (upload cost O(total overrides), not O(B*system)).

    Per-replica state is (c_bound, penalties, remaining, thresholds)
    with the batch axis leading; the structure tables and (by default)
    the element weights are shared and uploaded once.  Replicas whose
    overrides carry ``elem_w`` entries get per-replica weight tables
    materialized ON DEVICE from the indexed payload (upload bytes ~
    overridden slots, not B×E).  Finished or
    diverged replicas go dark via the alive mask instead of forcing
    ragged shapes; the fleet repacks NEVER (lockstep shapes), so each
    lane's reduction order — and therefore its event order and clock —
    is bit-identical to a solo no-repack DrainSim of the same scenario.

    ``pipeline=D`` keeps up to D speculative fleet supersteps in
    flight beyond the one being collected (see ops.lmm_drain): the
    host demultiplexes ring N's [B, ·] fetch — a serial Python walk
    over every lane — while the device already executes fleet
    superstep N+1.  Any alive-mask change or budget rescue while
    processing ring N discards the in-flight tokens; results are
    bit-identical to ``pipeline=0``.

    ``mesh=M`` (int device count or a ("batch",) jax Mesh) shards the
    replica axis across M devices: every [B, ·] array — payloads,
    materialized state, alive mask, completion rings — is placed under
    ``NamedSharding(mesh, P("batch"))`` while the shared flattening is
    replicated.  One fleet superstep is still ONE logical dispatch and
    one FleetToken; the ring comes back as one fetch per shard,
    reassembled in replica order before the demux, so events and
    clocks are bit-identical to ``mesh=None``.  When B is ragged the
    fleet is padded with dead lanes (see module docstring); padded
    lanes are asserted to produce zero events.
    """

    def __init__(self, e_var, e_cnst, e_w, c_bound, sizes,
                 overrides: List[ReplicaOverrides],
                 eps: float = 1e-5, done_eps: float = 1e-4,
                 dtype=np.float64, done_mode: str = "rel",
                 superstep: int = 8, superstep_rounds: int = 0,
                 device=None, v_bound=None, penalty=None, remains=None,
                 pipeline: int = 0, mesh=None, tapes=None,
                 plan=None, tape_slots: int = 0, start_dead=(),
                 batch_w: Optional[bool] = None, watchdog=None,
                 collective=None):
        if not overrides:
            raise ValueError("BatchDrainSim needs at least one replica")
        if done_mode not in ("rel", "abs"):
            raise ValueError(f"Unknown done_mode {done_mode!r} "
                             "(expected rel or abs)")
        #: serving.plancache.CompiledPlan routing the fleet's jitted
        #: programs through AOT-compiled executables (None = plain jit)
        self._plan = plan
        #: DispatchWatchdog wrapping every device dispatch/fetch in
        #: wall-clock accounting + seeded-backoff retries (None = raw)
        self._watchdog = watchdog
        self.eps = float(eps)
        self.done_eps = float(done_eps)
        self.done_mode = done_mode
        self.dtype = np.dtype(dtype)
        self.device = device
        self._mesh = _as_mesh(mesh)
        self.n_shards = (int(np.prod(list(self._mesh.shape.values())))
                         if self._mesh is not None else 1)
        if self._mesh is not None:
            self._bspec = NamedSharding(self._mesh, P(BATCH_AXIS))
            self._rspec = NamedSharding(self._mesh, P())
            opstats.bump("shards", self.n_shards)
        self.B = len(overrides)
        self.overrides = list(overrides)
        # ragged-fleet guard: pad to a multiple of the shard count with
        # lanes that are dead from birth (neutral overrides, alive
        # False) — the vmap batching rule freezes them at k=0 and the
        # collect asserts they never log an event
        self.B_padded = self.B + (-self.B) % self.n_shards
        overrides = (list(overrides)
                     + [ReplicaOverrides()
                        for _ in range(self.B_padded - self.B)])
        self.n_c = len(c_bound)
        self.n_v = len(sizes)
        self.superstep_k = int(superstep)
        if self.superstep_k <= 0:
            raise ValueError("BatchDrainSim is superstep-only "
                             "(superstep >= 1)")
        if not superstep_rounds:
            platform = (device.platform if device is not None
                        else jax.devices()[0].platform)
            # same per-dispatch round-budget reasoning as the solo
            # DrainSim: the watchdog bound is per KERNEL, and a vmapped
            # lane runs the same per-advance round count as solo
            superstep_rounds = (self.superstep_k * 512
                                if platform == "cpu" else 64 * 4)
        self.superstep_rounds = int(superstep_rounds)

        # shared base (f64 masters for materialization + dtype tables)
        self._base_cb = np.asarray(c_bound, np.float64)
        self._base_sizes = np.asarray(sizes, np.float64)
        self._base_rem = (np.asarray(remains, np.float64)
                          if remains is not None else self._base_sizes)
        self._base_pen = (np.asarray(penalty, np.float64)
                          if penalty is not None
                          else np.ones(self.n_v, np.float64))
        ev2 = _to2d(np.asarray(e_var, np.int32))
        ec2 = _to2d(np.asarray(e_cnst, np.int32))
        ew2 = _to2d(np.asarray(e_w, self.dtype))
        # per-replica element weights ride an INDEXED payload and are
        # materialized on device below — the shared 2D table is still
        # uploaded exactly once whatever B is.  ``batch_w=True`` forces
        # the per-replica tables even when no INITIAL lane overrides
        # weights, so mid-flight admissions may bring elem_w specs.
        self.batch_w = (any(ov.elem_w for ov in overrides)
                        if batch_w is None else bool(batch_w))
        ew_payload = (_pack_elem_w(overrides, ew2.size, self.dtype)
                      if self.batch_w else None)
        if v_bound is not None:
            vb = np.asarray(v_bound, self.dtype)
            self.has_bounds = bool(np.any(vb > 0))
        else:
            vb = np.full(self.n_v, -1.0, self.dtype)
            self.has_bounds = False

        ew_dev = self._put_shared(ew2)
        # base (pre-materialize) weight table + pad index, kept for
        # per-lane re-materialization on admission
        self._base_ew_dev = ew_dev
        self._ew_pad_idx = int(ew2.size)
        if self.batch_w:
            ei_dev, ewv_dev = [self._put_batched(a)
                               for a in ew_payload]
            opstats.bump("uploaded_bytes_delta",
                         sum(a.nbytes for a in ew_payload))
            ew_dev = self._call_plan(
                "materialize_ew", _materialize_ew,
                (self._base_ew_dev, ei_dev, ewv_dev), {})
            opstats.bump("dispatches")
            opstats.bump("batch_dispatches")
            ew_dev = self._pin(ew_dev)
        self._dev = [self._put_shared(ev2),
                     self._put_shared(ec2), ew_dev]
        self._vb = self._put_shared(vb)
        ids = np.arange(self.n_v, dtype=np.int32)
        self._ids_dev = self._put_shared(ids)
        base_dev = [self._put_shared(a) for a in
                    (self._base_cb, self._base_sizes, self._base_rem,
                     self._base_pen)]
        payload = _pack_overrides(overrides, self.n_c, self.n_v)
        payload_dev = [self._put_batched(a) for a in payload]
        opstats.bump("uploaded_bytes_full",
                     ev2.nbytes + ec2.nbytes + ew2.nbytes + vb.nbytes
                     + ids.nbytes
                     + sum(a.nbytes for a in (self._base_cb,
                                              self._base_sizes,
                                              self._base_rem,
                                              self._base_pen)))
        opstats.bump("uploaded_bytes_delta",
                     sum(a.nbytes for a in payload))

        # one materialization dispatch derives the whole fleet's f64
        # state on device; the dtype cast below mirrors DrainSim's
        # host-side casts exactly (f64 math first, cast second)
        self._base_dev = base_dev
        cb64, sz64, rem64, pen64 = self._call_plan(
            "materialize", _materialize,
            (*base_dev, *payload_dev), {})
        opstats.bump("dispatches")
        opstats.bump("batch_dispatches")
        if done_mode == "rel":
            thresh64 = self.done_eps * sz64
        else:
            thresh64 = jnp.full_like(sz64, self.done_eps)
        self._cb = self._pin(cb64.astype(self.dtype))
        self._pen = self._pin(pen64.astype(self.dtype))
        self._rem = self._pin(rem64.astype(self.dtype))
        self._thresh = self._pin(thresh64.astype(self.dtype))

        # per-replica fault event tapes: `tapes` is one (dates, slots,
        # values) triple — or None — per replica (see DrainSim's tape=;
        # identical semantics per lane).  Packed to [B_padded, T] with
        # inf date padding (a padded entry can never fire) and sharded
        # shard-local like every other per-replica payload.
        self.has_tape = False
        self._last_fired = False
        self._tape_width = 0
        if tapes is not None and any(
                t is not None and len(t[0]) for t in tapes):
            need = max(len(t[0]) for t in tapes if t is not None)
        else:
            need = 0
            tapes = None
        # `tape_slots` reserves ring capacity for tapes that arrive
        # later via admit_lane; only then is the width bucketed to a
        # power of two, so admissions and warm restarts hit stable
        # compiled shapes (inf-padded entries never fire —
        # bit-identity is unaffected).  A fleet whose tapes are all
        # known at build keeps the exact width: no padding overhead on
        # the plain batched path.
        reserving = int(tape_slots) > 0
        need = max(need, int(tape_slots))
        if need:
            if tapes is None:
                tapes = [None] * self.B
            if len(tapes) != self.B:
                raise ValueError(f"tapes must have one entry per "
                                 f"replica ({len(tapes)} != {self.B})")
            tapes = list(tapes) + [None] * (self.B_padded - self.B)
            T = _pow2_bucket(need) if reserving else need
            self._tape_width = T
            tt = np.full((self.B_padded, T), np.inf, np.float64)
            ts = np.full((self.B_padded, T), self.n_c, np.int32)
            tv = np.zeros((self.B_padded, T), np.float64)
            n_slots = 0
            for b, t in enumerate(tapes):
                if t is None or not len(t[0]):
                    continue
                dates = np.asarray(t[0], np.float64)
                slots = np.asarray(t[1], np.int32)
                vals = np.asarray(t[2], np.float64)
                if not (len(dates) == len(slots) == len(vals)):
                    raise ValueError(
                        f"replica {b}: tape arrays must have equal "
                        f"length")
                if np.any(np.diff(dates) < 0):
                    raise ValueError(
                        f"replica {b}: tape dates must be time-sorted")
                if np.any((slots < 0) | (slots >= self.n_c)):
                    raise ValueError(f"replica {b}: tape slot out of "
                                     f"range")
                n = len(dates)
                tt[b, :n] = dates
                ts[b, :n] = slots
                tv[b, :n] = vals
                n_slots += n
            # same f64 -> dtype cast order as the solo DrainSim tape
            tvd = tv.astype(self.dtype)
            self.has_tape = True
            self._tape = (self._put_batched(tt), self._put_batched(ts),
                          self._put_batched(tvd))
            opstats.bump("fault_tape_slots", n_slots)
            opstats.bump("uploaded_bytes_delta",
                         tt.nbytes + ts.nbytes + tvd.nbytes)
        else:
            # dummy [B, 1] triple keeps the jit call sites uniform;
            # DCE'd when has_tape=False
            self._tape = (
                self._put_batched(np.full((self.B_padded, 1), np.inf)),
                self._put_batched(np.full((self.B_padded, 1), self.n_c,
                                          np.int32)),
                self._put_batched(np.zeros((self.B_padded, 1),
                                           self.dtype)))
        self._tpos = self._put_batched(
            np.zeros(self.B_padded, np.int32))

        # collective schedule tape: ONE compiled comm DAG (pred, ready,
        # edge_src, edge_dst, exec_cost — see DrainSim's collective=)
        # shared across the fleet.  The schedule STRUCTURE (edges,
        # exec costs) is platform-like and replicated; the walk STATE
        # (predecessor counts, pending-activation dates, the carried
        # Kahan clock pair) is per-lane, so lanes differing only in
        # overrides sweep the same collective independently.
        self.has_coll = False
        if collective is not None:
            cp, cr, ces, ced, cec = collective
            cp = np.asarray(cp, np.int32)
            cr = np.asarray(cr, np.float64)
            ces = np.asarray(ces, np.int32)
            ced = np.asarray(ced, np.int32)
            cec = np.asarray(cec, np.float64)
            if not (len(cp) == len(cr) == len(cec) == self.n_v):
                raise ValueError("collective arrays must be per-flow "
                                 f"(n_v={self.n_v})")
            if len(ces) != len(ced):
                raise ValueError("collective edge arrays must have "
                                 "equal length")
            if self.dtype != np.float64:
                raise ValueError("collective= needs dtype=float64 "
                                 "(see DrainSim)")
            if any(ov.dead_flows for ov in self.overrides):
                raise ValueError("collective fleets cannot kill DAG "
                                 "flows via dead_flows overrides")
            self.has_coll = True
            self._coll_base = (cp, cr)
            self._coll_edges = tuple(self._put_shared(a)
                                     for a in (ces, ced, cec))
            self._coll_pred = self._put_batched(
                np.broadcast_to(cp, (self.B_padded, self.n_v)).copy())
            self._coll_ready = self._put_batched(
                np.broadcast_to(cr, (self.B_padded, self.n_v)).copy())
            self._coll_clk = self._put_batched(
                np.zeros((self.B_padded, 2), np.float64))
            opstats.bump("collective_tape_slots", self.n_v * self.B)
            opstats.bump("uploaded_bytes_delta",
                         cp.nbytes * self.B_padded
                         + cr.nbytes * self.B_padded
                         + ces.nbytes + ced.nbytes + cec.nbytes
                         + 16 * self.B_padded)
        else:
            self._coll_edges = (
                self._put_shared(np.zeros(1, np.int32)),
                self._put_shared(np.zeros(1, np.int32)),
                self._put_shared(np.zeros(1, np.float64)))
            self._coll_pred = self._put_batched(
                np.zeros((self.B_padded, 1), np.int32))
            self._coll_ready = self._put_batched(
                np.full((self.B_padded, 1), np.inf))
            self._coll_clk = self._put_batched(
                np.zeros((self.B_padded, 2), np.float64))

        self.replicas = [ReplicaState(b) for b in range(self.B)]
        self._alive = np.zeros(self.B_padded, bool)
        self._alive[:self.B] = True
        # serving fleets are built wider than their initial spec list:
        # `start_dead` lanes are dead at birth (k=0, state frozen) and
        # wait for admit_lane to revive them mid-flight
        for b in start_dead:
            self._alive[int(b)] = False
            self.replicas[int(b)].alive = False
        self.admitted = 0
        self.pad_events = 0
        self.rescues = 0
        self.supersteps = 0
        self.syncs = 0
        self.rounds = 0
        self.pipeline = int(pipeline)
        # speculation census (pipelined fleet driver)
        self.spec_issued = 0
        self.spec_committed = 0
        self.spec_rolled_back = 0
        opstats.bump("batch_replicas", self.B)

    # -- device placement (single-device or replica-sharded) ---------------

    def _put_shared(self, a):
        """Upload one fleet-shared array: replicated onto every mesh
        device (counted per device copy — a pod really ships M copies)
        or plain device_put when unsharded."""
        if self._mesh is not None:
            opstats.bump("replicated_upload_bytes",
                         a.nbytes * self.n_shards)
            return jax.device_put(a, self._rspec)
        return jax.device_put(a, self.device)

    def _put_batched(self, a):
        """Upload one [B, ·] per-replica array split over the batch
        axis: every byte lands on exactly one device."""
        if self._mesh is not None:
            opstats.bump("sharded_upload_bytes", a.nbytes)
            return jax.device_put(a, self._bspec)
        return jax.device_put(a, self.device)

    def _pin(self, arr):
        """Re-commit a device-resident [B, ·] result to the batch
        sharding (device-side reshard, no host bytes; GSPMD usually
        already chose this layout and the put is a no-op)."""
        if self._mesh is not None:
            return jax.device_put(arr, self._bspec)
        return arr

    def _put_mask(self, m: np.ndarray):
        if self._mesh is not None:
            return jax.device_put(m, self._bspec)
        return jnp.asarray(m)

    def _call_plan(self, kind: str, fn, args, statics):
        """Dispatch one fleet program: through the AOT plan cache when
        the fleet carries a CompiledPlan (warm restarts reuse
        serialized executables, zero traces), else the plain jit.
        With a watchdog every dispatch runs under its wall-clock guard
        (seeded backoff + bounded retries); dispatches are pure
        functions of committed device state, so a retry is safe."""
        if self._plan is not None:
            issue = lambda: self._plan.call(kind, fn, args, statics)
        else:
            issue = lambda: fn(*args, **statics)
        if self._watchdog is not None:
            return self._watchdog.guard(issue, what=f"dispatch:{kind}")
        return issue()

    # -- fleet stepping ----------------------------------------------------

    def _fetch(self, packed) -> np.ndarray:
        self.syncs += 1
        if self._watchdog is not None:
            # the ring fetch is the sync point where a wedged device
            # program actually surfaces — time it, but do NOT retry on
            # failure (the buffer is gone; the superstep must replay)
            return self._watchdog.timed(
                lambda: self._fetch_raw(packed), what="fetch")
        return self._fetch_raw(packed)

    def _fetch_raw(self, packed) -> np.ndarray:
        if self._mesh is None:
            return opstats.timed_fetch(packed)
        # per-shard ring demux: each device's [B/M, ·] block comes back
        # as its own transfer (counted in demux_fetches) and the blocks
        # are reassembled in replica order, so the host walk below
        # commits events in the same deterministic order as mesh=None.
        # Dedupe by block start: a compiler-replicated output shows the
        # same rows on every device.
        parts = {}
        for sh in packed.addressable_shards:
            start = sh.index[0].start or 0
            if start not in parts:
                parts[start] = sh.data
        fetched = [opstats.timed_fetch(parts[s]) for s in sorted(parts)]
        opstats.bump("demux_fetches", len(fetched))
        return np.concatenate(fetched, axis=0)

    def _superstep_issue_all(self, k: Optional[int] = None, pen=None,
                             rem=None, speculative: bool = False,
                             alive=None, cb=None, tpos=None, t0=None,
                             round_budget: int = 0,
                             pred=None, ready=None,
                             clk=None,
                             donate: bool = False) -> "FleetToken":
        """Dispatch ONE fleet superstep without touching the committed
        state: chains from `(pen, rem)` (default: committed) under the
        CURRENT alive mask (or an explicit `alive` restriction — the
        tape-aware rescue); inputs/outputs ride the returned token
        (see ops.lmm_drain — same issue/collect speculation protocol,
        one [B, ·] ring per token).  With a fault tape the dispatch
        chains per-lane bounds/cursors (`cb`, `tpos`) and [B] f64 base
        clocks `t0` (default: the committed replica clocks)."""
        k_max = self.superstep_k
        k = k_max if k is None else min(int(k), k_max)
        budget = int(round_budget) or self.superstep_rounds
        group = _pos_group(self.n_v)
        alive = (self._alive.copy() if alive is None
                 else np.asarray(alive, bool).copy())
        pen_in = self._pen if pen is None else pen
        rem_in = self._rem if rem is None else rem
        cb_in = self._cb if cb is None else cb
        tpos_in = self._tpos if tpos is None else tpos
        if t0 is None:
            # the committed host clocks ARE the lanes' f64 base clocks
            # (padded lanes never advance, 0.0 is fine)
            t0_in = np.zeros(self.B_padded, np.float64)
            for b, rep in enumerate(self.replicas):
                t0_in[b] = rep.t
            t0_in = self._put_batched(t0_in)
        else:
            t0_in = t0
        pred_in = self._coll_pred if pred is None else pred
        ready_in = self._coll_ready if ready is None else ready
        clk_in = self._coll_clk if clk is None else clk
        # donation gate: only non-speculative dispatches chained from
        # the COMMITTED state may consume their inputs, and never
        # under a watchdog (its retry would replay over buffers the
        # first attempt already consumed — dispatches stop being pure)
        donate = (donate and not speculative
                  and pen is None and rem is None
                  and self._watchdog is None)
        kind, fn = (("superstep_donate", _batch_superstep_donate)
                    if donate else ("superstep", _batch_superstep))
        (pen_out, rem_out, cb_out, tpos_out, pred_out, ready_out,
         clk_out, packed) = self._call_plan(
            kind, fn,
            (*self._dev, cb_in, self._vb, pen_in, rem_in,
             self._thresh, self._ids_dev,
             self._put_mask(alive), np.int32(k),
             np.int32(budget), _ZERO_BITS,
             *self._tape, tpos_in,
             pred_in, ready_in, clk_in, *self._coll_edges, t0_in),
            dict(eps=self.eps, n_c=self.n_c, n_v=self.n_v, k_max=k_max,
                 group=group, has_bounds=self.has_bounds,
                 batch_w=self.batch_w, has_tape=self.has_tape,
                 has_coll=self.has_coll))
        if donate:
            # the committed buffers are gone: adopt the outputs NOW
            # (collect re-adopts them, a no-op) and strip the dead
            # inputs from the token so misuse fails loudly
            self._pen, self._rem = pen_out, rem_out
            pen_in = rem_in = None
            opstats.bump("donated_buffers", 2)
        t0_out = None
        if self.has_tape:
            # derive the post-dispatch base clocks DEVICE-side with the
            # exact f64 add the host collect performs (rep.t = t0 +
            # t_sum), so a chained speculative issue is bit-identical
            # to a fresh issue from the committed clocks
            t0_out = t0_in + packed[:, 3].astype(jnp.float64)
        self.supersteps += 1
        opstats.bump("dispatches")
        opstats.bump("batch_dispatches")
        if speculative:
            self.spec_issued += 1
            opstats.bump("speculations_issued")
        return FleetToken(pen_in, rem_in, pen_out, rem_out, packed,
                          k, alive, speculative,
                          cb_in=cb_in, cb_out=cb_out, tpos_out=tpos_out,
                          t0_in=t0_in, t0_out=t0_out,
                          pred_out=pred_out, ready_out=ready_out,
                          clk_out=clk_out)

    def _discard_token(self, tok: "FleetToken") -> None:
        """Drop an un-collected speculative fleet superstep (the alive
        mask changed or a rescue ran while processing the preceding
        ring): issue never committed anything, so rollback is O(1)."""
        self.spec_rolled_back += 1
        opstats.bump("speculations_rolled_back")

    def _stall_cause(self, b: int, n_live: int) -> Tuple[str, str]:
        """Attribute a fatal stall honestly: the superstep kernel's
        masked arithmetic surfaces a NaN-poisoned scenario (NaN
        capacity/size/penalty) as "no flow holds bandwidth" rather
        than a NaN clock, so on this already-fatal path we pay one
        extra fetch of the lane's committed arrays and classify NaN
        state as ``nan_solve`` instead of ``stall``."""
        for name, arr in (("remaining work", self._rem),
                          ("penalties", self._pen),
                          ("capacities", self._cb)):
            if np.isnan(np.asarray(arr[b])).any():
                return ("nan_solve",
                        f"drain solve consumed non-finite lane state "
                        f"(NaN in {name})")
        return ("stall",
                f"drain stalled: no flow holds bandwidth "
                f"({n_live} live)")

    def _quarantine(self, b: int, cause: str, detail: str) -> None:
        """Kill exactly lane ``b`` with a structured cause: the lane
        goes dark via the alive mask (like any death — every other
        lane's vmapped math is untouched, so their streams stay
        bit-identical to solo) and the replica record carries a
        :class:`LaneFault` for the serving layer to surface on the
        ticket."""
        rep = self.replicas[b]
        rep.error = detail
        rep.fault = LaneFault(cause, detail, b,
                              superstep=self.supersteps, t=rep.t)
        rep.alive = False
        self._alive[b] = False
        opstats.bump("lane_quarantined_" + cause)

    def _superstep_collect_all(self, tok: "FleetToken",
                               rescue: bool = False
                               ) -> Tuple[int, bool]:
        """Commit one issued fleet superstep: adopt its output arrays,
        fetch its [B, ·] packed rings (ONE transfer) and demultiplex
        per-replica events/clocks on the host.  Returns
        ``(n_alive, clean)`` — clean False when processing this ring
        mutated the fleet (a lane died, a tape event fired, or a
        rescue ran), so in-flight speculative successors must be
        discarded.  With ``rescue=True`` (the tape-aware rescue's own
        collect — the dispatch already ran with the FULL round budget)
        still-stuck lanes are converted to non-convergence deaths
        instead of re-rescued."""
        self._pen, self._rem = tok.pen_out, tok.rem_out
        if self.has_tape:
            self._cb = tok.cb_out
            self._tpos = tok.tpos_out
        if self.has_coll:
            self._coll_pred = tok.pred_out
            self._coll_ready = tok.ready_out
            self._coll_clk = tok.clk_out
        k_max = self.superstep_k
        p = self._fetch(tok.packed)
        n_v = self.n_v
        ring_n = (n_v + (k_max if self.has_tape else 0)
                  + (n_v if self.has_coll else 0))
        o = 7
        stuck: List[int] = []
        deaths = 0
        fired = 0
        coll_fired = 0
        for b in range(self.B):
            if not tok.alive[b]:
                continue
            rep = self.replicas[b]
            row = p[b]
            rounds, adv, n_ev = int(row[0]), int(row[1]), int(row[2])
            t_sum = float(row[3])
            n_live, flag = int(row[4]), int(row[5])
            ring_t = row[o + 2 * k_max:o + 2 * k_max + ring_n]
            ring_id = row[o + 2 * k_max + ring_n:
                          o + 2 * k_max + 2 * ring_n].astype(np.int64)
            self.rounds += rounds
            opstats.bump("fixpoint_rounds", rounds)
            if np.isnan(t_sum):
                # a poisoned scenario (e.g. NaN link capacity) turns
                # the lane's whole advance into NaN — quarantine it
                # BEFORE the ring demux so its garbage events never
                # reach the committed stream; the vmapped lane math is
                # per-lane, so no other lane saw the NaN
                self._quarantine(
                    b, "nan_solve",
                    "drain solve produced a non-finite clock advance "
                    "(NaN)")
                deaths += 1
                continue
            if n_ev > ring_n:
                # defensive: a ring claiming more events than it has
                # slots would walk the demux off the row and corrupt
                # neighbouring lanes' streams
                self._quarantine(
                    b, "ring_overflow",
                    f"completion ring overflow: {n_ev} events for "
                    f"{ring_n} slots")
                deaths += 1
                continue
            rep.advances += adv
            # collective lanes carry ABSOLUTE ring dates/clocks (the
            # Kahan pair chains on device across dispatches)
            t_base = 0.0 if self.has_coll else rep.t
            if self.has_tape or self.has_coll:
                # demux: negative ids are tagged — fault fires
                # (idx < n_c, fault stream) or collective activations
                # (idx >= n_c, activation stream) — see DrainSim
                for j in range(n_ev):
                    fid = int(ring_id[j])
                    tj = t_base + float(ring_t[j])
                    if fid < 0:
                        idx = -fid - 1
                        if idx >= self.n_c:
                            rep.collective_events.append(
                                (tj, idx - self.n_c))
                            coll_fired += 1
                        else:
                            rep.fault_events.append((tj, idx))
                            fired += 1
                    else:
                        rep.events.append((tj, fid))
            else:
                for j in range(n_ev):
                    rep.events.append((t_base + float(ring_t[j]),
                                       int(ring_id[j])))
            rep.t = t_base + t_sum
            coll_pending = (self.has_coll
                            and len(rep.events) < self.n_v)
            if flag == _FLAG_STALLED:
                self._quarantine(b, *self._stall_cause(b, n_live))
                deaths += 1
            elif n_live == 0 and not coll_pending:
                rep.alive = False
                self._alive[b] = False
                deaths += 1
            elif n_live == 0 and coll_pending and adv == 0:
                # no live flow, no progress, schedule still owes
                # completions: a cyclic/truncated DAG would spin the
                # fleet forever — kill exactly this lane
                self._quarantine(
                    b, "collective_deadlock",
                    f"collective schedule deadlocked: "
                    f"{len(rep.events)}/{self.n_v} flows completed "
                    f"and nothing is pending")
                deaths += 1
            elif flag == _FLAG_BUDGET and adv == 0:
                if rescue:
                    self._quarantine(b, "non_convergence",
                                     "drain solve did not converge")
                    deaths += 1
                else:
                    stuck.append(b)
        self._last_fired = fired > 0
        if fired:
            opstats.bump("fault_tape_events", fired)
        if coll_fired:
            opstats.bump("collective_tape_fires", coll_fired)
        if self.B_padded != self.B:
            # ragged-fleet guard: padded lanes are dead from birth
            # (k=0, state frozen), so any event they log would be a
            # sharding/vmap bug silently corrupting the fleet
            pad_ev = int(p[self.B:, 2].sum())
            self.pad_events += pad_ev
            if pad_ev:
                raise RuntimeError(
                    f"ragged-fleet guard: {self.B_padded - self.B} "
                    f"padded dead lane(s) logged {pad_ev} completion "
                    f"event(s) — the frozen-lane invariant is broken")
        if stuck:
            # the round budget expired inside a replica's FIRST solve:
            # finish exactly one advance for those lanes.  Tape- or
            # collective-armed fleets must stay on the superstep path
            # (the fused rescue is tape-blind and would step over
            # events); otherwise the chunked fused program (converges
            # across dispatches), the batched mirror of the solo run()
            # rescue.
            if self.has_tape or self.has_coll:
                self._rescue_superstep(stuck)
            else:
                self._rescue_fused(stuck)
        if tok.speculative:
            self.spec_committed += 1
            opstats.bump("speculations_committed")
        clean = not deaths and not stuck and not fired
        return int(self._alive.sum()), clean

    def superstep_all(self, k: Optional[int] = None) -> int:
        """ONE batched superstep dispatch for every live replica and
        ONE [B, ·] fetch; commits per-replica events and clocks.
        Returns the number of still-live replicas."""
        n_alive, _clean = self._superstep_collect_all(
            self._superstep_issue_all(k, donate=True))
        return n_alive

    # -- mid-flight lane admission (serving) -------------------------------

    def admit_lane(self, b: int, overrides: ReplicaOverrides,
                   tape=None) -> None:
        """Revive dead lane ``b`` with a NEW scenario, between
        supersteps: the lane's state row is re-materialized ON DEVICE
        from the admitted spec's compact payload (O(overrides) upload,
        the same lane math as fleet birth, so the admitted lane is
        bit-identical to a solo run of the same spec), its tape slot is
        replaced and its cursor reset, and its host replica record
        starts fresh at k=0, t=0.  Raises :class:`AdmissionError` when
        the fleet's birth-time capacity cannot absorb the scenario
        (lane alive/out of range, elem_w into a shared-weight fleet,
        tape wider than the reserved slots).

        The caller must treat a fired admission as a fleet MUTATION:
        in-flight speculative supersteps assumed the old alive mask and
        state, so they must be discarded (``run(between=...)`` does
        this automatically when the hook returns truthy)."""
        b = int(b)
        if not 0 <= b < self.B:
            raise AdmissionError(
                f"lane {b} out of range (fleet width {self.B})")
        if self._alive[b]:
            raise AdmissionError(f"lane {b} is still alive")
        ov = overrides
        if ov.elem_w and not self.batch_w:
            raise AdmissionError(
                "fleet shares one element-weight table (batch_w "
                "False); a spec with elem_w overrides needs a fleet "
                "built with batch_w=True")
        if tape is not None and not len(tape[0]):
            tape = None
        if tape is not None:
            if not self.has_tape:
                raise AdmissionError(
                    "fleet has no tape capacity (built without tapes "
                    "or tape_slots); a faulted spec cannot be "
                    "admitted")
            if len(tape[0]) > self._tape_width:
                raise AdmissionError(
                    f"tape with {len(tape[0])} entries exceeds the "
                    f"fleet's reserved tape width {self._tape_width}")
        # compact single-lane payload, widths bucketed to powers of two
        # so repeat admissions reuse a handful of compiled shapes
        sl = _pow2_bucket(len(ov.link_scale))
        sf = _pow2_bucket(len(ov.flow_scale))
        sd = _pow2_bucket(len(ov.dead_flows))
        li = np.full(sl, self.n_c, np.int32)
        lf = np.ones(sl, np.float64)
        fi = np.full(sf, self.n_v, np.int32)
        ff = np.ones(sf, np.float64)
        di = np.full(sd, self.n_v, np.int32)
        for j, slot in enumerate(sorted(ov.link_scale)):
            li[j] = slot
            lf[j] = ov.link_scale[slot]
        for j, slot in enumerate(sorted(ov.flow_scale)):
            fi[j] = slot
            ff[j] = ov.flow_scale[slot]
        for j, slot in enumerate(ov.dead_flows):
            di[j] = slot
        opstats.bump("uploaded_bytes_delta",
                     li.nbytes + lf.nbytes + fi.nbytes + ff.nbytes
                     + di.nbytes)
        cb, pen, rem, thresh = self._call_plan(
            "admit_state", _admit_lane_state,
            (*self._base_dev, np.float64(ov.bw_scale),
             np.float64(ov.size_scale), li, lf, fi, ff, di,
             self._cb, self._pen, self._rem, self._thresh,
             np.int32(b), np.float64(self.done_eps)),
            dict(done_rel=self.done_mode == "rel"))
        self._cb = self._pin(cb)
        self._pen = self._pin(pen)
        self._rem = self._pin(rem)
        self._thresh = self._pin(thresh)
        opstats.bump("dispatches")
        opstats.bump("batch_dispatches")
        if self.has_tape:
            # always rewrite the lane's tape row — the previous
            # occupant may have left unfired entries behind
            T = self._tape_width
            row_t = np.full(T, np.inf, np.float64)
            row_s = np.full(T, self.n_c, np.int32)
            row_v = np.zeros(T, np.float64)
            if tape is not None:
                dates = np.asarray(tape[0], np.float64)
                slots = np.asarray(tape[1], np.int32)
                vals = np.asarray(tape[2], np.float64)
                if not (len(dates) == len(slots) == len(vals)):
                    raise AdmissionError(
                        "tape arrays must have equal length")
                if np.any(np.diff(dates) < 0):
                    raise AdmissionError(
                        "tape dates must be time-sorted")
                if np.any((slots < 0) | (slots >= self.n_c)):
                    raise AdmissionError("tape slot out of range")
                n = len(dates)
                row_t[:n] = dates
                row_s[:n] = slots
                row_v[:n] = vals
                opstats.bump("fault_tape_slots", n)
            # same f64 -> dtype cast order as fleet birth
            row_vd = row_v.astype(self.dtype)
            tt, ts, tv, tpos = self._call_plan(
                "admit_tape", _admit_lane_tape,
                (*self._tape, self._tpos, row_t, row_s, row_vd,
                 np.int32(b)), {})
            self._tape = (self._pin(tt), self._pin(ts), self._pin(tv))
            self._tpos = self._pin(tpos)
            opstats.bump("uploaded_bytes_delta",
                         row_t.nbytes + row_s.nbytes + row_vd.nbytes)
            opstats.bump("dispatches")
            opstats.bump("batch_dispatches")
        if self.has_coll:
            # the admitted lane replays the fleet's shared schedule
            # from its own t=0: fresh DAG walk state, zeroed clock
            if ov.dead_flows:
                raise AdmissionError(
                    "collective fleets cannot kill DAG flows via "
                    "dead_flows overrides")
            cp, cr = self._coll_base
            pred, ready, clk = self._call_plan(
                "admit_coll", _admit_lane_coll,
                (self._coll_pred, self._coll_ready, self._coll_clk,
                 cp, cr, np.int32(b)), {})
            self._coll_pred = self._pin(pred)
            self._coll_ready = self._pin(ready)
            self._coll_clk = self._pin(clk)
            opstats.bump("collective_tape_slots", self.n_v)
            opstats.bump("uploaded_bytes_delta",
                         cp.nbytes + cr.nbytes + 16)
            opstats.bump("dispatches")
            opstats.bump("batch_dispatches")
        if self.batch_w:
            # re-materialize the lane's weight row from the shared base
            # + this spec's indexed payload (clears the previous lane)
            se = _pow2_bucket(len(ov.elem_w))
            ei = np.full(se, self._ew_pad_idx, np.int32)
            ewv = np.zeros(se, self.dtype)
            for j, slot in enumerate(sorted(ov.elem_w)):
                ei[j] = slot
                ewv[j] = ov.elem_w[slot]
            new_ew = self._call_plan(
                "admit_ew", _admit_lane_ew,
                (self._base_ew_dev, self._dev[2], ei, ewv,
                 np.int32(b)), {})
            self._dev[2] = self._pin(new_ew)
            opstats.bump("uploaded_bytes_delta",
                         ei.nbytes + ewv.nbytes)
            opstats.bump("dispatches")
            opstats.bump("batch_dispatches")
        self.overrides[b] = ov
        self.replicas[b] = ReplicaState(b)
        self._alive[b] = True
        self.admitted += 1
        opstats.bump("lanes_admitted")
        opstats.bump("batch_replicas")

    def _rescue_fused(self, stuck: List[int]) -> None:
        self.rescues += 1
        active = np.zeros(self.B_padded, bool)
        active[stuck] = True
        chunk = 16 if self._dev[0].size >= 1 << 20 else 64
        carry = None
        k_live = 4 + self.n_v
        while True:
            if carry is None:
                self._pen, self._rem, carry, stats = _batch_fused_fresh(
                    *self._dev, self._cb, self._vb, self._pen,
                    self._rem, self._thresh, self._put_mask(active),
                    _ZERO_BITS, eps=self.eps, n_c=self.n_c,
                    n_v=self.n_v, chunk=chunk,
                    has_bounds=self.has_bounds, batch_w=self.batch_w)
            else:
                self._pen, self._rem, carry, stats = _batch_fused_cont(
                    *self._dev, self._cb, self._vb, self._pen,
                    self._rem, self._thresh, carry,
                    self._put_mask(active), _ZERO_BITS, eps=self.eps,
                    n_c=self.n_c, n_v=self.n_v, chunk=chunk,
                    has_bounds=self.has_bounds, batch_w=self.batch_w)
            opstats.bump("dispatches")
            opstats.bump("batch_dispatches")
            st = self._fetch(stats)[:, :k_live]
            for b in list(stuck):
                if not active[b]:
                    continue
                # rounds (st[b,0]) is the lane's TOTAL fixpoint
                # iteration count across chunks — count it once, at
                # commit/error time, like the solo _advance_fused
                rounds, n_light = int(st[b, 0]), int(st[b, 1])
                if n_light:
                    if rounds >= _MAX_ROUNDS:
                        self._quarantine(b, "non_convergence",
                                         "drain solve did not converge")
                        active[b] = False
                        self.rounds += rounds
                        opstats.bump("fixpoint_rounds", rounds)
                    continue
                self.rounds += rounds
                opstats.bump("fixpoint_rounds", rounds)
                rep = self.replicas[b]
                dt, n_live = float(st[b, 2]), int(st[b, 3])
                done = st[b, 4:] > 0
                if np.isnan(dt):
                    self._quarantine(
                        b, "nan_solve",
                        "drain solve produced a non-finite clock "
                        "advance (NaN)")
                    active[b] = False
                    continue
                if not np.isfinite(dt):
                    self._quarantine(b, *self._stall_cause(b, n_live))
                    active[b] = False
                    continue
                rep.t += dt
                rep.advances += 1
                for fid in np.flatnonzero(done):
                    rep.events.append((rep.t, int(fid)))
                if n_live == 0:
                    rep.alive = False
                    self._alive[b] = False
                active[b] = False
            if not active.any():
                break
        self._pen = self._pin(self._pen)
        self._rem = self._pin(self._rem)

    def _rescue_superstep(self, stuck: List[int]) -> None:
        """The tape-aware budget rescue: re-dispatch the stuck lanes
        only (restricted alive mask — every other lane runs k=0 and is
        frozen bit-for-bit) for ONE advance with the FULL round budget.
        Collecting with rescue=True converts lanes that still cannot
        converge into non-convergence deaths, the fleet mirror of the
        solo tape rescue raising "did not converge"."""
        self.rescues += 1
        restricted = np.zeros(self.B_padded, bool)
        restricted[stuck] = True
        tok = self._superstep_issue_all(k=1, alive=restricted,
                                        round_budget=_MAX_ROUNDS,
                                        donate=True)
        self._superstep_collect_all(tok, rescue=True)

    def _run_pipelined(self, max_supersteps: int,
                       between=None) -> None:
        """The speculative fleet driver: up to ``self.pipeline``
        supersteps in flight beyond the one being collected, FIFO
        collects, discard-on-mutation — the fleet mirror of
        ops.lmm_drain.DrainSim._run_pipelined.  The host's serial
        per-lane ring demux overlaps the device's next vmapped
        superstep; a lane death or budget rescue discards the
        speculative tail (their dispatches assumed a stale alive
        mask)."""
        from collections import deque
        inflight: deque = deque()
        left = max_supersteps
        try:
            while self._alive.any() and left > 0:
                while (not inflight
                       or (len(inflight) <= self.pipeline
                           and len(inflight) < left)):
                    spec = bool(inflight)
                    if inflight:
                        prev = inflight[-1]
                        pen, rem = prev.pen_out, prev.rem_out
                        cb, tpos, t0 = (
                            (prev.cb_out, prev.tpos_out, prev.t0_out)
                            if self.has_tape else (None, None, None))
                        pred, ready, clk = (
                            (prev.pred_out, prev.ready_out,
                             prev.clk_out)
                            if self.has_coll else (None, None, None))
                    else:
                        pen = rem = cb = tpos = t0 = None
                        pred = ready = clk = None
                    inflight.append(self._superstep_issue_all(
                        pen=pen, rem=rem, speculative=spec,
                        cb=cb, tpos=tpos, t0=t0,
                        pred=pred, ready=ready, clk=clk,
                        donate=not spec))
                tok = inflight.popleft()
                _n_alive, clean = self._superstep_collect_all(tok)
                left -= 1
                # the between-supersteps hook (serving admission): a
                # truthy return means the hook MUTATED the fleet
                # (admitted a lane), which forces clean=False — the
                # in-flight speculation assumed the old alive mask and
                # state, so it is discarded and replayed
                mutated = bool(between(self)) if between else False
                if not clean or mutated:
                    # a lane death/rescue invalidated the in-flight
                    # alive masks, or a tape fire ended the clean
                    # window — discard and replay from committed state
                    if self.has_tape and self._last_fired and inflight:
                        opstats.bump("fault_replays", len(inflight))
                    if self.has_coll and inflight:
                        opstats.bump("collective_replays",
                                     len(inflight))
                    while inflight:
                        self._discard_token(inflight.popleft())
        finally:
            while inflight:
                self._discard_token(inflight.popleft())

    def run(self, max_supersteps: int = 10_000_000,
            between=None) -> None:
        """Drain every replica to completion (or error).  ``between``
        is called after every committed superstep with the sim as its
        argument (the serving layer's admission window: emit completed
        lanes, admit queued scenarios via :meth:`admit_lane`); a truthy
        return marks the fleet mutated, discarding any in-flight
        speculative supersteps.  The drain continues while the hook
        revives lanes and returns once every lane is dead and the hook
        admits nothing more."""
        if self.pipeline:
            self._run_pipelined(max_supersteps, between=between)
            return
        while self._alive.any() and max_supersteps > 0:
            self.superstep_all()
            if between is not None:
                between(self)
            max_supersteps -= 1

    # -- superstep-boundary checkpoint/resume ------------------------------

    def committed_state(self) -> Dict:
        """Snapshot the fleet's COMMITTED state at a collect boundary:
        the materialized per-lane device arrays (bounds, penalties,
        remaining, thresholds, tape rows + cursors, per-replica weight
        tables), the alive mask, the f64 host clocks and advance
        counts, the committed event/fault-event prefixes (ragged-
        flattened, f64/i64 exact) and the per-lane error/LaneFault
        records.  In-flight pipeline speculation is NEVER part of the
        snapshot — speculative tokens carry their state on their own
        buffers and commit nothing until collected — so a checkpoint
        between supersteps is exactly the state resume replays from
        (the same replay semantics as a mispredict discard)."""
        reps = self.replicas
        arrays = {
            "cb": np.asarray(self._cb),
            "pen": np.asarray(self._pen),
            "rem": np.asarray(self._rem),
            "thresh": np.asarray(self._thresh),
            "alive": self._alive.copy(),
            "tpos": np.asarray(self._tpos),
            "clocks": np.array([r.t for r in reps], np.float64),
            "advances": np.array([r.advances for r in reps],
                                 np.int64),
            "ev_counts": np.array([len(r.events) for r in reps],
                                  np.int64),
            "ev_t": np.array([t for r in reps
                              for t, _ in r.events], np.float64),
            "ev_id": np.array([i for r in reps
                               for _, i in r.events], np.int64),
            "fev_counts": np.array(
                [len(r.fault_events) for r in reps], np.int64),
            "fev_t": np.array([t for r in reps
                               for t, _ in r.fault_events],
                              np.float64),
            "fev_slot": np.array([s for r in reps
                                  for _, s in r.fault_events],
                                 np.int64),
        }
        if self.has_tape:
            tt, ts, tv = self._tape
            arrays["tape_t"] = np.asarray(tt)
            arrays["tape_s"] = np.asarray(ts)
            arrays["tape_v"] = np.asarray(tv)
        if self.has_coll:
            arrays["coll_pred"] = np.asarray(self._coll_pred)
            arrays["coll_ready"] = np.asarray(self._coll_ready)
            arrays["coll_clk"] = np.asarray(self._coll_clk)
            arrays["cev_counts"] = np.array(
                [len(r.collective_events) for r in reps], np.int64)
            arrays["cev_t"] = np.array(
                [t for r in reps for t, _ in r.collective_events],
                np.float64)
            arrays["cev_id"] = np.array(
                [i for r in reps for _, i in r.collective_events],
                np.int64)
        if self.batch_w:
            arrays["ew"] = np.asarray(self._dev[2])
        return {
            "arrays": arrays,
            "errors": [r.error for r in reps],
            "faults": [r.fault.to_dict() if r.fault is not None
                       else None for r in reps],
            "counters": {
                "admitted": self.admitted,
                "supersteps": self.supersteps,
                "syncs": self.syncs,
                "rounds": self.rounds,
                "rescues": self.rescues,
                "pad_events": self.pad_events,
                "spec_issued": self.spec_issued,
                "spec_committed": self.spec_committed,
                "spec_rolled_back": self.spec_rolled_back,
            },
        }

    def restore_state(self, st: Dict) -> None:
        """Adopt a :meth:`committed_state` snapshot into THIS fleet
        (built from the same plan/geometry): uploads the saved device
        arrays, rebuilds every host replica record — committed events,
        fault streams, clocks, errors, LaneFaults — and restores the
        alive mask and counters.  Raises ``ValueError`` on any
        geometry mismatch (a snapshot from a different plan)."""
        arrays = st["arrays"]
        B, Bp = self.B, self.B_padded

        def _chk(name, dtype, shape):
            if name not in arrays:
                raise ValueError(
                    f"fleet snapshot is missing array {name!r}")
            a = np.asarray(arrays[name])
            if tuple(a.shape) != tuple(shape):
                raise ValueError(
                    f"fleet snapshot array {name!r} has shape "
                    f"{a.shape}, this fleet expects {tuple(shape)} — "
                    f"the snapshot is from a different plan")
            return np.ascontiguousarray(a, dtype)

        cb = _chk("cb", self.dtype, (Bp, self.n_c))
        pen = _chk("pen", self.dtype, (Bp, self.n_v))
        rem = _chk("rem", self.dtype, (Bp, self.n_v))
        thresh = _chk("thresh", self.dtype, (Bp, self.n_v))
        alive = _chk("alive", bool, (Bp,))
        tpos = _chk("tpos", np.int32, (Bp,))
        clocks = _chk("clocks", np.float64, (B,))
        advances = _chk("advances", np.int64, (B,))
        ev_counts = _chk("ev_counts", np.int64, (B,))
        fev_counts = _chk("fev_counts", np.int64, (B,))
        ev_t = _chk("ev_t", np.float64, (int(ev_counts.sum()),))
        ev_id = _chk("ev_id", np.int64, (int(ev_counts.sum()),))
        fev_t = _chk("fev_t", np.float64, (int(fev_counts.sum()),))
        fev_slot = _chk("fev_slot", np.int64,
                        (int(fev_counts.sum()),))
        if "tape_t" in arrays:
            if not self.has_tape:
                raise ValueError(
                    "fleet snapshot carries fault tapes but this "
                    "fleet was built without tape capacity (pass "
                    "tape_slots at build)")
            T = self._tape_width
            tt = _chk("tape_t", np.float64, (Bp, T))
            ts = _chk("tape_s", np.int32, (Bp, T))
            tv = _chk("tape_v", self.dtype, (Bp, T))
            self._tape = (self._put_batched(tt),
                          self._put_batched(ts),
                          self._put_batched(tv))
        cev_counts = None
        if "coll_pred" in arrays:
            if not self.has_coll:
                raise ValueError(
                    "fleet snapshot carries a collective schedule "
                    "but this fleet was built without collective=")
            cp = _chk("coll_pred", np.int32, (Bp, self.n_v))
            crd = _chk("coll_ready", np.float64, (Bp, self.n_v))
            ck = _chk("coll_clk", np.float64, (Bp, 2))
            cev_counts = _chk("cev_counts", np.int64, (B,))
            cev_t = _chk("cev_t", np.float64,
                         (int(cev_counts.sum()),))
            cev_id = _chk("cev_id", np.int64,
                          (int(cev_counts.sum()),))
            self._coll_pred = self._put_batched(cp)
            self._coll_ready = self._put_batched(crd)
            self._coll_clk = self._put_batched(ck)
        elif self.has_coll:
            raise ValueError(
                "this fleet carries a collective schedule but the "
                "snapshot has no collective arrays — it is from a "
                "different plan")
        if "ew" in arrays:
            if not self.batch_w:
                raise ValueError(
                    "fleet snapshot carries per-replica weight "
                    "tables but this fleet was built with a shared "
                    "table (pass batch_w=True at build)")
            ew = _chk("ew", self.dtype, tuple(self._dev[2].shape))
            self._dev[2] = self._put_batched(ew)
        self._cb = self._put_batched(cb)
        self._pen = self._put_batched(pen)
        self._rem = self._put_batched(rem)
        self._thresh = self._put_batched(thresh)
        self._tpos = self._put_batched(tpos)
        errors = st.get("errors") or [None] * B
        faults = st.get("faults") or [None] * B
        eo = fo = co = 0
        for b in range(B):
            rep = ReplicaState(b)
            n_e, n_f = int(ev_counts[b]), int(fev_counts[b])
            rep.events = [(float(ev_t[eo + j]), int(ev_id[eo + j]))
                          for j in range(n_e)]
            rep.fault_events = [(float(fev_t[fo + j]),
                                 int(fev_slot[fo + j]))
                                for j in range(n_f)]
            eo += n_e
            fo += n_f
            if cev_counts is not None:
                n_cv = int(cev_counts[b])
                rep.collective_events = [
                    (float(cev_t[co + j]), int(cev_id[co + j]))
                    for j in range(n_cv)]
                co += n_cv
            rep.t = float(clocks[b])
            rep.advances = int(advances[b])
            rep.alive = bool(alive[b])
            rep.error = errors[b]
            rep.fault = (LaneFault.from_dict(faults[b])
                         if faults[b] else None)
            self.replicas[b] = rep
        self._alive = alive.copy()
        c = st.get("counters") or {}
        self.admitted = int(c.get("admitted", 0))
        self.supersteps = int(c.get("supersteps", 0))
        self.syncs = int(c.get("syncs", 0))
        self.rounds = int(c.get("rounds", 0))
        self.rescues = int(c.get("rescues", 0))
        self.pad_events = int(c.get("pad_events", 0))
        self.spec_issued = int(c.get("spec_issued", 0))
        self.spec_committed = int(c.get("spec_committed", 0))
        self.spec_rolled_back = int(c.get("spec_rolled_back", 0))

    # -- results -----------------------------------------------------------

    def events_of(self, b: int) -> List[Tuple[float, int]]:
        return self.replicas[b].events

    def clock_of(self, b: int) -> float:
        return self.replicas[b].t
