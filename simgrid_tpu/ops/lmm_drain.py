"""Device-resident flow-drain executor: the LMM_TPU batch mode.

The north-star benchmark (BASELINE config #4) is a pure *drain*: a large
set of concurrent flows, posted up front, that only ever complete —
exactly the structure of an SMPI alltoall's network phase, where every
rank has posted all sends/receives and the maestro's loop degenerates to

    while flows remain:
        solve rates -> next completion time -> advance -> retire flows

(reference: surf_solve + Model::update_actions_state,
src/kernel/resource/Model.cpp:40-101).  The reference executes that loop
one C++ step at a time; this executor keeps ALL solver and flow state
device-resident across advances and runs each advance as two dispatches
(solve chunks + an advance step), so the per-advance host traffic is two
~70 ms tunnel round-trips instead of re-uploading the system.

Python bookkeeping is O(completed flows) per advance (recording events),
not O(system).  When the live flow population halves, the element list
is repacked host-side (one re-upload) so per-round device cost tracks
the live system — the cross-advance analogue of lmm/chain's in-solve
compaction.
"""

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

import jax
import jax.numpy as jnp

from .lmm_jax import _MAX_ROUNDS, fixpoint


def _to2d(a: np.ndarray, group: int = 8) -> np.ndarray:
    """Element arrays keep a 2D shape end-to-end: the axon backend
    lowers flat-1D-index gathers/scatters ~7x slower than 2D ones."""
    n = len(a)
    if n % group:
        pad = group - n % group
        fill = np.zeros(pad, a.dtype)
        a = np.concatenate([a, fill])
    return a.reshape(-1, group)


@functools.partial(jax.jit,
                   static_argnames=("eps", "n_c", "n_v", "chunk"))
def _drain_solve_chunk(e_var, e_cnst, e_w, c_bound, v_penalty, carry,
                       eps: float, n_c: int, n_v: int, chunk: int):
    dtype = e_w.dtype
    zeros_bound = jnp.full(n_v, -1.0, dtype)
    out = fixpoint(e_var, e_cnst, e_w, c_bound,
                   jnp.zeros(n_c, bool), v_penalty, zeros_bound,
                   jnp.asarray(eps, dtype), n_c, n_v,
                   parallel_rounds=True, carry=carry, max_rounds=chunk,
                   return_carry=True, has_bounds=False,
                   has_fatpipe=False)
    carry2 = out[4]
    stats = jnp.stack([out[3].astype(dtype),
                       jnp.count_nonzero(carry2[4]).astype(dtype)])
    return carry2, stats


@functools.partial(jax.jit, static_argnames=("done_eps",))
def _drain_advance(v_penalty, rem, values, done_eps: float):
    """One time advance from solved rates: dt to the next completion,
    retire finished flows.  Mirrors Model::update_actions_state (FULL
    mode) with the reference's precision clamp."""
    dtype = rem.dtype
    live = v_penalty > 0
    rate = jnp.where(live, values, 0.0)
    flowing = live & (rate > 0)
    dt_all = jnp.where(flowing, rem / jnp.where(flowing, rate, 1.0),
                       jnp.inf)
    dt = jnp.min(dt_all)
    rem2 = jnp.where(flowing, rem - rate * dt, rem)
    done = flowing & (rem2 <= done_eps)
    pen2 = jnp.where(done, 0.0, v_penalty)
    rem2 = jnp.where(done, 0.0, rem2)
    n_live = jnp.count_nonzero(pen2 > 0)
    head = jnp.stack([dt.astype(dtype), n_live.astype(dtype)])
    return pen2, rem2, jnp.concatenate([head, done.astype(dtype)])


class DrainSim:
    """Drain a fixed flow set to completion on the JAX backend.

    Parameters mirror a flattened network-only LMM system: COO elements
    (e_var, e_cnst, e_w), constraint capacities, per-flow penalties
    (1.0 = live) and sizes (bytes).  `solve_chunk` bounds device rounds
    per dispatch (axon watchdog); `repack_at` triggers a host-side
    element repack when the live fraction drops below it.
    """

    def __init__(self, e_var, e_cnst, e_w, c_bound, sizes,
                 eps: float = 1e-5, done_eps: float = 1e-4,
                 dtype=np.float32, solve_chunk: int = 0,
                 repack_at: float = 0.5, device=None):
        self.eps = float(eps)
        self.done_eps = float(done_eps)
        self.dtype = np.dtype(dtype)
        if not solve_chunk:
            # bound per-dispatch kernel time: big-system rounds cost
            # ~100-150 ms of device time and the axon watchdog kills
            # kernels in the ~10 s range (observed: a 64-round chunk at
            # 1.24M elements hangs the worker)
            solve_chunk = 16 if len(e_var) >= 1 << 20 else 64
        self.solve_chunk = int(solve_chunk)
        self.repack_at = float(repack_at)
        self.device = device

        self._host = dict(
            e_var=np.asarray(e_var, np.int32),
            e_cnst=np.asarray(e_cnst, np.int32),
            e_w=np.asarray(e_w, self.dtype))
        self.n_c = len(c_bound)
        self.n_v = len(sizes)
        self._c_bound = np.asarray(c_bound, self.dtype)
        self._sizes = np.asarray(sizes, np.float64)
        # flow slot -> original flow id (survives repacks)
        self._ids = np.arange(self.n_v)

        self._pen = jax.device_put(np.ones(self.n_v, self.dtype), device)
        self._rem = jax.device_put(self._sizes.astype(self.dtype), device)
        self._dev = [jax.device_put(_to2d(self._host[k]), device)
                     for k in ("e_var", "e_cnst", "e_w")]
        self._cb = jax.device_put(self._c_bound, device)
        self._live0 = self.n_v

        self.t = 0.0
        self.events: list = []   # (time, original flow id), completion order
        self.advances = 0
        self.rounds = 0
        self.syncs = 0
        self.repacks = 0

    def _repack(self) -> None:
        """Drop retired flows' elements and rows (host-side, one
        re-upload).  Live relative order is preserved, so reduction
        order over survivors — and therefore event ordering — is
        unchanged."""
        pen = np.asarray(self._pen)
        rem = np.asarray(self._rem)
        self.syncs += 1
        live = pen > 0
        keep = np.flatnonzero(live)
        old2new = np.full(self.n_v, -1, np.int32)
        old2new[keep] = np.arange(len(keep), dtype=np.int32)
        emask = live[self._host["e_var"]]
        self._host = dict(
            e_var=old2new[self._host["e_var"][emask]],
            e_cnst=self._host["e_cnst"][emask],
            e_w=self._host["e_w"][emask])
        self._ids = self._ids[keep]
        self._sizes = self._sizes[keep]
        self.n_v = len(keep)
        self._pen = jax.device_put(pen[keep], self.device)
        self._rem = jax.device_put(rem[keep], self.device)
        self._dev = [jax.device_put(_to2d(self._host[k]), self.device)
                     for k in ("e_var", "e_cnst", "e_w")]
        self._live0 = self.n_v
        self.repacks += 1

    def advance(self) -> int:
        """One solve + time advance; returns the remaining live count."""
        carry = None
        while True:
            carry, stats = _drain_solve_chunk(
                *self._dev, self._cb, self._pen, carry,
                eps=self.eps, n_c=self.n_c, n_v=self.n_v,
                chunk=self.solve_chunk)
            st = np.asarray(stats)
            self.syncs += 1
            rounds, n_light = int(st[0]), int(st[1])
            if n_light == 0:
                break
            if rounds >= _MAX_ROUNDS:
                raise RuntimeError("drain solve did not converge")
        self.rounds += rounds

        self._pen, self._rem, out = _drain_advance(
            self._pen, self._rem, carry[0], done_eps=self.done_eps)
        out = np.asarray(out)
        self.syncs += 1
        dt, n_live = float(out[0]), int(out[1])
        done = out[2:] > 0
        if not np.isfinite(dt):
            raise RuntimeError(
                f"drain stalled: no flow holds bandwidth "
                f"({n_live} live)")
        self.t += dt
        self.advances += 1
        for fid in self._ids[np.flatnonzero(done)]:
            self.events.append((self.t, int(fid)))
        if n_live and n_live <= self._live0 * self.repack_at \
                and n_live >= 1024:
            self._repack()
        return n_live

    def run(self, max_advances: int = 10_000_000) -> None:
        n = self.n_v
        while n and max_advances:
            n = self.advance()
            max_advances -= 1
