"""Device-resident flow-drain executor: the LMM_TPU batch mode.

The north-star benchmark (BASELINE config #4) is a pure *drain*: a large
set of concurrent flows, posted up front, that only ever complete —
exactly the structure of an SMPI alltoall's network phase, where every
rank has posted all sends/receives and the maestro's loop degenerates to

    while flows remain:
        solve rates -> next completion time -> advance -> retire flows

(reference: surf_solve + Model::update_actions_state,
src/kernel/resource/Model.cpp:40-101).  The reference executes that loop
one C++ step at a time; this executor keeps ALL solver and flow state
device-resident across advances and offers three dispatch shapes:

* **unfused** (legacy): one dispatch for the solve chunks, one for the
  dt/retire step — >= 2 host syncs per advance;
* **fused** (``fused=True``): the fixpoint chunk AND the dt/retire step
  run in ONE jitted dispatch whose single fetch carries the stats and
  the completion mask — 1 sync per advance (each ~70 ms on the tunneled
  accelerator);
* **supersteps** (``superstep=K``): a ``lax.while_loop`` over
  (solve -> dt -> retire) executes up to K advances per dispatch,
  logging completions into a fixed-size device ring buffer
  ``(time, flow_id)`` fetched in ONE transfer — amortized syncs drop to
  ~1/K per advance.  K's round budget is bounded by the axon watchdog
  (same reasoning as lmm_jax._CHUNK_ROUNDS_ACCEL: per-dispatch kernel
  runtime, not math, is what kills a TPU worker).

Completion grouping is RELATIVE by default (``rem2 <= done_eps * size``,
the reference's sg_maxmin_precision/sg_surf_precision semantics,
maxmin.cpp:12-14,470-479): an absolute epsilon under f32 splits the f64
tie groups — flows the f64 backends retire in one advance spread over
many f32 advances, which is the diagnosed round-5 blocker of the TPU
end-to-end drain (bench_results/e2e_drain.jsonl row 3).  A threshold
that scales with flow size keeps accumulated f32 rounding noise
(~size * 1.2e-7 per step) below the retirement cut, so chip-precision
ties coalesce exactly like the f64 oracle's.  ``done_mode="abs"``
restores the absolute rule for f64 engine-fidelity runs.

The simulation clock is accumulated in f64 ON THE HOST (``self.t`` is a
Python float); inside a superstep dispatch the per-advance dt values are
combined with compensated (Kahan) summation in the device dtype, so a
100k-advance f32 drain does not drift event timestamps against the f64
backends: per-superstep error is O(K ulp) instead of compounding across
the whole run.

Python bookkeeping is O(completed flows) per advance (recording events),
not O(system).  When the live flow population halves, the element list
is repacked: host-side (one re-upload) on the unfused/fused paths, and
ON DEVICE on the superstep path — a stable live-first partition (the
same machinery as lmm_jax's compaction chain) dispatched without any
host round-trip, so halving the live set costs one kernel launch
instead of a fetch + re-upload.

The kernel programs (`_solve_chunk_program`, `_fused_step_program`,
`_superstep_program`) double as the LANE bodies of the batched
multi-replica executor (ops.lmm_batch), which vmaps them over a
leading replica axis to drain whole scenario fleets per dispatch —
keep them pure functions of their arguments.

Speculative pipelining (``pipeline=D``): JAX dispatch is ASYNC — only
the completion-ring fetch blocks the host — so the superstep driver
can keep D extra supersteps in flight against double-buffered flow
state: while the host parses ring N (a pure-Python O(events) walk),
superstep N+1 is already executing on the device, and the fetch of
ring N+1 finds its buffer ready instead of eating the full tunnel
round trip.  The dispatch of a superstep is split into an *issue*
(:meth:`DrainSim._superstep_issue` — pure with respect to the sim's
committed flow state; the dispatch inputs/outputs ride a
:class:`SuperstepToken`) and a *collect* (the blocking fetch + host
event commit).  Speculation is validated at collect time: if
processing ring N mutated anything the in-flight dispatch assumed
frozen (a device repack, the stop-for-repack trigger decay, a budget
rescue, a stall, or drain completion), every un-collected token is
DISCARDED — issue never touched the committed state, jax arrays are
immutable, so rollback is O(1) — and the pipeline restarts from the
post-N state, recomputing exactly what the unpipelined driver would
have.  Committed speculative supersteps are bit-identical to the
unpipelined path by construction: the program is a deterministic
function of its inputs and a token commits only when its inputs
turned out to equal the unpipelined path's inputs.
"""

from __future__ import annotations

import functools
from collections import deque
from typing import List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import opstats
from .lmm_jax import (_MAX_ROUNDS, _bucket, _pos_group,
                      _stable_livefirst_perm, fixpoint)


def _to2d(a: np.ndarray, group: int = 8) -> np.ndarray:
    """Element arrays keep a 2D shape end-to-end: the axon backend
    lowers flat-1D-index gathers/scatters ~7x slower than 2D ones."""
    n = len(a)
    if n % group:
        pad = group - n % group
        fill = np.zeros(pad, a.dtype)
        a = np.concatenate([a, fill])
    return a.reshape(-1, group)


# The three kernel *programs* below are defined as plain functions and
# jitted by assignment so the batched executor (ops.lmm_batch) can vmap
# the raw programs over a leading replica axis: one device program then
# solves/advances a whole scenario fleet, amortizing the per-dispatch
# tunnel latency across replicas.  Keep them functional (no global
# state) — both the solo jits and the vmapped jits share them.

def _solve_chunk_program(e_var, e_cnst, e_w, c_bound, v_penalty, v_bound,
                         carry, eps: float, n_c: int, n_v: int, chunk: int,
                         has_bounds: bool = False):
    dtype = e_w.dtype
    out = fixpoint(e_var, e_cnst, e_w, c_bound,
                   jnp.zeros(n_c, bool), v_penalty, v_bound,
                   jnp.asarray(eps, dtype), n_c, n_v,
                   parallel_rounds=True, carry=carry, max_rounds=chunk,
                   return_carry=True, has_bounds=has_bounds,
                   has_fatpipe=False)
    carry2 = out[4]
    stats = jnp.stack([out[3].astype(dtype),
                       jnp.count_nonzero(carry2[4]).astype(dtype)])
    return carry2, stats


_drain_solve_chunk = functools.partial(
    jax.jit, static_argnames=("eps", "n_c", "n_v", "chunk",
                              "has_bounds"))(_solve_chunk_program)


#: the traced runtime zero handed to every advance kernel (see
#: _rounded_product) — an argument, never a constant, so neither XLA's
#: simplifier nor LLVM can fold the integer detour away
_ZERO_BITS = np.int64(0)


def _rounded_product(a, b, zero_bits):
    """a*b rounded to f64 BEFORE the consumer sees it.  XLA:CPU's LLVM
    backend contracts mul+sub chains into FMAs no matter how the HLO is
    structured (selects and optimization_barriers are speculated/erased
    at instruction selection), but the engine's double_update walk
    rounds the product first — so the chained device remains would
    drift a ulp per advance from the host walk.  Routing the product's
    bits through an integer add of `zero_bits` (a TRACED runtime zero
    the compiler cannot constant-fold) pins the standalone rounding."""
    prod = a * b
    itype = jnp.int64 if prod.dtype == jnp.float64 else jnp.int32
    bits = lax.bitcast_convert_type(prod, itype) + zero_bits.astype(itype)
    return lax.bitcast_convert_type(bits, prod.dtype)


def _advance_math(pen, rem, thresh, values, zero_bits=None):
    """The shared dt/retire step: dt to the next completion, relative-
    or absolute-threshold retirement (thresh is a per-flow array, so
    the caller chooses the semantics).  Mirrors
    Model::update_actions_state (FULL mode).

    ``zero_bits`` (a TRACED int zero) routes the rate*dt product
    through `_rounded_product` so the chained remains walk stays
    bit-identical to the host engine — every drain path passes
    `_ZERO_BITS`.  Callers that don't chain remains against the host
    (the rate-level `parallel.sharded` step) may omit it and keep the
    plain product."""
    live = pen > 0
    rate = jnp.where(live, values, 0.0)
    flowing = live & (rate > 0)
    dt = jnp.min(jnp.where(flowing,
                           rem / jnp.where(flowing, rate, 1.0),
                           jnp.inf))
    prod = (rate * dt if zero_bits is None
            else _rounded_product(rate, dt, zero_bits))
    rem2 = jnp.where(flowing, rem - prod, rem)
    # strict <, matching the reference double_update's `value <
    # precision` zeroing (so the absolute mode is bit-compatible with
    # the engine's generic remains bookkeeping)
    done = flowing & (rem2 < thresh)
    pen2 = jnp.where(done, 0.0, pen)
    rem2 = jnp.where(done, 0.0, rem2)
    return dt, pen2, rem2, done


@jax.jit
def _drain_advance(v_penalty, rem, thresh, values, zero_bits):
    """One time advance from solved rates (unfused path)."""
    dtype = rem.dtype
    dt, pen2, rem2, done = _advance_math(v_penalty, rem, thresh, values,
                                         zero_bits)
    n_live = jnp.count_nonzero(pen2 > 0)
    head = jnp.stack([dt.astype(dtype), n_live.astype(dtype)])
    return pen2, rem2, jnp.concatenate([head, done.astype(dtype)])


def _fused_step_program(e_var, e_cnst, e_w, c_bound, v_bound, pen, rem,
                        thresh, carry, zero_bits, eps: float, n_c: int,
                        n_v: int, chunk: int, has_bounds: bool = False):
    """Fused solve+advance: run up to `chunk` more saturation rounds
    and — if the fixpoint converged inside this dispatch — the dt/retire
    step too, all in ONE dispatch whose single fetch returns
    [rounds, n_light, dt, n_live] + the completion mask.  When the
    solve needs more rounds the flow state is returned unchanged and
    the caller re-dispatches with the carry (rare: local-rounds drains
    converge in O(10) rounds)."""
    opstats.bump("retraces")      # trace-time only; see _superstep
    dtype = e_w.dtype
    out = fixpoint(e_var, e_cnst, e_w, c_bound,
                   jnp.zeros(n_c, bool), pen, v_bound,
                   jnp.asarray(eps, dtype), n_c, n_v,
                   parallel_rounds=True, carry=carry, max_rounds=chunk,
                   return_carry=True, has_bounds=has_bounds,
                   has_fatpipe=False)
    carry2 = out[4]
    n_light = jnp.count_nonzero(carry2[4])
    converged = n_light == 0
    dt, pen2, rem2, done = _advance_math(pen, rem, thresh, carry2[0],
                                         zero_bits)
    ok = converged & jnp.isfinite(dt)
    pen_out = jnp.where(ok, pen2, pen)
    rem_out = jnp.where(ok, rem2, rem)
    done = done & ok
    n_live = jnp.count_nonzero(pen_out > 0)
    head = jnp.stack([out[3].astype(dtype), n_light.astype(dtype),
                      dt.astype(dtype), n_live.astype(dtype)])
    return pen_out, rem_out, carry2, \
        jnp.concatenate([head, done.astype(dtype)])


_drain_fused_step = functools.partial(
    jax.jit, static_argnames=("eps", "n_c", "n_v", "chunk",
                              "has_bounds"))(_fused_step_program)


#: superstep completion flags (stats slot 5)
_FLAG_OK = 0          # exited on k / live-count / natural completion
_FLAG_STALLED = 1     # no flow holds bandwidth (dt not finite)
_FLAG_BUDGET = 2      # solve hit the round budget mid-superstep


def _superstep_program(e_var, e_cnst, e_w, c_bound, v_bound, pen, rem,
                       thresh, ids, k, round_budget, stop_live, zero_bits,
                       tape_t, tape_slot, tape_val, tape_pos,
                       coll_pred, coll_ready, coll_clk,
                       edge_src, edge_dst, exec_cost, t0,
                       eps: float, n_c: int, n_v: int, k_max: int,
                       group: int, has_bounds: bool = False,
                       has_tape: bool = False, has_coll: bool = False):
    """Up to `k` (<= k_max) full advances in ONE dispatch: an outer
    lax.while_loop of (fixpoint to convergence -> dt -> retire), with
    completions logged into a device ring buffer and the clock carried
    as a compensated (Kahan) pair.  Returns the new flow state, the
    (possibly fault-mutated) constraint bounds and tape cursor, plus
    one packed vector (stats + per-advance dt/event-count tables +
    ring) so the host pays a single transfer per superstep.

    `k`, `round_budget` and `stop_live` are TRACED (dynamic) so replay
    (re-running a prefix of a batch deterministically) and budget
    tuning never trigger a recompile; `k_max` is the static table
    capacity.  The round budget bounds total device rounds per dispatch
    — the axon watchdog kills long kernels, so the budget, not k, is
    the hard safety bound (reusing the _CHUNK_ROUNDS_ACCEL reasoning).

    ``has_tape`` arms the FAULT EVENT TAPE: ``(tape_t, tape_slot,
    tape_val)`` is a time-sorted schedule of constraint-capacity
    flips (absolute f64 sim dates / constraint slots / new absolute
    bounds) and ``tape_pos`` the cursor of the first un-fired entry.
    Between the solve and the retire of every advance the loop peeks
    the next tape date against the absolute clock ``t0 + t_sum`` (both
    f64, so the comparison never loses to f32 clock granularity): if
    the planned dt would step over it, dt is CLAMPED to land exactly on
    the event, the new bound is scattered into ``c_bound`` (carried in
    the loop state, so the next iteration's fixpoint sees it — the
    device analogue of a Profile event invalidating the solver), a
    TAGGED entry ``id = -(1 + slot)`` is logged in the ring at the
    event time, and the cursor advances.  A fire consumes an advance
    slot, which bounds fires per dispatch by k_max — the ring is
    therefore oversized to ``n_v + k_max``.  A fire also rescues a
    stalled plan (dt = inf with a pending tape date is a wake-up, not
    a stall), mirroring how a Profile event re-arms an idle engine.
    With ``has_tape=False`` the tape arguments are ignored and the
    loop state/HLO are exactly the legacy 12-tuple.

    ``has_coll`` arms the COLLECTIVE SCHEDULE TAPE: the flow set is a
    compiled communication DAG (collectives.tape) whose dormant flows
    (penalty 0, full remains) activate when their predecessors
    complete.  ``coll_pred`` carries the per-flow outstanding
    predecessor counts, ``(edge_src, edge_dst)`` the static successor
    edge list (padded rows scatter to the dropped slot ``n_v``),
    ``exec_cost`` the per-flow delay between the last predecessor's
    completion and the flow's activation (the compute leg of a
    compute/comm phase), and ``coll_ready`` the f64 pending-activation
    dates (+inf = not scheduled).  Each advance takes the earliest of
    {planned completion, fault date, activation date}; an activation
    scatters penalty 1.0 into the fired flows, consumes their ready
    slots, and logs tagged ring entries ``id = -(1 + n_c + flow_id)``
    (disjoint from fault fires, whose slots are < n_c) — no host
    involvement until the schedule is exhausted.  Because collective
    runs must be bit-identical at EVERY dispatch grouping (the
    host-maestro oracle replays the same recurrence one advance per
    dispatch), the Kahan clock pair is carried ACROSS dispatches via
    ``coll_clk = (t, comp)`` and ring times are ABSOLUTE f64 dates;
    the dtype must be float64.  The ring grows by another n_v
    activation slots.
    """
    # trace-time only: a steady-state superstep loop re-enters the jit
    # cache, so this stays flat; a nonzero delta on a repeat run means
    # something is busting the cache (shape/static churn)
    opstats.bump("retraces")
    dtype = e_w.dtype
    fat = jnp.zeros(n_c, bool)
    eps_c = jnp.asarray(eps, dtype)
    k = jnp.asarray(k, jnp.int32)
    round_budget = jnp.asarray(round_budget, jnp.int32)
    stop_live = jnp.asarray(stop_live, jnp.int32)
    # completions scatter to [0, n_ev); the out-of-range sentinel and
    # the ring capacity grow by k_max when faults may interleave and
    # by n_v when collective activations may
    ring_n = (n_v + (k_max if has_tape else 0)
              + (n_v if has_coll else 0))
    if has_tape:
        T = tape_t.shape[0]
        t0 = jnp.asarray(t0, jnp.float64)

    def cond(st):
        pen_c = st[0]
        flag, adv, rounds = st[11], st[9], st[10]
        n_live = jnp.count_nonzero(pen_c > 0).astype(jnp.int32)
        alive = n_live > stop_live
        if has_coll:
            # a dormant flow with a pending activation keeps the loop
            # walking even when nothing currently holds bandwidth
            alive = alive | jnp.any(jnp.isfinite(st[-1]))
        return ((flag == _FLAG_OK) & (adv < k) & (rounds < round_budget)
                & alive)

    def body(st):
        idx = 12
        (pen_c, rem_c, t_sum, t_comp, ring_t, ring_id, adv_dt,
         adv_nev, n_ev, adv, rounds, flag) = st[:12]
        if has_tape:
            cb_c, tpos = st[idx], st[idx + 1]
            idx += 2
        else:
            cb_c = c_bound
        if has_coll:
            pred_c, ready_c = st[idx], st[idx + 1]
        out = fixpoint(e_var, e_cnst, e_w, cb_c, fat, pen_c, v_bound,
                       eps_c, n_c, n_v, parallel_rounds=True,
                       carry=None, max_rounds=round_budget - rounds,
                       return_carry=True, has_bounds=has_bounds,
                       has_fatpipe=False)
        carry2 = out[4]
        r = out[3].astype(jnp.int32)
        converged = jnp.count_nonzero(carry2[4]) == 0
        if has_tape or has_coll:
            # planned dt (the _advance_math front half), then the event
            # peek: fire iff the next fault/activation date lands inside
            # this advance (ties go to the event, and a pending event
            # rescues an infinite dt).  Clock math in f64: the event
            # dates are f64, so placement is exact even on f32 drains.
            live = pen_c > 0
            rate = jnp.where(live, carry2[0], 0.0)
            flowing = live & (rate > 0)
            dt_plan = jnp.min(jnp.where(
                flowing, rem_c / jnp.where(flowing, rate, 1.0), jnp.inf))
            if has_tape:
                ti = jnp.minimum(tpos, T - 1)
                next_ft = jnp.where(tpos < T, tape_t[ti], jnp.inf)
            else:
                next_ft = jnp.asarray(jnp.inf, jnp.float64)
            if has_coll:
                # collective clocks are absolute (carried across
                # dispatches); t0 is already folded into t_sum
                next_at = jnp.min(ready_c)
                now = t_sum.astype(jnp.float64)
            else:
                next_at = jnp.asarray(jnp.inf, jnp.float64)
                now = t0 + t_sum.astype(jnp.float64)
            next_t = jnp.minimum(next_ft, next_at)
            fire = jnp.isfinite(next_t) & (
                next_t <= now + dt_plan.astype(jnp.float64))
            dt = jnp.where(
                fire, jnp.maximum(next_t - now, 0.0).astype(dtype),
                dt_plan)
            f_fire = fire & (next_ft <= next_at)
            prod = _rounded_product(rate, dt, zero_bits)
            rem2 = jnp.where(flowing, rem_c - prod, rem_c)
            done = flowing & (rem2 < thresh)
            pen2 = jnp.where(done, 0.0, pen_c)
            rem2 = jnp.where(done, 0.0, rem2)
        else:
            dt, pen2, rem2, done = _advance_math(pen_c, rem_c, thresh,
                                                 carry2[0], zero_bits)
        ok = converged & jnp.isfinite(dt)

        # Kahan clock: per-advance dts combine compensated so the f32
        # in-dispatch clock error is O(k ulp), not O(advances) drift
        y = dt - t_comp
        t_new = t_sum + y
        t_comp2 = (t_new - t_sum) - y

        # completion ring: positions by stable slot order (cumsum), the
        # same within-advance order the host paths emit; non-done slots
        # scatter out-of-range and are dropped.  2D index shape keeps
        # the axon scatter fast path.
        dcount = jnp.cumsum(done.astype(jnp.int32))
        pos = jnp.where(done, n_ev + dcount - 1, ring_n)
        pos2 = pos.reshape(-1, group)
        ring_t2 = ring_t.at[pos2].set(
            jnp.broadcast_to(t_new, pos2.shape), mode="drop")
        ring_id2 = ring_id.at[pos2].set(ids.reshape(-1, group),
                                        mode="drop")
        n_done = dcount[-1]

        if has_tape:
            # the fault fires AFTER this advance's completions (they
            # retire AT the event date; the new capacity governs from
            # the event onward): tagged ring entry, bound scatter, and
            # cursor bump — all dropped when not firing
            slot = tape_slot[ti]
            fpos = jnp.where(f_fire, n_ev + n_done, ring_n)
            ring_t2 = ring_t2.at[fpos].set(t_new, mode="drop")
            ring_id2 = ring_id2.at[fpos].set(-(1 + slot), mode="drop")
            n_new = n_ev + n_done + f_fire.astype(jnp.int32)
            cb2 = cb_c.at[jnp.where(f_fire, slot, n_c)].set(
                tape_val[ti], mode="drop")
            tpos2 = tpos + (ok & f_fire).astype(jnp.int32)
        else:
            n_new = n_ev + n_done

        if has_coll:
            # activations fire AFTER completions and any fault entry:
            # every pending flow whose ready date is <= the event date
            # wakes up (penalty scatter), its ready slot is consumed,
            # and a tagged entry id = -(1 + n_c + flow_id) logs the
            # fired successor at the (absolute) advance clock
            a_any = fire & (next_at <= next_ft)
            act = a_any & (ready_c <= next_t)
            acount = jnp.cumsum(act.astype(jnp.int32))
            apos = jnp.where(act, n_new + acount - 1, ring_n)
            ring_t2 = ring_t2.at[apos].set(
                jnp.broadcast_to(t_new, apos.shape), mode="drop")
            ring_id2 = ring_id2.at[apos].set(-(1 + n_c + ids),
                                             mode="drop")
            n_new = n_new + acount[-1]
            pen2 = jnp.where(act, jnp.asarray(1.0, dtype), pen2)
            ready2 = jnp.where(act, jnp.inf, ready_c)
            # DAG walk: completions decrement their successors'
            # outstanding-predecessor counts; flows reaching zero get
            # a ready date = completion clock + exec cost (activation
            # happens on a LATER advance, never the completing one)
            pred2 = pred_c.at[edge_dst].add(
                -jnp.take(done.astype(jnp.int32), edge_src), mode="drop")
            newly = (pred2 <= 0) & (pred_c > 0)
            ready2 = jnp.where(
                newly, t_new.astype(jnp.float64) + exec_cost, ready2)

        adv_dt2 = adv_dt.at[adv].set(dt.astype(dtype))
        adv_nev2 = adv_nev.at[adv].set(n_new)

        flag2 = jnp.where(~converged, _FLAG_BUDGET,
                          jnp.where(jnp.isfinite(dt), _FLAG_OK,
                                    _FLAG_STALLED)).astype(jnp.int32)

        sel = lambda a, b: jnp.where(ok, a, b)
        out_st = (sel(pen2, pen_c), sel(rem2, rem_c),
                  sel(t_new, t_sum), sel(t_comp2, t_comp),
                  jnp.where(ok, ring_t2, ring_t),
                  jnp.where(ok, ring_id2, ring_id),
                  jnp.where(ok, adv_dt2, adv_dt),
                  jnp.where(ok, adv_nev2, adv_nev),
                  sel(n_new, n_ev),
                  adv + ok.astype(jnp.int32), rounds + r, flag2)
        if has_tape:
            out_st = out_st + (jnp.where(ok, cb2, cb_c),
                               jnp.where(ok, tpos2, tpos))
        if has_coll:
            out_st = out_st + (jnp.where(ok, pred2, pred_c),
                               jnp.where(ok, ready2, ready_c))
        return out_st

    zero = jnp.asarray(0, jnp.int32)
    if has_coll:
        # the Kahan clock pair is carried across dispatches so the
        # recurrence — and therefore every event date — is invariant
        # to how advances are grouped into dispatches
        clk0 = (coll_clk[0].astype(dtype), coll_clk[1].astype(dtype))
    else:
        clk0 = (jnp.asarray(0.0, dtype), jnp.asarray(0.0, dtype))
    st0 = (pen, rem) + clk0 + (
           jnp.zeros(ring_n, dtype), jnp.zeros(ring_n, jnp.int32),
           jnp.zeros(k_max, dtype), jnp.zeros(k_max, jnp.int32),
           zero, zero, zero, zero)
    if has_tape:
        st0 = st0 + (c_bound, jnp.asarray(tape_pos, jnp.int32))
    if has_coll:
        st0 = st0 + (coll_pred, coll_ready)
    st = lax.while_loop(cond, body, st0)
    (pen_o, rem_o, t_sum, t_comp_o, ring_t, ring_id, adv_dt, adv_nev,
     n_ev, adv, rounds, flag) = st[:12]
    idx = 12
    if has_tape:
        cb_o, tpos_o = st[idx], st[idx + 1]
        idx += 2
    else:
        cb_o = c_bound
        tpos_o = jnp.asarray(tape_pos, jnp.int32)
    if has_coll:
        pred_o, ready_o = st[idx], st[idx + 1]
        clk_o = jnp.stack([t_sum.astype(jnp.float64),
                           t_comp_o.astype(jnp.float64)])
    else:
        pred_o, ready_o, clk_o = coll_pred, coll_ready, coll_clk
    n_live = jnp.count_nonzero(pen_o > 0)
    live_elems = jnp.count_nonzero(
        (e_w > 0) & jnp.take(pen_o > 0, e_var, fill_value=False))
    stats = jnp.stack([rounds.astype(dtype), adv.astype(dtype),
                       n_ev.astype(dtype), t_sum,
                       n_live.astype(dtype), flag.astype(dtype),
                       live_elems.astype(dtype)])
    packed = jnp.concatenate([stats, adv_dt, adv_nev.astype(dtype),
                              ring_t, ring_id.astype(dtype)])
    return pen_o, rem_o, cb_o, tpos_o, pred_o, ready_o, clk_o, packed


_drain_superstep = functools.partial(
    jax.jit, static_argnames=("eps", "n_c", "n_v", "k_max", "group",
                              "has_bounds", "has_tape",
                              "has_coll"))(_superstep_program)

#: the donating twin: steady-state dispatches that chain from the
#: COMMITTED flow state hand their (pen, rem) buffers to XLA for
#: in-place reuse — the inputs are dead the moment the outputs are
#: adopted, so the only cost is that the dispatch may never be
#: retried or replayed from those inputs (see _superstep_issue's
#: donate gate).  Donation is an aliasing hint, not a numeric change:
#: the program text is identical, so events/clocks are bit-identical.
_drain_superstep_donate = functools.partial(
    jax.jit, static_argnames=("eps", "n_c", "n_v", "k_max", "group",
                              "has_bounds", "has_tape", "has_coll"),
    donate_argnames=("pen", "rem"))(_superstep_program)


#: transition-payload field order (index = the static target code in
#: the payload layout); the first three scatter into the 2D element
#: arrays, the rest into the per-constraint / per-flow vectors
_TRANSITION_FIELDS = ("e_var", "e_cnst", "e_w", "c_bound",
                      "v_penalty", "remains", "thresh", "v_bound")


@functools.partial(jax.jit, static_argnames=("layout", "group"))
def _apply_transition_payload(payload, ev, ec, ew, cb, pen, rem,
                              thresh, vb, layout, group: int):
    """Scatter one fused transition payload into the plan's device
    arrays (the drain-path analogue of lmm_warm._apply_deltas): the
    payload is a single f64 vector of per-field [indices..., values...]
    runs and `layout` is the static ``(target, offset, n)`` tuple
    describing them.  Flow slots and element slots are < 2^32, so the
    f64 round trip is exact; element targets are 2D (group columns) to
    keep the axon scatter fast path.  Padded payload entries repeat a
    run's first (index, value) pair — duplicate same-value scatters are
    harmless."""
    targets = [ev, ec, ew, cb, pen, rem, thresh, vb]
    for ti, off, n in layout:
        idx = payload[off:off + n].astype(jnp.int32)
        vals = payload[off + n:off + 2 * n]
        t = targets[ti]
        if t.ndim == 2:
            targets[ti] = t.at[idx // group, idx % group].set(
                vals.astype(t.dtype))
        else:
            targets[ti] = t.at[idx].set(vals.astype(t.dtype))
    return tuple(targets)


@jax.jit
def _drain_forced_advance(pen, rem, thresh, values, delta, zero_bits):
    """Advance the flow state by an EXTERNALLY chosen delta (an engine
    advance decided by another model or a latency expiry, delta <= the
    plan's own dt): decrement remains at the solved rates and retire
    threshold crossings with the same strict-< rule as _advance_math,
    so a partial advance that does push a flow under its threshold
    finishes it exactly where the generic double_update walk would."""
    dtype = rem.dtype
    live = pen > 0
    rate = jnp.where(live, values, 0.0)
    flowing = live & (rate > 0)
    rem2 = jnp.where(flowing,
                     rem - _rounded_product(rate, delta, zero_bits), rem)
    done = flowing & (rem2 < thresh)
    pen2 = jnp.where(done, 0.0, pen)
    rem2 = jnp.where(done, 0.0, rem2)
    n_live = jnp.count_nonzero(pen2 > 0)
    head = n_live.astype(dtype)[None]
    return pen2, rem2, jnp.concatenate([head, done.astype(dtype)])


@functools.partial(jax.jit,
                   static_argnames=("vh", "eh", "gv", "ge"))
def _drain_repack(e_var, e_cnst, e_w, pen, rem, thresh, ids,
                  vh: int, eh: int, gv: int, ge: int):
    """On-device repack to halved static shapes: stable live-first
    partition of the flow rows and the element rows (the compaction-
    chain machinery, lmm_jax._stable_livefirst_perm), then a static
    slice.  Exact for the same reason the chain is: live relative
    order is preserved, so the scatter-reduction order over survivors —
    and therefore event ordering — is unchanged, and dropped rows only
    contributed identity values.  NO host transfer: the caller decides
    from counts it already fetched, and every output stays on device.
    """
    V = pen.shape[0]
    livemask = pen > 0
    perm_v = _stable_livefirst_perm(livemask, gv)
    keep_v = perm_v[:vh]
    pen2 = jnp.take(pen, keep_v)
    rem2 = jnp.take(rem, keep_v)
    thresh2 = jnp.take(thresh, keep_v)
    ids2 = jnp.take(ids, keep_v)
    old2new = jnp.zeros(V, jnp.int32).at[
        perm_v.reshape(-1, gv)].set(
        jnp.arange(V, dtype=jnp.int32).reshape(-1, gv))

    ev = e_var.reshape(-1)
    ec = e_cnst.reshape(-1)
    ew = e_w.reshape(-1)
    elive = (ew > 0) & jnp.take(livemask, ev)
    perm_e = _stable_livefirst_perm(elive, ge)
    sel = perm_e[:eh]
    ev2 = jnp.take(old2new, jnp.take(ev, sel))
    # dead-tail elements (weight forced to 0) may map past vh: clamp so
    # downstream gathers stay in range — their weight masks them out
    ev2 = jnp.minimum(ev2, vh - 1)
    ec2 = jnp.take(ec, sel)
    ew2 = jnp.where(jnp.take(elive, sel), jnp.take(ew, sel), 0.0)
    return (ev2.reshape(-1, 8), ec2.reshape(-1, 8), ew2.reshape(-1, 8),
            pen2, rem2, thresh2, ids2)


@functools.partial(jax.jit, static_argnames=("vh",))
def _repack_vbound(v_bound, pen, vh: int):
    """Bound rows follow the same stable live-first permutation."""
    perm_v = _stable_livefirst_perm(pen > 0, _pos_group(pen.shape[0]))
    return jnp.take(v_bound, perm_v[:vh])


class SuperstepToken:
    """One issued (possibly still in-flight) superstep dispatch.

    The token owns the dispatch's input AND output device arrays: jax
    arrays are immutable, so ``(pen_in, rem_in)`` is a free snapshot of
    the pre-dispatch flow state and ``(pen_out, rem_out)`` is the
    double-buffered post-dispatch state the NEXT speculative dispatch
    chains from.  Nothing is committed to the owning sim until the
    token is collected; discarding an un-collected token costs nothing
    but the device work it already burned."""

    __slots__ = ("pen_in", "rem_in", "pen_out", "rem_out", "packed",
                 "k", "k_max", "want_stop", "speculative",
                 "cb_in", "cb_out", "tpos_out", "t0",
                 "pred_out", "ready_out", "clk_out")

    def __init__(self, pen_in, rem_in, pen_out, rem_out, packed,
                 k: int, k_max: int, want_stop: int, speculative: bool,
                 cb_in=None, cb_out=None, tpos_out=None, t0=None,
                 pred_out=None, ready_out=None, clk_out=None):
        self.pen_in = pen_in
        self.rem_in = rem_in
        self.pen_out = pen_out
        self.rem_out = rem_out
        self.packed = packed
        self.k = k
        self.k_max = k_max
        self.want_stop = want_stop
        self.speculative = speculative
        # fault-tape double buffers: the dispatch's input/output bounds
        # and the post-dispatch tape cursor + the dispatch's f64 base
        # clock (what chained speculative issues derive their t0 from)
        self.cb_in = cb_in
        self.cb_out = cb_out
        self.tpos_out = tpos_out
        self.t0 = t0
        # collective-tape double buffers: post-dispatch predecessor
        # counts, pending-activation dates, and the carried Kahan
        # clock pair speculative successors chain from
        self.pred_out = pred_out
        self.ready_out = ready_out
        self.clk_out = clk_out


class DrainSim:
    """Drain a fixed flow set to completion on the JAX backend.

    Parameters mirror a flattened network-only LMM system: COO elements
    (e_var, e_cnst, e_w), constraint capacities, per-flow penalties
    (1.0 = live) and sizes (bytes).  `solve_chunk` bounds device rounds
    per dispatch (axon watchdog); `repack_at` triggers a repack when
    the live fraction drops below it.

    `done_eps` retires a flow when its post-advance remainder falls to
    ``done_eps * size`` (``done_mode="rel"``, the reference's relative
    sg_maxmin_precision semantics — REQUIRED for f32 backends to keep
    the f64 tie groups) or to the absolute ``done_eps``
    (``done_mode="abs"``, bit-matching the engine's generic
    double_update path in f64).

    `fused=True` runs solve+advance in one dispatch (1 sync/advance);
    `superstep=K` batches up to K advances per dispatch (~1/K
    syncs/advance) with on-device repacks.  `v_bound` optionally caps
    per-flow rates (TCP-gamma windows etc.).

    `pipeline=D` (superstep mode only) keeps up to D speculative
    supersteps in flight beyond the one being collected: the host
    processes ring N while the device executes ring N+1, hiding the
    dispatch round trip.  Results are bit-identical to `pipeline=0` —
    any host-side mutation while processing a ring (repack, budget
    rescue, stall, completion) discards the in-flight work and replays
    it from the committed state (see the module docstring).
    """

    def __init__(self, e_var, e_cnst, e_w, c_bound, sizes,
                 eps: float = 1e-5, done_eps: float = 1e-4,
                 dtype=np.float32, solve_chunk: int = 0,
                 repack_at: float = 0.5, device=None,
                 v_bound=None, done_mode: str = "rel",
                 fused: bool = False, superstep: int = 0,
                 superstep_rounds: int = 0, repack_min: int = 1024,
                 penalty=None, remains=None, pipeline: int = 0,
                 tape=None, collective=None):
        self.eps = float(eps)
        self.done_eps = float(done_eps)
        if done_mode not in ("rel", "abs"):
            raise ValueError(f"Unknown done_mode {done_mode!r} "
                             "(expected rel or abs)")
        self.done_mode = done_mode
        self.dtype = np.dtype(dtype)
        if not solve_chunk:
            # bound per-dispatch kernel time: big-system rounds cost
            # ~100-150 ms of device time and the axon watchdog kills
            # kernels in the ~10 s range (observed: a 64-round chunk at
            # 1.24M elements hangs the worker)
            solve_chunk = 16 if len(e_var) >= 1 << 20 else 64
        self.solve_chunk = int(solve_chunk)
        self.repack_at = float(repack_at)
        # below this live count a repack costs more than it saves
        # (and halved shapes recompile); tests lower it to exercise
        # the repack kernels at small scale
        self.repack_min = int(repack_min)
        self.device = device
        self.fused = bool(fused)
        self.superstep_k = int(superstep)
        if self.superstep_k:
            if not superstep_rounds:
                # Per-dispatch round budget, the watchdog-safety bound:
                # on an accelerator a superstep may burn at most what a
                # few solve chunks would (each chunk size was itself
                # derived from per-round device cost); on CPU there is
                # no watchdog and the budget just has to cover K
                # advances of O(10-100)-round solves.
                platform = (device.platform if device is not None
                            else jax.devices()[0].platform)
                if platform == "cpu":
                    superstep_rounds = self.superstep_k * 512
                else:
                    superstep_rounds = self.solve_chunk * 4
            self.superstep_rounds = int(superstep_rounds)
        else:
            self.superstep_rounds = 0

        self._host: Optional[dict] = dict(
            e_var=np.asarray(e_var, np.int32),
            e_cnst=np.asarray(e_cnst, np.int32),
            e_w=np.asarray(e_w, self.dtype))
        self.n_c = len(c_bound)
        self.n_v = len(sizes)
        self._c_bound = np.asarray(c_bound, self.dtype)
        self._sizes = np.asarray(sizes, np.float64)
        if self.n_v >= 1 << 24 and self.dtype == np.float32:
            raise ValueError(
                "flow ids beyond 2^24 are not exact in the f32 "
                "single-transfer fetch; use float64 or shard the drain")
        # flow slot -> original flow id (survives repacks); host mirror
        # may go stale after an on-device repack and is refetched
        # lazily (_host_ids)
        self._ids = np.arange(self.n_v)
        self._ids_stale = False

        if done_mode == "rel":
            thresh = self.done_eps * self._sizes
        else:
            thresh = np.full(self.n_v, self.done_eps)
        # engine plans hand in mid-simulation state: per-slot penalties
        # (0 = not a live flow) and already-partially-drained remains
        pen0 = (np.asarray(penalty, self.dtype) if penalty is not None
                else np.ones(self.n_v, self.dtype))
        rem0 = (np.asarray(remains, self.dtype) if remains is not None
                else self._sizes.astype(self.dtype))
        self._pen = jax.device_put(pen0, device)
        self._rem = jax.device_put(rem0, device)
        self._thresh = jax.device_put(thresh.astype(self.dtype), device)
        self._ids_dev = jax.device_put(
            np.arange(self.n_v, dtype=np.int32), device)
        self._dev = [jax.device_put(_to2d(self._host[k]), device)
                     for k in ("e_var", "e_cnst", "e_w")]
        self._cb = jax.device_put(self._c_bound, device)
        if v_bound is not None:
            vb = np.asarray(v_bound, self.dtype)
            self.has_bounds = bool(np.any(vb > 0))
        else:
            vb = np.full(self.n_v, -1.0, self.dtype)
            self.has_bounds = False
        self._vb = jax.device_put(vb, device)

        # fault event tape: `tape` is (dates, slots, values) — f64
        # absolute sim dates (sorted), constraint slots, and the
        # ABSOLUTE new capacity each event installs (mirroring the
        # engine's set_bandwidth semantics, so a recovery restores the
        # exact pre-fault bound).  Device-resident; the superstep loop
        # clamps dt so no advance steps over an entry (see
        # _superstep_program).
        self.has_tape = False
        self.fault_events: list = []     # (time, constraint slot)
        self._tpos_host = 0              # fired-entry count (host view)
        self._last_fired = False
        if tape is not None and len(tape[0]):
            tt = np.asarray(tape[0], np.float64)
            ts = np.asarray(tape[1], np.int32)
            tv = np.asarray(tape[2], np.float64).astype(self.dtype)
            if not (len(tt) == len(ts) == len(tv)):
                raise ValueError("tape arrays must have equal length")
            if np.any(np.diff(tt) < 0):
                raise ValueError("tape dates must be time-sorted")
            if np.any((ts < 0) | (ts >= self.n_c)):
                raise ValueError("tape slot out of range")
            if not superstep:
                raise ValueError("tape= needs superstep=K (faults fire "
                                 "inside the superstep loop)")
            self.has_tape = True
            self._tape = tuple(jax.device_put(a, device)
                               for a in (tt, ts, tv))
            self._tpos = jax.device_put(np.int32(0), device)
            opstats.bump("fault_tape_slots", len(tt))
            opstats.bump("uploaded_bytes_delta",
                         tt.nbytes + ts.nbytes + tv.nbytes)
        else:
            # dummy triple keeps the jit call sites uniform; with
            # has_tape=False the program never reads it (XLA DCE)
            self._tape = (
                jax.device_put(np.full(1, np.inf), device),
                jax.device_put(np.full(1, self.n_c, np.int32), device),
                jax.device_put(np.zeros(1, self.dtype), device))
            self._tpos = np.int32(0)

        # collective schedule tape: `collective` is (pred, ready,
        # edge_src, edge_dst, exec_cost) — the compiled comm DAG
        # (collectives.tape.DeviceCollective.drain_args()).  Dormant
        # flows (penalty 0) activate on device when their outstanding
        # predecessor count hits zero; the superstep loop walks the
        # whole schedule without host involvement (see
        # _superstep_program's has_coll docs).
        self.has_coll = False
        self.collective_events: list = []   # (time, flow id) activations
        if collective is not None:
            cp, cr, ces, ced, cec = collective
            cp = np.asarray(cp, np.int32)
            cr = np.asarray(cr, np.float64)
            ces = np.asarray(ces, np.int32)
            ced = np.asarray(ced, np.int32)
            cec = np.asarray(cec, np.float64)
            if not (len(cp) == len(cr) == len(cec) == self.n_v):
                raise ValueError("collective arrays must be per-flow "
                                 f"(n_v={self.n_v})")
            if len(ces) != len(ced):
                raise ValueError("collective edge arrays must have "
                                 "equal length")
            if not superstep:
                raise ValueError("collective= needs superstep=K (the "
                                 "DAG walks inside the superstep loop)")
            if self.dtype != np.float64:
                raise ValueError("collective= needs dtype=float64 (the "
                                 "carried Kahan clock must match the "
                                 "host-maestro oracle bit-for-bit)")
            self.has_coll = True
            # a repack would scramble the DAG's static slot indexing
            self.repack_min = 1 << 62
            self._coll = tuple(jax.device_put(a, device)
                               for a in (cp, cr))
            self._coll_edges = tuple(jax.device_put(a, device)
                                     for a in (ces, ced, cec))
            self._coll_clk = jax.device_put(
                np.zeros(2, np.float64), device)
            self._coll_total = int(self.n_v)
            opstats.bump("collective_tape_slots", self.n_v)
            opstats.bump("uploaded_bytes_delta",
                         cp.nbytes + cr.nbytes + ces.nbytes
                         + ced.nbytes + cec.nbytes)
        else:
            self._coll = (
                jax.device_put(np.zeros(1, np.int32), device),
                jax.device_put(np.full(1, np.inf), device))
            self._coll_edges = (
                jax.device_put(np.zeros(1, np.int32), device),
                jax.device_put(np.zeros(1, np.int32), device),
                jax.device_put(np.zeros(1, np.float64), device))
            self._coll_clk = jax.device_put(np.zeros(2, np.float64),
                                            device)
            self._coll_total = 0

        opstats.bump("uploaded_bytes_full",
                     pen0.nbytes + rem0.nbytes + thresh.nbytes
                     + self._ids_dev.nbytes + self._cb.nbytes + vb.nbytes
                     + sum(d.nbytes for d in self._dev))
        self._live0 = (int(np.count_nonzero(pen0 > 0))
                       if penalty is not None else self.n_v)

        self.pipeline = int(pipeline)
        if self.pipeline and not self.superstep_k:
            raise ValueError("pipeline=D needs superstep=K (speculation "
                             "is a property of the superstep driver)")

        self.t = 0.0              # f64 master clock (host-accumulated)
        self.events: list = []   # (time, original flow id), completion order
        self.advances = 0
        self.rounds = 0
        self.syncs = 0
        self.repacks = 0
        self.supersteps = 0
        # speculation census (pipelined driver + drain fast path)
        self.spec_issued = 0
        self.spec_committed = 0
        self.spec_rolled_back = 0
        #: optional event consumer, called once per collected superstep
        #: with the batch list [(dt, [flow ids])] — the host-side work
        #: (engine bookkeeping, demux, logging) the pipelined driver
        #: overlaps with the next in-flight dispatch.  Runs INSIDE the
        #: collect, i.e. between the ring fetch and the next blocking
        #: point, for both the pipelined and synchronous drivers.
        self.on_batches = None

    # -- host-side helpers -------------------------------------------------

    def _host_ids(self) -> np.ndarray:
        """The slot -> original-flow-id mirror, refetched after an
        on-device repack made it stale (one transfer, counted)."""
        if self._ids_stale:
            self._ids = opstats.timed_fetch(
                self._ids_dev).astype(np.int64)
            self.syncs += 1
            self._ids_stale = False
        return self._ids

    def _repack_host(self) -> None:
        """Drop retired flows' elements and rows (host-side, one
        re-upload).  Live relative order is preserved, so reduction
        order over survivors — and therefore event ordering — is
        unchanged.  Unfused/fused paths only; the superstep path
        repacks on device."""
        pen = opstats.timed_fetch(self._pen)
        rem = opstats.timed_fetch(self._rem)
        thresh = opstats.timed_fetch(self._thresh)
        self.syncs += 1
        live = pen > 0
        keep = np.flatnonzero(live)
        old2new = np.full(self.n_v, -1, np.int32)
        old2new[keep] = np.arange(len(keep), dtype=np.int32)
        emask = live[self._host["e_var"]]
        self._host = dict(
            e_var=old2new[self._host["e_var"][emask]],
            e_cnst=self._host["e_cnst"][emask],
            e_w=self._host["e_w"][emask])
        self._ids = self._host_ids()[keep]
        self._sizes = self._sizes[keep]
        self.n_v = len(keep)
        self._pen = jax.device_put(pen[keep], self.device)
        self._rem = jax.device_put(rem[keep], self.device)
        self._thresh = jax.device_put(thresh[keep], self.device)
        self._ids_dev = jax.device_put(
            self._ids.astype(np.int32), self.device)
        self._vb = jax.device_put(
            opstats.timed_fetch(self._vb)[keep], self.device)
        self._dev = [jax.device_put(_to2d(self._host[k]), self.device)
                     for k in ("e_var", "e_cnst", "e_w")]
        self._live0 = self.n_v
        self.repacks += 1

    def _repack_device(self, n_live: int, live_elems: int) -> bool:
        """Halve the device arrays in place with the stable live-first
        partition kernel — a dispatch with NO transfer.  Only when both
        the live flow and live element populations fit the halves."""
        E = self._dev[0].size
        vh = self.n_v // 2
        eh = -(-(E // 2) // 8) * 8
        if n_live > vh or live_elems > eh:
            return False
        gv = _pos_group(self.n_v)
        ge = _pos_group(E)
        ev, ec, ew, pen, rem, thresh, ids = _drain_repack(
            *self._dev, self._pen, self._rem, self._thresh,
            self._ids_dev, vh=vh, eh=eh, gv=gv, ge=ge)
        if self.has_bounds:
            self._vb = _repack_vbound(self._vb, self._pen, vh=vh)
        else:
            self._vb = jax.device_put(
                np.full(vh, -1.0, self.dtype), self.device)
        self._dev = [ev, ec, ew]
        self._pen, self._rem, self._thresh = pen, rem, thresh
        self._ids_dev = ids
        self.n_v = vh
        self._live0 = n_live
        self._ids_stale = True
        self._host = None        # host mirrors no longer meaningful
        self.repacks += 1
        return True

    def _should_repack(self, n_live: int) -> bool:
        return bool(n_live and n_live <= self._live0 * self.repack_at
                    and n_live >= self.repack_min)

    # -- per-advance paths -------------------------------------------------

    def advance(self) -> int:
        """One solve + time advance; returns the remaining live count.
        Uses the fused single-dispatch kernel when `fused=True`, the
        legacy two-dispatch shape otherwise."""
        if self.fused:
            return self._advance_fused()
        carry = None
        while True:
            carry, stats = _drain_solve_chunk(
                *self._dev, self._cb, self._pen, self._vb, carry,
                eps=self.eps, n_c=self.n_c, n_v=self.n_v,
                chunk=self.solve_chunk, has_bounds=self.has_bounds)
            st = opstats.timed_fetch(stats)
            self.syncs += 1
            rounds, n_light = int(st[0]), int(st[1])
            if n_light == 0:
                break
            if rounds >= _MAX_ROUNDS:
                raise RuntimeError("drain solve did not converge")
        self.rounds += rounds
        opstats.bump("dispatches")
        opstats.bump("fixpoint_rounds", rounds)

        self._pen, self._rem, out = _drain_advance(
            self._pen, self._rem, self._thresh, carry[0], _ZERO_BITS)
        out = opstats.timed_fetch(out)
        self.syncs += 1
        dt, n_live = float(out[0]), int(out[1])
        done = out[2:] > 0
        return self._commit_advance(dt, n_live, done)

    def _advance_fused(self) -> int:
        carry = None
        while True:
            self._pen, self._rem, carry, stats = _drain_fused_step(
                *self._dev, self._cb, self._vb, self._pen, self._rem,
                self._thresh, carry, _ZERO_BITS, eps=self.eps,
                n_c=self.n_c, n_v=self.n_v, chunk=self.solve_chunk,
                has_bounds=self.has_bounds)
            st = opstats.timed_fetch(stats)
            self.syncs += 1
            rounds, n_light = int(st[0]), int(st[1])
            if n_light == 0:
                break
            if rounds >= _MAX_ROUNDS:
                raise RuntimeError("drain solve did not converge")
        self.rounds += rounds
        opstats.bump("dispatches")
        opstats.bump("fixpoint_rounds", rounds)
        dt, n_live = float(st[2]), int(st[3])
        done = st[4:] > 0
        return self._commit_advance(dt, n_live, done)

    def _commit_advance(self, dt: float, n_live: int,
                        done: np.ndarray) -> int:
        if not np.isfinite(dt):
            raise RuntimeError(
                f"drain stalled: no flow holds bandwidth "
                f"({n_live} live)")
        # f64 host accumulation of the (dtype-precision) dt values
        self.t += dt
        self.advances += 1
        ids = self._host_ids()
        for fid in ids[np.flatnonzero(done)]:
            self.events.append((self.t, int(fid)))
        if self._should_repack(n_live):
            if self._host is not None:
                self._repack_host()
            else:
                # a previous device repack dropped the host mirrors
                self._repack_device(n_live, self._live_elems())
        return n_live

    def solve_rates(self) -> np.ndarray:
        """Solve the CURRENT flow state to convergence and fetch the
        rate vector (no time advance) — the engine fast path uses this
        to hand a partial advance back to the generic model loop."""
        carry = None
        while True:
            carry, stats = _drain_solve_chunk(
                *self._dev, self._cb, self._pen, self._vb, carry,
                eps=self.eps, n_c=self.n_c, n_v=self.n_v,
                chunk=self.solve_chunk, has_bounds=self.has_bounds)
            st = np.asarray(stats)
            self.syncs += 1
            if int(st[1]) == 0:
                break
            if int(st[0]) >= _MAX_ROUNDS:
                raise RuntimeError("drain solve did not converge")
        self.rounds += int(st[0])
        rates = np.asarray(carry[0])
        self.syncs += 1
        return rates

    def apply_transitions(self, updates: dict) -> int:
        """Absorb a batch of recognized engine transitions into the
        device plan: `updates` maps _TRANSITION_FIELDS names to
        ``(slot_indices, values)`` pairs, shipped as ONE fused indexed
        payload (pow2-bucketed, so payload shapes — and therefore jit
        signatures — are bounded) and applied as device scatters.  No
        re-flatten, no platform re-upload; cost is O(dirty slots).
        Returns the number of real (unpadded) slots scattered."""
        layout = []
        chunks = []
        off = 0
        slots = 0
        for ti, field in enumerate(_TRANSITION_FIELDS):
            pair = updates.get(field)
            if pair is None or len(pair[0]) == 0:
                continue
            ix = np.asarray(pair[0], np.float64)
            vals = np.asarray(pair[1], np.float64)
            slots += len(ix)
            n = _bucket(len(ix), floor=8)
            if n > len(ix):
                ix = np.concatenate([ix, np.repeat(ix[:1], n - len(ix))])
                vals = np.concatenate([vals,
                                       np.repeat(vals[:1], n - len(vals))])
            layout.append((ti, off, n))
            chunks.append(ix)
            chunks.append(vals)
            off += 2 * n
        if not layout:
            return 0
        vb_pair = updates.get("v_bound")
        if vb_pair is not None and len(vb_pair[0]) \
                and np.any(np.asarray(vb_pair[1]) > 0):
            self.has_bounds = True
        payload = jax.device_put(np.concatenate(chunks), self.device)
        out = _apply_transition_payload(
            payload, *self._dev, self._cb, self._pen, self._rem,
            self._thresh, self._vb, layout=tuple(layout),
            group=self._dev[0].shape[1])
        self._dev = list(out[:3])
        (self._cb, self._pen, self._rem, self._thresh, self._vb) = out[3:]
        self._host = None      # host element mirrors are stale now
        opstats.bump("dispatches")
        opstats.bump("uploaded_bytes_delta", payload.nbytes)
        return slots

    def partial_advance(self, delta: float):
        """Solve the CURRENT flow state to convergence, then advance it
        by an EXTERNALLY chosen `delta` (an engine advance won by
        another model or a latency expiry; delta <= this plan's own
        next-completion dt) with the forced-advance kernel.  Returns
        ``(done_slots, n_live)`` — the flow slots that crossed their
        retirement threshold inside the partial advance (emitting them
        in started-set order is the caller's concern).  The clock is
        the engine's on this path, so self.t/self.events are untouched.
        """
        carry = None
        while True:
            carry, stats = _drain_solve_chunk(
                *self._dev, self._cb, self._pen, self._vb, carry,
                eps=self.eps, n_c=self.n_c, n_v=self.n_v,
                chunk=self.solve_chunk, has_bounds=self.has_bounds)
            st = np.asarray(stats)
            self.syncs += 1
            if int(st[1]) == 0:
                break
            if int(st[0]) >= _MAX_ROUNDS:
                raise RuntimeError("drain solve did not converge")
        self.rounds += int(st[0])
        opstats.bump("dispatches")
        opstats.bump("fixpoint_rounds", int(st[0]))
        self._pen, self._rem, out = _drain_forced_advance(
            self._pen, self._rem, self._thresh, carry[0],
            jnp.asarray(delta, self.dtype), _ZERO_BITS)
        out = np.asarray(out)
        self.syncs += 1
        self.advances += 1
        n_live = int(out[0])
        done = np.flatnonzero(out[1:] > 0)
        return done, n_live

    def _live_elems(self) -> int:
        pen = np.asarray(self._pen)
        ew = np.asarray(self._dev[2]).reshape(-1)
        ev = np.asarray(self._dev[0]).reshape(-1)
        self.syncs += 1
        return int(np.count_nonzero((ew > 0) & (pen[ev] > 0)))

    # -- superstep path ----------------------------------------------------

    def _superstep_issue(self, k: Optional[int] = None, pen=None,
                         rem=None, speculative: bool = False,
                         stop_live: int = 0, cb=None, tpos=None,
                         t0=None, round_budget: int = 0,
                         pred=None, ready=None, clk=None,
                         donate: bool = False
                         ) -> SuperstepToken:
        """Dispatch ONE superstep of up to `k` advances WITHOUT
        touching the committed flow state: the dispatch chains from
        `(pen, rem)` (default: the committed state) and its outputs
        ride the returned token.  Pure host-side except the async
        dispatch itself, so speculative issues are free to discard.

        With a fault tape the dispatch additionally chains the
        constraint bounds and tape cursor (`cb`, `tpos`) and needs the
        f64 base clock `t0` the dispatch starts from (default: the
        committed ``self.t``); speculative issues derive all three
        from their predecessor's token.

        ``donate=True`` hands the committed (pen, rem) buffers to XLA
        for in-place reuse and adopts the outputs as the committed
        state IMMEDIATELY (the inputs are deleted by the dispatch, so
        leaving ``self._pen`` pointing at them would be a landmine).
        Only honored on non-speculative issues chained from the
        committed state: speculative issues must leave their inputs
        alive for the mispredict replay, and explicit (pen, rem)
        chains belong to callers (fastpath/replay) that snapshot
        them."""
        if not self.superstep_k and k is None:
            raise ValueError("superstep_batch needs superstep=K "
                             "(constructor) or an explicit k")
        k_max = self.superstep_k or int(k)
        if k is None:
            k = k_max
        k = min(int(k), k_max)
        budget = (int(round_budget) or self.superstep_rounds
                  or k_max * 512)
        want_stop = (stop_live if stop_live
                     else (int(self._live0 * self.repack_at)
                           if self._live0 * self.repack_at
                           >= self.repack_min else 0))
        group = _pos_group(self.n_v)
        pen_in = self._pen if pen is None else pen
        rem_in = self._rem if rem is None else rem
        cb_in = self._cb if cb is None else cb
        tpos_in = self._tpos if tpos is None else tpos
        t0_in = np.float64(self.t) if t0 is None else t0
        pred_in = self._coll[0] if pred is None else pred
        ready_in = self._coll[1] if ready is None else ready
        clk_in = self._coll_clk if clk is None else clk
        donate = (donate and not speculative
                  and pen is None and rem is None)
        step = _drain_superstep_donate if donate else _drain_superstep
        (pen_out, rem_out, cb_out, tpos_out, pred_out, ready_out,
         clk_out, packed) = step(
            *self._dev, cb_in, self._vb, pen_in, rem_in,
            self._thresh, self._ids_dev,
            np.int32(k), np.int32(budget), np.int32(want_stop),
            _ZERO_BITS, *self._tape, tpos_in,
            pred_in, ready_in, clk_in, *self._coll_edges, t0_in,
            eps=self.eps, n_c=self.n_c, n_v=self.n_v,
            k_max=k_max, group=group, has_bounds=self.has_bounds,
            has_tape=self.has_tape, has_coll=self.has_coll)
        if donate:
            # the dispatch consumed the committed buffers: adopt the
            # outputs NOW so no reachable reference is left deleted
            # (collect re-adopts them, a no-op), and strip the dead
            # inputs from the token so misuse fails loudly
            self._pen, self._rem = pen_out, rem_out
            pen_in = rem_in = None
            opstats.bump("donated_buffers", 2)
        self.supersteps += 1
        opstats.bump("dispatches")
        if speculative:
            self.spec_issued += 1
            opstats.bump("speculations_issued")
        return SuperstepToken(pen_in, rem_in, pen_out, rem_out, packed,
                              k, k_max, want_stop, speculative,
                              cb_in=cb_in, cb_out=cb_out,
                              tpos_out=tpos_out, t0=t0_in,
                              pred_out=pred_out, ready_out=ready_out,
                              clk_out=clk_out)

    def _discard_token(self, tok: SuperstepToken) -> None:
        """Drop an un-collected speculative superstep: processing the
        preceding ring mutated the system, so the dispatch's inputs are
        wrong.  Issue never committed anything, so discarding is O(1) —
        only the device work is wasted (and counted)."""
        self.spec_rolled_back += 1
        opstats.bump("speculations_rolled_back")

    def _superstep_collect(self, tok: SuperstepToken
                           ) -> Tuple[int, List[Tuple[float, List[int]]],
                                      bool]:
        """Commit one issued superstep: make its output arrays the
        committed flow state, fetch its packed ring (the ONLY blocking
        transfer) and replay the events into the host clock/stream.

        Returns ``(n_live, batches, clean)`` — `clean` is the
        speculation-validation verdict: True iff processing this ring
        left the system exactly as an in-flight next superstep assumed
        it (no repack, no stop-trigger decay, flow set still live, the
        dispatch exited _FLAG_OK), so a speculative successor may
        commit; on False the caller must discard in-flight tokens."""
        self._pen, self._rem = tok.pen_out, tok.rem_out
        if self.has_tape:
            self._cb = tok.cb_out
            self._tpos = tok.tpos_out
        if self.has_coll:
            self._coll = (tok.pred_out, tok.ready_out)
            self._coll_clk = tok.clk_out
        k_max = tok.k_max
        p = opstats.timed_fetch(tok.packed)
        self.syncs += 1
        rounds, adv, n_ev = int(p[0]), int(p[1]), int(p[2])
        t_sum = float(p[3])
        if np.isnan(t_sum):
            # a poisoned scenario (e.g. NaN link capacity) makes the
            # whole advance NaN — fail with a cause instead of
            # committing a garbage clock/ring (the solo mirror of the
            # fleet's nan_solve lane quarantine)
            raise RuntimeError(
                "drain solve produced a non-finite clock advance "
                "(NaN)")
        n_live, flag = int(p[4]), int(p[5])
        live_elems = int(p[6])
        o = 7
        adv_dt = p[o:o + k_max]
        adv_nev = p[o + k_max:o + 2 * k_max].astype(np.int64)
        o += 2 * k_max
        ring_n = (self.n_v + (k_max if self.has_tape else 0)
                  + (self.n_v if self.has_coll else 0))
        ring_t = p[o:o + ring_n]
        ring_id = p[o + ring_n:o + 2 * ring_n].astype(np.int64)

        self.rounds += rounds
        opstats.bump("fixpoint_rounds", rounds)
        self.advances += adv
        batches: List[Tuple[float, List[int]]] = []
        start = 0
        # collective rings carry ABSOLUTE dates (the Kahan clock pair is
        # carried across dispatches), so the base folds to zero
        t_base = 0.0 if self.has_coll else self.t
        fired = 0
        coll_fired = 0
        if self.has_tape or self.has_coll:
            # demux the ring: negative ids are tagged entries — fault
            # fires (idx < n_c, into the fault stream) or collective
            # activations (idx >= n_c, flow idx - n_c fired into the
            # activation stream) — neither joins the completion batches
            for i in range(adv):
                end = int(adv_nev[i])
                batch_ids: List[int] = []
                for j in range(start, end):
                    fid = int(ring_id[j])
                    tj = t_base + float(ring_t[j])
                    if fid < 0:
                        idx = -fid - 1
                        if idx >= self.n_c:
                            self.collective_events.append(
                                (tj, idx - self.n_c))
                            coll_fired += 1
                        else:
                            self.fault_events.append((tj, idx))
                            fired += 1
                    else:
                        batch_ids.append(fid)
                        self.events.append((tj, fid))
                batches.append((float(adv_dt[i]), batch_ids))
                start = end
            self._tpos_host += fired
            self._last_fired = fired > 0
            if fired:
                opstats.bump("fault_tape_events", fired)
            if coll_fired:
                opstats.bump("collective_tape_fires", coll_fired)
        else:
            for i in range(adv):
                end = int(adv_nev[i])
                batches.append((float(adv_dt[i]),
                                [int(f) for f in ring_id[start:end]]))
                for j in range(start, end):
                    self.events.append((t_base + float(ring_t[j]),
                                        int(ring_id[j])))
                start = end
        # f64 master clock: one Kahan-compensated dtype total per
        # superstep, accumulated on host in f64 (collective runs carry
        # the absolute clock on device; t_base is 0 there)
        self.t = t_base + t_sum

        if flag == _FLAG_STALLED:
            raise RuntimeError(
                f"drain stalled: no flow holds bandwidth "
                f"({n_live} live)")
        if flag == _FLAG_BUDGET and adv == 0 and rounds >= _MAX_ROUNDS:
            raise RuntimeError("drain solve did not converge")
        repacked = False
        decayed = False
        if self._should_repack(n_live):
            repacked = self._repack_device(n_live, live_elems)
        if not repacked and tok.want_stop and n_live <= tok.want_stop:
            # the stop-for-repack threshold fired but no repack was
            # possible (small live set / dense elements): decay the
            # trigger so the next superstep doesn't exit immediately
            self._live0 = max(n_live, 1)
            decayed = True
        self._last_flag = flag
        if tok.speculative:
            self.spec_committed += 1
            opstats.bump("speculations_committed")
        # a tape fire is a clean-collect boundary for speculation: the
        # spec issue chained from the fired bounds (values were right),
        # but replaying from the committed state keeps the oracle
        # trivially aligned with the unpipelined driver
        clean = (flag == _FLAG_OK and n_live > 0
                 and not repacked and not decayed and not fired)
        if self.on_batches is not None and batches:
            self.on_batches(batches)
        return n_live, batches, clean

    def superstep_batch(self, k: Optional[int] = None,
                        fetch: bool = True, stop_live: int = 0,
                        round_budget: int = 0,
                        donate: bool = False):
        """Dispatch ONE superstep of up to `k` advances and (optionally)
        fetch its packed result — a single transfer.

        Returns (n_live, batches) where batches is a list of
        (dt, [original flow ids]) per executed advance; with
        fetch=False nothing is transferred (replay) and (None, None) is
        returned.  Events/clock/counters are committed on fetch.
        ``donate=True`` (steady-state drivers only — never replay
        paths that keep a batch-start snapshot) lets the dispatch
        reuse the committed (pen, rem) buffers in place."""
        tok = self._superstep_issue(k, stop_live=stop_live,
                                    round_budget=round_budget,
                                    donate=donate)
        if not fetch:
            self._pen, self._rem = tok.pen_out, tok.rem_out
            if self.has_tape:
                self._cb = tok.cb_out
                self._tpos = tok.tpos_out
            if self.has_coll:
                self._coll = (tok.pred_out, tok.ready_out)
                self._coll_clk = tok.clk_out
            return None, None
        n_live, batches, _clean = self._superstep_collect(tok)
        return n_live, batches

    def _run_pipelined(self, max_advances: int) -> None:
        """The speculative superstep driver: keep up to
        ``self.pipeline`` supersteps in flight beyond the one being
        collected, each chained from its predecessor's (immutable,
        double-buffered) output arrays.  Collect order is strictly
        FIFO, so event order, timestamps and clocks are the committed
        prefix of exactly the computation the unpipelined driver runs;
        any unclean collect (repack/decay/rescue/stall/done) discards
        the speculative tail and re-issues from the committed state."""
        budget = max_advances
        inflight: deque = deque()
        issued_k = 0            # advances the in-flight tokens may eat
        n = self.n_v
        try:
            while (n or self._coll_open()) and budget > 0:
                # fill the pipeline: the head issue mirrors the
                # unpipelined k=min(K, remaining); speculative issues
                # only when a FULL K is guaranteed to still be within
                # the advance budget whatever the in-flight tokens
                # consume — otherwise their k would depend on counts
                # the host has not fetched yet
                while (not inflight
                       or (len(inflight) <= self.pipeline
                           and budget - issued_k >= self.superstep_k)):
                    spec = bool(inflight)
                    k = (self.superstep_k if spec
                         else min(self.superstep_k, budget))
                    if inflight:
                        prev = inflight[-1]
                        pen, rem = prev.pen_out, prev.rem_out
                        if self.has_tape:
                            # chain bounds/cursor and derive the f64
                            # base clock DEVICE-side: the same IEEE
                            # add the host collect will perform, so a
                            # committed chain is bit-identical to a
                            # fresh issue from the committed clock
                            cb, tpos = prev.cb_out, prev.tpos_out
                            t0 = prev.t0 + prev.packed[3].astype(
                                jnp.float64)
                        else:
                            cb = tpos = t0 = None
                        if self.has_coll:
                            # the DAG carry (pred counts, ready dates,
                            # Kahan clock pair) chains device-side, so
                            # a committed speculative chain replays the
                            # exact unpipelined recurrence
                            pred, ready = prev.pred_out, prev.ready_out
                            clk = prev.clk_out
                        else:
                            pred = ready = clk = None
                    else:
                        pen = rem = cb = tpos = t0 = None
                        pred = ready = clk = None
                    inflight.append(self._superstep_issue(
                        k, pen=pen, rem=rem, speculative=spec,
                        cb=cb, tpos=tpos, t0=t0,
                        pred=pred, ready=ready, clk=clk,
                        donate=not spec))
                    issued_k += k
                tok = inflight.popleft()
                issued_k -= tok.k
                before = self.advances
                n, _batches, clean = self._superstep_collect(tok)
                budget -= self.advances - before
                if not clean:
                    # speculation mispredicted: processing this ring
                    # mutated the system (repack/decay), hit a tape
                    # fire (clean-collect boundary) or the batch needs
                    # a host-side continuation (rescue/stall) —
                    # discard the in-flight tail and restart from the
                    # committed state
                    if self.has_tape and self._last_fired and inflight:
                        opstats.bump("fault_replays", len(inflight))
                    if self.has_coll and inflight:
                        # schedule exhaustion / stop boundary while a
                        # collective tape is armed: the discarded tail
                        # is replayed from the committed DAG carry
                        opstats.bump("collective_replays",
                                     len(inflight))
                    while inflight:
                        self._discard_token(inflight.popleft())
                    issued_k = 0
                    if (n or self._coll_open()) \
                            and self.advances == before:
                        # the round budget expired inside the first
                        # solve: finish ONE advance (full-budget
                        # superstep when a tape is armed — the fused
                        # rescue path cannot see tape events — else
                        # the chunked fused path)
                        after = self.advances
                        n = self._rescue_one()
                        budget -= 1
                        if self.advances == after \
                                and self._coll_open():
                            raise RuntimeError(
                                "collective schedule deadlocked: "
                                f"{len(self.events)}/"
                                f"{self._coll_total} flows completed "
                                "and nothing is pending")
        finally:
            while inflight:
                self._discard_token(inflight.popleft())

    def _rescue_one(self) -> int:
        """Finish ONE advance after the superstep round budget expired
        inside its first solve.  With a fault tape the rescue must stay
        on the superstep path (the fused kernel would step straight
        over a tape event): re-dispatch k=1 with the FULL round budget
        — its collect raises "did not converge" if even that fails.
        Without a tape, the chunked fused path (which converges across
        dispatches) is cheaper."""
        if self.has_tape or self.has_coll:
            n, _ = self.superstep_batch(k=1, round_budget=_MAX_ROUNDS,
                                        donate=True)
            return n
        return self._advance_fused()

    def _coll_open(self) -> bool:
        """True while an armed collective schedule still owes
        completions: a superstep may exit with zero LIVE flows while
        dormant successors wait on pending activation dates, so the
        drivers must keep dispatching until every DAG flow completed."""
        return self.has_coll and len(self.events) < self._coll_total

    def run(self, max_advances: int = 10_000_000) -> None:
        n = self.n_v
        if self.superstep_k and self.pipeline:
            self._run_pipelined(max_advances)
            return
        if self.superstep_k:
            while (n or self._coll_open()) and max_advances > 0:
                before = self.advances
                k = min(self.superstep_k, max_advances)
                n, _ = self.superstep_batch(k=k, donate=True)
                max_advances -= self.advances - before
                if (n or self._coll_open()) and self.advances == before:
                    # the round budget expired inside the first solve:
                    # finish ONE advance, then resume
                    n = self._rescue_one()
                    max_advances -= 1
                    if self.advances == before and self._coll_open():
                        # no live flow, no pending activation, but the
                        # schedule still owes completions: a cyclic or
                        # truncated DAG would spin here forever
                        raise RuntimeError(
                            "collective schedule deadlocked: "
                            f"{len(self.events)}/{self._coll_total} "
                            "flows completed and nothing is pending")
            return
        while n and max_advances:
            n = self.advance()
            max_advances -= 1
