"""ctypes bridge to the native (C++) exact LMM solver (native/lmm.cc).

Third solver backend next to the exact Python list solver and the JAX
fixpoint: same flatten/solve/scatter handoff as the JAX backend
(lmm_jax.solve_jax), but the solve itself runs in native code — the
host-side floor of the auto dispatch (small live sets stay native-fast,
large ones go to the device; SURVEY.md hard part (e)).

The shared library is built on demand from native/lmm.cc with g++ (no
pip/pybind11 dependency; plain C ABI)."""

from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional

import numpy as np

from .lmm_host import System

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_LIB_PATH = os.path.join(_NATIVE_DIR, "libsimgrid_lmm.so")

_lib = None
_lib_error: Optional[str] = None


def _build_library() -> None:
    src = os.path.join(_NATIVE_DIR, "lmm.cc")
    subprocess.run(
        ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", _LIB_PATH,
         src],
        check=True, capture_output=True, text=True)


def load_library():
    """Load (building if needed) the native solver; None if unavailable."""
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    try:
        if not os.path.exists(_LIB_PATH):
            _build_library()
        lib = ctypes.CDLL(_LIB_PATH)
    except (OSError, subprocess.CalledProcessError) as exc:
        _lib_error = str(exc)
        return None
    lib.lmm_solve_coo.restype = ctypes.c_int32
    # raw pointers, not np.ctypeslib.ndpointer: the per-call from_param
    # validation machinery cost ~18s of a 175s Chord run (the solver
    # itself was 10s); callers guarantee dtype/contiguity
    lib.lmm_solve_coo.argtypes = [
        ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ctypes.c_void_p, ctypes.c_double,
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
    ]
    _lib = lib
    return _lib


def available() -> bool:
    return load_library() is not None


def solve_coo(e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty, v_bound,
              eps: float, n_e: int, n_c: int, n_v: int):
    """Solve a flattened COO system natively; returns (values, remaining,
    usage) over the first n_v / n_c slots."""
    lib = load_library()
    if lib is None:
        raise RuntimeError(f"native LMM solver unavailable: {_lib_error}")
    values = np.empty(n_v, np.float64)
    remaining = np.empty(n_c, np.float64)
    usage = np.empty(n_c, np.float64)
    a = (np.ascontiguousarray(e_var[:n_e], np.int32),
         np.ascontiguousarray(e_cnst[:n_e], np.int32),
         np.ascontiguousarray(e_w[:n_e], np.float64),
         np.ascontiguousarray(c_bound[:n_c], np.float64),
         np.ascontiguousarray(c_fatpipe[:n_c], np.uint8),
         np.ascontiguousarray(v_penalty[:n_v], np.float64),
         np.ascontiguousarray(v_bound[:n_v], np.float64))
    lib.lmm_solve_coo(
        n_c, n_v, n_e,
        a[0].ctypes.data, a[1].ctypes.data, a[2].ctypes.data,
        a[3].ctypes.data, a[4].ctypes.data, a[5].ctypes.data,
        a[6].ctypes.data, float(eps),
        values.ctypes.data, remaining.ctypes.data, usage.ctypes.data)
    return values, remaining, usage


def _solve_flat(arrays, eps):
    return solve_coo(
        arrays.e_var, arrays.e_cnst, arrays.e_w, arrays.c_bound,
        arrays.c_fatpipe, arrays.v_penalty, arrays.v_bound, eps,
        arrays.n_elem, arrays.n_cnst, arrays.n_var)


def solve_native(system: System) -> None:
    """Backend entry: flatten host graph, solve natively, scatter back
    (same side-effect contract as lmm_jax.solve_jax)."""
    from .lmm_jax import solve_flattened
    solve_flattened(system, np.float64, _solve_flat)
