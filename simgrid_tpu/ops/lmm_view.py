"""Array-primary incremental view of an LMM System (TPU-native hot path).

The reference rebuilds its solver state by walking intrusive lists on
every solve (maxmin.cpp:509-539), which is fine at C++ speeds; our
device backend initially did the same through ``flatten()`` and the
O(E) Python walk became the simulation bottleneck at scale (~5 s per
time advance at 100k flows — the solve itself was milliseconds).

This view keeps the padded COO arrays (see lmm_jax.LmmArrays) alive
across solves and applies every System mutation incrementally:

* new constraints / variables take slots from a free list (O(1));
* ``expand`` appends element triples into bucketed spare capacity
  (O(1) amortized);
* enable/disable/penalty/bound updates are single array writes — the
  device kernel already derives element validity from
  ``(e_w > 0) & (v_penalty > 0)``, so enabling a variable after its
  latency phase (the hottest structural event in the advance loop) is
  a pure value update here;
* freeing a variable zeroes its elements' weights (masked out on
  device) and recycles the slot; dead element slots are compacted away
  only when they outnumber live ones (amortized O(1) per free).

Mutated fields are handed to the solver as copy-on-write snapshots:
an unchanged field keeps its previous ndarray identity, so the
device-side per-array cache re-uploads only what actually changed —
on a tunneled accelerator where every transfer costs 150-500 ms this
is the difference between one small upload and eleven large ones.

Beyond whole-field dirtiness the view also tracks dirty *indices* per
named consumer (``consume``): the warm-start solver (ops.lmm_warm)
keeps the master arrays resident on device and applies mutations as
one indexed scatter update, so its upload cost scales with the number
of touched slots instead of field size.  The drain fast path
(ops.drain_path) registers the same way under the name ``"drain"``
and uses the dirty-index map as a mutation CLASSIFIER: together with
``version``/``expected_frees`` it decides per batch whether the
engine's transitions are resumable (scattered into the live device
plan as one transition payload) or a true plan invalidation.  Index
tracking is only meaningful while slot numbering is stable, so every
renumbering or reallocation (growth, ``_compact``) bumps
``layout_epoch`` — consumers treat an epoch change as
everything-dirty.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from .lmm_host import SharingPolicy
from .lmm_jax import LmmArrays, _bucket

#: Fields whose mutation does not change the element structure.
_FIELDS = ("e_var", "e_cnst", "e_w", "c_bound", "c_fatpipe",
           "v_penalty", "v_bound")


class ArrayView:
    """Incrementally-maintained flat arrays for one System."""

    #: fields cast to the requested solve dtype on handout (masters are
    #: always float64 so native-f64 and jax-f32 dispatch can alternate
    #: without rebuilding the view)
    _CAST_FIELDS = ("e_w", "c_bound", "v_penalty", "v_bound")

    def __init__(self, system):
        self.system = system
        self.dtype = np.float64          # master array dtype
        #: mutation census for plan-based consumers (the drain fast
        #: path): bumped ONCE per mutation event (not once per touched
        #: field) by every hook EXCEPT the free of a variable the
        #: consumer pre-registered in `expected_frees` — retiring a
        #: flow the device plan already retired changes nothing the
        #: plan does not know about
        self.version = 0
        #: bumped whenever slot numbering or array allocation changes
        #: (growth, _compact): per-index dirtiness from before the bump
        #: no longer addresses the same data
        self.layout_epoch = 0
        self.expected_frees: set = set()
        #: per-requested-dtype dirty sets and handout snapshots
        self._dirty: Dict[np.dtype, set] = {}
        self._handout: Dict[np.dtype, Dict[str, np.ndarray]] = {}
        #: named consumers tracking dirty INDICES per field (see
        #: consume()); value per field is a set of slots, or True when
        #: index identity was lost (whole field dirty)
        self._consumers: Dict[str, Dict[str, object]] = {}
        self._free_var_slots: List[int] = []
        self._free_cnst_slots: List[int] = []
        self.slot_var: List = []
        self.slot_cnst: List = []
        self.n_elem = 0
        self.dead_elems = 0
        self._build()
        system.array_view = self

    # -- initial build ----------------------------------------------------
    def _build(self) -> None:
        """Walk the existing System once (same element order as
        lmm_jax.flatten: per-constraint, enabled then disabled) and
        seed the arrays."""
        sys_ = self.system
        cnsts = list(sys_.constraint_set)
        variables = list(sys_.variable_set)
        n_c, n_v = len(cnsts), len(variables)
        e_triples = []
        var_slot: Dict[int, int] = {}
        self.slot_var = list(variables)
        self.slot_cnst = list(cnsts)
        for slot, var in enumerate(variables):
            var._view_slot = slot
            var_slot[id(var)] = slot
        for ci, cnst in enumerate(cnsts):
            cnst._view_slot = ci
            for elem in list(cnst.enabled_element_set) + \
                    list(cnst.disabled_element_set):
                e_triples.append((elem, var_slot[id(elem.variable)], ci))
        n_e = len(e_triples)
        E, C, V = _bucket(max(n_e, 1)), _bucket(max(n_c, 1)), \
            _bucket(max(n_v, 1))
        self.e_var = np.zeros(E, np.int32)
        self.e_cnst = np.zeros(E, np.int32)
        self.e_w = np.zeros(E, self.dtype)
        self.c_bound = np.zeros(C, self.dtype)
        self.c_fatpipe = np.zeros(C, bool)
        self.v_penalty = np.zeros(V, self.dtype)
        self.v_bound = np.full(V, -1.0, self.dtype)
        for k, (elem, vs, cs) in enumerate(e_triples):
            elem._view_eslot = k
            self.e_var[k] = vs
            self.e_cnst[k] = cs
            self.e_w[k] = elem.consumption_weight
        for ci, cnst in enumerate(cnsts):
            self.c_bound[ci] = cnst.bound
            self.c_fatpipe[ci] = cnst.sharing_policy == SharingPolicy.FATPIPE
        for slot, var in enumerate(variables):
            self.v_penalty[slot] = var.sharing_penalty
            self.v_bound[slot] = var.bound
        self.n_elem = n_e
        self.dead_elems = 0

    # -- mutation hooks (called from System) ------------------------------
    # Each hook bumps `version` exactly ONCE per mutation event (plan
    # invalidation censuses count mutations, not fields) and marks the
    # touched field/slot pairs via _mark.
    def _mark(self, field: str, idx=None) -> None:
        """Record `field` (slot `idx`, or the whole field when None) as
        dirty for every handout dtype and every index consumer."""
        for dt in sorted(self._dirty, key=str):
            self._dirty[dt].add(field)
        for name in sorted(self._consumers):
            cons = self._consumers[name]
            cur = cons[field]
            if cur is True:
                continue
            if idx is None:
                cons[field] = True
            else:
                cur.add(idx)

    def consume(self, name: str):
        """Hand the named consumer its accumulated dirty-index map
        ({field: set-of-slots | True}) and reset it.  Returns None on
        the first call (unseen consumer: everything is dirty).  Index
        validity is scoped to `layout_epoch`: after an epoch bump the
        returned indices address renumbered slots and must be ignored
        in favor of a full refresh."""
        prev = self._consumers.get(name)
        self._consumers[name] = {f: set() for f in _FIELDS}
        return prev

    def on_policy(self, cnst) -> None:
        self.version += 1
        self.c_fatpipe[cnst._view_slot] = \
            cnst.sharing_policy == SharingPolicy.FATPIPE
        self._mark("c_fatpipe", cnst._view_slot)

    def on_new_cnst(self, cnst) -> None:
        self.version += 1
        if self._free_cnst_slots:
            slot = self._free_cnst_slots.pop()
            self.slot_cnst[slot] = cnst
        else:
            slot = len(self.slot_cnst)
            self.slot_cnst.append(cnst)
            if slot >= len(self.c_bound):
                grow = _bucket(slot + 1, grow=True)
                cb = np.zeros(grow, self.dtype)
                cb[:len(self.c_bound)] = self.c_bound
                self.c_bound = cb
                fat = np.zeros(grow, bool)
                fat[:len(self.c_fatpipe)] = self.c_fatpipe
                self.layout_epoch += 1
                self.c_fatpipe = fat
                self._mark("c_bound")
                self._mark("c_fatpipe")
        cnst._view_slot = slot
        self.c_bound[slot] = cnst.bound
        self.c_fatpipe[slot] = cnst.sharing_policy == SharingPolicy.FATPIPE
        self._mark("c_bound", slot)
        self._mark("c_fatpipe", slot)

    def on_new_var(self, var) -> None:
        self.version += 1
        if self._free_var_slots:
            slot = self._free_var_slots.pop()
            self.slot_var[slot] = var
        else:
            slot = len(self.slot_var)
            self.slot_var.append(var)
            if slot >= len(self.v_penalty):
                grow = _bucket(slot + 1, grow=True)
                vp = np.zeros(grow, self.dtype)
                vp[:len(self.v_penalty)] = self.v_penalty
                self.v_penalty = vp
                vb = np.full(grow, -1.0, self.dtype)
                vb[:len(self.v_bound)] = self.v_bound
                self.layout_epoch += 1
                self.v_bound = vb
                self._mark("v_penalty")
                self._mark("v_bound")
        var._view_slot = slot
        self.v_penalty[slot] = var.sharing_penalty
        self.v_bound[slot] = var.bound
        self._mark("v_penalty", slot)
        self._mark("v_bound", slot)

    def on_expand(self, elem) -> None:
        self.version += 1          # ONE bump per structural mutation
        k = self.n_elem
        if k >= len(self.e_var):
            grow = _bucket(k + 1, grow=True)
            ev = np.zeros(grow, np.int32); ev[:len(self.e_var)] = self.e_var
            ec = np.zeros(grow, np.int32); ec[:len(self.e_cnst)] = self.e_cnst
            self.e_var, self.e_cnst = ev, ec
            ew = np.zeros(grow, self.dtype)
            ew[:len(self.e_w)] = self.e_w
            self.layout_epoch += 1
            self.e_w = ew
            self._mark("e_var")
            self._mark("e_cnst")
            self._mark("e_w")
        elem._view_eslot = k
        self.e_var[k] = elem.variable._view_slot
        self.e_cnst[k] = elem.constraint._view_slot
        self.e_w[k] = elem.consumption_weight
        self.n_elem = k + 1
        self._mark("e_var", k)
        self._mark("e_cnst", k)
        self._mark("e_w", k)

    def on_weight(self, elem) -> None:
        self.version += 1
        self.e_w[elem._view_eslot] = elem.consumption_weight
        self._mark("e_w", elem._view_eslot)

    def on_penalty(self, var) -> None:
        self.version += 1
        self.v_penalty[var._view_slot] = var.sharing_penalty
        self._mark("v_penalty", var._view_slot)

    def on_vbound(self, var) -> None:
        self.version += 1
        self.v_bound[var._view_slot] = var.bound
        self._mark("v_bound", var._view_slot)

    def on_cbound(self, cnst) -> None:
        self.version += 1
        self.c_bound[cnst._view_slot] = cnst.bound
        self._mark("c_bound", cnst._view_slot)

    def on_var_free(self, var) -> None:
        """Called BEFORE var.cnsts is cleared: kill the elements on
        device (zero weight) and recycle the variable slot."""
        # an expected free (a retirement the drain fast path already
        # applied on device) leaves the plan-consistency version alone;
        # the dirty-index marks still happen — device-resident masters
        # must see the zeroing either way
        bump = True
        if self.expected_frees:
            bump = id(var) not in self.expected_frees
            if not bump:
                self.expected_frees.discard(id(var))
        if bump:
            self.version += 1
        for elem in var.cnsts:
            self.e_w[elem._view_eslot] = 0.0
            self.dead_elems += 1
            self._mark("e_w", elem._view_eslot)
        slot = var._view_slot
        self.v_penalty[slot] = 0.0
        self.slot_var[slot] = None
        self._free_var_slots.append(slot)
        self._mark("v_penalty", slot)

    def on_cnst_free(self, cnst) -> None:
        self.version += 1
        slot = cnst._view_slot
        self.c_bound[slot] = 0.0
        self.slot_cnst[slot] = None
        self._free_cnst_slots.append(slot)
        self._mark("c_bound", slot)

    # -- solve-side -------------------------------------------------------
    def _compact(self) -> None:
        """Drop dead element slots (weight 0 from freed variables).
        Live zero-weight elements (e.g. staged concurrency edges) are
        kept: they are re-registered from their objects."""
        keep = []
        for cnst in self.slot_cnst:
            if cnst is None:
                continue
            for elem in (list(cnst.enabled_element_set)
                         + list(cnst.disabled_element_set)):
                keep.append(elem)
        n_e = len(keep)
        E = _bucket(max(n_e, 1))
        e_var = np.zeros(E, np.int32)
        e_cnst = np.zeros(E, np.int32)
        e_w = np.zeros(E, self.dtype)
        for k, elem in enumerate(keep):
            elem._view_eslot = k
            e_var[k] = elem.variable._view_slot
            e_cnst[k] = elem.constraint._view_slot
            e_w[k] = elem.consumption_weight
        self.e_var, self.e_cnst, self.e_w = e_var, e_cnst, e_w
        self.n_elem = n_e
        self.dead_elems = 0
        self.version += 1          # element slots renumbered
        self.layout_epoch += 1
        self._mark("e_var")
        self._mark("e_cnst")
        self._mark("e_w")

    def maybe_compact(self) -> None:
        """Drop dead element slots once they outnumber live ones
        (amortized O(1) per free); bumps layout_epoch when it runs."""
        if self.dead_elems > max(64, self.n_elem - self.dead_elems):
            self._compact()

    def snapshot(self, dtype) -> LmmArrays:
        """Copy-on-write handout in the requested dtype: dirty fields
        get a fresh copy (new identity => device re-upload), clean
        fields keep their previous object (device cache hit)."""
        self.maybe_compact()
        key = np.dtype(dtype)
        if key not in self._handout:
            self._handout[key] = {}
            self._dirty[key] = set(_FIELDS)
        h, dirty = self._handout[key], self._dirty[key]
        for f in dirty:
            src = getattr(self, f)
            h[f] = src.astype(key) if f in self._CAST_FIELDS \
                else src.copy()
        dirty.clear()
        return LmmArrays(h["e_var"], h["e_cnst"], h["e_w"], h["c_bound"],
                         h["c_fatpipe"], h["v_penalty"], h["v_bound"],
                         self.n_elem, len(self.slot_cnst),
                         len(self.slot_var))

