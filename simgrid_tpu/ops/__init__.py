"""Device-side numerical kernels (JAX/XLA/Pallas) + their exact host oracles.

f64 is enabled globally: the solver's epsilon semantics (maxmin/precision,
reference maxmin.cpp:12-14) are defined on doubles.  TPU executions opt
into f32 explicitly via the ``lmm/dtype`` flag.
"""

import jax

jax.config.update("jax_enable_x64", True)

from .lmm_host import (System, Constraint, Variable, Element, SharingPolicy,  # noqa: E402
                       make_new_maxmin_system, double_update, double_positive,
                       double_equals)
from . import lmm_jax  # noqa: E402

__all__ = ["System", "Constraint", "Variable", "Element", "SharingPolicy",
           "make_new_maxmin_system", "double_update", "double_positive",
           "double_equals", "lmm_jax"]
