"""Engine-side drain fast path: delegate pure-drain phases — and, with
``drain/transitions``, whole compute/comm ALTERNATION phases — to the
device-resident superstep executor.

A *pure-drain phase* is the shape the end-to-end north star degenerates
to (BASELINE config #4): every started network flow has paid its
latency, none carries a deadline, and no profile event fires before the
next completion — the maestro's loop is then exactly

    solve rates -> dt to next completion -> retire flows

per advance, costing >= 3 host<->device syncs plus an O(V) Python walk
each time through the generic `Model::update_actions_state` path.  This
module detects that phase from `NetworkCm02Model`'s FULL-mode hooks and
serves *batches* of advances from one `DrainSim` superstep dispatch
(ops.lmm_drain), keeping completion-event ordering identical:

* completions are emitted by walking `started_action_set` in order and
  finishing exactly the planned set — the same traversal order the
  generic path uses;
* the plan is built from the incrementally-maintained ArrayView
  (ops.lmm_view) — no graph walk — and audited by its mutation
  `version` counter, with the frees caused by *our own* served
  completions whitelisted (`expected_frees`);
* with ``drain/pipeline`` > 0 the NEXT superstep is issued
  speculatively the moment ring N is fetched — JAX dispatch is async,
  so the device executes ring N+1 while the engine consumes ring N's
  batches, and the next fetch finds a ready buffer instead of paying
  the tunnel round trip.  Speculation never touches the committed
  flow state (the dispatch chains from double-buffered immutable
  arrays), so ANY plan teardown — profile event before the horizon,
  an unrecognized ArrayView mutation, a stall — simply discards the
  in-flight token and the existing deterministic-replay rollback
  proceeds exactly as in the unpipelined path.  Event order,
  timestamps and clocks are bit-identical to ``drain/pipeline:0``
  (enforced by ``tools/check_determinism.py --runtime-pipeline``).

Device-resident mutating phases (``drain/transitions``, the PR 9
tentpole): the mutation census is a CLASSIFIER, not a tripwire.  When
the ArrayView version moves while a plan is live, the per-consumer
dirty-INDEX map (``ArrayView.consume("drain")``) is classified:

* **resumable transitions** — a latency wake or suspend/resume
  (v_penalty), a bound/weight change (c_bound / v_bound / e_w from
  set_bandwidth, set_latency, TCP windows), a NEW flow posted on
  existing routes (recycled or fresh variable slot + appended element
  slots within the plan's padded capacity), or the echo of our own
  retirements — are batched into ONE fused indexed *transition
  payload* (the lmm_warm delta-upload shape: [indices..., values...]
  runs with a static layout tuple) and scattered into the live device
  plan (`DrainSim.apply_transitions`).  No re-flatten, no platform
  re-upload; the superstep resumes from the patched state.
* **true invalidations** — a layout epoch bump (array reallocation /
  compaction), whole-field dirtiness, sharing-policy changes, a
  fatpipe route, deadlines, route-less flows, non-finite (parked)
  penalties, or any lane the classifier cannot attribute to a started
  action — keep today's bit-identical replay fallback: rewind to the
  served prefix, write remains/rates back, hand the phase to the
  generic loop.

Latency phases ride the plan as *invisible lanes* (device penalty 0 —
not flowing), so a comm wave is planned the moment it is posted:
`serve` returns min(plan dt, min latency) and `apply` replicates the
generic walk's latency double_update + wake (the wake's penalty update
is itself absorbed as a transition on the next serve).  An engine
advance decided by ANOTHER model (a CPU exec completing mid-drain)
becomes a forced partial advance ON DEVICE (`DrainSim.partial_advance`
— the same strict-< retirement rule at an externally fixed delta)
instead of a plan teardown.  Together these keep the compute/comm
alternation of the SMPI NAS workloads on the superstep path end to
end; coverage is counted per run (`fastpath_advances` vs
`native_advances`, plus the `drain_cause_*` histogram) and bit-identity
against the native path is enforced by
``tools/check_determinism.py --runtime-phase``.

Precision: f64 plans retire flows at the engine's absolute
`maxmin/precision * surf/precision` threshold — bit-matching the
generic double_update path — while f32 plans use the RELATIVE
`drain/done-eps * size` rule so chip-precision ties stay grouped
(see ops.lmm_drain).

Fidelity trade documented in README: while a plan is being served, the
`remains` of still-live flows and link usage introspection lag until
the plan ends (they are synced on every invalidation); actors in a
drain are blocked in comm waits, so nothing observes the lag.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.config import config
from . import opstats
from .lmm_host import double_update

#: started-flow census below which a plan is never attempted (plan
#: bookkeeping beats the generic path only at scale); the config flag
#: drain/min-flows overrides per run.
_MIN_FLOWS_FLOOR = 8


def _plan_inputs(model, dtype, allow_latency: bool = False):
    """The drain precondition walk + flattened state, shared by the
    fast path's plan builder and the campaign capture: one O(V) pass
    maps view slots to started actions and rejects anything the device
    plan has no semantics for (deadlines, route-less flows, live
    non-flow variables, zero remains — and, unless ``allow_latency``,
    latency phases and suspensions).  With ``allow_latency`` (the
    drain/transitions mode) latency-phase and suspended actions are
    accepted as INVISIBLE lanes (device penalty 0, not flowing) and
    their slots returned in `lat_slots`.  Returns
    ``(slot_action, view, snap, sizes, rem, pen, lat_slots)`` or None.
    """
    from ..kernel.resource import NO_MAX_DURATION
    from .lmm_view import ArrayView

    system = model.system
    view = system.array_view
    if view is None:
        view = ArrayView(system)

    slot_action: Dict[int, object] = {}
    lat_slots: set = set()
    for action in model.started_action_set:
        var = action.variable
        if (var is None
                or action.max_duration != NO_MAX_DURATION
                or var.get_number_of_constraint() == 0):
            return None
        if (action.latency > 0 or action.is_suspended()
                or var.sharing_penalty <= 0):
            if not allow_latency:
                return None
            if action.latency > 0:
                lat_slots.add(var._view_slot)
        slot_action[var._view_slot] = action

    snap = view.snapshot(dtype)
    # NOTE: snapshot() may compact, which renumbers element slots
    # but not variable slots — the slot map above stays valid.
    pen_all = snap.v_penalty
    live = np.flatnonzero(pen_all > 0)
    # a live variable that is NOT a started flow (e.g. a failed
    # action not yet reaped) shares bandwidth in the generic solve:
    # not servable by a plan
    if not all(int(s) in slot_action for s in live):
        return None
    if not allow_latency and len(live) != len(slot_action):
        return None

    n_v = len(pen_all)
    sizes = np.ones(n_v)
    rem = np.zeros(n_v)
    pen = np.zeros(n_v, dtype)
    for slot, action in sorted(slot_action.items()):
        sizes[slot] = max(action.cost, 1.0)
        rem[slot] = action.get_remains_no_update()
        pen[slot] = pen_all[slot]
    if np.any(rem[live] <= 0):
        return None         # zero-remains flows: let generic finish
    return slot_action, view, snap, sizes, rem, pen, lat_slots


def classify_phase(sim) -> str:
    """Classify what kind of phase a drain executor is walking, from
    its armed device tapes: ``collective-tape`` (a comm-DAG schedule
    tape drives activations on device — optionally composed with a
    fault tape as ``collective-tape+faults``), ``fault-tape`` (link
    events only) or ``pure-drain``.  Bumps the matching
    ``phase_<kind>`` opstats counter so the phase mix shows up in
    ``tools/e2e_drain.py --phase-stats`` and on campaign rows.
    Accepts any executor with the DrainSim flag surface (DrainSim,
    BatchDrainSim, a fast-path plan)."""
    has_coll = bool(getattr(sim, "has_coll", False))
    has_tape = bool(getattr(sim, "has_tape", False))
    if has_coll:
        kind = ("collective-tape+faults" if has_tape
                else "collective-tape")
    elif has_tape:
        kind = "fault-tape"
    else:
        kind = "pure-drain"
    opstats.bump("phase_" + kind.replace("-", "_").replace("+", "_"))
    return kind


def capture_scenario(model):
    """Snapshot the model's CURRENT pure-drain phase as the shared base
    scenario of a batched campaign (parallel.campaign.Campaign): the
    same preconditions as the fast path's plan builder, returned as
    plain numpy arrays plus the slot->action and constraint->link-name
    maps a campaign needs to label its dimensions.  None when the
    phase is not a pure drain."""
    plan = _plan_inputs(model, np.float64)
    if plan is None:
        return None
    slot_action, view, snap, sizes, rem, pen, _lat = plan
    E = snap.n_elem
    names = [getattr(getattr(c, "id", None), "name", None)
             for c in view.slot_cnst]
    names += [None] * (len(snap.c_bound) - len(names))
    return dict(e_var=snap.e_var[:E].copy(),
                e_cnst=snap.e_cnst[:E].copy(),
                e_w=snap.e_w[:E].copy(),
                c_bound=snap.c_bound.copy(),
                sizes=sizes, remains=rem,
                penalty=pen.astype(np.float64),
                v_bound=snap.v_bound.copy(),
                link_names=names,
                slot_action=dict(slot_action))


class DrainFastPath:
    """Per-network-model drain plan server (see module docstring)."""

    def __init__(self, model):
        self.model = model
        self.sim = None                     # active DrainSim, or None
        self.phase_kind = "none"            # classify_phase at build
        self.slot_action: Dict[int, object] = {}
        self.lat_actions: Dict[int, object] = {}   # latency-phase lanes
        self.live_slots: set = set()        # slots with device pen > 0
        self.version = -1                   # ArrayView version at build
        self.epoch = -1                     # ArrayView layout epoch
        self.absorbing = False              # transitions enabled at build
        self._done_mode = "abs"
        self._done_eps = 0.0
        self.batches: List[Tuple[float, List[int]]] = []
        self.saved = None                   # (pen, rem) at batch start
        self.served = 0                     # advances of current batch
        self.spec = None                    # in-flight speculative token
        # observability (asserted by tests, reported by tools)
        self.plans = 0
        self.advances_served = 0
        self.invalidations = 0
        self.rollbacks = 0
        self.speculations = 0
        self.spec_commits = 0
        self.spec_discards = 0
        self.transitions_absorbed = 0
        self.transition_slots = 0
        self.partial_advances = 0

    # -- eligibility -------------------------------------------------------

    def _enabled(self) -> bool:
        mode = config["drain/fastpath"]
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"Unknown drain/fastpath {mode!r} "
                             "(expected auto, on or off)")
        if mode == "off":
            return False
        backend = config["lmm/backend"]
        if backend not in ("jax", "auto"):
            return False
        model = self.model
        # FULL-mode only (the hooks live in next_occurring_event_full);
        # selective-update systems are fine: served completions feed
        # the modified set through the var-free closure, so the warm
        # solver (ops.lmm_warm) picks up exactly where the plan left
        # off when the drain phase ends
        if model.is_lazy():
            return False
        n = len(model.started_action_set)
        if n < max(int(config["drain/min-flows"]), _MIN_FLOWS_FLOOR):
            return False
        if backend == "auto" and n < config["lmm/jax-threshold"]:
            return False
        if model.latency_phase_count and not self._transitions_enabled():
            # without transition absorption the plan cannot see latency
            # wakes; with it, latency phases ride as invisible lanes
            return False
        return True

    def _transitions_enabled(self) -> bool:
        mode = config["drain/transitions"]
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"Unknown drain/transitions {mode!r} "
                             "(expected auto, on or off)")
        return mode != "off"

    def _build(self) -> bool:
        """One O(V) walk to check the drain preconditions and map view
        slots to actions, then a snapshot + DrainSim construction.
        Amortized over the K advances each superstep serves."""
        from .lmm_drain import DrainSim

        dtype = (np.float32 if config["lmm/dtype"] == "float32"
                 else np.float64)
        absorbing = self._transitions_enabled()
        plan = _plan_inputs(self.model, dtype, allow_latency=absorbing)
        if plan is None:
            return False
        slot_action, view, snap, sizes, rem, pen, lat_slots = plan

        # fatpipe constraints (the default loopback) have no drain
        # program (the superstep kernel hardcodes SHARED): refuse the
        # plan while any mapped element rides one
        used = np.zeros(len(snap.c_bound), bool)
        used[snap.e_cnst[snap.e_w > 0]] = True
        if np.any(used & snap.c_fatpipe):
            return False

        if dtype == np.float64:
            done_mode = "abs"
            done_eps = (config["maxmin/precision"]
                        * config["surf/precision"])
        else:
            done_mode = "rel"
            done_eps = config["drain/done-eps"]

        # the plan spans the FULL padded view arrays (not the tight
        # n_elem slice): the pow2 slack is what lets transition
        # payloads append new flows' elements without a re-upload —
        # padding carries weight 0 and is masked by the solver
        sim = DrainSim(
            snap.e_var, snap.e_cnst, snap.e_w,
            snap.c_bound, sizes,
            eps=config["maxmin/precision"], done_eps=done_eps,
            dtype=dtype, done_mode=done_mode,
            v_bound=snap.v_bound,
            superstep=int(config["drain/superstep"]),
            penalty=pen, remains=rem,
            # device repacks would detach the replay snapshot from the
            # element tables; plans are rebuilt often enough that the
            # view's own host-side compaction covers shrinkage
            repack_min=1 << 62)
        self.sim = sim
        self.phase_kind = classify_phase(sim)
        self.slot_action = slot_action
        self.lat_actions = {s: slot_action[s]
                            for s in sorted(lat_slots)}
        self.live_slots = {int(s) for s in np.flatnonzero(pen > 0)}
        self.version = view.version
        self.epoch = view.layout_epoch
        self.absorbing = absorbing
        self._done_mode = done_mode
        self._done_eps = float(done_eps)
        view.consume("drain")      # reset the dirty-index census
        self.batches = []
        self.saved = None
        self.served = 0
        self.spec = None
        self.plans += 1
        return True

    # -- plan serving ------------------------------------------------------

    def _discard_spec(self) -> None:
        """Drop the in-flight speculative superstep (mispredict: the
        plan is being invalidated or patched, or its batch never
        materialized).  Issue never committed anything, so there is no
        state to restore — only the device work is wasted (and
        counted)."""
        if self.spec is not None:
            if self.sim is not None:
                self.sim._discard_token(self.spec)
            self.spec_discards += 1
            self.spec = None

    def _sync_to_served(self) -> None:
        """Rewind the committed device flow state to the advances
        actually served to the engine: deterministic replay of the
        served prefix from the immutable batch-start arrays, then drop
        the now-stale batch tail.  No-op when nothing is outstanding
        (the committed state already IS the served state)."""
        sim = self.sim
        if self.batches and self.saved is not None:
            sim._pen, sim._rem = self.saved
            if self.served:
                sim.superstep_batch(k=self.served, fetch=False)
            self.rollbacks += 1
        self.batches = []
        self.saved = None
        self.served = 0

    def _dispatch_batch(self) -> bool:
        """Collect one superstep (the in-flight speculative one when
        the prediction held, else a fresh dispatch + fetch); False when
        it made no progress (solve exceeded the round budget, or the
        drain stalled — a parked/zero-rate remainder the generic path
        knows how to diagnose)."""
        sim = self.sim
        tok, self.spec = self.spec, None
        if tok is None:
            tok = sim._superstep_issue()
        # batch-start snapshot for deterministic replay: the token's
        # input arrays ARE the pre-dispatch state (immutable, O(1))
        self.saved = (tok.pen_in, tok.rem_in)
        self.served = 0
        try:
            n_live, batches, clean = sim._superstep_collect(tok)
        except RuntimeError:
            # stall/non-convergence surfaced mid-batch: the advances it
            # applied were never served, so restore the batch-start
            # state (immutable arrays: an O(1) rollback) and hand the
            # phase back to the generic path
            sim._pen, sim._rem = self.saved
            return False
        if tok.speculative:
            self.spec_commits += 1
        if not batches:
            return False
        self.batches = batches
        if clean and int(config["drain/pipeline"]) > 0:
            # speculative issue of the NEXT superstep: the device
            # executes ring N+1 while the engine consumes ring N's
            # batches below (plans keep ONE token in flight — each
            # ring already covers K engine advances of host work)
            self.spec = sim._superstep_issue(speculative=True)
            self.speculations += 1
        return True

    def serve(self, now: float) -> Optional[float]:
        """next_occurring_event_full hook: the dt to the next planned
        completion or latency expiry, or None to fall back to the
        generic path (None with a live idle plan means no started
        action — the plan is parked awaiting the next wave)."""
        model = self.model
        if self.sim is not None:
            view = model.system.array_view
            if view is None:
                self._invalidate(sync=True)
            else:
                # mirror the native path's per-advance compaction
                # cadence: the generic solve runs maybe_compact() every
                # next-event, and the per-constraint element ORDER it
                # produces decides the usage sums' rounding — serving
                # from a stale layout would drift the rates a ulp off
                # the host walk.  A compaction here epoch-bumps the
                # view, which is a full (bit-identical) replay below.
                view.maybe_compact()
                if view.layout_epoch != self.epoch:
                    self._invalidate(sync=True)
                elif view.version != self.version:
                    if not (self.absorbing and self._absorb()):
                        self._invalidate(sync=True)
        if self.sim is not None \
                and len(self.lat_actions) != model.latency_phase_count:
            # an action left the latency census behind the view's back
            # (cancel/kill carries no LMM mutation until destroy): the
            # classifier cannot see it, so the plan cannot either
            self._invalidate(sync=True)
        if self.sim is None:
            if not self._enabled() or not self._build():
                return None
        dt = None
        if self.live_slots:
            if not self.batches and not self._dispatch_batch():
                self._invalidate(sync=True, cause="stall")
                return None
            dt = self.batches[0][0]
        if self.lat_actions:
            dt_lat = min(self.lat_actions[s].latency
                         for s in sorted(self.lat_actions))
            if dt is None or dt_lat < dt:
                dt = dt_lat
        if dt is None:
            if self.absorbing and not len(model.started_action_set):
                # idle plan between waves: nothing to time, nothing to
                # go stale — hold it for the next absorbed transition
                return None
            self._invalidate(sync=True)
            return None
        # a profile event before the completion horizon can mutate the
        # system mid-advance: generic path's turn
        next_event = model.engine.future_evt_set.next_date()
        if 0.0 <= next_event <= now + dt:
            self._invalidate(sync=True, cause="profile_event")
            return None
        return dt

    def apply(self, now: float, delta: float) -> bool:
        """update_actions_state_full hook: commit the planned advance
        when the engine advanced by exactly its dt; otherwise absorb
        the partial advance on device (drain/transitions) or roll back
        deterministically and let the generic loop run.  Returns True
        when the advance was fully handled here."""
        if self.sim is None:
            return False
        if self.batches and delta == self.batches[0][0]:
            _dt, slots = self.batches.pop(0)
            self.served += 1
            self.advances_served += 1
            opstats.bump("fastpath_advances")
            self._finish_slots(slots)
            self._advance_latencies(delta)
            return True
        if not self.absorbing:
            if not self.batches:
                return False
            # partial advance (another model's event or a run bound):
            # replay to the served prefix, write remains+rates back,
            # generic loop takes it from here
            self._invalidate(sync=True, with_rates=True,
                             cause="partial_advance")
            return False
        if not self.batches and not self.live_slots \
                and not self.lat_actions \
                and not len(self.model.started_action_set):
            return False       # idle plan: nothing to account
        return self._partial_advance(delta)

    def _finish_slots(self, slots) -> None:
        """Finish the planned completion set in started-set order —
        exactly the generic sweep's traversal — whitelisting the frees
        our own retirements are about to cause."""
        from ..kernel.resource import ActionState
        done = set(slots)
        if not done:
            return
        self.live_slots.difference_update(done)
        view = self.model.system.array_view
        for action in self.model.started_action_set:
            var = action.variable
            if var is not None and var._view_slot in done:
                view.expected_frees.add(id(var))
                action.finish(ActionState.FINISHED)

    def _advance_latencies(self, delta: float) -> None:
        """The generic walk's latency bookkeeping, applied to the
        plan's invisible lanes: double_update decrement, census
        maintenance, and the wake's penalty update — which the view
        marks as dirty, so the NEXT serve absorbs it as a transition
        and the lane starts flowing on device."""
        if not self.lat_actions:
            return
        eps = config["surf/precision"]
        model = self.model
        woken = []
        for slot, action in sorted(self.lat_actions.items()):
            if action.latency > delta:
                action.latency = double_update(action.latency, delta,
                                               eps)
            else:
                action.latency = 0.0
            if action.latency <= 0.0:
                if action._lat_counted:
                    action._lat_counted = False
                    model.latency_phase_count -= 1
                if not action.is_suspended():
                    model.system.update_variable_penalty(
                        action.variable, action.effective_penalty)
                woken.append(slot)
        for slot in woken:
            del self.lat_actions[slot]

    def _partial_advance(self, delta: float) -> bool:
        """Serve an engine advance SMALLER than the plan's own dt
        (another model's event, a latency expiry) on device: forced
        remains decrement + threshold retirement at the given delta,
        batches flushed (their schedule shifted), plan kept alive."""
        if self.batches and delta > self.batches[0][0]:
            # the engine advanced PAST our served horizon: a serve/
            # apply protocol breach this path has no semantics for
            self._invalidate(sync=True, with_rates=True)
            return False
        self.partial_advances += 1
        opstats.bump("drain_cause_partial_advance")
        if self.live_slots:
            self._discard_spec()
            try:
                self._sync_to_served()
                done_slots, _n_live = self.sim.partial_advance(delta)
            except RuntimeError:
                self._invalidate(sync=True, with_rates=True,
                                 cause="stall")
                return False
            self._finish_slots(int(s) for s in done_slots)
        else:
            self._discard_spec()
            self.batches = []
            self.saved = None
            self.served = 0
        self._advance_latencies(delta)
        self.advances_served += 1
        opstats.bump("fastpath_advances")
        return True

    # -- transition absorption ---------------------------------------------

    def _absorb(self) -> bool:
        """Classify the mutation batch since the plan's version and
        absorb it into the device plan as ONE fused transition payload.
        Returns False when any mutation is not recognized as resumable
        — the caller then runs the bit-identical replay invalidation.
        Nothing is shipped before classification completes, so a False
        return leaves the device state untouched."""
        model = self.model
        view = model.system.array_view
        if view.layout_epoch != self.epoch:
            return False       # slots renumbered: indices are garbage
        dirty = view.consume("drain")
        if dirty is None:
            return False
        if any(dirty[f] is True for f in sorted(dirty)):
            return False       # index identity lost for a whole field
        if dirty["c_fatpipe"]:
            return False       # sharing-policy change: no drain program

        # classification MUST NOT mutate tracking state before it is
        # complete: a False return hands the plan to _invalidate, whose
        # remains write-back trusts slot_action — stage everything and
        # commit only after the whole batch is recognized
        updates: Dict[str, tuple] = {}
        pen_ix: List[int] = []
        pen_v: List[float] = []
        rem_ix: List[int] = []
        rem_v: List[float] = []
        th_v: List[float] = []
        vb_ix: List[int] = []
        vb_v: List[float] = []
        track: List[Tuple[int, object]] = []   # slot -> action (re)binds
        drop: List[int] = []                   # slots leaving the plan
        lat_add: List[Tuple[int, object]] = []
        lat_del: List[int] = []
        live_add: List[int] = []
        live_del: List[int] = []
        from ..kernel.resource import NO_MAX_DURATION

        # element dirt: structural appends from new flows, weight
        # changes (set_bandwidth re-weighing), retirement zeroing —
        # final-state scatters straight from the f64 masters
        e_dirty = sorted(dirty["e_var"] | dirty["e_cnst"]
                         | dirty["e_w"])
        for i in e_dirty:
            if view.e_w[i] > 0 and view.c_fatpipe[view.e_cnst[i]]:
                return False   # a fatpipe route joined the plan
        if e_dirty:
            updates["e_var"] = (e_dirty,
                                [int(view.e_var[i]) for i in e_dirty])
            updates["e_cnst"] = (e_dirty,
                                 [int(view.e_cnst[i]) for i in e_dirty])
            updates["e_w"] = (e_dirty,
                              [float(view.e_w[i]) for i in e_dirty])
        cb = sorted(dirty["c_bound"])
        if cb:
            updates["c_bound"] = (cb,
                                  [float(view.c_bound[i]) for i in cb])

        for slot in sorted(dirty["v_penalty"] | dirty["v_bound"]):
            var = (view.slot_var[slot]
                   if slot < len(view.slot_var) else None)
            known = self.slot_action.get(slot)
            if var is None:
                # freed lane: our own retirement's echo, or an external
                # free whose version bump rode along — dead either way
                pen_ix.append(slot)
                pen_v.append(0.0)
                drop.append(slot)
                continue
            action = getattr(var, "id", None)
            pen = float(view.v_penalty[slot])
            if (action is None or action.state_set
                    is not model.started_action_set):
                if pen > 0:
                    # a live lane not owned by a started action (e.g. a
                    # cancelled-but-undestroyed flow): the generic solve
                    # keeps sharing bandwidth with it forever; a plan
                    # would retire it — different semantics, bail
                    return False
                pen_ix.append(slot)
                pen_v.append(0.0)
                drop.append(slot)
                continue
            if not math.isfinite(pen):
                return False   # parked flow (inf penalty): replay path
            if known is None or known.variable is not var:
                # a NEW lane (fresh or recycled slot): full admission
                if action.max_duration != NO_MAX_DURATION:
                    return False
                if var.get_number_of_constraint() == 0:
                    return False   # route-less: generic completes it
                remains = action.get_remains_no_update()
                if pen > 0 and remains <= 0:
                    return False
                track.append((slot, action))
                rem_ix.append(slot)
                rem_v.append(remains)
                size = max(action.cost, 1.0)
                th_v.append(self._done_eps if self._done_mode == "abs"
                            else self._done_eps * size)
                if action.latency > 0:
                    lat_add.append((slot, action))
                else:
                    lat_del.append(slot)
            if slot in dirty["v_penalty"]:
                pen_ix.append(slot)
                pen_v.append(pen)
                if pen > 0:
                    live_add.append(slot)
                else:
                    live_del.append(slot)
            if slot in dirty["v_bound"]:
                vb_ix.append(slot)
                vb_v.append(float(view.v_bound[slot]))

        # classification succeeded: commit the staged tracking updates
        for slot in drop:
            self.slot_action.pop(slot, None)
            self.lat_actions.pop(slot, None)
            self.live_slots.discard(slot)
        for slot, action in track:
            self.slot_action[slot] = action
        for slot in lat_del:
            self.lat_actions.pop(slot, None)
        for slot, action in lat_add:
            self.lat_actions[slot] = action
        for slot in live_del:
            self.live_slots.discard(slot)
        for slot in live_add:
            self.live_slots.add(slot)
        if pen_ix:
            updates["v_penalty"] = (pen_ix, pen_v)
        if rem_ix:
            updates["remains"] = (rem_ix, rem_v)
            updates["thresh"] = (rem_ix, th_v)
        if vb_ix:
            updates["v_bound"] = (vb_ix, vb_v)

        # commit: rewind to the served prefix (the scatters describe
        # mutations of the SERVED state), drop speculation, ship the
        # payload, resume — the next serve dispatches a fresh superstep
        self._discard_spec()
        self._sync_to_served()
        n = self.sim.apply_transitions(updates)
        self.version = view.version
        self.transitions_absorbed += 1
        self.transition_slots += n
        opstats.bump("drain_transitions")
        opstats.bump("drain_transition_slots", n)
        opstats.bump("drain_cause_transition")
        return True

    # -- teardown ----------------------------------------------------------

    def _invalidate(self, sync: bool, with_rates: bool = False,
                    cause: str = "unrecognized") -> None:
        """Retire the plan.  With sync=True the device flow state is
        replayed to the served prefix and `remains` written back to the
        still-live actions (with_rates also refreshes
        action.variable.value so the generic loop can apply a partial
        advance).  An in-flight speculative superstep is discarded
        FIRST — it was issued against post-batch state the rollback is
        about to rewind past, and it never committed anything."""
        self._discard_spec()
        sim, saved = self.sim, self.saved
        self.sim = None
        if sim is None:
            return
        self.invalidations += 1
        opstats.bump("drain_cause_" + cause)
        if not sync:
            return
        if self.batches or with_rates:
            # mid-batch stop: deterministic replay of the served prefix
            # from the immutable batch-start arrays (no transfer)
            if saved is not None:
                sim._pen, sim._rem = saved
                if self.served:
                    sim.superstep_batch(k=self.served, fetch=False)
                self.rollbacks += 1
        rem = np.asarray(sim._rem)
        pen = np.asarray(sim._pen)
        rates = sim.solve_rates() if with_rates else None
        # any advances this plan served mean the host System's cached
        # rates are stale: force the next generic call to re-solve
        self.model.system.modified = True
        for slot, action in sorted(self.slot_action.items()):
            if pen[slot] <= 0:
                continue
            if action.state_set is not self.model.started_action_set:
                continue
            action.remains = float(rem[slot])
            if rates is not None:
                action.variable.value = float(rates[slot])
        self.batches = []
        self.saved = None
        self.served = 0
        self.slot_action = {}
        self.lat_actions = {}
        self.live_slots = set()
