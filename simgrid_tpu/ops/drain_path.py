"""Engine-side drain fast path: delegate pure-drain phases to the
device-resident superstep executor.

A *pure-drain phase* is the shape the end-to-end north star degenerates
to (BASELINE config #4): every started network flow has paid its
latency, none carries a deadline, and no profile event fires before the
next completion — the maestro's loop is then exactly

    solve rates -> dt to next completion -> retire flows

per advance, costing >= 3 host<->device syncs plus an O(V) Python walk
each time through the generic `Model::update_actions_state` path.  This
module detects that phase from `NetworkCm02Model`'s FULL-mode hooks and
serves *batches* of advances from one `DrainSim` superstep dispatch
(ops.lmm_drain), keeping completion-event ordering identical:

* completions are emitted by walking `started_action_set` in order and
  finishing exactly the planned set — the same traversal order the
  generic path uses;
* the plan is built from the incrementally-maintained ArrayView
  (ops.lmm_view) — no graph walk — and is invalidated by its mutation
  `version` counter, with the frees caused by *our own* served
  completions whitelisted (`expected_frees`);
* a partial advance (the engine chose a smaller delta: another model's
  event, a profile event, a run-until bound) is handed back to the
  generic loop after a deterministic REPLAY: the batch is re-executed
  from its saved device state up to the served prefix (jax arrays are
  immutable, so batch-start state is a free O(1) snapshot), remains and
  rates are written back, and the generic code runs unchanged;
* with ``drain/pipeline`` > 0 the NEXT superstep is issued
  speculatively the moment ring N is fetched — JAX dispatch is async,
  so the device executes ring N+1 while the engine consumes ring N's
  batches, and the next fetch finds a ready buffer instead of paying
  the tunnel round trip.  Speculation never touches the committed
  flow state (the dispatch chains from double-buffered immutable
  arrays), so ANY plan invalidation — profile event before the
  horizon, ArrayView mutation, partial advance, stall — simply
  discards the in-flight token and the existing deterministic-replay
  rollback proceeds exactly as in the unpipelined path.  Event order,
  timestamps and clocks are bit-identical to ``drain/pipeline:0``
  (enforced by ``tools/check_determinism.py --runtime-pipeline``).

Precision: f64 plans retire flows at the engine's absolute
`maxmin/precision * surf/precision` threshold — bit-matching the
generic double_update path — while f32 plans use the RELATIVE
`drain/done-eps * size` rule so chip-precision ties stay grouped
(see ops.lmm_drain).

Fidelity trade documented in README: while a plan is being served, the
`remains` of still-live flows and link usage introspection lag until
the plan ends (they are synced on every invalidation); actors in a pure
drain are blocked in comm waits, so nothing observes the lag.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ..utils.config import config

#: started-flow census below which a plan is never attempted (plan
#: bookkeeping beats the generic path only at scale); the config flag
#: drain/min-flows overrides per run.
_MIN_FLOWS_FLOOR = 8


def _plan_inputs(model, dtype):
    """The pure-drain precondition walk + flattened state, shared by
    the fast path's plan builder and the campaign capture: one O(V)
    pass maps view slots to started actions and rejects anything that
    is not a pure drain (latency phases, deadlines, suspensions,
    route-less flows, live non-flow variables, zero remains).  Returns
    ``(slot_action, view, snap, sizes, rem, pen)`` or None."""
    from ..kernel.resource import NO_MAX_DURATION
    from .lmm_view import ArrayView

    system = model.system
    view = system.array_view
    if view is None:
        view = ArrayView(system)

    slot_action: Dict[int, object] = {}
    for action in model.started_action_set:
        var = action.variable
        if (var is None or var.sharing_penalty <= 0
                or action.latency > 0
                or action.max_duration != NO_MAX_DURATION
                or action.is_suspended()
                or var.get_number_of_constraint() == 0):
            return None
        slot_action[var._view_slot] = action

    snap = view.snapshot(dtype)
    # NOTE: snapshot() may compact, which renumbers element slots
    # but not variable slots — the slot map above stays valid.
    pen_all = snap.v_penalty
    live = np.flatnonzero(pen_all > 0)
    # a live variable that is NOT a started flow (e.g. a failed
    # action not yet reaped) shares bandwidth in the generic solve:
    # not a pure drain
    if len(live) != len(slot_action) or \
            not all(int(s) in slot_action for s in live):
        return None

    n_v = len(pen_all)
    sizes = np.ones(n_v)
    rem = np.zeros(n_v)
    pen = np.zeros(n_v, dtype)
    for slot, action in slot_action.items():
        sizes[slot] = max(action.cost, 1.0)
        rem[slot] = action.get_remains_no_update()
        pen[slot] = pen_all[slot]
    if np.any(rem[live] <= 0):
        return None         # zero-remains flows: let generic finish
    return slot_action, view, snap, sizes, rem, pen


def capture_scenario(model):
    """Snapshot the model's CURRENT pure-drain phase as the shared base
    scenario of a batched campaign (parallel.campaign.Campaign): the
    same preconditions as the fast path's plan builder, returned as
    plain numpy arrays plus the slot->action and constraint->link-name
    maps a campaign needs to label its dimensions.  None when the
    phase is not a pure drain."""
    plan = _plan_inputs(model, np.float64)
    if plan is None:
        return None
    slot_action, view, snap, sizes, rem, pen = plan
    E = snap.n_elem
    names = [getattr(getattr(c, "id", None), "name", None)
             for c in view.slot_cnst]
    names += [None] * (len(snap.c_bound) - len(names))
    return dict(e_var=snap.e_var[:E].copy(),
                e_cnst=snap.e_cnst[:E].copy(),
                e_w=snap.e_w[:E].copy(),
                c_bound=snap.c_bound.copy(),
                sizes=sizes, remains=rem,
                penalty=pen.astype(np.float64),
                v_bound=snap.v_bound.copy(),
                link_names=names,
                slot_action=dict(slot_action))


class DrainFastPath:
    """Per-network-model drain plan server (see module docstring)."""

    def __init__(self, model):
        self.model = model
        self.sim = None                     # active DrainSim, or None
        self.slot_action: Dict[int, object] = {}
        self.version = -1                   # ArrayView version at build
        self.batches: List[Tuple[float, List[int]]] = []
        self.saved = None                   # (pen, rem) at batch start
        self.served = 0                     # advances of current batch
        self.spec = None                    # in-flight speculative token
        # observability (asserted by tests, reported by tools)
        self.plans = 0
        self.advances_served = 0
        self.invalidations = 0
        self.rollbacks = 0
        self.speculations = 0
        self.spec_commits = 0
        self.spec_discards = 0

    # -- eligibility -------------------------------------------------------

    def _enabled(self) -> bool:
        mode = config["drain/fastpath"]
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"Unknown drain/fastpath {mode!r} "
                             "(expected auto, on or off)")
        if mode == "off":
            return False
        backend = config["lmm/backend"]
        if backend not in ("jax", "auto"):
            return False
        model = self.model
        # FULL-mode only (the hooks live in next_occurring_event_full);
        # selective-update systems are fine: served completions feed
        # the modified set through the var-free closure, so the warm
        # solver (ops.lmm_warm) picks up exactly where the plan left
        # off when the drain phase ends
        if model.is_lazy():
            return False
        n = len(model.started_action_set)
        if n < max(int(config["drain/min-flows"]), _MIN_FLOWS_FLOOR):
            return False
        if backend == "auto" and n < config["lmm/jax-threshold"]:
            return False
        if model.latency_phase_count:
            return False
        return True

    def _build(self) -> bool:
        """One O(V) walk to check the drain preconditions and map view
        slots to actions, then a snapshot + DrainSim construction.
        Amortized over the K advances each superstep serves."""
        from .lmm_drain import DrainSim

        dtype = (np.float32 if config["lmm/dtype"] == "float32"
                 else np.float64)
        plan = _plan_inputs(self.model, dtype)
        if plan is None:
            return False
        slot_action, view, snap, sizes, rem, pen = plan

        if dtype == np.float64:
            done_mode = "abs"
            done_eps = (config["maxmin/precision"]
                        * config["surf/precision"])
        else:
            done_mode = "rel"
            done_eps = config["drain/done-eps"]

        E = snap.n_elem
        sim = DrainSim(
            snap.e_var[:E], snap.e_cnst[:E], snap.e_w[:E],
            snap.c_bound, sizes,
            eps=config["maxmin/precision"], done_eps=done_eps,
            dtype=dtype, done_mode=done_mode,
            v_bound=snap.v_bound,
            superstep=int(config["drain/superstep"]),
            penalty=pen, remains=rem,
            # device repacks would detach the replay snapshot from the
            # element tables; plans are rebuilt often enough that the
            # view's own host-side compaction covers shrinkage
            repack_min=1 << 62)
        self.sim = sim
        self.slot_action = slot_action
        self.version = view.version
        self.batches = []
        self.saved = None
        self.served = 0
        self.spec = None
        self.plans += 1
        return True

    # -- plan serving ------------------------------------------------------

    def _discard_spec(self) -> None:
        """Drop the in-flight speculative superstep (mispredict: the
        plan is being invalidated, or its batch never materialized).
        Issue never committed anything, so there is no state to
        restore — only the device work is wasted (and counted)."""
        if self.spec is not None:
            if self.sim is not None:
                self.sim._discard_token(self.spec)
            self.spec_discards += 1
            self.spec = None

    def _dispatch_batch(self) -> bool:
        """Collect one superstep (the in-flight speculative one when
        the prediction held, else a fresh dispatch + fetch); False when
        it made no progress (solve exceeded the round budget, or the
        drain stalled — a parked/zero-rate remainder the generic path
        knows how to diagnose)."""
        sim = self.sim
        tok, self.spec = self.spec, None
        if tok is None:
            tok = sim._superstep_issue()
        # batch-start snapshot for deterministic replay: the token's
        # input arrays ARE the pre-dispatch state (immutable, O(1))
        self.saved = (tok.pen_in, tok.rem_in)
        self.served = 0
        try:
            n_live, batches, clean = sim._superstep_collect(tok)
        except RuntimeError:
            # stall/non-convergence surfaced mid-batch: the advances it
            # applied were never served, so restore the batch-start
            # state (immutable arrays: an O(1) rollback) and hand the
            # phase back to the generic path
            sim._pen, sim._rem = self.saved
            return False
        if tok.speculative:
            self.spec_commits += 1
        if not batches:
            return False
        self.batches = batches
        if clean and int(config["drain/pipeline"]) > 0:
            # speculative issue of the NEXT superstep: the device
            # executes ring N+1 while the engine consumes ring N's
            # batches below (plans keep ONE token in flight — each
            # ring already covers K engine advances of host work)
            self.spec = sim._superstep_issue(speculative=True)
            self.speculations += 1
        return True

    def serve(self, now: float) -> Optional[float]:
        """next_occurring_event_full hook: the dt to the next planned
        completion, or None to fall back to the generic path."""
        model = self.model
        if self.sim is not None:
            view = model.system.array_view
            if view is None or view.version != self.version:
                self._invalidate(sync=True)
            elif not self.batches and not self._dispatch_batch():
                self._invalidate(sync=True)
        if self.sim is None:
            if not self._enabled() or not self._build():
                return None
            if not self._dispatch_batch():
                self._invalidate(sync=True)
                return None
        if not self.batches:
            self._invalidate(sync=True)
            return None
        dt = self.batches[0][0]
        # a profile event before the completion horizon can mutate the
        # system mid-advance: generic path's turn
        next_event = model.engine.future_evt_set.next_date()
        if 0.0 <= next_event <= now + dt:
            self._invalidate(sync=True)
            return None
        return dt

    def apply(self, now: float, delta: float) -> bool:
        """update_actions_state_full hook: commit the planned advance
        when the engine advanced by exactly its dt; otherwise roll back
        deterministically and let the generic loop run.  Returns True
        when the advance was fully handled here."""
        if self.sim is None or not self.batches:
            return False
        dt, slots = self.batches[0]
        if delta != dt:
            # partial advance (another model's event or a run bound):
            # replay to the served prefix, write remains+rates back,
            # generic loop takes it from here
            self._invalidate(sync=True, with_rates=True)
            return False
        self.batches.pop(0)
        self.served += 1
        self.advances_served += 1
        done = set(slots)
        view = self.model.system.array_view
        from ..kernel.resource import ActionState
        # started-set order, exactly like the generic sweep
        for action in self.model.started_action_set:
            var = action.variable
            if var is not None and var._view_slot in done:
                view.expected_frees.add(id(var))
                action.finish(ActionState.FINISHED)
        return True

    # -- teardown ----------------------------------------------------------

    def _invalidate(self, sync: bool, with_rates: bool = False) -> None:
        """Retire the plan.  With sync=True the device flow state is
        replayed to the served prefix and `remains` written back to the
        still-live actions (with_rates also refreshes
        action.variable.value so the generic loop can apply a partial
        advance).  An in-flight speculative superstep is discarded
        FIRST — it was issued against post-batch state the rollback is
        about to rewind past, and it never committed anything."""
        self._discard_spec()
        sim, saved = self.sim, self.saved
        self.sim = None
        if sim is None:
            return
        self.invalidations += 1
        if not sync:
            return
        if self.batches or with_rates:
            # mid-batch stop: deterministic replay of the served prefix
            # from the immutable batch-start arrays (no transfer)
            if saved is not None:
                sim._pen, sim._rem = saved
                if self.served:
                    sim.superstep_batch(k=self.served, fetch=False)
                self.rollbacks += 1
        rem = np.asarray(sim._rem)
        pen = np.asarray(sim._pen)
        rates = sim.solve_rates() if with_rates else None
        # any advances this plan served mean the host System's cached
        # rates are stale: force the next generic call to re-solve
        self.model.system.modified = True
        for slot, action in self.slot_action.items():
            if pen[slot] <= 0:
                continue
            if action.state_set is not self.model.started_action_set:
                continue
            action.remains = float(rem[slot])
            if rates is not None:
                action.variable.value = float(rates[slot])
        self.batches = []
        self.saved = None
        self.served = 0
        self.slot_action = {}
