"""Vectorized max-min fairness solver on JAX (TPU-native hot path).

This is the north-star component: SimGrid's saturate-bottleneck fixpoint
(reference semantics: /root/reference/src/kernel/lmm/maxmin.cpp:502-693)
re-designed for the TPU/XLA execution model instead of intrusive linked
lists:

* the constraint/variable graph is flattened into COO-style element arrays
  ``(e_var, e_cnst, e_w)`` padded to bucketed static shapes (XLA wants
  static shapes; buckets bound recompiles);
* one *saturation round* = a handful of segment-sum / segment-max scatters
  plus two min-reductions over dense vectors — bandwidth-bound vector work
  XLA maps directly onto the TPU's VPU, with the whole fixpoint inside one
  ``lax.while_loop`` so there is a single device dispatch per solve;
* the epsilon semantics (``double_update`` clamping, saturation tests
  against ``bound*eps``) are applied batched, and ties in the min-reduce
  are detected by exact equality like the reference, so the returned rate
  vector matches the exact list solver bit-for-bit in f64 on identical
  round structures.

The same function runs unchanged on CPU (f64, used for validation and as
the oracle cross-check) and on TPU (f32 by default, f64 unsupported by the
hardware).  For multi-simulation batching it is ``vmap``-able, and the
segment ops shard over a device mesh for very large systems (see
simgrid_tpu.parallel.sharded_solve).
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils.config import config
from .lmm_host import SharingPolicy, System, Constraint, Variable

_MAX_ROUNDS = 100_000


class LmmArrays(NamedTuple):
    """Flattened (padded) view of an LMM system."""
    e_var: np.ndarray    # [E] int32 — variable slot per element
    e_cnst: np.ndarray   # [E] int32 — constraint slot per element
    e_w: np.ndarray      # [E] float — consumption weight (0 padding)
    c_bound: np.ndarray  # [C] float — constraint capacity (0 padding)
    c_fatpipe: np.ndarray  # [C] bool — max-sharing (FATPIPE) constraint
    v_penalty: np.ndarray  # [V] float — sharing penalty (0 = disabled/pad)
    v_bound: np.ndarray    # [V] float — variable rate bound (-1 = none)
    n_elem: int
    n_cnst: int
    n_var: int


def _bucket(n: int, floor: int = 16, grow: bool = False) -> int:
    """Round up to a bucketed static size to bound XLA recompiles.
    ELL row widths pass floor=4: every padded slot is gathered in EVERY
    round and the tunneled-TPU gather cost is proportional to gathered
    elements, so a deg-4 graph packed at width 16 would pay 4x on each
    vc-side gather.

    Default policy is power-of-2; ``lmm/pad:tight`` switches to exact
    row widths and multiple-of-4096 array sizes — per-round device cost
    is proportional to padded volume (~8 ns per gathered/scattered
    element on the tunneled TPU, bench_results/tpu_opcost.jsonl), so
    one-shot solves of large systems should not pay the up-to-2x pow2
    padding.  Hot simulation paths keep pow2: each fresh shape is a
    multi-second XLA compile.  ``grow=True`` callers (the incremental
    ArrayView's reallocation policy) always get pow2: ceil-to-4096
    growth would copy the arrays every 4096 insertions (O(n^2) total)
    and compile a fresh shape each time."""
    pad = config["lmm/pad"]
    if pad not in ("pow2", "tight"):
        raise ValueError(f"Unknown lmm/pad {pad!r} "
                         "(expected pow2 or tight)")
    if pad == "tight" and not grow:
        if floor <= 8:              # ELL row width: exact
            return max(n, 1)
        if n > 4096:
            return -(-n // 4096) * 4096
    if n <= floor:
        return floor
    return 1 << (n - 1).bit_length()


class LmmEllArrays(NamedTuple):
    """ELL (padded-row) layout of an LMM system, the accelerator-native
    form: every constraint owns a fixed-width row of (variable, weight)
    slots and every variable a fixed-width row of constraint slots, so
    each solver round is gathers + dense 2D row-reductions — no scatter
    at all. Unsorted scatters are the one op class TPUs execute poorly
    (the COO kernel spends ~100ms/round at 100k flows on them; this
    layout runs the same round in ~1ms). Skewed systems (one backbone
    constraint touching everything) would blow the row width up, so
    conversion falls back to COO beyond a width cap."""
    cv_var: np.ndarray    # [C, Wc] int32 — variable slot per element
    cv_w: np.ndarray      # [C, Wc] float — weight (0 padding)
    cv_valid: np.ndarray  # [C, Wc] bool
    vc_cnst: np.ndarray   # [V, Wv] int32 — constraint slot per element
    vc_valid: np.ndarray  # [V, Wv] bool
    c_bound: np.ndarray
    c_fatpipe: np.ndarray
    v_penalty: np.ndarray
    v_bound: np.ndarray
    n_cnst: int
    n_var: int
    #: [V, Wv] float — element weight in VARIABLE-row layout.  The
    #: var-side rows are near-unpadded (width = max var degree, usually
    #: the flow's route length), so the vc-centric round body gathers/
    #: scatters ~2-4x fewer elements than the constraint-side tables.
    vc_w: Optional[np.ndarray] = None


#: Conversion to ELL is refused when a row would exceed this width
#: (memory blow-up on skewed graphs) — COO handles those.
_ELL_MAX_WIDTH = 512
#: ...or when padding would inflate total slots by more than this
#: factor over the element count.
_ELL_MAX_FILL = 8.0


def ell_from_arrays(arrays: LmmArrays) -> Optional[LmmEllArrays]:
    """Host-side repack of the COO arrays into ELL rows (numpy)."""
    E, C, V = arrays.n_elem, len(arrays.c_bound), len(arrays.v_penalty)
    e_var = arrays.e_var[:E]
    e_cnst = arrays.e_cnst[:E]
    e_w = arrays.e_w[:E]

    c_deg = np.bincount(e_cnst, minlength=C)
    v_deg = np.bincount(e_var, minlength=V)
    wc = int(c_deg.max()) if E else 1
    wv = int(v_deg.max()) if E else 1
    if wc > _ELL_MAX_WIDTH or wv > _ELL_MAX_WIDTH:
        return None
    Wc, Wv = _bucket(max(wc, 1), floor=4), _bucket(max(wv, 1), floor=4)
    if E and (C * Wc + V * Wv) > _ELL_MAX_FILL * 2 * E:
        return None

    def row_slots(keys, n_rows):
        """Vectorized within-group slot index per element (stable)."""
        order = np.argsort(keys, kind="stable")
        sorted_keys = keys[order]
        group_start = np.searchsorted(sorted_keys, np.arange(n_rows))
        slots = np.arange(E, dtype=np.int64) - group_start[sorted_keys]
        return order, sorted_keys, slots

    cv_var = np.zeros((C, Wc), np.int32)
    cv_w = np.zeros((C, Wc), arrays.e_w.dtype)
    cv_valid = np.zeros((C, Wc), bool)
    order, rows, slots = row_slots(e_cnst, C)
    cv_var[rows, slots] = e_var[order]
    cv_w[rows, slots] = e_w[order]
    cv_valid[rows, slots] = e_w[order] > 0

    vc_cnst = np.zeros((V, Wv), np.int32)
    vc_valid = np.zeros((V, Wv), bool)
    vc_w = np.zeros((V, Wv), arrays.e_w.dtype)
    order, rows, slots = row_slots(e_var, V)
    vc_cnst[rows, slots] = e_cnst[order]
    vc_valid[rows, slots] = e_w[order] > 0
    vc_w[rows, slots] = e_w[order]

    return LmmEllArrays(cv_var, cv_w, cv_valid, vc_cnst, vc_valid,
                        arrays.c_bound, arrays.c_fatpipe,
                        arrays.v_penalty, arrays.v_bound,
                        arrays.n_cnst, arrays.n_var, vc_w)


def _run_rounds(cond, body, carry, max_rounds: int, unroll: bool):
    """Dispatch the round loop either as lax.while_loop or fully
    unrolled straight-line XLA.  Unrolling exists for backends that
    lower gathers inside while_loop/scan to serialized dynamic-slice
    loops (the axon TPU pathology: ~137 ms/round and 10-minute
    compiles, while the same gathers in straight-line code compile in
    seconds and run vectorized).  Each unrolled iteration is masked to
    a no-op once `cond` goes false, so the result is bit-identical to
    the while_loop truncated at max_rounds."""
    if not unroll:
        return lax.while_loop(cond, body, carry)
    if max_rounds > 4096:
        raise ValueError(
            f"unroll=True requires a bounded max_rounds (got {max_rounds}); "
            "compile time scales with the unroll factor — dispatch in "
            "chunks (see solve_arrays) instead")
    state = carry
    for _ in range(max_rounds):
        alive = cond(state)
        new_state = body(state)
        state = tuple(jnp.where(alive, n, o)
                      for n, o in zip(new_state, state))
    return state


def fixpoint_ell(ell: LmmEllArrays, eps, carry=None,
                 parallel_rounds: bool = False,
                 max_rounds: Optional[int] = None,
                 return_carry: bool = False,
                 unroll: bool = False,
                 has_bounds: bool = True,
                 has_fatpipe: bool = True):
    """The saturate-bottleneck fixpoint on the ELL layout: identical
    round structure and epsilon semantics to `fixpoint` (see there for
    the algorithm), with every segment reduction expressed as a masked
    dense 2D row-reduction."""
    cv_var, cv_w, cv_valid = ell.cv_var, ell.cv_w, ell.cv_valid
    vc_cnst, vc_valid = ell.vc_cnst, ell.vc_valid
    vc_w = ell.vc_w
    c_bound, c_fatpipe = ell.c_bound, ell.c_fatpipe
    v_penalty, v_bound = ell.v_penalty, ell.v_bound
    n_c = c_bound.shape[0]

    dtype = cv_w.dtype
    inf = jnp.array(jnp.inf, dtype)

    v_enabled = v_penalty > 0
    cv_evalid = cv_valid & jnp.take(v_enabled, cv_var)
    safe_pen = jnp.where(v_enabled, v_penalty, 1.0)
    cv_upen = jnp.where(cv_evalid, cv_w / jnp.take(safe_pen, cv_var), 0.0)

    usage_sum = cv_upen.sum(axis=1)
    usage_max = cv_upen.max(axis=1, initial=0.0)
    usage0 = jnp.where(c_fatpipe, usage_max, usage_sum)

    remaining0 = c_bound
    light0 = (remaining0 > c_bound * eps) & (usage0 > 0)

    v_value0 = jnp.where(jnp.isfinite(v_penalty), v_penalty, 0.0) * 0.0
    v_fixed0 = v_penalty < 0

    if carry is None:
        cv_live0 = cv_evalid & ~jnp.take(v_fixed0, cv_var)
        carry = (v_value0, v_fixed0, remaining0, usage0, light0,
                 jnp.array(0, jnp.int32), cv_live0)
    start_it = carry[5]
    if max_rounds is None:
        max_rounds = _MAX_ROUNDS

    # Variable-row element validity: a var row is enabled as a whole.
    vc_evalid = vc_valid & v_enabled[:, None]

    def cond(state):
        light = state[4]
        it = state[5]
        return (jnp.any(light) & (it < _MAX_ROUNDS)
                & (it - start_it < max_rounds))

    def apply_fixes(state, fix_now, new_value):
        v_value, v_fixed, remaining, usage, light, it = state[:6]
        cv_live_in = state[6]
        v_value = jnp.where(fix_now, new_value, v_value)
        v_fixed = v_fixed | fix_now

        # one stacked row-gather instead of three element gathers: the
        # tunneled-TPU gather cost is per INDEX, so fetching both
        # channels [v_value, v_fixed] per slot is ~free.  fix_now needs
        # no channel: newly-fixed = (was live at round start) & (fixed
        # now), and the round-start liveness rides the carry.
        stacked = jnp.stack([v_value, v_fixed.astype(dtype)], axis=1)
        g = jnp.take(stacked, cv_var, axis=0)
        g_fixed = g[..., 1] > 0
        cv_fix = cv_live_in & g_fixed
        d_rem = jnp.where(cv_fix, cv_w * g[..., 0], 0.0).sum(axis=1)
        d_use = jnp.where(cv_fix, cv_upen, 0.0).sum(axis=1)

        new_remaining = remaining - d_rem
        new_remaining = jnp.where(new_remaining < c_bound * eps, 0.0,
                                  new_remaining)
        new_usage_sum = usage - d_use
        new_usage_sum = jnp.where(new_usage_sum < eps, 0.0, new_usage_sum)

        cv_live2 = cv_evalid & ~g_fixed
        touched = cv_fix.any(axis=1)
        if has_fatpipe:
            new_usage_max = jnp.where(cv_live2, cv_upen,
                                      0.0).max(axis=1, initial=0.0)
            new_usage = jnp.where(c_fatpipe, new_usage_max, new_usage_sum)
            usage = jnp.where(touched, new_usage, usage)
            remaining = jnp.where(touched & ~c_fatpipe, new_remaining,
                                  remaining)
        else:
            # static specialization: no fatpipe constraint in the
            # system, so the max-usage recompute drops out
            usage = jnp.where(touched, new_usage_sum, usage)
            remaining = jnp.where(touched, new_remaining, remaining)

        drop = touched & (~(usage > eps) | ~(remaining > c_bound * eps))
        light = light & ~drop
        has_live = cv_live2.any(axis=1)
        light = light & has_live
        # the fresh liveness mask rides the carry so the next round
        # does not re-gather v_fixed over the cv table
        return v_value, v_fixed, remaining, usage, light, it + 1, cv_live2

    def body_global(state):
        v_value, v_fixed, remaining, usage, light, it = state[:6]
        rou = jnp.where(light, remaining / jnp.where(light, usage, 1.0),
                        inf)
        min_usage = jnp.min(rou)
        saturated_c = light & (rou == min_usage)

        vc_live = vc_evalid & ~v_fixed[:, None]
        v_sat = (vc_live & jnp.take(saturated_c, vc_cnst)).any(axis=1)

        bp = v_bound * v_penalty
        has_low_bound = v_sat & (v_bound > 0) & (bp < min_usage)
        min_bound = jnp.min(jnp.where(has_low_bound, bp, inf))
        use_bounds = jnp.isfinite(min_bound)

        fix_now = jnp.where(use_bounds,
                            v_sat & (jnp.abs(bp - min_bound) < eps),
                            v_sat)
        new_value = jnp.where(use_bounds, v_bound,
                              min_usage / jnp.where(v_enabled, v_penalty,
                                                    1.0))
        return apply_fixes(state, fix_now, new_value)

    def body_local(state):
        v_value, v_fixed, remaining, usage, light, it = state[:6]
        rou = jnp.where(light, remaining / jnp.where(light, usage, 1.0),
                        inf)
        vc_live = vc_evalid & ~v_fixed[:, None]
        cv_live = state[6]        # maintained by apply_fixes

        # Two-hop neighborhood min of rou: constraint -> vars -> cnst.
        # rou_vc is gathered ONCE and reused for nmin_v and level2_v.
        rou_vc = jnp.take(rou, vc_cnst)
        nmin_v = jnp.where(vc_live, rou_vc,
                           inf).min(axis=1, initial=jnp.inf)
        nmin_c = jnp.where(cv_live, jnp.take(nmin_v, cv_var),
                           inf).min(axis=1, initial=jnp.inf)
        processable = light & (rou <= nmin_c)

        vc_proc = vc_live & jnp.take(processable, vc_cnst)
        v_sat = vc_proc.any(axis=1)

        level_v = nmin_v
        bp = v_bound * v_penalty
        low_v = v_sat & (v_bound > 0) & (bp < level_v)
        cv_bp = jnp.where(cv_live & jnp.take(low_v, cv_var),
                          jnp.take(bp, cv_var), inf)
        mb_c = cv_bp.min(axis=1, initial=jnp.inf)
        mb_c = jnp.where(processable, mb_c, inf)
        mb_v = jnp.where(vc_proc, jnp.take(mb_c, vc_cnst),
                         inf).min(axis=1, initial=jnp.inf)
        cv_proc = cv_live & processable[:, None]
        blocked_c = (cv_proc
                     & jnp.isfinite(jnp.take(mb_v, cv_var))).any(axis=1)

        ok_c = processable & ~blocked_c
        level2_v = jnp.where(vc_live & jnp.take(ok_c, vc_cnst),
                             rou_vc,
                             inf).min(axis=1, initial=jnp.inf)

        fix_bound = low_v & (jnp.abs(bp - mb_v) < eps)
        fix_level = jnp.isfinite(level2_v) & ~v_fixed & ~fix_bound
        fix_now = fix_bound | fix_level
        new_value = jnp.where(fix_bound, v_bound,
                              level2_v / jnp.where(v_enabled, v_penalty,
                                                   1.0))
        return apply_fixes(state, fix_now, new_value)

    def body_local_vc(state):
        """The bound-free local round in the VARIABLE-row layout —
        shared with the compaction chain via _vc_round_body (see its
        docstring for the op-cost rationale); this wrapper threads the
        cv-side carry member the 6-tuple body does not use."""
        out6 = _vc_body6(state[:6])
        return (*out6, state[6])

    _vc_body6 = (_vc_round_body(vc_cnst, vc_w, vc_valid, v_penalty,
                                c_bound, c_fatpipe, eps, has_fatpipe)
                 if vc_w is not None else None)

    if parallel_rounds and not has_bounds and vc_w is not None:
        body = body_local_vc
    elif parallel_rounds:
        body = body_local
    else:
        body = body_global
    out = _run_rounds(cond, body, carry, max_rounds, unroll)
    v_value, v_fixed, remaining, usage, light, rounds = out[:6]
    if return_carry:
        return v_value, remaining, usage, rounds, out
    return v_value, remaining, usage, rounds


def fixpoint(e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty, v_bound,
             eps, n_c: int, n_v: int, axis: Optional[str] = None,
             parallel_rounds: bool = False, carry=None,
             max_rounds: Optional[int] = None, return_carry: bool = False,
             unroll: bool = False, has_bounds: bool = True,
             has_fatpipe: bool = True):
    """The saturate-bottleneck fixpoint over padded COO arrays.

    The single implementation behind every solve path: single-device
    (``axis=None`` — the reductions are plain segment ops), vmapped
    batches, and mesh-sharded element lists (``axis`` names the shard_map
    mesh axis; cross-shard combines are one psum/pmax pair per round in
    global mode and ~7 psum/pmax/pmin collectives per round in local
    mode, which still wins because local mode needs far fewer rounds —
    see simgrid_tpu.parallel.sharded).

    ``parallel_rounds=False`` replays the reference's sequential order
    exactly: one global bottleneck level per round.  ``True`` fixes every
    *local-minimum* constraint per round (a constraint whose rou is <= the
    rou of every constraint it shares a live variable with): since a
    constraint's remaining/usage ratio only increases as other variables
    get fixed, a local minimum's level is already final, so whole
    independent regions of the constraint graph saturate concurrently and
    the device round count drops from O(#distinct levels) to O(level-chain
    depth of the graph).

    ``carry``/``max_rounds``/``return_carry`` support *chunked* execution:
    run at most ``max_rounds`` additional rounds from ``carry`` (or the
    fresh initial state) and hand the full loop state back, so the host
    can bound device-kernel run time per dispatch and check convergence
    between chunks (a non-converging f32 solve must surface as a Python
    error, not a TPU watchdog kill).
    """
    dtype = e_w.dtype
    inf = jnp.array(jnp.inf, dtype)

    def allsum(x):
        return lax.psum(x, axis) if axis else x

    def allmax(x):
        return lax.pmax(x, axis) if axis else x

    def allmin(x):
        return lax.pmin(x, axis) if axis else x

    v_enabled = v_penalty > 0
    e_valid = (e_w > 0) & jnp.take(v_enabled, e_var, fill_value=False)
    safe_pen = jnp.where(v_enabled, v_penalty, 1.0)
    e_upen = jnp.where(e_valid, e_w / jnp.take(safe_pen, e_var), 0.0)

    # Initial usage per constraint: sum for SHARED, max for FATPIPE.
    usage_sum = allsum(jnp.zeros(n_c, dtype).at[e_cnst].add(e_upen))
    usage_max = allmax(jnp.zeros(n_c, dtype).at[e_cnst].max(e_upen))
    usage0 = jnp.where(c_fatpipe, usage_max, usage_sum)

    remaining0 = c_bound
    # Initial light set: usage strictly positive (exact, maxmin.cpp:545) and
    # remaining above the relative epsilon (maxmin.cpp:524).
    light0 = (remaining0 > c_bound * eps) & (usage0 > 0)

    # Derive the initial carry from the inputs (not fresh constants) so its
    # varying-manual-axes match the loop output under shard_map+vmap.
    # Parked variables carry penalty=inf and inf*0.0 is NaN, so sanitize.
    v_value0 = jnp.where(jnp.isfinite(v_penalty), v_penalty, 0.0) * 0.0
    v_fixed0 = v_penalty < 0

    if carry is None:
        carry = (v_value0, v_fixed0, remaining0, usage0, light0,
                 jnp.array(0, jnp.int32))
    start_it = carry[5]
    if max_rounds is None:
        max_rounds = _MAX_ROUNDS

    def cond(state):
        _, _, _, _, light, it = state
        return (jnp.any(light) & (it < _MAX_ROUNDS)
                & (it - start_it < max_rounds))

    def apply_fixes(state, fix_now, new_value):
        """Shared round tail: write fixed values, batched double_update of
        every touched constraint, epsilon-based light-set pruning."""
        v_value, v_fixed, remaining, usage, light, it = state
        v_value = jnp.where(fix_now, new_value, v_value)
        v_fixed = v_fixed | fix_now

        # Batched double_update on every constraint touched by fixed vars.
        e_fix = e_valid & jnp.take(fix_now, e_var)
        d_rem = allsum(jnp.zeros(n_c, dtype).at[e_cnst].add(
            jnp.where(e_fix, e_w * jnp.take(v_value, e_var), 0.0)))
        d_use = allsum(jnp.zeros(n_c, dtype).at[e_cnst].add(
            jnp.where(e_fix, e_upen, 0.0)))

        new_remaining = remaining - d_rem
        new_remaining = jnp.where(new_remaining < c_bound * eps, 0.0, new_remaining)
        new_usage_sum = usage - d_use
        new_usage_sum = jnp.where(new_usage_sum < eps, 0.0, new_usage_sum)

        e_live2 = e_valid & ~jnp.take(v_fixed, e_var)
        touched = allmax(jnp.zeros(n_c, dtype=bool).at[e_cnst].max(e_fix))
        if has_fatpipe:
            # FATPIPE: usage is re-derived as the max over unset variables.
            new_usage_max = allmax(jnp.zeros(n_c, dtype).at[e_cnst].max(
                jnp.where(e_live2, e_upen, 0.0)))
            new_usage = jnp.where(c_fatpipe, new_usage_max, new_usage_sum)
            usage = jnp.where(touched, new_usage, usage)
            remaining = jnp.where(touched & ~c_fatpipe, new_remaining,
                                  remaining)
        else:
            # static specialization (host-checked): no FATPIPE constraint
            # in the system, so the max-usage recompute drops out
            usage = jnp.where(touched, new_usage_sum, usage)
            remaining = jnp.where(touched, new_remaining, remaining)

        # A constraint leaves the light set only when *touched* by a fixed
        # variable and failing the epsilon tests (maxmin.cpp:607-609);
        # untouched constraints with tiny-but-positive usage stay in.
        drop = touched & (~(usage > eps) | ~(remaining > c_bound * eps))
        light = light & ~drop
        # Numerical safety net (no effect in exact arithmetic, where
        # usage - d_use reaches 0 exactly and the epsilon drop fires): a
        # constraint with no live variable left can never fix anything
        # again, so it must leave the light set even when f32 rounding of
        # the usage residual keeps it above eps — otherwise the loop spins
        # on an unfixable min-rou constraint until _MAX_ROUNDS (the round-1
        # TPU watchdog kill at 100k flows).
        has_live = allmax(jnp.zeros(n_c, bool).at[e_cnst].max(e_live2))
        light = light & has_live
        return v_value, v_fixed, remaining, usage, light, it + 1

    def body_global(state):
        """One global bottleneck level per round (reference order,
        maxmin.cpp:560-680)."""
        v_value, v_fixed, remaining, usage, light, it = state

        rou = jnp.where(light, remaining / jnp.where(light, usage, 1.0), inf)
        min_usage = jnp.min(rou)
        saturated_c = light & (rou == min_usage)

        # Saturated variables: any live element inside a saturated constraint.
        e_live = e_valid & ~jnp.take(v_fixed, e_var)
        e_sat = e_live & jnp.take(saturated_c, e_cnst)
        v_sat = allmax(jnp.zeros(n_v, dtype=bool).at[e_var].max(e_sat))

        if not has_bounds:
            # static specialization: no active variable bound, so the
            # bound-first rule drops out of the compiled round body
            return apply_fixes(state, v_sat,
                               min_usage / jnp.where(v_enabled, v_penalty,
                                                     1.0))

        # Bound-first rule (maxmin.cpp:566-596): if any saturated variable's
        # bound*penalty sits below min_usage, fix (only) the variables whose
        # bound*penalty equals the smallest such value this round.
        bp = v_bound * v_penalty
        has_low_bound = v_sat & (v_bound > 0) & (bp < min_usage)
        min_bound = jnp.min(jnp.where(has_low_bound, bp, inf))
        use_bounds = jnp.isfinite(min_bound)

        fix_now = jnp.where(use_bounds,
                            v_sat & (jnp.abs(bp - min_bound) < eps),
                            v_sat)
        new_value = jnp.where(use_bounds, v_bound,
                              min_usage / jnp.where(v_enabled, v_penalty, 1.0))
        return apply_fixes(state, fix_now, new_value)

    def body_local(state):
        """Fix every local-minimum constraint per round.  Exact: a
        constraint's rou = remaining/usage only ever increases when other
        variables are fixed (fixing removes a below-average contribution),
        so a constraint whose rou is minimal among every constraint it
        shares a live variable with already sits at its final level, no
        matter in which order the rest of the graph saturates."""
        v_value, v_fixed, remaining, usage, light, it = state

        rou = jnp.where(light, remaining / jnp.where(light, usage, 1.0), inf)
        e_live = e_valid & ~jnp.take(v_fixed, e_var)

        # Two-hop neighborhood min of rou: constraint -> vars -> constraint.
        e_rou = jnp.where(e_live, jnp.take(rou, e_cnst), inf)
        nmin_v = allmin(jnp.full(n_v, inf, dtype).at[e_var].min(e_rou))
        e_nmin = jnp.where(e_live, jnp.take(nmin_v, e_var), inf)
        nmin_c = allmin(jnp.full(n_c, inf, dtype).at[e_cnst].min(e_nmin))
        processable = light & (rou <= nmin_c)

        # Saturated vars and their levels (min processable rou containing v).
        e_proc = e_live & jnp.take(processable, e_cnst)

        if not has_bounds:
            # static specialization: with no active variable bound every
            # processable constraint is unblocked, so the level of a
            # saturated variable is just its min processable rou
            level2_v = allmin(jnp.full(n_v, inf, dtype).at[e_var].min(
                jnp.where(e_proc, e_rou, inf)))
            fix_now = jnp.isfinite(level2_v) & ~v_fixed
            return apply_fixes(state, fix_now,
                               level2_v / jnp.where(v_enabled, v_penalty,
                                                    1.0))

        v_sat = allmax(jnp.zeros(n_v, dtype=bool).at[e_var].max(e_proc))
        level_v = nmin_v

        # Bound-first rule, localized: a processable constraint holding a
        # below-level bounded variable only fixes its minimal such bounds
        # this round (the constraint re-enters with an updated rou), and
        # any constraint sharing a variable with it must wait, exactly as
        # the reference's global-min-bound round defers level fixing.
        bp = v_bound * v_penalty
        low_v = v_sat & (v_bound > 0) & (bp < level_v)
        e_bp = jnp.where(e_live & jnp.take(low_v, e_var),
                         jnp.take(bp, e_var), inf)
        mb_c = allmin(jnp.full(n_c, inf, dtype).at[e_cnst].min(e_bp))
        mb_c = jnp.where(processable, mb_c, inf)
        e_mb = jnp.where(e_proc, jnp.take(mb_c, e_cnst), inf)
        mb_v = allmin(jnp.full(n_v, inf, dtype).at[e_var].min(e_mb))
        e_blocked = e_proc & jnp.isfinite(jnp.take(mb_v, e_var))
        blocked_c = allmax(jnp.zeros(n_c, dtype=bool).at[e_cnst].max(e_blocked))

        # Level-fixing only through processable, unblocked constraints.
        ok_c = processable & ~blocked_c
        e_rou_ok = jnp.where(e_live & jnp.take(ok_c, e_cnst),
                             jnp.take(rou, e_cnst), inf)
        level2_v = allmin(jnp.full(n_v, inf, dtype).at[e_var].min(e_rou_ok))

        fix_bound = low_v & (jnp.abs(bp - mb_v) < eps)
        fix_level = jnp.isfinite(level2_v) & ~v_fixed & ~fix_bound
        fix_now = fix_bound | fix_level
        new_value = jnp.where(fix_bound, v_bound,
                              level2_v / jnp.where(v_enabled, v_penalty, 1.0))
        return apply_fixes(state, fix_now, new_value)

    out = _run_rounds(cond, body_local if parallel_rounds else body_global,
                      carry, max_rounds, unroll)
    v_value, v_fixed, remaining, usage, light, rounds = out
    if return_carry:
        return v_value, remaining, usage, rounds, out
    return v_value, remaining, usage, rounds


def _vc_round_body(vc_cnst, vc_w, vc_valid, v_penalty, c_bound,
                   c_fatpipe, eps, has_fatpipe):
    """THE bound-free vc-centric local round, on a 6-tuple state
    (v_value, v_fixed, remaining, usage, light, it): single source for
    both fixpoint_ell's dense path (which wraps it to thread its unused
    cv-side carry member) and the compaction chain.

    2 element gathers + 2 scatters over the near-unpadded vc tables.
    On the tunneled TPU both gather and scatter cost ~7-8 ns per
    ELEMENT, so working on [V, Wv] (~1x element count) instead of the
    padded [C, Wc] tables (~2.6x) and replacing constraint-row
    reductions with scatters more than halves the round latency.
    Every scatter keeps the 2D [V, Wv] index shape: the axon backend
    lowers flat-1D-index scatters ~7x slower than identical 2D-index
    ones (bench_results/tpu_opcost.jsonl)."""
    n_c = c_bound.shape[0]
    dtype = vc_w.dtype
    inf = jnp.array(jnp.inf, dtype)
    v_enabled = v_penalty > 0
    vc_evalid = vc_valid & v_enabled[:, None]
    vc_upen_v = (jnp.where(vc_evalid, vc_w, 0.0)
                 / jnp.where(v_enabled, v_penalty, 1.0)[:, None])

    def body(state):
        v_value, v_fixed, remaining, usage, light, it = state
        vc_live = vc_evalid & ~v_fixed[:, None]
        rou = jnp.where(light, remaining / jnp.where(light, usage, 1.0),
                        inf)
        rou_vc = jnp.take(rou, vc_cnst)
        nmin_v = jnp.where(vc_live, rou_vc,
                           inf).min(axis=1, initial=jnp.inf)
        el_nmin = jnp.where(vc_live, nmin_v[:, None], inf)
        nmin_c = jnp.full(n_c, jnp.inf, dtype).at[vc_cnst].min(el_nmin)
        processable = light & (rou <= nmin_c)
        vc_proc = vc_live & jnp.take(processable, vc_cnst)
        level2_v = jnp.where(vc_proc, rou_vc,
                             inf).min(axis=1, initial=jnp.inf)
        fix_now = jnp.isfinite(level2_v) & ~v_fixed
        new_value = level2_v / jnp.where(v_enabled, v_penalty, 1.0)
        v_value = jnp.where(fix_now, new_value, v_value)
        v_fixed = v_fixed | fix_now

        el_fix = vc_live & fix_now[:, None]
        live2 = vc_live & ~fix_now[:, None]
        contrib = jnp.stack(
            [jnp.where(el_fix, vc_w * v_value[:, None], 0.0),
             jnp.where(el_fix, vc_upen_v, 0.0),
             live2.astype(dtype)], axis=-1)
        sums = jnp.zeros((n_c, 3), dtype).at[vc_cnst].add(contrib)
        d_rem, d_use = sums[:, 0], sums[:, 1]
        touched = d_use > 0
        has_live = sums[:, 2] > 0

        new_remaining = remaining - d_rem
        new_remaining = jnp.where(new_remaining < c_bound * eps, 0.0,
                                  new_remaining)
        new_usage_sum = usage - d_use
        new_usage_sum = jnp.where(new_usage_sum < eps, 0.0,
                                  new_usage_sum)
        if has_fatpipe:
            el_upen = jnp.where(live2, vc_upen_v, 0.0)
            usage_max = jnp.zeros(n_c, dtype).at[vc_cnst].max(el_upen)
            new_usage = jnp.where(c_fatpipe, usage_max, new_usage_sum)
            usage = jnp.where(touched, new_usage, usage)
            remaining = jnp.where(touched & ~c_fatpipe, new_remaining,
                                  remaining)
        else:
            usage = jnp.where(touched, new_usage_sum, usage)
            remaining = jnp.where(touched, new_remaining, remaining)

        drop = touched & (~(usage > eps) | ~(remaining > c_bound * eps))
        light = light & ~drop & has_live
        return (v_value, v_fixed, remaining, usage, light, it + 1)

    return body


def _pos_group(n: int) -> int:
    """Index-array group width for scatters over [n] vectors: the axon
    backend lowers flat-1D-index scatters pathologically (~7x); any 2D
    shape takes the fast path."""
    for g in (128, 8):
        if n % g == 0:
            return g
    return 1


def _stable_livefirst_perm(livemask, group: int):
    """STABLE live-first partition permutation: perm[k] = index of the
    k-th row when live rows come first, each side keeping its original
    relative order.  Stability is what makes partition-based compaction
    exact: the reduction order over the survivors is unchanged, so
    dropping rows that contribute identity values keeps results
    bit-identical to the dense run.  Shared by the ELL compaction chain
    (_ell_chain_stage) and the drain executor's on-device repack.
    `group` is the 2D scatter-index width (_pos_group)."""
    lm = livemask.astype(jnp.int32)
    n_live = jnp.count_nonzero(livemask)
    pos = jnp.where(livemask, jnp.cumsum(lm) - 1,
                    n_live + jnp.cumsum(1 - lm) - 1).astype(jnp.int32)
    n = livemask.shape[0]
    return jnp.zeros(n, jnp.int32).at[pos.reshape(-1, group)].set(
        jnp.arange(n, dtype=jnp.int32).reshape(-1, group))


@functools.partial(jax.jit,
                   static_argnames=("eps", "cap", "half", "has_fatpipe"))
def _ell_chain_stage(vc_cnst, vc_w, vc_valid, v_penalty, orig_idx,
                     c_bound, c_fatpipe, v_final, carry,
                     eps: float, cap: int, half: int,
                     has_fatpipe: bool):
    """One compaction-chain stage: run vc rounds until the live variable
    count is <= half (or convergence / round cap), then partition the
    variable rows live-first (STABLE: live rows keep their relative
    order, so the scatter-add reduction order over the survivors is
    unchanged — dropping rows that contribute exact 0.0/inf identities
    keeps the chain bit-identical to the dense run) and slice the first
    `half` rows for the next stage.

    Dead rows' values are recorded into v_final (original numbering)
    before the slice.  Returns (new tables, new carry, v_final,
    overflow) — `overflow` set when the cap expired with > half rows
    live, in which case downstream stages are garbage and the caller
    falls back to the dense path."""
    dtype = vc_w.dtype
    body = _vc_round_body(vc_cnst, vc_w, vc_valid, v_penalty, c_bound,
                          c_fatpipe, jnp.asarray(eps, dtype),
                          has_fatpipe)
    v_enabled = v_penalty > 0
    start_it = carry[5]

    def cond(st):
        live = jnp.count_nonzero(~st[1] & v_enabled)
        return (jnp.any(st[4]) & (st[5] - start_it < cap)
                & (live > half))

    st = lax.while_loop(cond, body, carry)
    v_value, v_fixed = st[0], st[1]
    v_final = v_final.at[orig_idx].set(v_value)

    livemask = ~v_fixed & v_enabled
    n_live = jnp.count_nonzero(livemask)
    overflow = (n_live > half) & jnp.any(st[4])
    V = vc_cnst.shape[0]
    perm = _stable_livefirst_perm(livemask, _pos_group(V))
    keep = perm[:half]

    def rows(a):
        return jnp.take(a, keep, axis=0)

    tables = (rows(vc_cnst), rows(vc_w), rows(vc_valid),
              rows(v_penalty), rows(orig_idx))
    carry2 = (rows(st[0]), rows(st[1]), st[2], st[3], st[4], st[5])
    return tables, carry2, v_final, overflow


@functools.partial(jax.jit,
                   static_argnames=("eps", "chunk", "has_fatpipe"))
def _vc_chunk(vc_cnst, vc_w, vc_valid, v_penalty, c_bound, c_fatpipe,
              carry, eps: float, chunk: int, has_fatpipe: bool):
    """Finisher chunk for the chain: plain bounded vc rounds."""
    body = _vc_round_body(vc_cnst, vc_w, vc_valid, v_penalty, c_bound,
                          c_fatpipe, jnp.asarray(eps, vc_w.dtype),
                          has_fatpipe)
    start_it = carry[5]

    def cond(st):
        return (jnp.any(st[4]) & (st[5] < _MAX_ROUNDS)
                & (st[5] - start_it < chunk))

    return lax.while_loop(cond, body, carry)


@functools.partial(jax.jit, static_argnames=())
def _chain_fetch(v_final, orig_idx, carry, overflow):
    """Assemble the chain's single device->host transfer: stats,
    overflow flag, merged values, remaining, usage."""
    v_value, v_fixed, remaining, usage, light, it = carry
    dtype = v_final.dtype
    v_final = v_final.at[orig_idx].set(v_value)
    stats = jnp.stack([it.astype(dtype),
                       jnp.count_nonzero(light).astype(dtype),
                       jnp.count_nonzero(v_fixed).astype(dtype),
                       overflow.astype(dtype)])
    return jnp.concatenate([stats, v_final, remaining.astype(dtype),
                            usage.astype(dtype)])


#: Memo of chain init arrays per (ell identity, eps): fresh host->device
#: transfers per solve would cost more than the chain saves.
_CHAIN_INIT_CACHE: dict = {}
#: Chain stages stop once the halved shape would fall below this: the
#: per-round device time down there is microseconds and each extra
#: stage is one more XLA compile.
_CHAIN_MIN_V = 8192
#: Per-stage round cap.  The live set at the bench classes halves every
#: ~13 local rounds; 64 is generous while keeping one stage's device
#: time safely under the axon kernel watchdog.
_CHAIN_STAGE_CAP = 64


def _solve_ell_chain(ell: LmmEllArrays, eps: float, device,
                     has_fatpipe: bool, chunk: int):
    """Device-resident active-set compaction for the ELL/vc path: chain
    jitted stages at halving static shapes, each dispatched WITHOUT a
    host sync (the tunnel costs ~70 ms per round-trip); one fetch at
    the end returns stats + results.  Falls back (returns None) when a
    stage overflowed its cap or the system stalled.

    The CPU _Compactor repacks on the host between chunks — free there,
    ~70 ms + a fresh XLA compile per shape on a tunneled accelerator.
    This chain moves the same idea on-device: the partition is a stable
    live-first permutation, so dropped rows only remove exact-identity
    contributions (cf. _Compactor's docstring); results match the dense
    run up to XLA per-program reduction-order ulps (pinned by
    tests/test_lmm.py::test_ell_chain_matches_dense)."""
    dtype = ell.vc_w.dtype
    V0 = ell.v_penalty.shape[0]
    eps_f = float(eps)

    args = _device_args(
        "vc_chain",
        [ell.vc_cnst, ell.vc_w, ell.vc_valid, ell.v_penalty,
         ell.c_bound, ell.c_fatpipe], device)
    vc_cnst, vc_w, vc_valid, v_pen, c_bound, c_fat = args

    # Initial carry, matching fixpoint_ell's None-carry init (usage0
    # from cv row-sums; numpy's pairwise row-sum can differ from the
    # device reduce in final ulps — the oracle tests bound that).
    # Memoized per (ell, eps) so repeated solves reuse the same host
    # arrays and _DEVICE_ARGS_CACHE skips the ~150-500 ms re-upload.
    key = (id(ell.vc_cnst), id(ell.cv_w), eps_f)
    hit = _CHAIN_INIT_CACHE.get(key)
    if hit is not None and hit[0] is ell.vc_cnst and hit[1] is ell.cv_w:
        init_np = hit[2]
        # refresh LRU position so the hot entry survives transients
        # (eviction below pops oldest-first)
        _CHAIN_INIT_CACHE.pop(key)
        _CHAIN_INIT_CACHE[key] = hit
    else:
        np_pen = ell.v_penalty
        safe_pen = np.where(np_pen > 0, np_pen, 1.0)
        cv_evalid = ell.cv_valid & (np_pen[ell.cv_var] > 0)
        cv_upen = np.where(cv_evalid,
                           ell.cv_w / safe_pen[ell.cv_var],
                           0.0).astype(dtype)
        usage0_np = cv_upen.sum(axis=1, dtype=dtype)
        if has_fatpipe:
            usage0_np = np.where(ell.c_fatpipe,
                                 cv_upen.max(axis=1, initial=0.0),
                                 usage0_np)
        light0_np = ((ell.c_bound > ell.c_bound * eps_f)
                     & (usage0_np > 0))
        init_np = [np.zeros(V0, dtype), (np_pen < 0),
                   ell.c_bound.astype(dtype), usage0_np, light0_np,
                   np.arange(V0, dtype=np.int32)]
        if len(_CHAIN_INIT_CACHE) >= 8:
            _CHAIN_INIT_CACHE.pop(next(iter(_CHAIN_INIT_CACHE)))
        _CHAIN_INIT_CACHE[key] = (ell.vc_cnst, ell.cv_w, init_np)
    init = _device_args("vc_chain_init", init_np, device)
    carry = (init[0], init[1], init[2], init[3], init[4],
             jnp.asarray(0, jnp.int32))
    orig_idx = init[5]
    v_final = jnp.zeros(V0, dtype)

    overflow = jnp.asarray(False, jnp.bool_)
    tables = (vc_cnst, vc_w, vc_valid, v_pen, orig_idx)
    Vs = V0
    while Vs // 2 >= _CHAIN_MIN_V:
        tables, carry, v_final, ov = _ell_chain_stage(
            *tables, c_bound, c_fat, v_final, carry,
            eps=eps_f, cap=_CHAIN_STAGE_CAP, half=Vs // 2,
            has_fatpipe=has_fatpipe)
        overflow = overflow | ov
        Vs //= 2

    # Finisher: bounded chunks to convergence, still sync-free between
    # dispatches; each iteration fetches stats+results in ONE transfer.
    prev_progress = None
    while True:
        carry = _vc_chunk(*tables[:4], c_bound, c_fat, carry,
                          eps=eps_f, chunk=chunk,
                          has_fatpipe=has_fatpipe)
        fetched = np.asarray(_chain_fetch(v_final, tables[4], carry,
                                          overflow))
        rounds, n_light, n_fixed, oflow = (int(fetched[0]),
                                           int(fetched[1]),
                                           int(fetched[2]),
                                           bool(fetched[3]))
        if oflow:
            return None     # caller re-solves on the dense path
        if n_light == 0:
            break
        if rounds >= _MAX_ROUNDS:
            raise RuntimeError(
                f"LMM chain solve did not converge within {_MAX_ROUNDS} "
                f"saturation rounds ({ell.n_cnst} constraints, "
                f"{ell.n_var} variables, {n_light} still active); "
                f"check maxmin/precision vs the system's magnitudes")
        progress = (n_light, n_fixed)
        if progress == prev_progress:
            return None     # stalled: let the dense path diagnose
        prev_progress = progress

    n_cc = ell.c_bound.shape[0]
    values = fetched[4:4 + V0]
    remaining = fetched[4 + V0:4 + V0 + n_cc]
    usage = fetched[4 + V0 + n_cc:4 + V0 + 2 * n_cc]
    return values, remaining, usage, rounds


@functools.partial(jax.jit,
                   static_argnames=("eps", "parallel_rounds", "chunk",
                                    "unroll", "has_bounds",
                                    "has_fatpipe"))
def _solve_ell_chunk(cv_var, cv_w, cv_valid, vc_cnst, vc_valid, c_bound,
                     c_fatpipe, v_penalty, v_bound, vc_w, carry,
                     eps: float, parallel_rounds: bool, chunk: int,
                     unroll: bool = False, has_bounds: bool = True,
                     has_fatpipe: bool = True):
    """eps is static: it is fixed per run (maxmin/precision), and a
    traced scalar would be one more host->device transfer per chunk —
    each costing hundreds of ms of latency on a tunneled accelerator."""
    ell = LmmEllArrays(cv_var, cv_w, cv_valid, vc_cnst, vc_valid, c_bound,
                       c_fatpipe, v_penalty, v_bound, 0, 0, vc_w)
    return fixpoint_ell(ell, jnp.asarray(eps, cv_w.dtype), carry=carry,
                        parallel_rounds=parallel_rounds, max_rounds=chunk,
                        return_carry=True, unroll=unroll,
                        has_bounds=has_bounds, has_fatpipe=has_fatpipe)


#: Device-resident copies of solver inputs, keyed by (kind, ids,
#: device). The flagship accelerator sits behind a high-latency tunnel
#: where EVERY host->device transfer costs 150-500 ms regardless of
#: size; re-shipping ~11 arrays per solve dominated the round-1 solve
#: time (7 of 9.5 s at 100k flows). Values keep the host arrays alive
#: and identity-checked, like _ELL_CACHE.
_DEVICE_ARGS_CACHE: dict = {}


def _device_args(kind: str, host_args, device):
    # CONTRACT: callers must never mutate a host array in place after
    # passing it here — the identity check below cannot see mutation.
    # Safe today because flatten()/to_ell() always build fresh arrays.
    key = (kind, tuple(id(a) for a in host_args),
           None if device is None else str(device))
    hit = _DEVICE_ARGS_CACHE.get(key)
    if hit is not None:
        src, dev_args = hit
        if all(a is b for a, b in zip(src, host_args)):
            # refresh LRU position so a steady hot entry survives
            # transient keys (eviction below pops oldest-first)
            _DEVICE_ARGS_CACHE.pop(key)
            _DEVICE_ARGS_CACHE[key] = hit
            return dev_args
    dev_args = [jax.device_put(a, device) for a in host_args]
    from . import opstats
    opstats.bump("uploaded_bytes_full",
                 sum(getattr(a, "nbytes", 0) for a in host_args))
    if len(_DEVICE_ARGS_CACHE) >= 8:
        # evict oldest-first (dict preserves insertion order) instead of
        # dropping the whole cache — the hot entry is usually the newest
        _DEVICE_ARGS_CACHE.pop(next(iter(_DEVICE_ARGS_CACHE)))
    _DEVICE_ARGS_CACHE[key] = (list(host_args), dev_args)
    return dev_args


#: Tiny memo for COO->ELL conversions so repeated solves of the same
#: arrays (benchmarks, retries) do not re-pack on the host every call.
#: Values hold the source LmmArrays, which (a) keeps the ids in the key
#: alive so they cannot be recycled onto new arrays, and (b) allows an
#: identity check on every field before a hit is trusted.
_ELL_CACHE: dict = {}


def _ell_cached(arrays: LmmArrays) -> Optional[LmmEllArrays]:
    key = (id(arrays.e_var), id(arrays.e_cnst))
    hit = _ELL_CACHE.get(key)
    if hit is not None:
        src, ell = hit
        if all(a is b for a, b in zip(src, arrays)):
            return ell
    ell = ell_from_arrays(arrays)
    if len(_ELL_CACHE) >= 8:
        _ELL_CACHE.clear()
    _ELL_CACHE[key] = (arrays, ell)
    return ell


@functools.partial(jax.jit,
                   static_argnames=("eps", "n_c", "n_v",
                                    "parallel_rounds", "chunk", "unroll",
                                    "has_bounds", "has_fatpipe"))
def _solve_kernel_chunk(e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty,
                        v_bound, carry, eps: float, n_c: int, n_v: int,
                        parallel_rounds: bool, chunk: int,
                        unroll: bool = False, has_bounds: bool = True,
                        has_fatpipe: bool = True):
    """Run at most `chunk` more saturation rounds from `carry` (None =
    fresh start) and return (values, remaining, usage, rounds, carry).
    eps is static for the same reason as _solve_ell_chunk's."""
    return fixpoint(e_var, e_cnst, e_w, c_bound, c_fatpipe, v_penalty,
                    v_bound, jnp.asarray(eps, e_w.dtype), n_c, n_v,
                    axis=None, parallel_rounds=parallel_rounds,
                    carry=carry, max_rounds=chunk, return_carry=True,
                    unroll=unroll, has_bounds=has_bounds,
                    has_fatpipe=has_fatpipe)


def _solve_chunk_batched_lane(e_var, e_cnst, ew, cb, fat, pen, vb, carry,
                              eps: float, n_c: int, n_v: int,
                              parallel_rounds: bool, chunk: int,
                              has_bounds: bool, has_fatpipe: bool):
    return fixpoint(e_var, e_cnst, ew, cb, fat, pen, vb,
                    jnp.asarray(eps, ew.dtype), n_c, n_v, axis=None,
                    parallel_rounds=parallel_rounds, carry=carry,
                    max_rounds=chunk, return_carry=True,
                    has_bounds=has_bounds, has_fatpipe=has_fatpipe)


@functools.partial(jax.jit,
                   static_argnames=("eps", "n_c", "n_v",
                                    "parallel_rounds", "chunk",
                                    "has_bounds", "has_fatpipe",
                                    "batch_w"))
def _solve_kernel_chunk_batched_fresh(e_var, e_cnst, e_w, c_bound,
                                      c_fatpipe, v_penalty, v_bound,
                                      eps: float, n_c: int, n_v: int,
                                      parallel_rounds: bool, chunk: int,
                                      has_bounds: bool = True,
                                      has_fatpipe: bool = True,
                                      batch_w: bool = True):
    """Batched (leading replica axis) counterpart of _solve_kernel_chunk,
    fresh-start flavor: ONE device program runs the first `chunk`
    saturation rounds of B independent systems that share the COO
    structure (e_var/e_cnst uploaded once) but carry per-replica
    weights/bounds/penalties.  `batch_w=False` shares the element
    weights too (pure bound/penalty sweeps).  Consumed by
    ops.lmm_batch.solve_arrays_batch."""
    def lane(ew, cb, pen, vb):
        return _solve_chunk_batched_lane(
            e_var, e_cnst, ew, cb, c_fatpipe, pen, vb, None, eps, n_c,
            n_v, parallel_rounds, chunk, has_bounds, has_fatpipe)
    return jax.vmap(lane, in_axes=(0 if batch_w else None, 0, 0, 0))(
        e_w, c_bound, v_penalty, v_bound)


@functools.partial(jax.jit,
                   static_argnames=("eps", "n_c", "n_v",
                                    "parallel_rounds", "chunk",
                                    "has_bounds", "has_fatpipe",
                                    "batch_w"))
def _solve_kernel_chunk_batched(e_var, e_cnst, e_w, c_bound, c_fatpipe,
                                v_penalty, v_bound, carry, eps: float,
                                n_c: int, n_v: int,
                                parallel_rounds: bool, chunk: int,
                                has_bounds: bool = True,
                                has_fatpipe: bool = True,
                                batch_w: bool = True):
    """Continuation flavor: resume each replica from its carried loop
    state.  Converged lanes are frozen by their own while_loop cond, so
    re-dispatching a mixed fleet never perturbs finished replicas."""
    def lane(ew, cb, pen, vb, carry_l):
        return _solve_chunk_batched_lane(
            e_var, e_cnst, ew, cb, c_fatpipe, pen, vb, carry_l, eps,
            n_c, n_v, parallel_rounds, chunk, has_bounds, has_fatpipe)
    return jax.vmap(lane, in_axes=(0 if batch_w else None, 0, 0, 0, 0))(
        e_w, c_bound, v_penalty, v_bound, carry)


def flatten(cnst_list: List[Constraint], dtype=np.float64
            ) -> Optional[Tuple[LmmArrays, List["Variable"]]]:
    """Flatten the live portion of a host System into padded COO arrays.

    Slot numbering follows the constraint-list iteration order and, within
    each constraint, the enabled-element list order, giving the same
    deterministic structure the reference's intrusive lists provide.
    """
    var_slots = {}
    v_penalty: List[float] = []
    v_bound: List[float] = []
    vars_in_order = []
    e_var: List[int] = []
    e_cnst: List[int] = []
    e_w: List[float] = []
    c_bound: List[float] = []
    c_fat: List[bool] = []

    for ci, cnst in enumerate(cnst_list):
        c_bound.append(cnst.bound)
        c_fat.append(cnst.sharing_policy == SharingPolicy.FATPIPE)
        for elem in cnst.enabled_element_set:
            var = elem.variable
            slot = var_slots.get(id(var))
            if slot is None:
                slot = len(v_penalty)
                var_slots[id(var)] = slot
                v_penalty.append(var.sharing_penalty)
                v_bound.append(var.bound)
                vars_in_order.append(var)
            e_var.append(slot)
            e_cnst.append(ci)
            e_w.append(elem.consumption_weight)

    n_e, n_c, n_v = len(e_var), len(c_bound), len(v_penalty)
    if n_c == 0:
        return None
    E, C, V = _bucket(max(n_e, 1)), _bucket(n_c), _bucket(max(n_v, 1))

    arrays = LmmArrays(
        e_var=np.zeros(E, np.int32), e_cnst=np.zeros(E, np.int32),
        e_w=np.zeros(E, dtype), c_bound=np.zeros(C, dtype),
        c_fatpipe=np.zeros(C, bool), v_penalty=np.zeros(V, dtype),
        v_bound=np.full(V, -1.0, dtype), n_elem=n_e, n_cnst=n_c, n_var=n_v)
    arrays.e_var[:n_e] = e_var
    # Padding elements point at constraint slot 0 with weight 0: harmless.
    arrays.e_cnst[:n_e] = e_cnst
    arrays.e_w[:n_e] = e_w
    arrays.c_bound[:n_c] = c_bound
    arrays.c_fatpipe[:n_c] = c_fat
    arrays.v_penalty[:n_v] = v_penalty
    arrays.v_bound[:n_v] = v_bound
    return arrays, vars_in_order


def use_local_rounds() -> bool:
    """Parse + validate the lmm/rounds flag (local|global)."""
    mode = config["lmm/rounds"]
    if mode not in ("local", "global"):
        raise ValueError(f"Unknown lmm/rounds {mode!r} "
                         "(expected local or global)")
    return mode == "local"


# Device rounds per dispatch: bounds single-kernel run time (a spinning
# f32 solve must come back to the host and raise, not trip the TPU
# watchdog) while keeping the per-dispatch overhead negligible for the
# common small-round case. On an accelerator the cap is much lower: at
# 100k flows one COO round costs ~100ms of device time (scatter-bound),
# so 4096 rounds in one dispatch is minutes of kernel runtime — that,
# not the math, is what killed the TPU worker in round 1 (the axon
# watchdog kills kernels that run too long). 64 rounds keeps a
# dispatch under ~10s worst-case while local-rounds solves typically
# finish in one.
_CHUNK_ROUNDS = 4096
#: Local-rounds solves converge in O(10-100) rounds and the vc-centric
#: ELL round is ~2-17 ms of device time, so 256 rounds per dispatch
#: stays well under the axon watchdog while letting every practical
#: solve finish in ONE dispatch (each host sync costs a ~70 ms tunnel
#: round-trip); the while_loop cond exits early once converged.
_CHUNK_ROUNDS_ACCEL = 256
#: Rounds per dispatch in unrolled mode: compile time scales linearly
#: with the unroll factor, so keep chunks small — local-rounds solves
#: typically converge in O(10) rounds anyway.
_CHUNK_ROUNDS_UNROLL = 16
#: Below this element count the whole solve costs ~a millisecond and
#: compaction's per-chunk host sync + repack + per-shape recompiles
#: are pure overhead on the simulator's per-step hot path.
_COMPACT_MIN_ELEMS = 4096
#: one-shot flag for the lmm/compact:on-with-ELL warning
_WARNED_COMPACT_ELL = False


def _default_platform() -> str:
    try:
        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def _default_chunk() -> int:
    return _CHUNK_ROUNDS if _default_platform() == "cpu" \
        else _CHUNK_ROUNDS_ACCEL


class _Compactor:
    """Host-side active-set compaction for the COO chunk loop (see
    solve_arrays).  Owns the CURRENT (possibly repacked) host arrays,
    the current->original row maps, and the full-size result mirrors
    retired rows are merged into.

    Exact by construction: a retired element only ever contributes
    identity values to the round reductions (0.0 to the scatter-adds
    and the bool/float maxes, inf to the mins; 0.0 + x == x,
    max(0.0, u>=0) == u, min(inf, r) == r), and a retired row's state
    is frozen the moment its last live element dies — a variable
    retires fixed, a constraint that can never again be touched keeps
    its remaining/usage."""

    def __init__(self, arrays: LmmArrays, device):
        self.device = device
        self.e = (arrays.e_var, arrays.e_cnst, arrays.e_w)
        self.vc = (arrays.v_penalty, arrays.v_bound, arrays.c_bound,
                   arrays.c_fatpipe)
        self.v_map = self.c_map = None
        self.final = None
        self.orig_nv = len(arrays.v_penalty)
        self.orig_nc = len(arrays.c_bound)

    def try_compact(self, carry):
        """Repack when at most half the element rows are still live.
        Returns (device_args, carry, n_v, n_c) for the shrunken
        system, or None when density is still high."""
        e_var, e_cnst, e_w = self.e
        v_pen, v_bnd, c_bnd, c_fat = self.vc
        vfix = np.asarray(carry[1])
        live = (e_w > 0) & (v_pen[e_var] > 0) & ~vfix[e_var]
        n_live = int(live.sum())
        if n_live > len(e_var) // 2:
            return None
        dt = e_w.dtype
        # rows referenced by a live element stay; all others retire
        vmask = np.zeros(len(v_pen), bool)
        vmask[e_var[live]] = True
        kept_v = np.flatnonzero(vmask)
        cmask = np.zeros(len(c_bnd), bool)
        cmask[e_cnst[live]] = True
        kept_c = np.flatnonzero(cmask)

        vv, vfx, rem, use, lig = (np.asarray(x) for x in carry[:5])
        if self.final is None:
            self.final = (np.zeros(self.orig_nv, dt),
                          np.zeros(self.orig_nc, dt),
                          np.zeros(self.orig_nc, dt))
        vm = (self.v_map if self.v_map is not None
              else np.arange(len(v_pen)))
        cm = (self.c_map if self.c_map is not None
              else np.arange(len(c_bnd)))
        fv, fr, fu = self.final
        # current arrays are bucket-padded beyond the map length
        fv[vm] = vv[:len(vm)]
        fr[cm] = rem[:len(cm)]
        fu[cm] = use[:len(cm)]
        self.v_map, self.c_map = vm[kept_v], cm[kept_c]

        Eb = _bucket(max(n_live, 1))
        Vb = _bucket(max(len(kept_v), 1))
        Cb = _bucket(max(len(kept_c), 1))
        v_o2n = np.zeros(len(v_pen), np.int32)
        v_o2n[kept_v] = np.arange(len(kept_v), dtype=np.int32)
        c_o2n = np.zeros(len(c_bnd), np.int32)
        c_o2n[kept_c] = np.arange(len(kept_c), dtype=np.int32)

        def repack(src, fill, n, idx):
            out = np.full(n, fill, src.dtype)
            out[:len(idx)] = src[idx]
            return out

        ev = np.zeros(Eb, np.int32)
        ev[:n_live] = v_o2n[e_var[live]]
        ec = np.zeros(Eb, np.int32)
        ec[:n_live] = c_o2n[e_cnst[live]]
        ew = np.zeros(Eb, dt)
        ew[:n_live] = e_w[live]
        self.e = (ev, ec, ew)
        self.vc = (repack(v_pen, 0.0, Vb, kept_v),
                   repack(v_bnd, -1.0, Vb, kept_v),
                   repack(c_bnd, 0.0, Cb, kept_c),
                   repack(c_fat, False, Cb, kept_c))

        # compacted arrays bypass _DEVICE_ARGS_CACHE — they are fresh
        # per solve and would thrash it
        def put(a):
            return jax.device_put(a, self.device)
        args = [put(a) for a in
                (ev, ec, ew, self.vc[2], self.vc[3],
                 self.vc[0], self.vc[1])]
        carry = (put(repack(vv, 0.0, Vb, kept_v)),
                 put(repack(vfx, False, Vb, kept_v)),
                 put(repack(rem, 0.0, Cb, kept_c)),
                 put(repack(use, 0.0, Cb, kept_c)),
                 put(repack(lig, False, Cb, kept_c)),
                 carry[5])
        return args, carry, Vb, Cb

    def merge(self, values, remaining, usage):
        """Final (values, remaining, usage) at ORIGINAL row numbering,
        or None when no compaction ever ran."""
        if self.final is None:
            return None
        fv, fr, fu = self.final
        fv[self.v_map] = np.asarray(values)[:len(self.v_map)]
        fr[self.c_map] = np.asarray(remaining)[:len(self.c_map)]
        fu[self.c_map] = np.asarray(usage)[:len(self.c_map)]
        return fv, fr, fu


def solve_arrays(arrays: LmmArrays, eps: float, device=None,
                 parallel_rounds: Optional[bool] = None,
                 chunk: Optional[int] = None,
                 unroll: Optional[bool] = None):
    """Run the jit'd fixpoint in bounded-round chunks with host-side
    convergence checks between dispatches; returns
    (values, remaining, usage, rounds)."""
    chunk_given = chunk is not None
    if parallel_rounds is None:
        parallel_rounds = use_local_rounds()
    if unroll is None:
        mode = config["lmm/unroll"]
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"Unknown lmm/unroll {mode!r} "
                             "(expected auto, on or off)")
        # 'auto' now means OFF everywhere: the round-4 on-chip profile
        # (bench_results/tpu_round_profile.jsonl) shows while_loop
        # gathers lower fine on the axon TPU — the round-3 serialized-
        # gather pathology was the wedged chip, not the lowering — and
        # unrolling only multiplies compile time.  'on' stays available
        # as the escape hatch.
        unroll = mode == "on"
    if chunk is None:
        chunk = _CHUNK_ROUNDS_UNROLL if unroll else _default_chunk()

    # Layout: ELL (dense padded rows, no scatters) on accelerators when
    # the graph is not too skewed; COO everywhere else. lmm/layout
    # overrides (coo|ell|auto).
    layout = config["lmm/layout"]
    platform = (device.platform if device is not None
                else _default_platform())
    ell = None
    if layout == "ell" or (layout == "auto" and platform != "cpu"):
        ell = _ell_cached(arrays)

    # Active-set compaction: between chunks, repack the element list
    # dropping elements of already-fixed variables.  Bit-identical to
    # the dense run — a dead element contributes exact identities to
    # every reduction (0.0 to the scatter-adds and the bool/float
    # maxes, inf to the min-reductions), and float identities commute:
    # 0.0 + x == x, max(0.0, u>=0) == u, min(inf, r) == r.  COO on CPU
    # only by default: the per-chunk host sync and device_put that
    # compaction needs are free there, while on a tunneled accelerator
    # each costs a ~70 ms round-trip (and a fresh ~30 s XLA compile per
    # new element-bucket size).
    cmode = config["lmm/compact"]
    if cmode not in ("auto", "on", "off"):
        raise ValueError(f"Unknown lmm/compact {cmode!r} "
                         "(expected auto, on or off)")
    if cmode == "on" and ell is not None:
        global _WARNED_COMPACT_ELL
        if not _WARNED_COMPACT_ELL:
            _WARNED_COMPACT_ELL = True
            from ..utils import log as _log
            _log.get_category("lmm").warning(
                "lmm/compact:on has no effect on the ELL layout; set "
                "lmm/layout:coo to compact on this device")
    if (ell is None and platform != "cpu" and not chunk_given
            and len(arrays.e_var) >= 1 << 20):
        # Big COO systems on the accelerator: a round costs tens of ms
        # of device time, so 256 rounds in one dispatch can exceed the
        # axon watchdog's kernel-runtime budget (observed as "TPU
        # worker crashed" on the 1.3M-element config-#4 alltoall
        # system).  Cap the per-dispatch round count so one chunk
        # stays ~1-2 s worst case.
        chunk = min(chunk, 32)
    compacting = (ell is None
                  and arrays.n_elem >= _COMPACT_MIN_ELEMS
                  and (cmode == "on"
                       or (cmode == "auto" and platform == "cpu")))
    if compacting and not chunk_given:
        # short chunks create the compaction points (the live element
        # count at 100k flows halves roughly every 13 local rounds and
        # far faster on small systems); global mode fixes ~one variable
        # per round, so halvings are ~n_v rounds apart and short chunks
        # would only add per-dispatch sync overhead.  An explicit
        # caller-chosen chunk is honored as-is.
        chunk = min(chunk, 4 if parallel_rounds else 64)

    eps_f = float(eps)
    # static specialization: systems with no active variable bound
    # (the common network/bench case) compile a round body with half
    # the gathers — decided HOST-side so it stays a compile-time flag
    has_bounds = bool(np.any((arrays.v_bound[:arrays.n_var] > 0)
                             & (arrays.v_penalty[:arrays.n_var] > 0)))
    has_fatpipe = bool(np.any(arrays.c_fatpipe[:arrays.n_cnst]))
    chain_mode = config["lmm/chain"]
    if chain_mode not in ("auto", "on", "off"):
        raise ValueError(f"Unknown lmm/chain {chain_mode!r} "
                         "(expected auto, on or off)")
    if (ell is not None and ell.vc_w is not None and parallel_rounds
            and not has_bounds and not unroll
            and len(ell.v_penalty) >= 2 * _CHAIN_MIN_V
            and (chain_mode == "on"
                 or (chain_mode == "auto" and platform != "cpu"))):
        res = _solve_ell_chain(ell, eps_f, device, has_fatpipe,
                               chunk if chunk_given
                               else _CHUNK_ROUNDS_ACCEL)
        if res is not None:
            return res
        # overflow/stall: fall through to the dense path below

    compactor = None
    if ell is not None:
        args = _device_args(
            "ell",
            [ell.cv_var, ell.cv_w, ell.cv_valid, ell.vc_cnst,
             ell.vc_valid, ell.c_bound, ell.c_fatpipe, ell.v_penalty,
             ell.v_bound, ell.vc_w], device)

        def run_chunk(carry):
            return _solve_ell_chunk(*args, carry, eps=eps_f,
                                    parallel_rounds=parallel_rounds,
                                    chunk=chunk, unroll=unroll,
                                    has_bounds=has_bounds,
                                    has_fatpipe=has_fatpipe)
    else:
        args = _device_args(
            "coo",
            [arrays.e_var, arrays.e_cnst, arrays.e_w, arrays.c_bound,
             arrays.c_fatpipe, arrays.v_penalty, arrays.v_bound], device)
        cur_nc, cur_nv = len(arrays.c_bound), len(arrays.v_penalty)
        if compacting:
            compactor = _Compactor(arrays, device)

        def run_chunk(carry):
            return _solve_kernel_chunk(
                *args, carry, eps=eps_f, n_c=cur_nc, n_v=cur_nv,
                parallel_rounds=parallel_rounds, chunk=chunk,
                unroll=unroll, has_bounds=has_bounds,
                has_fatpipe=has_fatpipe)

    from . import opstats
    carry = None
    prev_progress = None
    while True:
        values, remaining, usage, rounds, carry = run_chunk(carry)
        opstats.bump("dispatches")
        # ONE host sync per chunk: [rounds, light count, fixed count]
        # AND the result vectors ride a single device->host transfer
        # (per-transfer latency, not size, is the cost driver on a
        # tunneled accelerator — a converged solve pays exactly one
        # ~70 ms round-trip).  Counts are exact in f32 (< 2^24).
        rdt = values.dtype
        n_vc, n_cc = values.shape[0], remaining.shape[0]
        fetched = np.asarray(jnp.concatenate([
            jnp.stack([rounds.astype(rdt),
                       jnp.count_nonzero(carry[4]).astype(rdt),
                       jnp.count_nonzero(carry[1]).astype(rdt)]),
            values, remaining.astype(rdt), usage.astype(rdt)]))
        rounds, n_light, n_fixed = (int(fetched[0]), int(fetched[1]),
                                    int(fetched[2]))
        if n_light == 0:
            values = fetched[3:3 + n_vc]
            remaining = fetched[3 + n_vc:3 + n_vc + n_cc]
            usage = fetched[3 + n_vc + n_cc:3 + n_vc + 2 * n_cc]
            break
        if rounds >= _MAX_ROUNDS:
            raise RuntimeError(
                f"LMM JAX solve did not converge within {_MAX_ROUNDS} "
                f"saturation rounds ({arrays.n_cnst} constraints, "
                f"{arrays.n_var} variables, {n_light} still active); "
                f"check maxmin/precision vs the system's magnitudes")
        progress = (n_light, n_fixed)
        if progress == prev_progress:
            raise RuntimeError(
                f"LMM JAX solve stalled after {rounds} rounds: "
                f"{n_light} active constraints and {n_fixed} fixed "
                f"variables unchanged over {chunk} rounds "
                f"({arrays.n_cnst} constraints, {arrays.n_var} variables); "
                f"the system does not converge at eps={eps} in "
                f"{arrays.e_w.dtype} precision")
        prev_progress = progress
        if compactor is not None:
            packed = compactor.try_compact(carry)
            if packed is not None:
                args, carry, cur_nv, cur_nc = packed
                # the repack drops the already-fixed rows, so the
                # fixed-count census restarts near zero — a progress
                # comparison across a compaction would false-positive
                # the stall detector (a stalled solve never compacts:
                # compaction requires the live set to halve)
                prev_progress = None
    opstats.bump("fixpoint_rounds", rounds)
    merged = (compactor.merge(values, remaining, usage)
              if compactor is not None else None)
    if merged is not None:
        return merged[0], merged[1], merged[2], rounds
    # values/remaining/usage are host np slices of the converged
    # chunk's single fetch.
    return values, remaining, usage, rounds


def check_convergence(rounds: int, n_cnst, n_var) -> None:
    """Raise if a (non-chunked) fixpoint hit the round cap (used by the
    sharded paths, which run the loop to completion in one dispatch)."""
    if rounds >= _MAX_ROUNDS:
        raise RuntimeError(
            f"LMM JAX solve did not converge within {_MAX_ROUNDS} saturation "
            f"rounds ({n_cnst} constraints, {n_var} variables); "
            f"check maxmin/precision vs the system's magnitudes")


def solve_flattened(system: System, dtype, solve_flat,
                    allow_device: bool = False) -> None:
    """Shared backend wrapper: flatten host graph, solve, scatter back.

    Mirrors the side effects of System::lmm_solve (maxmin.cpp:487-500):
    values written to variables, modified-action collection for lazy model
    updates, constraint usage left consistent, modified flags cleared.
    ``solve_flat(arrays, eps) -> (values, remaining, usage)`` is the
    actual solver (device fixpoint or native C++).

    Full-update systems run through the incrementally-maintained
    ArrayView (ops.lmm_view): no per-solve graph walk at all — the
    arrays were kept in sync by the mutation hooks, so a solve is
    snapshot + device dispatch + scatter-back.

    Selective-update systems on a device backend (``allow_device``)
    are served by the warm solver (ops.lmm_warm): device-resident
    masters, per-slot delta uploads, and warm-started modified-
    component fixpoint restarts.  ``lmm/warm-start:off`` restores the
    legacy behavior below — re-flatten the modified subset and solve
    it cold each time.
    """
    eps = config["maxmin/precision"]

    if system.selective_update_active and allow_device:
        from . import lmm_warm
        if lmm_warm.solve_selective(system, dtype, eps):
            return

    if not system.selective_update_active:
        view = system.array_view
        if view is None:
            from .lmm_view import ArrayView
            view = ArrayView(system)
        arrays = view.snapshot(dtype)
        if arrays.n_cnst:
            values, remaining, usage = solve_flat(arrays, eps)
            vals = np.asarray(values).tolist()
            for slot, var in enumerate(view.slot_var):
                if var is not None:
                    var.value = vals[slot]
            rem = np.asarray(remaining).tolist()
            use = np.asarray(usage).tolist()
            for slot, cnst in enumerate(view.slot_cnst):
                if cnst is not None:
                    cnst.remaining = rem[slot]
                    cnst.usage = use[slot]
        system.modified = False
        return

    cnst_list = list(system.modified_constraint_set)

    # Reset + collect modified actions exactly like the init pass of the
    # list solver (maxmin.cpp:509-539).
    for cnst in cnst_list:
        for elem in cnst.enabled_element_set:
            elem.variable.value = 0.0
    if system.modified_actions is not None:
        # Unlike the reference (maxmin.cpp:523-525) zero-bound constraints'
        # actions are reported too, so the lazy model drops their stale
        # completion dates (park support, see Model lazy path).
        for cnst in cnst_list:
            for elem in cnst.enabled_element_set:
                if elem.consumption_weight > 0:
                    system.flag_action_modified(elem.variable.id)

    flat = flatten(cnst_list, dtype)
    if flat is not None:
        arrays, vars_in_order = flat
        values, remaining, usage = solve_flat(arrays, eps)
        for slot, var in enumerate(vars_in_order):
            var.value = float(values[slot])
        # Scatter back the kernel's end-state remaining/usage so constraint
        # introspection matches the list solver's post-solve state.
        for ci, cnst in enumerate(cnst_list):
            cnst.remaining = float(remaining[ci])
            cnst.usage = float(usage[ci])

    system.modified = False
    if system.selective_update_active:
        system.remove_all_modified_set()


#: solves completed by the exact host solver after the device kernel
#: failed (non-convergence, stall, or non-finite output); see solve_jax
_fallback_count = 0
_fallback_warned = False


def get_fallback_count() -> int:
    return _fallback_count


def reset_fallback_count() -> None:
    global _fallback_count
    _fallback_count = 0


def _solve_host_exact(system: System) -> None:
    """The graceful-degradation target: exact host solve of the same
    system (native C++ when available, Python list solver otherwise)."""
    from . import lmm_native
    if lmm_native.available():
        lmm_native.solve_native(system)
    else:
        system.solve_exact()


def solve_jax(system: System) -> None:
    """Backend entry: flatten host graph, solve on device, scatter back.

    Graceful degradation: when the device fixpoint fails to converge
    (round cap, stall) or returns non-finite rates, the solve is redone
    by the exact host solver instead of aborting the whole simulation —
    a production run survives one numerically-degenerate system.  The
    hard raise is preserved behind ``--cfg=lmm/strict:1`` for
    convergence testing."""
    global _fallback_count, _fallback_warned
    dtype = np.float32 if config["lmm/dtype"] == "float32" else np.float64

    def solve_flat(arrays, eps):
        values, remaining, usage, _ = solve_arrays(arrays, eps)
        if not np.all(np.isfinite(np.asarray(values))):
            raise RuntimeError(
                "LMM JAX solve returned non-finite rates "
                f"({arrays.n_cnst} constraints, {arrays.n_var} variables, "
                f"dtype {np.dtype(dtype).name})")
        return values, remaining, usage

    try:
        solve_flattened(system, dtype, solve_flat, allow_device=True)
    except RuntimeError as exc:
        if config["lmm/strict"]:
            raise
        # the host-exact fallback solves outside the warm solver, so
        # any carried device fixpoint state is stale from here on
        if system.warm_solver is not None:
            system.warm_solver.invalidate()
        _fallback_count += 1
        system.fallback_count = getattr(system, "fallback_count", 0) + 1
        # per-stage visibility (the global int cannot be attributed):
        # quarantine decisions and bench rows read this scoped counter
        from . import opstats
        opstats.bump("solver_fallbacks")
        if not _fallback_warned:
            _fallback_warned = True
            from ..utils import log as _log
            _log.get_category("lmm").warning(
                "JAX solve failed (%s); falling back to the exact host "
                "solver for this solve. Further fallbacks are silent "
                "(lmm/strict:1 restores the hard error)." % (exc,))
        _solve_host_exact(system)


def _count_live_vars(system: System) -> int:
    n = 0
    for var in system.variable_set:
        if var.sharing_penalty <= 0:
            break  # enabled vars are kept at the list head
        n += 1
    return n


def dispatching_solve(system: System) -> None:
    """'auto' backend: exact host solver for small live sets (native C++
    when available, Python list solver otherwise), JAX above the
    lmm/jax-threshold crossover (SURVEY.md hard part (e))."""
    if _count_live_vars(system) >= config["lmm/jax-threshold"]:
        solve_jax(system)
    else:
        from . import lmm_native
        if lmm_native.available():
            lmm_native.solve_native(system)
        else:
            system.solve_exact()


def install(system: System, backend: Optional[str] = None) -> System:
    """Attach the configured solver backend to a System."""
    backend = backend or config["lmm/backend"]
    if backend == "jax":
        system.solve_fn = solve_jax
    elif backend == "auto":
        system.solve_fn = dispatching_solve
    elif backend == "native":
        from . import lmm_native
        system.solve_fn = lmm_native.solve_native
    elif backend == "list":
        system.solve_fn = None
    else:
        raise ValueError(f"Unknown lmm/backend {backend!r} "
                         "(expected list, native, jax or auto)")
    return system
