"""Reference-identical LMM benchmark system construction.

Replicates the construction protocol of the reference's solver benchmark
(/root/reference/teshsuite/surf/maxmin_bench/maxmin_bench.cpp:20-78,110-116):
the Lehmer LCG (16807 mod 2^31-1), the four size classes, the
concurrency-limit and share draws, and the expand/expand_add element
pattern — so the same seed produces a byte-identical system here, in the
native C++ bench replica, and (by validated equivalence) the reference.
Shared by tests/test_lmm.py and tools/measure_baseline.py.
"""

from .lmm_host import make_new_maxmin_system

#: name -> (nb_cnst, nb_var, pw_base_limit, pw_max_limit)
#: (maxmin_bench.cpp:110-116)
CLASSES = {
    "small": (10, 10, 1, 2),
    "medium": (100, 100, 3, 6),
    "big": (2000, 2000, 5, 8),
    "huge": (20000, 20000, 7, 10),
}

RATE_NO_LIMIT = 0.2
MAX_SHARE = 2


def nb_elem(pw_base_limit, pw_max_limit):
    """Elements per variable (maxmin_bench.cpp:172: int division)."""
    return (1 << pw_base_limit) + (1 << (8 * pw_max_limit // 10))


class Lehmer:
    """The reference bench's LCG (maxmin_bench.cpp:20-35)."""

    def __init__(self, seed):
        self.seedx = seed

    def myrand(self):
        self.seedx = self.seedx * 16807 % 2147483647
        return self.seedx % 1000

    def float_random(self, mx):
        return (mx * self.myrand()) / 1001.0

    def int_random(self, mx):
        return int(self.float_random(mx))


def build_bench_system(seed, nb_cnst, nb_var, nb_elem, pw_base_limit,
                       pw_max_limit, rate_no_limit=RATE_NO_LIMIT,
                       max_share=MAX_SHARE):
    """Build one bench system on the Python host solver
    (maxmin_bench.cpp:37-78). Returns (system, variables)."""
    rng = Lehmer(seed)
    rng.myrand()  # the bench prints one draw before test()
    s = make_new_maxmin_system(False)
    cnsts = []
    for _ in range(nb_cnst):
        c = s.constraint_new(None, rng.float_random(10.0))
        if rate_no_limit > rng.float_random(1.0):
            limit = -1
        else:
            limit = (1 << pw_base_limit) + (1 << rng.int_random(pw_max_limit))
        c.set_concurrency_limit(limit)
        cnsts.append(c)
    variables = []
    for _ in range(nb_var):
        v = s.variable_new(None, 1.0, -1.0, nb_elem)
        share = 1 + rng.int_random(max_share)
        v.set_concurrency_share(share)
        used = [0] * nb_cnst
        j = 0
        while j < nb_elem:
            k = rng.int_random(nb_cnst)
            if used[k] >= share:
                continue
            s.expand(cnsts[k], v, rng.float_random(1.5))
            s.expand_add(cnsts[k], v, rng.float_random(1.5))
            used[k] += 1
            j += 1
        variables.append(v)
    return s, variables


def build_class(name, seed=1):
    """Build one system of a named reference bench class."""
    nb_cnst, nb_var, pw_base, pw_max = CLASSES[name]
    return build_bench_system(seed, nb_cnst, nb_var,
                              nb_elem(pw_base, pw_max), pw_base, pw_max)
