"""s4u::Engine equivalent: simulation setup and run.

Reference: /root/reference/src/s4u/s4u_Engine.cpp — load_platform,
register_function, load_deployment, run, clock; plus --cfg command-line
handling (sg_config.cpp).
"""

from __future__ import annotations

import xml.etree.ElementTree as ET
from typing import Callable, Dict, List, Optional

from ..exceptions import ParseError
from ..kernel.engine import EngineImpl
from ..models.registry import setup_models
from ..platform.xml import PlatformLoader
from ..utils.config import config
from ..utils import log as _xlog

#: deployment warnings (ActorImpl::start / sg_platf's catch)
_deploy_log = _xlog.get_category("simix_process")
from ..utils.signal import Signal


class Engine:
    _instance: Optional["Engine"] = None

    on_platform_created = EngineImpl.on_platform_created
    on_simulation_end = EngineImpl.on_simulation_end
    on_time_advance = EngineImpl.on_time_advance
    on_deadlock = EngineImpl.on_deadlock

    def __init__(self, argv: Optional[List[str]] = None):
        # Replacing the engine singleton retires the previous engine: its
        # signal subscriptions must not fire into this one (same guarantee
        # as _reset, for code that constructs engines back-to-back).
        if Engine._instance is not None:
            Engine._instance.pimpl.disconnect_signals()
        # --cfg must land BEFORE the kernel comes up: EngineImpl's
        # ContextFactory freezes contexts/stack-size at creation
        # (reference order too: sg_config runs first, sg_config.cpp)
        if argv:
            rest = config.parse_argv(argv[1:])
            argv[1:] = rest
        self.pimpl = EngineImpl()
        self._registered_functions: Dict[str, Callable] = {}
        self._default_function: Optional[Callable] = None
        self._models_ready = False
        Engine._instance = self

    # -- singletons --------------------------------------------------------
    @classmethod
    def get_instance(cls) -> "Engine":
        if cls._instance is None:
            cls._instance = Engine(["simgrid_tpu"])
        return cls._instance

    @classmethod
    def _reset(cls) -> None:
        """Tear down the process-wide simulation state so a fresh Engine can
        be created (mainly for test harnesses; one engine per process in
        normal use, like the reference)."""
        from ..kernel import profile as profile_mod
        from ..utils import log as _xlog
        from .mailbox import Mailbox
        from .. import instr
        instr.stop()
        # drop the dead engine's log context closures (they pin the
        # whole platform in memory and would render stale actor info)
        _xlog.clock_getter = None
        _xlog.actor_info_getter = None
        if cls._instance is not None:
            cls._instance.pimpl.disconnect_signals()
            cls._instance.pimpl.shutdown_contexts()
        cls._instance = None
        EngineImpl.instance = None
        Mailbox._instances.clear()
        profile_mod.clear_trace_registry()

    @property
    def clock(self) -> float:
        return self.pimpl.now

    @classmethod
    def get_clock(cls) -> float:
        return cls.get_instance().pimpl.now

    # -- configuration -----------------------------------------------------
    def set_config(self, option: str, value=None) -> None:
        if value is None:
            config.set_from_string(option)
        else:
            config.set(option, value)

    # -- platform ----------------------------------------------------------
    def _ensure_models(self) -> None:
        if not self._models_ready:
            setup_models(self.pimpl)
            self._models_ready = True

    def load_platform(self, path: str) -> None:
        self._ensure_models()
        PlatformLoader(self.pimpl).load(path)
        # TRACE_start fires on platform creation in the reference
        # (instr_config.cpp:297); same here so actors created before
        # run() are captured.
        if config["tracing"]:
            from .. import instr
            instr.start(self.pimpl)

    def create_root_zone(self, name: str, routing: str = "Full"):
        """Programmatic platform building entry."""
        self._ensure_models()
        from ..platform.xml import _make_zone
        return _make_zone(self.pimpl, None, name, routing)

    # -- actors ------------------------------------------------------------
    def register_function(self, name: str, code: Callable) -> None:
        self._registered_functions[name] = code

    def register_default(self, code: Callable) -> None:
        self._default_function = code

    def load_deployment(self, path: str) -> None:
        """Start actors from a deployment XML (reference
        surf_parse deployment: <actor>/<process> with <argument> children)."""
        from .actor import Actor
        try:
            tree = ET.parse(path)
        except ET.ParseError as e:
            raise ParseError(f"{path}: {e}") from None
        for elem in tree.getroot():
            if elem.tag not in ("actor", "process"):
                continue
            host_name = elem.get("host")
            func_name = elem.get("function")
            host = self.host_by_name(host_name)
            code = self._registered_functions.get(func_name,
                                                  self._default_function)
            assert code is not None, f"Function '{func_name}' unknown"
            args = [child.get("value") for child in elem
                    if child.tag == "argument"]
            props = {child.get("id"): child.get("value")
                     for child in elem if child.tag == "prop"}
            start_time = float(elem.get("start_time", "0"))
            kill_time = float(elem.get("kill_time", "-1"))
            on_failure = elem.get("on_failure", "DIE")

            auto_restart = on_failure != "DIE"
            # every deployment actor joins its host's boot list
            # (sg_platf.cpp:447: unconditional emplace); turn_off
            # prunes non-restart entries, turn_on reboots the rest
            host.actors_at_boot.append(
                {"name": func_name, "code": code, "args": args,
                 "kill_time": kill_time, "auto_restart": auto_restart})

            def launch(code=code, args=args, host=host, name=func_name,
                       kill_time=kill_time, auto_restart=auto_restart,
                       props=props):
                if not host.is_on():
                    # ActorImpl::start + sg_platf's catch around it;
                    # the failed creation still consumed a PID (the
                    # ActorImpl was built before start() threw)
                    self.pimpl.next_pid()
                    _deploy_log.warning(
                        "Cannot launch actor '%s' on failed host '%s'"
                        % (name, host.name))
                    _deploy_log.warning(
                        "Deployment includes some initially turned off "
                        "Hosts ... nevermind.")
                    return None
                actor = Actor.create(name, host, code, *args)
                if props:
                    actor.pimpl.properties.update(props)
                if kill_time >= 0:
                    actor.set_kill_time(kill_time)
                if auto_restart:
                    actor.pimpl.auto_restart = True
                return actor

            if start_time > 0:
                self.pimpl.timer_set(start_time, launch)
            else:
                launch()

    # -- entity lookup -----------------------------------------------------
    def host_by_name(self, name: str):
        host = self.pimpl.hosts.get(name)
        assert host is not None, f"Host '{name}' not found"
        return host

    def host_by_name_or_null(self, name: str):
        return self.pimpl.hosts.get(name)

    def get_all_hosts(self) -> List:
        # name-sorted like the reference (its host registry is a
        # std::map, Engine::get_all_hosts iterates in name order — the
        # token-ring tesh oracle pins the resulting actor placement)
        return [h for _, h in sorted(self.pimpl.hosts.items())]

    def get_host_count(self) -> int:
        return len(self.pimpl.hosts)

    def link_by_name(self, name: str):
        link = self.pimpl.links.get(name)
        assert link is not None, f"Link '{name}' not found"
        return link

    def get_all_links(self) -> List:
        return list(self.pimpl.links.values())

    def get_netzone_root(self):
        return self.pimpl.netzone_root

    def netpoint_by_name(self, name: str):
        return self.pimpl.netpoints.get(name)

    def get_all_netpoints(self) -> List:
        return list(self.pimpl.netpoints.values())

    # -- run ---------------------------------------------------------------
    def run_until(self, date: float) -> None:
        """Advance the simulation up to `date` and pause (the kernel
        state stays live; call run()/run_until() again to continue)."""
        if config["tracing"]:
            from .. import instr
            instr.start(self.pimpl)
        self.pimpl.run(until=date)

    def run(self) -> None:
        if config["tracing"]:
            from .. import instr
            instr.start(self.pimpl)
        self.pimpl.run()


def get_clock() -> float:
    return Engine.get_clock()
