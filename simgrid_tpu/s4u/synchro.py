"""s4u synchronization: Mutex, ConditionVariable, Semaphore, Barrier.

Reference: /root/reference/src/s4u/{s4u_Mutex,s4u_ConditionVariable,
s4u_Semaphore,s4u_Barrier}.cpp, over the kernel synchro implementations.
"""

from __future__ import annotations

from typing import Optional

from ..exceptions import TimeoutException
from ..kernel import activity as kact
from .engine import Engine


class Mutex:
    def __init__(self):
        self.pimpl = kact.MutexImpl(Engine.get_instance().pimpl)

    def lock(self) -> None:
        from .actor import _current_impl
        issuer = _current_impl()
        issuer.simcall("mutex_lock", lambda sc: self.pimpl.lock(sc),
                       mc_object=self.pimpl)

    def try_lock(self) -> bool:
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            sc.result = self.pimpl.try_lock(sc.issuer)
            sc.issuer.simcall_answer()
        return issuer.simcall("mutex_trylock", handler,
                              mc_object=self.pimpl)

    def unlock(self) -> None:
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            self.pimpl.unlock(sc.issuer)
            sc.issuer.simcall_answer()
        issuer.simcall("mutex_unlock", handler, mc_object=self.pimpl)

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()
        return False


class ConditionVariable:
    def __init__(self):
        self.pimpl = kact.CondVarImpl(Engine.get_instance().pimpl)

    def wait(self, mutex: Mutex) -> None:
        from .actor import _current_impl
        issuer = _current_impl()
        issuer.simcall("cond_wait",
                       lambda sc: self.pimpl.wait(mutex.pimpl, -1.0, sc),
                       mc_object=(self.pimpl, mutex.pimpl))

    def wait_for(self, mutex: Mutex, timeout: float) -> bool:
        """Returns True on timeout (std::cv_status semantics)."""
        from .actor import _current_impl
        issuer = _current_impl()
        try:
            issuer.simcall("cond_wait_timeout",
                           lambda sc: self.pimpl.wait(mutex.pimpl, timeout, sc),
                           mc_object=(self.pimpl, mutex.pimpl))
            return False
        except TimeoutException:
            # per the reference (s4u_ConditionVariable.cpp:73-80): on timeout
            # the mutex must be re-acquired before returning
            mutex.lock()
            return True

    def wait_until(self, mutex: Mutex, timeout_time: float) -> bool:
        now = Engine.get_clock()
        return self.wait_for(mutex, max(0.0, timeout_time - now))

    def notify_one(self) -> None:
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            self.pimpl.signal()
            sc.issuer.simcall_answer()
        issuer.simcall("cond_signal", handler, mc_object=self.pimpl)

    def notify_all(self) -> None:
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            self.pimpl.broadcast()
            sc.issuer.simcall_answer()
        issuer.simcall("cond_broadcast", handler, mc_object=self.pimpl)


class Semaphore:
    def __init__(self, initial_capacity: int):
        self.pimpl = kact.SemImpl(Engine.get_instance().pimpl,
                                  initial_capacity)

    def acquire(self) -> None:
        from .actor import _current_impl
        issuer = _current_impl()
        issuer.simcall("sem_acquire", lambda sc: self.pimpl.acquire(sc, -1.0),
                       mc_object=self.pimpl)

    def acquire_timeout(self, timeout: float) -> bool:
        """Returns True on timeout."""
        from .actor import _current_impl
        issuer = _current_impl()
        try:
            issuer.simcall("sem_acquire_timeout",
                           lambda sc: self.pimpl.acquire(sc, timeout),
                           mc_object=self.pimpl)
            return False
        except TimeoutException:
            return True

    def release(self) -> None:
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            self.pimpl.release()
            sc.issuer.simcall_answer()
        issuer.simcall("sem_release", handler, mc_object=self.pimpl)

    def get_capacity(self) -> int:
        return self.pimpl.value

    def would_block(self) -> bool:
        return self.pimpl.would_block()


class Barrier:
    """Cyclic barrier over mutex+condvar (reference s4u_Barrier.cpp)."""

    def __init__(self, expected_actors: int):
        assert expected_actors > 0
        self.expected = expected_actors
        self.arrived = 0
        self.mutex = Mutex()
        self.cond = ConditionVariable()

    def wait(self) -> bool:
        """Returns True for exactly one of the participants (the 'serial'
        actor), False for the others."""
        self.mutex.lock()
        self.arrived += 1
        if self.arrived == self.expected:
            self.cond.notify_all()
            self.mutex.unlock()
            self.arrived = 0
            return True
        self.cond.wait(self.mutex)
        self.mutex.unlock()
        return False
