"""s4u::Actor + this_actor: the user-facing actor API.

Reference: /root/reference/src/s4u/s4u_Actor.cpp and
include/simgrid/s4u/Actor.hpp: create, daemonize, suspend/resume, join,
kill, migrate, on_exit; this_actor::{sleep_for, sleep_until, execute,
yield, exit, ...} issuing simcalls under the hood.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from ..exceptions import ForcefulKillException
from ..kernel import activity as kact
from ..kernel.actor import ActorImpl
from ..utils.signal import Signal
from .engine import Engine


class Actor:
    """User handle on an actor."""

    on_creation = ActorImpl.on_creation
    on_termination = ActorImpl.on_termination
    on_destruction = ActorImpl.on_destruction
    on_suspend = Signal()
    on_resume = Signal()
    on_sleep = Signal()
    on_wake_up = Signal()
    on_migration = Signal()

    def __init__(self, pimpl: ActorImpl):
        self.pimpl = pimpl
        pimpl.s4u_actor = self

    # -- creation ----------------------------------------------------------
    @staticmethod
    def create(name: str, host, code: Callable, *args, **kwargs) -> "Actor":
        engine = Engine.get_instance().pimpl
        current = engine.context_factory.current_actor
        fn = lambda: code(*args, **kwargs)
        if current is None:
            pimpl = engine.create_actor(name, host, fn)
        else:
            # in-simulation creation is a simcall (reference
            # simcall_process_create): the parent yields, so the child
            # runs before the parent's next statement — the actor-join
            # tesh oracle pins that interleaving
            def handler(sc):
                sc.result = engine.create_actor(name, host, fn)
                sc.issuer.simcall_answer()
            pimpl = current.simcall("actor_create", handler)
        return Actor(pimpl)

    @staticmethod
    def by_pid(pid: int) -> Optional["Actor"]:
        """Retrieve a live actor from its PID, or None (reference
        s4u::Actor::by_pid, s4u_Actor.cpp)."""
        engine = Engine.get_instance().pimpl
        impl = engine.process_list.get(pid)
        if impl is None or impl.finished:
            return None
        return getattr(impl, "s4u_actor", None) or Actor(impl)

    @staticmethod
    def self() -> Optional["Actor"]:
        engine = Engine.get_instance().pimpl
        actor = engine.context_factory.current_actor
        if actor is None:
            return None
        return getattr(actor, "s4u_actor", None) or Actor(actor)

    # -- properties --------------------------------------------------------
    @property
    def name(self) -> str:
        return self.pimpl.name

    @property
    def pid(self) -> int:
        return self.pimpl.pid

    def get_pid(self) -> int:
        return self.pimpl.pid

    @property
    def ppid(self) -> int:
        return self.pimpl.ppid

    @property
    def host(self):
        return self.pimpl.host

    def get_properties(self):
        return self.pimpl.properties

    def is_daemon(self) -> bool:
        return self.pimpl.daemonized

    def is_suspended(self) -> bool:
        return self.pimpl.suspended

    # -- control (issued from any actor) -----------------------------------
    def daemonize(self) -> "Actor":
        issuer = _current_impl()
        issuer.simcall("actor_daemonize",
                       lambda sc: (self.pimpl.daemonize(),
                                   sc.issuer.simcall_answer()))
        return self

    def suspend(self) -> None:
        issuer = _current_impl()
        target = self.pimpl
        if issuer is target:
            # suspending myself: block until someone resumes me
            Actor.on_suspend(self)
            issuer.suspended = True
            issuer.simcall("actor_suspend", lambda sc: None)
        else:
            def handler(sc):
                target.suspend_actor()
                sc.issuer.simcall_answer()
            Actor.on_suspend(self)
            issuer.simcall("actor_suspend_other", handler)

    def resume(self) -> None:
        issuer = _current_impl()

        def handler(sc):
            self.pimpl.resume_actor()
            sc.issuer.simcall_answer()
        issuer.simcall("actor_resume", handler)
        Actor.on_resume(self)

    def join(self, timeout: float = -1.0) -> None:
        """Block until this actor terminates (reference s4u_Actor.cpp join:
        a simcall answered from the target's termination)."""
        issuer = _current_impl()
        target = self.pimpl

        def handler(sc):
            if target.finished:
                sc.issuer.simcall_answer()
                return
            waiters = getattr(target, "_join_simcalls", None)
            if waiters is None:
                waiters = target._join_simcalls = []
            waiters.append(sc)
            if timeout >= 0:
                def on_timeout():
                    if sc in waiters:
                        waiters.remove(sc)
                        sc.issuer.simcall_answer()
                sc.timeout_cb = sc.issuer.engine.timer_set(
                    sc.issuer.engine.now + timeout, on_timeout)
        issuer.simcall("actor_join", handler)

    def kill(self) -> None:
        issuer = _current_impl()

        def handler(sc):
            sc.issuer.engine.maestro.kill(self.pimpl)
            if sc.issuer is not self.pimpl:
                sc.issuer.simcall_answer()
        issuer.simcall("actor_kill", handler)

    @staticmethod
    def kill_all() -> None:
        issuer = _current_impl()

        def handler(sc):
            engine = sc.issuer.engine
            for actor in list(engine.process_list.values()):
                if actor is not sc.issuer:
                    engine.maestro.kill(actor)
            sc.issuer.simcall_answer()
        issuer.simcall("actor_kill_all", handler)

    def set_kill_time(self, time: float) -> None:
        engine = Engine.get_instance().pimpl
        target = self.pimpl
        engine.timer_set(time, lambda: engine.maestro.kill(target))

    def set_auto_restart(self, autorestart: bool = True) -> None:
        already = self.pimpl.auto_restart
        self.pimpl.auto_restart = autorestart
        host = self.pimpl.host
        if not hasattr(host, "actors_at_boot"):
            return
        if autorestart and not already:
            # programmatically-created actors record their boot spec on
            # the host too (s4u::Actor::set_auto_restart appends a
            # ProcessArg to actors_at_boot_); idempotent on re-enable
            host.actors_at_boot.append(
                {"name": self.pimpl.name, "code": self.pimpl.code,
                 "args": (), "auto_restart": True, "owner": self.pimpl})
        elif not autorestart:
            host.actors_at_boot = [
                spec for spec in host.actors_at_boot
                if spec.get("owner") is not self.pimpl]

    def set_host(self, new_host) -> None:
        issuer = _current_impl()
        target = self.pimpl

        def handler(sc):
            if target.host is not None and target in target.host.actor_list:
                target.host.actor_list.remove(target)
            target.host = new_host
            new_host.actor_list.append(target)
            # a RUNNING execution migrates with its actor (reference
            # ActorImpl::set_host + ExecImpl::migrate): the remaining
            # flops continue at the destination's speed
            synchro = getattr(target, "waiting_synchro", None)
            if synchro is not None and hasattr(synchro, "migrate") \
                    and getattr(synchro, "hosts", None):
                synchro.migrate(new_host)
            sc.issuer.simcall_answer()
        issuer.simcall("actor_set_host", handler)
        Actor.on_migration(self)

    migrate = set_host

    def on_exit(self, callback: Callable[[bool], None]) -> None:
        self.pimpl.on_exit_callbacks.append(callback)


def _current_impl() -> ActorImpl:
    engine = Engine.get_instance().pimpl
    actor = engine.context_factory.current_actor
    # Outside any actor context (main thread / maestro): simcalls execute
    # inline through the maestro pseudo-actor.
    return actor if actor is not None else engine.maestro


# ---------------------------------------------------------------------------
# this_actor: the current-actor namespace
# ---------------------------------------------------------------------------

class this_actor:
    """Static namespace mirroring simgrid::s4u::this_actor."""

    @staticmethod
    def get_pid() -> int:
        return _current_impl().pid

    @staticmethod
    def get_ppid() -> int:
        return _current_impl().ppid

    @staticmethod
    def get_name() -> str:
        return _current_impl().name

    @staticmethod
    def get_cname() -> str:
        return _current_impl().name

    @staticmethod
    def get_host():
        return _current_impl().host

    @staticmethod
    def set_host(host) -> None:
        Actor(_current_impl()).set_host(host)

    @staticmethod
    def is_maestro() -> bool:
        return Engine.get_instance().pimpl.context_factory.current_actor is None

    @staticmethod
    def sleep_for(duration: float) -> None:
        issuer = _current_impl()
        if duration <= 0:
            return
        Actor.on_sleep(getattr(issuer, "s4u_actor", None))

        def handler(sc):
            sleep = kact.SleepImpl(sc.issuer.engine)
            sleep.host = sc.issuer.host
            sleep.duration = duration
            sleep.start()
            sleep.register_simcall(sc)
        issuer.simcall("process_sleep", handler)
        Actor.on_wake_up(getattr(issuer, "s4u_actor", None))

    @staticmethod
    def sleep_until(wakeup_time: float) -> None:
        now = Engine.get_clock()
        if wakeup_time > now:
            this_actor.sleep_for(wakeup_time - now)

    @staticmethod
    def yield_() -> None:
        issuer = _current_impl()
        issuer.simcall("actor_yield", lambda sc: sc.issuer.simcall_answer())

    @staticmethod
    def execute(flops: float, priority: float = 1.0) -> None:
        this_actor.exec_init(flops).set_priority(priority).wait()

    @staticmethod
    def parallel_execute(hosts, flops_amounts, bytes_amounts,
                         timeout: float = -1.0) -> None:
        from .activity import Exec
        exec_ = Exec()
        exec_.hosts = list(hosts)
        exec_.flops_amounts = list(flops_amounts)
        exec_.bytes_amounts = list(bytes_amounts)
        if timeout > 0:
            exec_.set_timeout(timeout)
        # a fired timeout detector surfaces as a TimeoutException
        # raised out of the wait simcall
        exec_.wait()

    @staticmethod
    def exec_init(flops: float) -> "Exec":
        from .activity import Exec
        exec_ = Exec()
        exec_.hosts = [_current_impl().host]
        exec_.flops_amounts = [flops]
        return exec_

    @staticmethod
    def exec_async(flops: float) -> "Exec":
        return this_actor.exec_init(flops).start()

    @staticmethod
    def suspend() -> None:
        Actor(_current_impl()).suspend()

    @staticmethod
    def set_host(new_host) -> None:
        Actor(_current_impl()).set_host(new_host)

    migrate = set_host

    @staticmethod
    def exit() -> None:
        raise ForcefulKillException("exited")

    @staticmethod
    def on_exit(callback: Callable[[bool], None]) -> None:
        """Register a termination callback.  A SIMCALL, like the
        reference's simcall_process_on_exit: the registering actor
        yields to the kernel, so an actor killed in the same scheduling
        round dies having registered its callback but before executing
        its next statement (pinned by the actor-kill oracle, where
        victim C logs 'I have been killed!' but never 'Hello!')."""
        issuer = _current_impl()
        issuer.on_exit_callbacks.append(callback)
        issuer.simcall("actor_on_exit",
                       lambda sc: sc.issuer.simcall_answer())
