"""s4u async activities: Comm, Exec, Io.

Reference: /root/reference/src/s4u/{s4u_Comm,s4u_Exec,s4u_Io}.cpp — handles
with start/wait/test/cancel/wait_any/wait_all composing the kernel
activities via simcalls.
"""

from __future__ import annotations

import math
from enum import Enum
from typing import List, Optional

from ..exceptions import (HostFailureException, NetworkFailureException,
                          StorageFailureException, TimeoutException)
from ..kernel import activity as kact
from ..utils.rngstream import seeded_stream
from ..utils.signal import Signal
from .engine import Engine

#: transient failures worth re-issuing an activity for
RETRYABLE_EXCEPTIONS = (TimeoutException, NetworkFailureException,
                        StorageFailureException)


class RetryPolicy:
    """Bounded retry with exponential backoff and deterministic jitter.

    Delays are ``base_delay * multiplier**(attempt-1)`` capped at
    ``max_delay``; with ``jitter`` in (0, 1] each delay is scaled by a
    factor drawn from ``[1-jitter, 1]`` on a seeded RngStream, so two
    runs of the same simulation de-synchronize their retries identically
    (simulated-time jitter, bit-reproducible across runs)."""

    def __init__(self, max_attempts: int = 4, base_delay: float = 1.0,
                 multiplier: float = 2.0, max_delay: float = math.inf,
                 jitter: float = 0.0, seed: int = 0):
        assert max_attempts >= 1, "max_attempts must be >= 1"
        assert base_delay >= 0 and multiplier > 0, "invalid backoff"
        assert 0.0 <= jitter <= 1.0, "jitter must be in [0, 1]"
        self.max_attempts = max_attempts
        self.base_delay = base_delay
        self.multiplier = multiplier
        self.max_delay = max_delay
        self.jitter = jitter
        self._rng = seeded_stream(seed, "retry-policy") if jitter else None

    def backoff(self, attempt: int) -> float:
        """The delay to sleep after failed attempt number `attempt`
        (1-based)."""
        delay = min(self.base_delay * self.multiplier ** (attempt - 1),
                    self.max_delay)
        if self._rng is not None:
            delay *= 1.0 - self.jitter * self._rng.rand_u01()
        return delay


class ActivityState(Enum):
    INITED = 0
    STARTING = 1
    STARTED = 2
    CANCELED = 3
    FINISHED = 4


class Activity:
    def __init__(self):
        self.state = ActivityState.INITED
        self.pimpl: Optional[kact.ActivityImpl] = None
        self.remains = 0.0

    def is_finished(self) -> bool:
        return self.state == ActivityState.FINISHED

    @staticmethod
    def wait_any_of(activities: List["Activity"],
                    timeout: float = -1.0) -> int:
        """Wait for the first of a MIXED set of started activities
        (Comm/Exec/Io together) — s4u::Activity::wait_any; the kernel
        waitany machinery is kind-agnostic (register_simcall/finish on
        ActivityImpl). Returns the finished index, or -1 on timeout."""
        from .actor import _current_impl
        issuer = _current_impl()
        impls = [a.pimpl for a in activities]

        def handler(sc):
            kact.activity_waitany(sc, impls, timeout)
        idx = issuer.simcall("activity_waitany", handler)
        if idx is not None and idx >= 0:
            act = activities[idx]
            act.state = ActivityState.FINISHED
            on_completion = getattr(type(act), "on_completion", None)
            if on_completion is not None:
                on_completion(act)
            return idx
        return -1


class ActivitySet:
    """A bag of heterogeneous activities to wait on (the reference's
    s4u::ActivitySet)."""

    def __init__(self, activities: Optional[List[Activity]] = None):
        self._activities: List[Activity] = list(activities or [])

    def push(self, activity: Activity) -> None:
        self._activities.append(activity)

    def erase(self, activity: Activity) -> None:
        self._activities.remove(activity)

    def empty(self) -> bool:
        return not self._activities

    def size(self) -> int:
        return len(self._activities)

    def wait_any(self, timeout: float = -1.0) -> Optional[Activity]:
        """Wait for one activity to finish, remove and return it
        (None on timeout)."""
        idx = Activity.wait_any_of(self._activities, timeout)
        if idx < 0:
            return None
        return self._activities.pop(idx)

    def wait_all(self) -> None:
        while self._activities:
            self.wait_any()


class Comm(Activity):
    """One communication, sender or receiver side (s4u_Comm.cpp)."""

    on_sender_start = Signal()
    on_receiver_start = Signal()
    on_completion = Signal()
    on_retry = Signal()          # (mailbox, attempt, exception)

    def __init__(self, mailbox=None):
        super().__init__()
        self.mailbox = mailbox
        self.sender = None       # ActorImpl
        self.receiver = None
        self.payload = None      # what the sender ships
        self._src_buff = None
        self._dst_buff = None
        self.size = 0.0
        self.rate = -1.0
        self.detached_ = False
        self.match_fun = None
        self.copy_data_fun = None
        self.clean_fun = None

    # -- declaration -------------------------------------------------------
    def set_payload(self, payload, size: float) -> "Comm":
        self.payload = payload
        self.size = size
        return self

    def set_rate(self, rate: float) -> "Comm":
        self.rate = rate
        return self

    def detach(self) -> "Comm":
        assert self.state == ActivityState.INITED, \
            "You cannot use detach() once your communication started"
        self.detached_ = True
        # the reference's Comm::detach STARTS the communication
        # (s4u_Comm.cpp:192-198): fire-and-forget sends go on the wire
        # immediately
        return self.start()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "Comm":
        from .actor import _current_impl
        assert self.state == ActivityState.INITED
        issuer = _current_impl()
        mbox_impl = self.mailbox.pimpl

        if self.sender is not None:
            Comm.on_sender_start(self)
            self._src_buff = [self.payload]

            def handler(sc):
                sc.result = kact.comm_isend(
                    sc.issuer.engine, sc.issuer, mbox_impl, self.size,
                    self.rate, self._src_buff, self.match_fun, self.clean_fun,
                    self.copy_data_fun, self.payload, self.detached_)
                sc.issuer.simcall_answer()
            self.pimpl = issuer.simcall("comm_isend", handler,
                                        mc_object=mbox_impl)
        else:
            Comm.on_receiver_start(self)
            self._dst_buff = [None]

            def handler(sc):
                sc.result = kact.comm_irecv(
                    sc.issuer.engine, sc.issuer, mbox_impl, self._dst_buff,
                    self.match_fun, self.copy_data_fun, None, self.rate)
                sc.issuer.simcall_answer()
            self.pimpl = issuer.simcall("comm_irecv", handler,
                                        mc_object=mbox_impl)
        self.state = ActivityState.STARTED
        return self

    def wait(self) -> "Comm":
        return self.wait_for(-1.0)

    def wait_for(self, timeout: float) -> "Comm":
        from .actor import _current_impl
        issuer = _current_impl()
        if self.state == ActivityState.INITED:
            self.start()
        assert self.state == ActivityState.STARTED
        comm_impl = self.pimpl

        def handler(sc):
            kact.comm_wait(sc, comm_impl, timeout)
        issuer.simcall("comm_wait", handler)
        self.state = ActivityState.FINISHED
        Comm.on_completion(self)
        return self

    def test(self) -> bool:
        from .actor import _current_impl
        issuer = _current_impl()
        if self.state in (ActivityState.INITED, ActivityState.STARTING):
            self.start()
        if self.state == ActivityState.FINISHED:
            return True
        comm_impl = self.pimpl
        res = issuer.simcall("comm_test", lambda sc: kact.comm_test(sc, comm_impl))
        if res:
            self.state = ActivityState.FINISHED
            Comm.on_completion(self)
        return res

    def cancel(self) -> "Comm":
        from .actor import _current_impl
        issuer = _current_impl()
        comm_impl = self.pimpl
        if comm_impl is not None:
            def handler(sc):
                comm_impl.cancel()
                sc.issuer.simcall_answer()
            issuer.simcall("comm_cancel", handler)
        self.state = ActivityState.CANCELED
        return self

    def get_payload(self):
        """Receiver side: the delivered payload (valid after wait)."""
        return self._dst_buff[0] if self._dst_buff is not None else None

    # -- collections -------------------------------------------------------
    @staticmethod
    def wait_any(comms: List["Comm"]) -> int:
        return Comm.wait_any_for(comms, -1.0)

    @staticmethod
    def wait_any_for(comms: List["Comm"], timeout: float) -> int:
        from .actor import _current_impl
        issuer = _current_impl()
        impls = [c.pimpl for c in comms]

        def handler(sc):
            kact.comm_waitany(sc, impls, timeout)
        idx = issuer.simcall("comm_waitany", handler)
        if idx is not None and idx >= 0:
            comms[idx].state = ActivityState.FINISHED
            Comm.on_completion(comms[idx])
            return idx
        return -1

    @staticmethod
    def test_any(comms: List["Comm"]) -> int:
        from .actor import _current_impl
        issuer = _current_impl()
        impls = [c.pimpl for c in comms]
        idx = issuer.simcall("comm_testany",
                             lambda sc: kact.comm_testany(sc, impls))
        if idx is not None and idx >= 0:
            comms[idx].state = ActivityState.FINISHED
            Comm.on_completion(comms[idx])
            return idx
        return -1

    @staticmethod
    def wait_all(comms: List["Comm"]) -> None:
        for comm in comms:
            comm.wait()

    @staticmethod
    def send_with_retry(mailbox, payload, size: float,
                        policy: Optional[RetryPolicy] = None,
                        timeout: float = -1.0) -> int:
        """Send with retry: a blocking put re-issued on transient failure
        (timeout, link failure, peer host failure), sleeping the policy's
        backoff in simulated time between attempts.  Returns the number
        of attempts used; re-raises the last exception once the policy's
        attempt budget is exhausted."""
        from .actor import this_actor
        policy = policy or RetryPolicy()
        attempt = 1
        while True:
            try:
                mailbox.put(payload, size, timeout=timeout)
                return attempt
            except RETRYABLE_EXCEPTIONS as exc:
                if attempt >= policy.max_attempts:
                    raise
                Comm.on_retry(mailbox, attempt, exc)
                this_actor.sleep_for(policy.backoff(attempt))
                attempt += 1


class Exec(Activity):
    """A computation activity (s4u_Exec.cpp)."""

    on_start = Signal()
    on_completion = Signal()
    on_retry = Signal()          # (exec, attempt, exception)

    def __init__(self):
        super().__init__()
        self.hosts = []
        self.flops_amounts: List[float] = []
        self.bytes_amounts: List[float] = []
        self.priority = 1.0
        self.bound = 0.0
        self.timeout = -1.0
        self.name = ""

    def set_priority(self, priority: float) -> "Exec":
        self.priority = priority
        return self

    def set_bound(self, bound: float) -> "Exec":
        self.bound = bound
        return self

    def set_host(self, host) -> "Exec":
        self.hosts = [host]
        if self.state == ActivityState.STARTED and self.pimpl is not None:
            # re-home the RUNNING execution (reference Exec::set_host ->
            # ExecImpl::migrate): remaining flops continue at the
            # destination's speed
            from .actor import _current_impl
            issuer = _current_impl()
            target = self.pimpl

            def handler(sc):
                target.migrate(host)
                sc.issuer.simcall_answer()
            issuer.simcall("execution_change_host", handler)
        return self

    def set_flops_amount(self, flops: float) -> "Exec":
        self.flops_amounts = [flops]
        return self

    def set_timeout(self, timeout: float) -> "Exec":
        self.timeout = timeout
        return self

    def set_name(self, name: str) -> "Exec":
        self.name = name
        return self

    def start(self) -> "Exec":
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            impl = kact.ExecImpl(sc.issuer.engine, self.name)
            impl.hosts = list(self.hosts)
            impl.flops_amounts = list(self.flops_amounts)
            impl.bytes_amounts = list(self.bytes_amounts)
            impl.sharing_penalty = 1.0 / self.priority
            impl.bound = self.bound
            if self.timeout > 0:
                impl.set_timeout(self.timeout)
            impl.start()
            sc.result = impl
            sc.issuer.simcall_answer()
        self.pimpl = issuer.simcall("execution_start", handler)
        self.state = ActivityState.STARTED
        Exec.on_start(self)
        return self

    def wait(self) -> "Exec":
        from .actor import _current_impl
        if self.state == ActivityState.INITED:
            self.start()
        issuer = _current_impl()
        exec_impl = self.pimpl

        def handler(sc):
            exec_impl.register_simcall(sc)
            if exec_impl.state not in (kact.State.WAITING, kact.State.RUNNING):
                exec_impl.finish()
        issuer.simcall("execution_wait", handler)
        self.state = ActivityState.FINISHED
        Exec.on_completion(self)
        return self

    def test(self) -> bool:
        if self.state == ActivityState.INITED:
            self.start()
        if self.pimpl.state not in (kact.State.WAITING, kact.State.RUNNING):
            self.wait()
            return True
        return False

    def cancel(self) -> "Exec":
        from .actor import _current_impl
        issuer = _current_impl()
        exec_impl = self.pimpl

        def handler(sc):
            if exec_impl is not None:
                exec_impl.cancel()
            sc.issuer.simcall_answer()
        issuer.simcall("execution_cancel", handler)
        self.state = ActivityState.CANCELED
        return self

    @staticmethod
    def wait_any(execs: List["Exec"]) -> int:
        """Index of the first completed execution (s4u::Exec::wait_any)."""
        return Activity.wait_any_of(list(execs))

    @staticmethod
    def wait_any_for(execs: List["Exec"], timeout: float) -> int:
        """wait_any with a timeout; -1 when it expires."""
        return Activity.wait_any_of(list(execs), timeout)

    def _clone(self) -> "Exec":
        """A fresh INITED copy of this execution's declaration (a failed
        kernel activity cannot be restarted; retries re-issue)."""
        clone = Exec()
        clone.hosts = list(self.hosts)
        clone.flops_amounts = list(self.flops_amounts)
        clone.bytes_amounts = list(self.bytes_amounts)
        clone.priority = self.priority
        clone.bound = self.bound
        clone.timeout = self.timeout
        clone.name = self.name
        return clone

    def with_retry(self, policy: Optional[RetryPolicy] = None) -> "Exec":
        """Run this execution to completion with retry: on a transient
        failure (timeout, or the target host down — surfacing as
        HostFailureException for a remote issuer) sleep the policy's
        backoff in simulated time and re-issue a fresh copy.  Returns
        the Exec that completed; re-raises once the attempt budget is
        exhausted.  Call on an un-started Exec (the declaration is
        cloned for every attempt)."""
        from .actor import this_actor
        assert self.state == ActivityState.INITED, \
            "with_retry() drives the whole lifecycle: call it instead " \
            "of start()/wait()"
        policy = policy or RetryPolicy()
        attempt = 1
        while True:
            exec_ = self._clone()
            try:
                exec_.start().wait()
                return exec_
            except RETRYABLE_EXCEPTIONS + (HostFailureException,) as exc:
                if attempt >= policy.max_attempts:
                    raise
                Exec.on_retry(exec_, attempt, exc)
                this_actor.sleep_for(policy.backoff(attempt))
                attempt += 1

    def get_remaining(self) -> float:
        return self.pimpl.get_remaining() if self.pimpl else 0.0

    def get_remaining_ratio(self) -> float:
        if self.pimpl is None or self.pimpl.surf_action is None:
            return 0.0
        act = self.pimpl.surf_action
        if len(self.hosts) > 1:
            return act.get_remains()
        return act.get_remains() / act.cost


class Io(Activity):
    """A disk I/O activity (s4u_Io.cpp)."""

    class OpType(Enum):
        READ = 0
        WRITE = 1

    def __init__(self, storage, size: float, op_type: "Io.OpType"):
        super().__init__()
        self.storage = storage
        self.size = size
        self.op_type = op_type

    def start(self) -> "Io":
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            impl = kact.IoImpl(sc.issuer.engine)
            impl.storage = self.storage
            impl.size = self.size
            impl.io_type = ("read" if self.op_type == Io.OpType.READ
                            else "write")
            impl.start()
            sc.result = impl
            sc.issuer.simcall_answer()
        self.pimpl = issuer.simcall("io_start", handler)
        self.state = ActivityState.STARTED
        return self

    def wait(self) -> "Io":
        from .actor import _current_impl
        if self.state == ActivityState.INITED:
            self.start()
        issuer = _current_impl()
        io_impl = self.pimpl

        def handler(sc):
            io_impl.register_simcall(sc)
            if io_impl.state not in (kact.State.WAITING, kact.State.RUNNING):
                io_impl.finish()
        issuer.simcall("io_wait", handler)
        self.state = ActivityState.FINISHED
        return self

    def cancel(self) -> "Io":
        from .actor import _current_impl
        issuer = _current_impl()
        io_impl = self.pimpl

        def handler(sc):
            if io_impl is not None:
                io_impl.cancel()
            sc.issuer.simcall_answer()
        issuer.simcall("io_cancel", handler)
        self.state = ActivityState.CANCELED
        return self

    def get_performed_ioops(self) -> float:
        return self.pimpl.performed_ioops if self.pimpl else 0.0
