"""s4u — the user-facing simulation API (reference include/simgrid/s4u/).

Usage:
    from simgrid_tpu import s4u

    def pinger():
        mbox = s4u.Mailbox.by_name("ping")
        mbox.put("hello", 1_000_000)

    e = s4u.Engine()
    e.load_platform("small_platform.xml")
    s4u.Actor.create("pinger", e.host_by_name("Tremblay"), pinger)
    e.run()
"""

from ..models.host import Host
from ..models.network import LinkImpl as Link
from .activity import (Activity, ActivitySet, Comm, Exec, Io, RetryPolicy)
from .actor import Actor, this_actor
from .engine import Engine, get_clock
from .mailbox import Mailbox
from .synchro import Barrier, ConditionVariable, Mutex, Semaphore

from ..plugins.vm import VirtualMachine  # noqa: E402  (s4u::VirtualMachine)

__all__ = ["Engine", "Actor", "this_actor", "Host", "Link", "Mailbox",
           "Comm", "Exec", "Io", "Activity", "ActivitySet", "Mutex",
           "ConditionVariable", "Semaphore", "Barrier", "get_clock",
           "RetryPolicy", "VirtualMachine"]
