"""s4u::Mailbox: named rendezvous points.

Reference: /root/reference/src/s4u/s4u_Mailbox.cpp — put/get (+ _async,
_init variants), iprobe, listen, ready, set_receiver (permanent receiver
for eager delivery).
"""

from __future__ import annotations

from typing import Any, Optional

from ..kernel import activity as kact
from .activity import Comm
from .engine import Engine


class Mailbox:
    _instances = {}

    def __init__(self, pimpl: kact.MailboxImpl):
        self.pimpl = pimpl

    @staticmethod
    def by_name(name: str) -> "Mailbox":
        mbox = Mailbox._instances.get(name)
        if mbox is None:
            engine = Engine.get_instance().pimpl
            mbox = Mailbox(engine.mailbox_by_name_or_create(name))
            Mailbox._instances[name] = mbox
        return mbox

    @property
    def name(self) -> str:
        return self.pimpl.name

    # -- sending -----------------------------------------------------------
    def put_init(self, payload=None, size: float = 0.0) -> Comm:
        from .actor import _current_impl
        comm = Comm(self)
        comm.sender = _current_impl()
        comm.payload = payload
        comm.size = size
        return comm

    def put_async(self, payload, size: float) -> Comm:
        assert payload is not None, "Cannot send nullptr data"
        return self.put_init(payload, size).start()

    def put(self, payload, size: float, timeout: float = -1.0) -> None:
        assert payload is not None, "Cannot send nullptr data"
        self.put_init(payload, size).start().wait_for(timeout)

    # -- receiving ---------------------------------------------------------
    def get_init(self) -> Comm:
        from .actor import _current_impl
        comm = Comm(self)
        comm.receiver = _current_impl()
        return comm

    def get_async(self) -> Comm:
        return self.get_init().start()

    def get(self, timeout: float = -1.0) -> Any:
        comm = self.get_async()
        comm.wait_for(timeout)
        return comm.get_payload()

    # -- probing -----------------------------------------------------------
    def iprobe(self, sender_side: bool = False, match_fun=None,
               data=None) -> Optional[kact.CommImpl]:
        from .actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            sc.result = self.pimpl.iprobe(sender_side, match_fun, data)
            sc.issuer.simcall_answer()
        return issuer.simcall("mbox_iprobe", handler)

    def listen(self) -> bool:
        """True if something is queued for reception."""
        return bool(self.pimpl.comm_queue) or bool(self.pimpl.done_comm_queue)

    def ready(self) -> bool:
        """True if a completed comm is deliverable right now
        (reference s4u_Mailbox.cpp:47-56 — the permanent-receiver mode
        checks the done queue)."""
        if self.pimpl.comm_queue:
            return self.pimpl.comm_queue[0].state == kact.State.DONE
        if self.pimpl.permanent_receiver is not None and \
                self.pimpl.done_comm_queue:
            return self.pimpl.done_comm_queue[0].state == kact.State.DONE
        return False

    def set_receiver(self, actor) -> None:
        """Declare a permanent receiver: messages start flowing upon send,
        without waiting for the matching receive (SMPI eager mode)."""
        self.pimpl.set_receiver(actor.pimpl if actor is not None else None)

    def get_receiver(self):
        return self.pimpl.permanent_receiver
