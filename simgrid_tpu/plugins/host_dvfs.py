"""host_dvfs plugin: pstate governors (reference src/plugins/
host_dvfs.cpp): a per-host daemon samples the load every
``plugin/dvfs/sampling-rate`` simulated seconds and drives the pstate
like the Linux cpufreq governors — performance (fastest), powersave
(slowest), ondemand (jump to fastest above the up-threshold, else
proportional), conservative (step one pstate at a time)."""

from __future__ import annotations

from ..utils.config import config, declare_flag
from . import host_load

declare_flag("plugin/dvfs/sampling-rate",
             "Sampling rate of the DVFS governors (seconds)", 0.1)
declare_flag("plugin/dvfs/governor",
             "Default DVFS governor "
             "(performance|powersave|ondemand|conservative)",
             "performance")


def _governor_step(host, governor: str, up_threshold: float = 0.8) -> None:
    """One sampling decision (host_dvfs.cpp update())."""
    n = host.get_pstate_count()
    if n <= 1:
        return
    if governor == "performance":
        target = 0
    elif governor == "powersave":
        target = n - 1
    else:
        # Governors decide on the load averaged over the sampling
        # interval, then reset it (host_dvfs.cpp update()).
        # The reference scales by core count (host_dvfs.cpp:191,239):
        # "load" counts busy cores, not a [0,1] fraction.
        load = host.get_core_count() * host_load.get_average_load(host)
        host_load.reset(host)
        current = host.get_pstate()
        if governor == "ondemand":
            # host_dvfs.cpp OnDemand::update: above the threshold jump
            # to full speed, else pstate = max_pstate - load*(max+1).
            if load > up_threshold:
                target = 0
            else:
                target = max(0, min(n - 1, int((n - 1) - load * n)))
        else:   # conservative: one step at a time
            if load > up_threshold:
                target = max(0, current - 1)
            elif load < up_threshold / 2:
                target = min(n - 1, current + 1)
            else:
                target = current
    if target != host.get_pstate():
        host.set_pstate(target)


def host_dvfs_plugin_init(engine=None) -> None:
    """sg_host_dvfs_plugin_init: spawn one governor daemon per host
    whose properties (or the global flag) request one."""
    from ..s4u import Actor, this_actor
    from ._base import resolve_engine

    impl = resolve_engine(engine)
    host_load.host_load_plugin_init(impl)
    rate = config["plugin/dvfs/sampling-rate"]

    for host in list(impl.hosts.values()):
        governor = host.properties.get("plugin/dvfs/governor",
                                       config["plugin/dvfs/governor"])
        if governor == "performance" and \
                "plugin/dvfs/governor" not in host.properties:
            continue    # no daemon needed for the default no-op case

        def daemon(host=host, governor=governor):
            while True:
                this_actor.sleep_for(rate)
                _governor_step(host, governor)

        Actor.create(f"dvfs-daemon-{host.name}", host,
                     daemon).daemonize()
