"""Plugin ecosystem (reference src/plugins/): opt-in extensions hooked
into the kernel's signals, activated per engine.

Each plugin module exposes ``<name>_plugin_init(engine=None)`` mirroring
the reference's ``sg_<name>_plugin_init()`` registration entry points
(e.g. host_energy.cpp:481-500); subscriptions are engine-scoped so a
torn-down engine's plugins never fire into a fresh one.
"""

from . import (fault_stats, file_system, host_energy, host_load,  # noqa: F401
               link_energy, vm)

__all__ = ["host_energy", "host_load", "link_energy", "file_system",
           "fault_stats", "vm"]
