"""fault_stats plugin: failure/recovery observability.

Counts per-resource failures and accumulated downtime, actor kills and
auto-restart reboots, communications failed and retried — everything a
fault-injection campaign (simgrid_tpu.faults) perturbs — through the
same engine-scoped signal subscriptions as host_load.  Exposed as a
plain dict (``summary()``) and via the underlying signals for live
consumers.
"""

from __future__ import annotations

from typing import Dict, Optional

from ._base import resolve_engine


class _ResourceStat:
    __slots__ = ("failures", "downtime", "off_since")

    def __init__(self):
        self.failures = 0
        self.downtime = 0.0
        self.off_since: Optional[float] = None


class FaultStats:
    """Aggregated failure statistics for one engine."""

    def __init__(self, engine):
        self.engine = engine
        self.hosts: Dict[str, _ResourceStat] = {}
        self.links: Dict[str, _ResourceStat] = {}
        self.actors_killed = 0
        self.actors_restarted = 0
        self.comms_failed = 0
        self.comms_retried = 0
        self.execs_retried = 0

    # -- state-change accounting ------------------------------------------
    def _stat(self, table: Dict[str, _ResourceStat], name: str) -> _ResourceStat:
        stat = table.get(name)
        if stat is None:
            stat = table[name] = _ResourceStat()
        return stat

    def _on_state_change(self, table: Dict[str, _ResourceStat], name: str,
                         is_on: bool) -> None:
        stat = self._stat(table, name)
        now = self.engine.now
        if not is_on:
            if stat.off_since is None:
                stat.failures += 1
                stat.off_since = now
        elif stat.off_since is not None:
            stat.downtime += now - stat.off_since
            stat.off_since = None

    # -- reporting ---------------------------------------------------------
    def _table_dict(self, table: Dict[str, _ResourceStat]) -> dict:
        now = self.engine.now
        out = {}
        for name in sorted(table):
            stat = table[name]
            downtime = stat.downtime
            if stat.off_since is not None:     # still down: bill up to now
                downtime += now - stat.off_since
            out[name] = {"failures": stat.failures, "downtime": downtime}
        return out

    def summary(self) -> dict:
        from ..ops import lmm_jax
        return {
            "hosts": self._table_dict(self.hosts),
            "links": self._table_dict(self.links),
            "actors_killed": self.actors_killed,
            "actors_restarted": self.actors_restarted,
            "comms_failed": self.comms_failed,
            "comms_retried": self.comms_retried,
            "execs_retried": self.execs_retried,
            "lmm_fallbacks": lmm_jax.get_fallback_count(),
        }


#: engine -> FaultStats (one live engine at a time, like ExtensionMap)
_active: Dict[str, object] = {"engine": None, "stats": None}


def fault_stats_plugin_init(engine=None) -> FaultStats:
    """Activate the plugin on an engine (idempotent); returns the stats
    object (also reachable later via get_stats())."""
    from ..kernel.actor import ActorImpl
    from ..models.host import Host
    from ..models.network import LinkImpl, NetworkAction
    from ..s4u.activity import Comm, Exec

    impl = resolve_engine(engine)
    if _active["engine"] is impl:
        return _active["stats"]
    stats = FaultStats(impl)
    _active["engine"] = impl
    _active["stats"] = stats

    impl.connect_signal(
        Host.on_state_change,
        lambda host, *a: stats._on_state_change(stats.hosts, host.name,
                                                host.is_on()))
    impl.connect_signal(
        LinkImpl.on_state_change,
        lambda link, *a: stats._on_state_change(stats.links, link.name,
                                                link.is_on()))

    def on_kill(victim):
        stats.actors_killed += 1
    impl.connect_signal(ActorImpl.on_kill, on_kill)

    def on_restart(host, n):
        stats.actors_restarted += n
    impl.connect_signal(Host.on_restart, on_restart)

    def on_net_action_state(action, *a):
        from ..kernel.activity import CommImpl
        from ..kernel.resource import ActionState
        if (action.get_state() == ActionState.FAILED
                and isinstance(action.activity, CommImpl)):
            stats.comms_failed += 1
    impl.connect_signal(NetworkAction.on_state_change, on_net_action_state)

    def on_comm_retry(mailbox, attempt, exc):
        stats.comms_retried += 1
    impl.connect_signal(Comm.on_retry, on_comm_retry)

    def on_exec_retry(exec_, attempt, exc):
        stats.execs_retried += 1
    impl.connect_signal(Exec.on_retry, on_exec_retry)

    return stats


def get_stats(engine=None) -> FaultStats:
    impl = resolve_engine(engine)
    assert _active["engine"] is impl and _active["stats"] is not None, \
        "The fault_stats plugin is not active on this engine"
    return _active["stats"]


def summary(engine=None) -> dict:
    return get_stats(engine).summary()
