"""file_system plugin: files and mount points over simulated storage.

Reference: src/plugins/file_system/s4u_FileSystem.cpp. Each storage
carries a content map (path -> size) and a used-size counter; a File
resolves its mount point by longest-prefix match over the host's
mounted storages (s4u_FileSystem.cpp:28-60), and read/write issue
blocking I/O activities on the backing storage sized by the actual
transferred bytes (:93-160). Writes grow the file and the storage's
used size until the disk is full.
"""

from __future__ import annotations

from typing import Dict, Optional

from ..s4u.activity import Io


class FileSystemStorageExt:
    """Per-storage content map + used size (FileSystemStorageExt)."""

    def __init__(self, storage):
        self.storage = storage
        self.content: Dict[str, int] = {}
        self.used_size = 0

    def size(self) -> float:
        return self.storage.size


_EXT: Dict[int, FileSystemStorageExt] = {}


def _storage_ext(storage) -> FileSystemStorageExt:
    ext = _EXT.get(id(storage))
    if ext is None:
        ext = FileSystemStorageExt(storage)
        _EXT[id(storage)] = ext
    return ext


def _mounts_of(host, engine) -> Dict[str, object]:
    """mount_point -> storage for one host: the host's <mount> table
    when present (it may mount storages attached elsewhere), otherwise
    every storage attached to the host at its default point."""
    mounts = {}
    table = getattr(host, "mounts", None)
    if table:
        for point, storage_id in table.items():
            storage = engine.storages.get(storage_id)
            if storage is not None:
                mounts[point] = storage
        return mounts
    for storage in engine.storages.values():
        if storage.attach == host.name:
            mounts[getattr(storage, "mount_point", "/") or "/"] = storage
    return mounts


class File:
    """An open file (s4u_FileSystem.cpp File)."""

    def __init__(self, fullpath: str, host=None):
        from ..kernel.engine import EngineImpl
        from ..s4u.actor import _current_impl
        engine = EngineImpl.instance
        if host is None:
            host = _current_impl().host
        self.host = host
        self.fullpath = fullpath
        mounts = _mounts_of(host, engine)
        best = ""
        for mount_point in mounts:
            if fullpath.startswith(mount_point) and \
                    len(mount_point) > len(best):
                best = mount_point
        assert best or "/" in mounts, \
            f"Can't find mount point for '{fullpath}' on '{host.name}'"
        self.mount_point = best or "/"
        self.local_storage = mounts[self.mount_point]
        self.path = fullpath[len(best):] if best else fullpath
        ext = _storage_ext(self.local_storage)
        self.size = ext.content.get(self.path, 0)
        if self.path not in ext.content:
            ext.content[self.path] = 0
        self.current_position = 0

    # -- I/O (s4u_FileSystem.cpp:93-160) ----------------------------------
    def read(self, size: int) -> int:
        if self.size == 0:
            return 0
        read_size = min(int(size), self.size - self.current_position)
        if read_size <= 0:
            return 0
        Io(self.local_storage, read_size, Io.OpType.READ).wait()
        self.current_position += read_size
        return read_size

    def write(self, size: int) -> int:
        ext = _storage_ext(self.local_storage)
        if ext.used_size >= self.local_storage.size:
            return 0  # disk full (s4u_FileSystem.cpp:135-136)
        write_size = min(int(size),
                         int(self.local_storage.size - ext.used_size))
        Io(self.local_storage, write_size, Io.OpType.WRITE).wait()
        self.current_position += write_size
        if self.current_position > self.size:
            ext.used_size += self.current_position - self.size
            self.size = self.current_position
            ext.content[self.path] = self.size
        return write_size

    # -- metadata ----------------------------------------------------------
    def seek(self, pos: int, origin: int = 0) -> None:
        """origin: 0=SEEK_SET, 1=SEEK_CUR, 2=SEEK_END."""
        if origin == 0:
            self.current_position = pos
        elif origin == 1:
            self.current_position += pos
        else:
            self.current_position = self.size + pos

    def tell(self) -> int:
        return self.current_position

    def get_size(self) -> int:
        return self.size

    def unlink(self) -> None:
        ext = _storage_ext(self.local_storage)
        ext.used_size -= ext.content.pop(self.path, 0)
        self.size = 0

    def move(self, new_fullpath: str) -> None:
        """Rename within the same mount (File::move)."""
        assert new_fullpath.startswith(self.mount_point), \
            "Cannot move a file across mount points"
        ext = _storage_ext(self.local_storage)
        new_path = new_fullpath[len(self.mount_point):]
        ext.content[new_path] = ext.content.pop(self.path, self.size)
        self.path = new_path
        self.fullpath = new_fullpath

    def remote_copy(self, to_host, to_fullpath: str) -> "File":
        """Read here, ship over the network, write there; blocks until
        the destination write completed like the reference
        (File::remote_copy)."""
        from ..s4u.actor import Actor
        from ..s4u.mailbox import Mailbox
        self.seek(0)
        read = self.read(self.size)
        mbox = Mailbox.by_name(f"__fs_copy__{id(self)}")
        done = Mailbox.by_name(f"__fs_copy_done__{id(self)}")

        def receiver():
            mbox.get()
            dst = File(to_fullpath, to_host)
            dst.write(read)
            done.put(b"", 1)

        Actor.create("__fs_copy__", to_host, receiver)
        mbox.put(b"", read or 1)
        done.get()
        return File(to_fullpath, to_host)


def storage_used_size(storage) -> int:
    return _storage_ext(storage).used_size


def storage_content(storage) -> Dict[str, int]:
    return _storage_ext(storage).content


def _load_contents(impl) -> None:
    """Populate each storage's content map from its declared content
    file (path + size per line); files resolve against the platform
    file's directory and the 'path' config entries."""
    import os

    from ..utils.config import config
    search = [getattr(impl, "platform_dir", "."), config["path"], "."]
    for storage in impl.storages.values():
        content_name = getattr(storage, "content_name", "")
        if not content_name or id(storage) in _EXT:
            continue
        for base in search:
            candidate = os.path.join(base, content_name)
            if os.path.isfile(candidate):
                ext = _storage_ext(storage)
                with open(candidate) as fh:
                    for line in fh:
                        parts = line.split()
                        if len(parts) == 2:
                            ext.content[parts[0]] = int(parts[1])
                ext.used_size = sum(ext.content.values())
                break


def file_system_plugin_init(engine=None) -> None:
    """sg_storage_file_system_init: loads declared storage contents so
    used/free sizes match the platform description.  Works in either
    call order: storages already created are loaded now, and a
    platform loaded LATER (the reference's mandatory init-first order)
    is handled through the platform-created hook."""
    _EXT.clear()
    if engine is None:
        from ..s4u.engine import Engine
        engine = Engine._instance
        if engine is None:
            # no engine yet: defer everything to the platform hook
            from ..kernel.engine import EngineImpl

            def on_created():
                from ..s4u.engine import Engine as E
                if E._instance is not None:
                    _load_contents(E._instance.pimpl)
            EngineImpl.on_platform_created.connect(on_created)
            return
    impl = getattr(engine, "pimpl", engine)
    _load_contents(impl)
    from ..kernel.engine import EngineImpl
    impl.connect_signal(EngineImpl.on_platform_created,
                        lambda: _load_contents(impl))
