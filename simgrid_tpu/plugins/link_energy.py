"""link_energy plugin: joules = integral of P(link utilization) dt.

Reference: src/plugins/link_energy.cpp: links declare ``wattage_range``
("idle_watts:busy_watts") and ``wattage_off`` properties; instantaneous
power interpolates linearly between idle and busy with utilization
(= used bandwidth / capacity). Updated on every communicate and link
state change.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple


class LinkEnergy:
    def __init__(self, link, clock_getter):
        self.link = link
        self._clock = clock_getter
        self.total_energy = 0.0
        self.last_updated = clock_getter()
        props = getattr(link, "properties", {})
        # the reference plugin reads 'watt_range' (link_energy.cpp:90);
        # 'wattage_range' is the post-3.25 rename — accept both
        rng = props.get("watt_range") or props.get("wattage_range")
        if rng:
            idle, busy = (float(x) for x in rng.split(":"))
            self.range: Optional[Tuple[float, float]] = (idle, busy)
        else:
            self.range = None
        self.wattage_off = float(getattr(link, "properties", {})
                                 .get("wattage_off", 0.0))

    def _utilization(self) -> float:
        bw = self.link.get_bandwidth()
        if bw <= 0:
            return 0.0
        # get_usage honors the sharing policy (max for FATPIPE links).
        return min(self.link.constraint.get_usage() / bw, 1.0)

    def get_power(self) -> float:
        if self.range is None:
            return 0.0
        if not self.link.is_on():
            return self.wattage_off
        idle, busy = self.range
        return idle + self._utilization() * (busy - idle)

    def update(self) -> None:
        now = self._clock()
        if now > self.last_updated:
            self.total_energy += self.get_power() \
                * (now - self.last_updated)
            self.last_updated = now

    def get_consumed_energy(self) -> float:
        self.update()
        return self.total_energy


from ._base import ExtensionMap, resolve_engine

_EXT = ExtensionMap(LinkEnergy)


def link_energy_plugin_init(engine=None) -> None:
    """sg_link_energy_plugin_init (link_energy.cpp registration)."""
    from ..models.network import LinkImpl, NetworkAction

    impl = resolve_engine(engine)
    if not _EXT.activate(impl):
        return
    ext = _EXT.of

    for link in impl.links.values():
        ext(link)

    def on_communicate(action, src, dst):
        # Bill the elapsed interval on every link the new flow crosses
        # (the utilization is about to change).
        var = action.variable
        if var is None:
            return
        for elem in var.cnsts:
            link = elem.constraint.id
            if _EXT.get(link) is not None \
                    or hasattr(link, "bandwidth_peak"):
                ext(link).update()

    impl.connect_signal(LinkImpl.on_communicate, on_communicate)
    impl.connect_signal(LinkImpl.on_state_change,
                        lambda link, *a: ext(link).update())
    impl.connect_signal(NetworkAction.on_state_change,
                        lambda action, *a: on_communicate(action, None,
                                                          None))

    # end-of-run totals + per-link teardown report (link_energy.cpp
    # on_simulation_end / Link::on_destruction; energy-link tesh)
    from ..kernel.engine import EngineImpl
    from ..utils import log as _xlog
    _logger = _xlog.get_category("link_energy")

    def on_end():
        total = 0.0
        for link in impl.links.values():
            le = _EXT.get(link)
            if le is not None:
                le.update()
                total += le.get_consumed_energy()
        _logger.info("Total energy over all links: %f" % total)

    impl.connect_signal(EngineImpl.on_simulation_end, on_end)

    from ._base import register_atexit_report
    register_atexit_report("link_energy", _per_link_report)


def _per_link_report() -> None:
    from ..s4u.engine import Engine
    from ..utils import log as _xlog
    if Engine._instance is None:
        return
    logger = _xlog.get_category("link_energy")
    for link in Engine._instance.pimpl.links.values():
        le = _EXT.get(link)
        if le is None or link.name == "__loopback__":
            continue
        logger.info("Energy consumption of link '%s': %f Joules"
                    % (link.name, le.get_consumed_energy()))


def get_consumed_energy(link) -> float:
    le = _EXT.get(link)
    assert le is not None, "The link_energy plugin is not active"
    return le.get_consumed_energy()
