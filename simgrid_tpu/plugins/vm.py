"""VirtualMachine support: VM-as-host with VCPU coupling + live
migration.

Reference: src/plugins/vm/{VirtualMachineImpl,VmLiveMigration}.cpp and
s4u_VirtualMachine.cpp. A VM is a Host whose CPU lives in a *separate*
VM CPU model; the VM appears on its physical machine as one CpuAction
whose solved share (X1) is fed back as the VM CPU's constraint bound on
every time-advance — the two-layer fairness of VMModel::next_occuring_
event (VirtualMachineImpl.cpp:90-132): PM solves X1+X2=C, the VM layer
solves P1+P2=X1 under that bound. The VM's impact on the PM scales with
min(#active tasks, core amount) (update_action_weight, :298-309).

Live migration implements the reference's three-stage pre-copy
(VmLiveMigration.cpp): (1) transfer the RAM working set, (2) iterate
re-sending pages dirtied while transferring (dirty-page intensity x
elapsed), (3) stop-and-copy the final residue, then re-home the VM and
its actors onto the destination PM.
"""

from __future__ import annotations

from typing import List, Optional

from ..models.cpu import CpuCas01Model
from ..models.host import Host
from ..kernel.resource import ActionState, UpdateAlgo
from ..utils.signal import Signal


#: Cost of the dummy VM process on the PM: never reached in any
#: simulation (the action exists only for its solved share).
_VM_ACTION_COST = 1e300


class VMModel(CpuCas01Model):
    """The VM CPU layer (surf_cpu_model_vm): its own LMM system whose
    constraint bounds are refreshed from the PM layer's solution before
    every solve."""

    def __init__(self, engine):
        super().__init__(engine, UpdateAlgo.FULL)  # base registers us
        engine.vm_model = self
        self.vms: List["VirtualMachine"] = []
        # Active-task counters belong to the model itself (the reference
        # wires them in VMModel::VMModel, VirtualMachineImpl.cpp:83-88):
        # a VM with zero counted tasks has bound 0 on the PM, so execs
        # on it would deadlock if this were left to an optional plugin.
        from ..kernel.activity import ExecImpl

        def on_exec_creation(exec_impl):
            for host in exec_impl.hosts:
                if isinstance(host, VirtualMachine):
                    host.add_active_task()
                    host.active_execs.add(exec_impl)
                    if host.dp_enabled and exec_impl.surf_action:
                        host.dp_objs[exec_impl] = \
                            exec_impl.surf_action.get_remains()

        def on_exec_completion(exec_impl):
            for host in exec_impl.hosts:
                if isinstance(host, VirtualMachine):
                    host.remove_active_task()
                    host.active_execs.discard(exec_impl)
                    if host.dp_enabled:
                        # a finished exec consumed everything recorded
                        # for it since the last lookup
                        host.dp_updated_by_deleted += \
                            host.dp_objs.pop(exec_impl, 0.0)

        engine.connect_signal(ExecImpl.on_creation, on_exec_creation)
        engine.connect_signal(ExecImpl.on_completion, on_exec_completion)

    def next_occurring_event(self, now: float) -> float:
        # Step 1 (VirtualMachineImpl.cpp:90-129): propagate each VM's
        # PM-layer share into the VM-layer constraint bound.
        for vm in self.vms:
            if vm.pm_action is not None and vm.pm_action.variable is not None:
                solved = vm.pm_action.variable.value
                self.system.update_constraint_bound(vm.cpu.constraint,
                                                    max(solved, 0.0))
        # Step 2: the usual min over this model's actions.
        return super().next_occurring_event(now)


def _vm_model(engine) -> VMModel:
    if engine.vm_model is None:
        VMModel(engine)
    return engine.vm_model


class VirtualMachine(Host):
    """A VM: a schedulable host backed by a slice of a physical host
    (s4u_VirtualMachine.cpp + VirtualMachineImpl)."""

    on_creation = Signal()
    on_start = Signal()
    on_suspend = Signal()
    on_resume = Signal()
    on_shutdown = Signal()
    on_destruction = Signal()
    on_migration_start = Signal()
    on_migration_end = Signal()

    # lifecycle states (s4u::VirtualMachine::state)
    CREATED, RUNNING, SUSPENDED, DESTROYED = range(4)

    def __init__(self, name: str, pm: Host, core_amount: int = 1,
                 ramsize: int = 0):
        engine = pm.engine
        super().__init__(engine, name)
        model = _vm_model(engine)
        self.pm = pm
        self.core_amount = core_amount
        self.ramsize = ramsize
        self.user_bound = float("inf")
        self.active_tasks = 0
        self.state = VirtualMachine.CREATED
        self.params = {"dp_intensity": 0.0, "dp_cap": 0.9,
                       "mig_speed": -1.0}
        # dirty-page tracking (VirtualMachineImpl dp_* machinery):
        # computed flops per tracking interval drive the stage-2
        # re-send volume of a live migration
        self.active_execs: set = set()
        self.dp_enabled = False
        self.dp_objs: dict = {}
        self.dp_updated_by_deleted = 0.0
        self.is_migrating = False
        # VCPU: a cpu in the VM model, capacity core_amount x PM speed
        # for now; the real bound arrives from the PM solution each
        # round.
        model.create_cpu(self, [pm.cpu.get_speed()] * 1, core_amount)
        # The VM process on the PM's operating system
        # (VirtualMachineImpl.cpp:150-153). The reference gives it cost
        # 0 and keeps it alive through its lazy-heap bookkeeping; here
        # an effectively infinite cost expresses the same "never
        # completes by itself" lifetime in both optim modes — only its
        # solved share (X1) is ever read.
        self.pm_action = pm.cpu.execution_start(_VM_ACTION_COST,
                                                core_amount)
        self._update_action_weight()
        # Network position: a VM rides its PM's NIC.
        self.netpoint = pm.netpoint
        model.vms.append(self)
        VirtualMachine.on_creation(self)

    # -- PM coupling (VirtualMachineImpl.cpp:298-309) ---------------------
    def _update_action_weight(self) -> None:
        impact = min(self.active_tasks, self.core_amount)
        sys = self.pm.cpu.model.system
        if impact > 0:
            sys.update_variable_penalty(self.pm_action.variable,
                                        1.0 / impact)
        else:
            sys.update_variable_penalty(self.pm_action.variable, 0.0)
        bound = min(impact * self.pm.get_speed(), self.user_bound)
        sys.update_variable_bound(self.pm_action.variable, bound)

    def add_active_task(self) -> None:
        self.active_tasks += 1
        self._update_action_weight()

    def remove_active_task(self) -> None:
        self.active_tasks -= 1
        self._update_action_weight()

    def set_bound(self, bound: float) -> None:
        self.user_bound = bound
        self._update_action_weight()

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "VirtualMachine":
        assert self.state == VirtualMachine.CREATED, \
            f"Cannot start VM {self.name} in state {self.state}"
        # The reference start() has NO core-capacity check — CPU
        # overcommit is allowed and resolved by the two-layer fairness
        # (s4u_VirtualMachine.cpp:63-94 only guards RAM overcommit,
        # and only when the PM declares a ramsize) — pinned by the
        # cloud-migration oracle, which runs two 1-core VMs on the
        # 1-core Fafard.
        self.state = VirtualMachine.RUNNING
        VirtualMachine.on_start(self)
        return self

    def suspend(self) -> None:
        assert self.state == VirtualMachine.RUNNING
        from ..s4u.actor import _current_impl
        issuer = _current_impl()
        assert issuer not in self.actor_list, \
            (f"Actor {issuer.name} cannot suspend the VM {self.name} in "
             f"which it runs (VirtualMachineImpl.cpp:178-180)")
        for actor in list(self.actor_list):
            s4u_actor = getattr(actor, "s4u_actor", None)
            if s4u_actor is not None:
                s4u_actor.suspend()
        self.pm_action.suspend()
        self.state = VirtualMachine.SUSPENDED
        VirtualMachine.on_suspend(self)

    def resume(self) -> None:
        assert self.state == VirtualMachine.SUSPENDED
        self.pm_action.resume()
        for actor in list(self.actor_list):
            s4u_actor = getattr(actor, "s4u_actor", None)
            if s4u_actor is not None:
                s4u_actor.resume()
        self.state = VirtualMachine.RUNNING
        VirtualMachine.on_resume(self)

    def shutdown(self) -> None:
        killer = self.engine.maestro
        for actor in list(self.actor_list):
            killer.kill(actor)
        self.state = VirtualMachine.CREATED
        VirtualMachine.on_shutdown(self)

    def destroy(self) -> None:
        if self.state == VirtualMachine.RUNNING:
            self.shutdown()
        self.pm_action.cancel()
        self.engine.vm_model.vms.remove(self)
        self.engine.hosts.pop(self.name, None)
        self.state = VirtualMachine.DESTROYED
        VirtualMachine.on_destruction(self)

    # -- dirty-page tracking (VirtualMachineImpl::start_dirty_page_
    # tracking / lookup_computed_flops) -----------------------------------
    def start_dirty_page_tracking(self) -> None:
        self.dp_enabled = True
        self.dp_objs = {e: e.surf_action.get_remains()
                        for e in self.active_execs if e.surf_action}
        self.dp_updated_by_deleted = 0.0

    def stop_dirty_page_tracking(self) -> None:
        self.dp_enabled = False
        self.dp_objs = {}

    def lookup_computed_flops(self) -> float:
        """Flops the VM computed since tracking started / the previous
        lookup; resets the interval."""
        total = self.dp_updated_by_deleted
        for e, recorded in list(self.dp_objs.items()):
            cur = e.surf_action.get_remains() if e.surf_action else 0.0
            total += recorded - cur
            self.dp_objs[e] = cur
        self.dp_updated_by_deleted = 0.0
        return total

    # -- migration (VirtualMachineImpl::migrate + VmLiveMigration) --------
    def migrate_now(self, dst_pm: Host) -> None:
        """Instant re-homing (VirtualMachineImpl::migrate): move the PM
        action and every hosted actor to the destination."""
        if self.pm_action.get_state() in (ActionState.INITED,
                                          ActionState.STARTED,
                                          ActionState.IGNORED):
            self.pm_action.cancel()
        self.pm = dst_pm
        self.pm_action = dst_pm.cpu.execution_start(_VM_ACTION_COST,
                                                    self.core_amount)
        # _update_action_weight derives the bound from the VM's task
        # population, which migrates with it.
        self._update_action_weight()
        self.netpoint = dst_pm.netpoint


def vm_live_migration_plugin_init(engine=None) -> None:
    """sg_vm_live_migration_plugin_init: ensure the VM model (and its
    task counters, wired in VMModel.__init__) exists on this engine."""
    from ._base import resolve_engine
    _vm_model(resolve_engine(engine))


def migrate(vm: VirtualMachine, dst_pm: Host) -> None:
    """Live migration with the reference's three-stage pre-copy
    (VmLiveMigration.cpp MigrationTx/MigrationRx); must be called from
    inside an actor.  Stage 1 ships the whole RAM, stage 2 iterates on
    the pages dirtied meanwhile (the VM's computed flops per interval
    x dp_rate, capped at the working set) until the residue fits under
    bandwidth x max_downtime, stage 3 stops the VM and ships the
    residue; the RECEIVER re-homes and resumes the VM, then ACKs the
    issuer (timestamps pinned by the cloud-migration oracle)."""
    from ..s4u import Engine, Mailbox
    from ..s4u.actor import Actor
    from ..exceptions import TimeoutException

    assert vm.state == VirtualMachine.RUNNING, \
        "Cannot migrate a VM that is not running"
    assert not vm.is_migrating, \
        f"Cannot migrate VM '{vm.name}' that is already migrating"
    VirtualMachine.on_migration_start(vm)
    vm.is_migrating = True
    src_pm = vm.pm
    sid = f"{vm.name}({src_pm.name}-{dst_pm.name})"
    mbox = Mailbox.by_name(f"__mbox_mig_dst:{sid}")
    mbox_ctl = Mailbox.by_name(f"__mbox_mig_ctl:{sid}")

    def rx():
        # MigrationRx::operator() (VmLiveMigration.cpp:24-85).  Like
        # the reference's rx, an in-flight failure (the ~1e7 s
        # migration timeout, a dying link) is not caught here: the
        # escape hatch is shutting the VM down, which kills both
        # migration actors (reference onVirtualMachineShutdown).
        finalize = f"__mig_stage3:{sid}"
        while mbox.get() != finalize:
            pass
        vm.migrate_now(dst_pm)
        vm.resume()
        vm.is_migrating = False
        mbox_ctl.put(f"__mig_stage4:{sid}", 0)

    def tx():
        # MigrationTx::operator() (VmLiveMigration.cpp:137-280)
        host_speed = vm.pm.get_speed()
        ramsize = vm.ramsize
        mig_speed = vm.params["mig_speed"]
        # dp_rate couples the dirtying volume to the migration speed
        # (VmLiveMigration.cpp:144-146): with mig_speed unset (<=0,
        # the default) the reference computes no dirtied pages at all
        # — clamp so the sentinel -1 cannot produce negative sizes
        dp_rate = ((max(mig_speed, 0.0) * vm.params["dp_intensity"])
                   / host_speed if host_speed else 1.0)
        dp_cap = vm.params["dp_cap"] * ramsize
        max_downtime = 0.03
        mig_timeout = 10000000.0

        def send(size, stage, timeout):
            sent = size
            comm = mbox.put_init(f"__mig_stage{stage}:{sid}", size)
            if mig_speed > 0:
                comm.set_rate(mig_speed)
            try:
                comm.wait_for(timeout)
            except TimeoutException:
                sent -= comm.get_remaining()
            return sent

        remaining = ramsize
        vm.start_dirty_page_tracking()
        skip_stage2 = False
        t0 = Engine.get_clock()
        sent = send(ramsize, 1, -1)
        computed = vm.lookup_computed_flops()
        remaining -= sent
        if sent < ramsize:
            skip_stage2 = True
        t1 = Engine.get_clock()
        mig_timeout -= t1 - t0
        if mig_timeout < 0:
            skip_stage2 = True
        bandwidth = ramsize / (t1 - t0) if t1 > t0 else float("inf")
        threshold = bandwidth * max_downtime

        if not skip_stage2:
            updated = min(computed * dp_rate, dp_cap)
            remaining += updated
            while threshold < remaining:
                tp = Engine.get_clock()
                sent = send(updated, 2, mig_timeout)
                remaining -= sent
                computed = vm.lookup_computed_flops()
                tq = Engine.get_clock()
                if sent == updated and tq > tp:
                    bandwidth = updated / (tq - tp)
                    threshold = bandwidth * max_downtime
                    mig_timeout -= tq - tp
                    updated = min(computed * dp_rate, dp_cap)
                    remaining += updated
                else:
                    # timeout: the pages dirtied before it still count
                    remaining += min(computed * dp_rate, dp_cap)
                    break

        # Stage 3: stop-and-copy.
        vm.suspend()
        vm.stop_dirty_page_tracking()
        send(remaining, 3, -1)

    # The migration stream runs between the CURRENT physical host and
    # the destination (sg_vm_migrate puts MigrationTx on src_pm): the
    # caller may sit on a third host, and after a first migration the
    # source is wherever the VM lives NOW — not where the caller is.
    Actor.create(f"__pr_mig_rx:{sid}", dst_pm, rx)
    Actor.create(f"__pr_mig_tx:{sid}", src_pm, tx)
    mbox_ctl.get()
    VirtualMachine.on_migration_end(vm)
