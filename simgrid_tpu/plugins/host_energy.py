"""host_energy plugin: joules = integral of P(cpu load, pstate) dt.

Reference: src/plugins/host_energy.cpp. Hosts declare a
``watt_per_state`` property ("Idle:OneCore:AllCores" triples per
pstate, comma-separated; "Idle:FullSpeed" pairs on single-core hosts,
host_energy.cpp:344-397) and optionally ``watt_off``. Consumption is
updated lazily at every CPU action state change / host state or speed
change / exec start, using the pstate and load of the *elapsed*
interval (host_energy.cpp:167-197).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..utils import log as _log

_logger = _log.get_category("plugin_energy")


class PowerRange:
    __slots__ = ("idle", "min", "max")

    def __init__(self, idle: float, min_: float, max_: float):
        self.idle = idle
        self.min = min_
        self.max = max_


class HostEnergy:
    """Per-host energy accounting (host_energy.cpp HostEnergy)."""

    def __init__(self, host, clock_getter):
        self.host = host
        self._clock = clock_getter
        self.total_energy = 0.0
        self.last_updated = clock_getter()
        self.host_was_used = False
        self.watts_off = float(host.properties.get("watt_off", 0.0))
        self.power_ranges = self._parse_ranges()
        # pstate of the *elapsed* interval (-1 = off), saved so a change
        # notification bills the old state (host_energy.cpp:148-151).
        self._pstate = host.cpu.pstate if host.is_on() else -1

    def _parse_ranges(self) -> List[PowerRange]:
        spec = self.host.properties.get("watt_per_state")
        if spec is None:
            return []
        ranges = []
        cores = self.host.cpu.core_count
        for part in spec.split(","):
            vals = [float(x) for x in part.strip().split(":")]
            if cores == 1:
                assert len(vals) in (2, 3), \
                    (f"Power properties incorrectly defined for host "
                     f"{self.host.name}: expected 'Idle:FullSpeed' for a "
                     f"single-core host")
                if len(vals) == 2:
                    vals = [vals[0], vals[1], vals[1]]
                else:
                    # single core: only the AllCores value is meaningful
                    vals = [vals[0], vals[2], vals[2]]
            else:
                assert len(vals) == 3, \
                    (f"Power properties incorrectly defined for host "
                     f"{self.host.name}: expected 'Idle:OneCore:AllCores'")
            ranges.append(PowerRange(vals[0], vals[1], vals[2]))
        return ranges

    # -- power model (host_energy.cpp:240-332) ---------------------------
    def get_current_watts_value(self,
                                cpu_load: Optional[float] = None) -> float:
        if self._pstate == -1:
            return self.watts_off
        assert self.power_ranges, \
            f"No power range properties specified for host {self.host.name}"
        if cpu_load is None:
            current_speed = self.host.cpu.speed_per_pstate[self._pstate]
            if current_speed <= 0:
                cpu_load = 1.0
            else:
                cpu_load = (self.host.cpu.constraint.get_usage()
                            / current_speed
                            / self.host.cpu.core_count)
                cpu_load = min(cpu_load, 1.0)
            if cpu_load > 0:
                self.host_was_used = True
        rng = self.power_ranges[self._pstate]
        if cpu_load <= 0:
            return rng.idle
        cores = self.host.cpu.core_count
        core_recip = 1.0 / cores
        slope = ((rng.max - rng.min) / (1 - core_recip)) if cores > 1 else 0.0
        return rng.min + (cpu_load - core_recip) * slope

    def update(self) -> None:
        start, finish = self.last_updated, self._clock()
        if start < finish:
            watts = self.get_current_watts_value()
            self.total_energy += watts * (finish - start)
            self.last_updated = finish
        self._pstate = self.host.cpu.pstate if self.host.is_on() else -1

    def get_consumed_energy(self) -> float:
        if self.last_updated < self._clock():
            self.update()
        return self.total_energy

    def get_idle_consumption(self) -> float:
        return self.power_ranges[0].idle

    def get_watt_min_at(self, pstate: int) -> float:
        return self.power_ranges[pstate].min

    def get_watt_max_at(self, pstate: int) -> float:
        return self.power_ranges[pstate].max


from ._base import ExtensionMap, cpu_hosts_of_action, resolve_engine

_EXT = ExtensionMap(HostEnergy)


def host_energy_plugin_init(engine=None) -> None:
    """sg_host_energy_plugin_init (host_energy.cpp:481-512): hook every
    update trigger through engine-scoped signal subscriptions."""
    from ..kernel.activity import ExecImpl
    from ..kernel.engine import EngineImpl
    from ..models.cpu import CpuAction
    from ..models.host import Host

    impl = resolve_engine(engine)
    if not _EXT.activate(impl):
        return
    clock = lambda: impl.now
    ext = _EXT.of

    for host in impl.hosts.values():
        ext(host)
    impl.connect_signal(Host.on_creation, lambda h: ext(h))

    def on_host_change(host, *_):
        ext(host).update()

    impl.connect_signal(Host.on_state_change, on_host_change)
    impl.connect_signal(Host.on_speed_change_sig, on_host_change)

    def on_action_state_change(action, *_):
        for host in cpu_hosts_of_action(action):
            ext(host).update()

    impl.connect_signal(CpuAction.on_state_change, on_action_state_change)

    def on_exec_creation(exec_impl):
        # compute -> recv -> compute must bill the idle gap
        # (host_energy.cpp:495-509).
        if len(exec_impl.hosts) == 1:
            host = exec_impl.hosts[0]
            host = getattr(host, "pm", host)  # VM -> physical machine
            he = ext(host)
            if he.last_updated < clock():
                he.update()

    impl.connect_signal(ExecImpl.on_creation, on_exec_creation)

    def on_end():
        total = used = 0.0
        for host in impl.hosts.values():
            he = _EXT.get(host)
            if he is None or not he.power_ranges:
                continue
            energy = he.get_consumed_energy()
            total += energy
            if he.host_was_used:
                used += energy
        _logger.info("Total energy consumption: %f Joules "
                     "(used hosts: %f Joules; unused/idle hosts: %f)",
                     total, used, total - used)

    impl.connect_signal(EngineImpl.on_simulation_end, on_end)

    # Per-host consumption reports at engine teardown (the reference
    # logs them from on_host_destruction, which runs after main's last
    # statement).
    from ._base import register_atexit_report
    register_atexit_report("host_energy", _per_host_report)


def _per_host_report() -> None:
    from ..s4u.engine import Engine
    if Engine._instance is None:
        return
    for host in Engine._instance.pimpl.hosts.values():
        he = _EXT.get(host)
        if he is None or not he.power_ranges:
            continue
        _logger.info("Energy consumption of host %s: %f Joules",
                     host.name, he.get_consumed_energy())


def get_consumed_energy(host) -> float:
    """sg_host_get_consumed_energy."""
    he = _EXT.get(host)
    assert he is not None, \
        "The Energy plugin is not active on this engine"
    return he.get_consumed_energy()


def get_watt_min_at(host, pstate: int) -> float:
    """sg_host_get_wattmin_at."""
    he = _EXT.get(host)
    assert he is not None
    return he.get_watt_min_at(pstate)


def get_watt_max_at(host, pstate: int) -> float:
    """sg_host_get_wattmax_at."""
    he = _EXT.get(host)
    assert he is not None
    return he.get_watt_max_at(pstate)


def get_current_consumption(host) -> float:
    """sg_host_get_current_consumption (watts right now)."""
    he = _EXT.get(host)
    assert he is not None
    he.update()
    return he.get_current_watts_value()
