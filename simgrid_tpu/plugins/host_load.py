"""host_load plugin: per-host computed-flops and average-load tracking.

Reference: src/plugins/host_load.cpp (HostLoad extension): tracks
current load (used speed / available speed), cumulative computed flops,
average load since reset, and idle/total time split. Updated on the
same triggers as host_energy.
"""

from __future__ import annotations

from ._base import ExtensionMap, cpu_hosts_of_action, resolve_engine


class HostLoad:
    def __init__(self, host, clock_getter):
        self.host = host
        self._clock = clock_getter
        self.last_updated = clock_getter()
        self.last_reset = clock_getter()
        self.computed_flops = 0.0
        self.idle_time = 0.0
        self.total_idle_time = 0.0
        self.theor_max_flops = 0.0
        self.current_speed = host.get_speed()
        self.current_load = self._instantaneous_load()

    def _instantaneous_load(self) -> float:
        speed = self.host.cpu.get_speed() * self.host.cpu.core_count
        if speed <= 0:
            return 0.0
        return min(self.host.cpu.constraint.get_usage() / speed, 1.0)

    def update(self) -> None:
        """Bill the elapsed constant-rate interval. Callers hook the
        *ends* of such intervals (action start/finish, speed change),
        where the LMM values of the elapsed interval are still current
        — so the interval is billed with the usage sampled now."""
        now = self._clock()
        delta = now - self.last_updated
        if delta > 0:
            # usage is flop/s directly — no speed factor to get stale
            # across a pstate change mid-billing.
            usage = self.host.cpu.constraint.get_usage()
            self.computed_flops += usage * delta
            self.theor_max_flops += self.current_speed \
                * self.host.cpu.core_count * delta
            if usage == 0:
                self.idle_time += delta
                self.total_idle_time += delta
            self.last_updated = now
        self.current_load = self._instantaneous_load()
        self.current_speed = self.host.get_speed()

    def get_average_load(self) -> float:
        self.update()
        if self.theor_max_flops <= 0:
            return 0.0
        return self.computed_flops / self.theor_max_flops

    def reset(self) -> None:
        self.update()
        self.computed_flops = 0.0
        self.theor_max_flops = 0.0
        self.idle_time = 0.0
        self.last_reset = self._clock()


_EXT = ExtensionMap(HostLoad)


def host_load_plugin_init(engine=None) -> None:
    """sg_host_load_plugin_init (host_load.cpp registration)."""
    from ..kernel.activity import ExecImpl
    from ..models.cpu import CpuAction
    from ..models.host import Host

    impl = resolve_engine(engine)
    if not _EXT.activate(impl):
        return
    ext = _EXT.of

    for host in impl.hosts.values():
        ext(host)
    impl.connect_signal(Host.on_creation, lambda h: ext(h))
    impl.connect_signal(Host.on_state_change, lambda h, *a: ext(h).update())
    impl.connect_signal(Host.on_speed_change_sig,
                        lambda h, *a: ext(h).update())

    def on_action_state_change(action, *_):
        for host in cpu_hosts_of_action(action):
            ext(host).update()

    impl.connect_signal(CpuAction.on_state_change, on_action_state_change)

    def on_exec_creation(exec_impl):
        # compute -> recv -> compute: bill the idle gap before the new
        # exec's rates are solved (same trap as host_energy.cpp:495).
        if len(exec_impl.hosts) == 1:
            ext(getattr(exec_impl.hosts[0], "pm",
                        exec_impl.hosts[0])).update()

    impl.connect_signal(ExecImpl.on_creation, on_exec_creation)


def get_current_load(host) -> float:
    hl = _EXT.get(host)
    assert hl is not None, "The host_load plugin is not active"
    hl.update()
    return hl.current_load


def get_computed_flops(host) -> float:
    hl = _EXT.get(host)
    assert hl is not None, "The host_load plugin is not active"
    hl.update()
    return hl.computed_flops


def get_average_load(host) -> float:
    hl = _EXT.get(host)
    assert hl is not None, "The host_load plugin is not active"
    return hl.get_average_load()


def get_idle_time(host) -> float:
    hl = _EXT.get(host)
    assert hl is not None, "The host_load plugin is not active"
    hl.update()
    return hl.idle_time


def reset(host) -> None:
    hl = _EXT.get(host)
    assert hl is not None, "The host_load plugin is not active"
    hl.reset()
