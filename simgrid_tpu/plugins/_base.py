"""Shared plugin scaffolding: engine resolution, per-object extension
maps, and the CpuAction -> hosts walker (the bits every plugin's
registration entry point needs)."""

from __future__ import annotations

from typing import Callable, Dict, Iterator


def resolve_engine(engine=None):
    """Accept an s4u Engine, an EngineImpl, or None (current engine)."""
    from ..kernel.engine import EngineImpl
    impl = engine.pimpl if hasattr(engine, "pimpl") else engine
    return impl if impl is not None else EngineImpl.instance


class ExtensionMap:
    """Lazy id()-keyed extension registry bound to one engine at a time
    (the reference's xbt::Extendable, scoped like our engine-scoped
    signals)."""

    def __init__(self, factory: Callable):
        self._factory = factory
        self._map: Dict[int, object] = {}
        self.engine = None

    def activate(self, engine) -> bool:
        """Bind to an engine; returns False when already active on it
        (registration entry points are idempotent)."""
        if self.engine is engine:
            return False
        self._map.clear()
        self.engine = engine
        return True

    def of(self, obj):
        ext = self._map.get(id(obj))
        if ext is None:
            ext = self._factory(obj, lambda: self.engine.now)
            self._map[id(obj)] = ext
        return ext

    def get(self, obj):
        return self._map.get(id(obj))

    def values(self):
        return self._map.values()


_atexit_reports: set = set()


def register_atexit_report(key: str, callback: Callable) -> None:
    """One module-level atexit hook per plugin (keyed by name): mirrors
    the reference's destruction-time reports, which run after main's
    last statement.  The callback must look up the CURRENT engine
    itself — closing over an engine would pin every torn-down engine in
    memory for the whole process."""
    if key in _atexit_reports:
        return
    _atexit_reports.add(key)
    import atexit
    atexit.register(callback)


def cpu_hosts_of_action(action) -> Iterator:
    """The hosts whose CPUs an action's LMM variable touches (reference
    CpuAction::cpus walks the same element structure)."""
    var = action.variable
    if var is None:
        return
    for elem in var.cnsts:
        cpu = elem.constraint.id
        host = getattr(cpu, "host", None)
        if host is not None:
            yield host
