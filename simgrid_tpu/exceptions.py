"""User-visible simulation exceptions.

Equivalents of the reference's simgrid::Exception hierarchy
(/root/reference/include/simgrid/Exception.hpp): raised inside actor code
when the simulated world misbehaves (timeouts, failed resources, canceled
activities).
"""


class SimgridException(Exception):
    """Base of every simulation-level exception; `value` carries the index
    of the failed activity for waitany/testany."""

    def __init__(self, message: str = "", value: int = 0):
        super().__init__(message)
        self.value = value


class TimeoutException(SimgridException):
    pass


class HostFailureException(SimgridException):
    pass


class NetworkFailureException(SimgridException):
    pass


class StorageFailureException(SimgridException):
    pass


class VmFailureException(SimgridException):
    pass


class CancelException(SimgridException):
    pass


class TracingError(SimgridException):
    pass


class ParseError(SimgridException):
    """Platform file parsing error."""


class ForcefulKillException(BaseException):
    """Internal: unwinds an actor's stack when it gets killed.  Derives from
    BaseException so user `except Exception` blocks don't swallow it (the
    reference relies on C++ stack unwinding the same way,
    ActorImpl.cpp:230)."""
