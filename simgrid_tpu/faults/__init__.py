"""Fault injection: deterministic failure campaigns + programmatic injector.

The simulator's failure machinery (state profiles -> ``apply_event`` ->
``HostFailureException`` / auto-restart) is driven from two entry points:

- :class:`FaultCampaign` compiles seeded MTBF/MTTR schedules into kernel
  state :class:`~simgrid_tpu.kernel.profile.Profile` streams, so injected
  failures ride the exact same FutureEvtSet path as platform traces and
  keep event ordering bit-deterministic.
- :class:`Injector` scripts point failures (host/link off, bandwidth
  degradation, network partitions) with engine timers, usable
  mid-simulation from maestro or from actors.

See also :mod:`simgrid_tpu.plugins.fault_stats` for the observability
side and ``RetryPolicy`` in :mod:`simgrid_tpu.s4u.activity` for the
application-level recovery side.
"""

from .campaign import FaultCampaign
from .injector import Injector

__all__ = ["FaultCampaign", "Injector"]
