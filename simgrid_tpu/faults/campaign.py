"""Deterministic fault-injection campaigns.

A :class:`FaultCampaign` turns per-resource MTBF/MTTR distributions into
explicit failure/recovery schedules, generated from seeded RngStream
draws (one substream per resource, assigned in sorted name order, so the
event stream of each resource is independent of every other resource's
draw count and of insertion order).  Schedules are compiled into kernel
state :class:`~simgrid_tpu.kernel.profile.Profile` streams and scheduled
on the engine's FutureEvtSet: an injected host failure flows through
``Cpu.apply_event`` exactly like a platform ``<trace>`` state event —
actors are killed, auto-restart actors reboot on recovery, and the
deterministic event ordering of the engine loop is preserved.

Same modeling role as the availability-trace-driven campaigns of the
infrastructure papers (see PAPERS.md): identical seeds give bit-identical
event streams and final clocks.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from ..kernel.profile import Profile
from ..utils.rngstream import RngStream, seeded_stream

#: supported inter-event distributions
DISTRIBUTIONS = ("exponential", "weibull", "fixed")


def _draw(rng: RngStream, dist: str, mean: float, shape: float) -> float:
    """One inter-event delay by inverse-CDF sampling (u in [0,1))."""
    if dist == "fixed":
        return mean
    u = rng.rand_u01()
    if dist == "exponential":
        return -mean * math.log(1.0 - u)
    # weibull, parameterized by its mean: scale = mean / Gamma(1 + 1/shape)
    scale = mean / math.gamma(1.0 + 1.0 / shape)
    return scale * (-math.log(1.0 - u)) ** (1.0 / shape)


class _Spec:
    __slots__ = ("kind", "name", "mtbf", "mttr", "dist", "shape")

    def __init__(self, kind: str, name: str, mtbf: float, mttr: float,
                 dist: str, shape: float):
        if dist not in DISTRIBUTIONS:
            raise ValueError(f"Unknown distribution {dist!r} "
                             f"(expected one of {DISTRIBUTIONS})")
        if mtbf <= 0 or mttr <= 0:
            raise ValueError(f"{kind} '{name}': MTBF and MTTR must be > 0")
        if shape <= 0:
            raise ValueError(f"{kind} '{name}': weibull shape must be > 0")
        self.kind = kind
        self.name = name
        self.mtbf = mtbf
        self.mttr = mttr
        self.dist = dist
        self.shape = shape


class FaultCampaign:
    """A seeded host/link failure+recovery schedule generator.

    Usage::

        campaign = FaultCampaign(seed=42, horizon=3600.0)
        campaign.add_host("Jupiter", mtbf=300.0, mttr=60.0)
        campaign.add_link("backbone", mtbf=900.0, mttr=30.0,
                          dist="weibull", shape=1.5)
        campaign.schedule(engine)     # before or between run() calls
        engine.run()

    A campaign is ONE-SHOT with respect to an engine: :meth:`schedule`
    (and :meth:`schedule_degrade`) compiles the event stream into
    Profiles attached to live resources, and attaching the same stream
    twice would double-fire every event — so a second ``schedule`` call
    raises.  To drive another engine with the same schedule, or to
    derive per-replica campaigns for a fleet, use :meth:`fork`: it
    returns a FRESH campaign with the same resource specs and an
    optionally offset seed (``fork()`` reproduces this campaign
    bit-for-bit, ``fork(seed_offset=k)`` is replica k's independent
    draw).  The pure projections — :meth:`generate`,
    :meth:`mean_availability`, :meth:`compile_tape` — are repeatable
    and never consume the one shot.
    """

    def __init__(self, seed: int = 0, horizon: float = 1000.0):
        if horizon <= 0:
            raise ValueError("horizon must be > 0")
        self.seed = int(seed)
        self.horizon = float(horizon)
        self._specs: Dict[Tuple[str, str], _Spec] = {}
        self._events: Optional[Dict[Tuple[str, str],
                                    List[Tuple[float, float]]]] = None
        self._scheduled = False

    # -- declaration -------------------------------------------------------
    def _add(self, kind: str, resource, mtbf: float, mttr: float,
             dist: str, shape: float) -> "FaultCampaign":
        name = getattr(resource, "name", resource)
        self._specs[(kind, str(name))] = _Spec(kind, str(name), mtbf, mttr,
                                               dist, shape)
        self._events = None     # invalidate any generated schedule
        return self

    def add_host(self, host, mtbf: float, mttr: float,
                 dist: str = "exponential", shape: float = 1.0
                 ) -> "FaultCampaign":
        """Declare a host to fail with the given mean-time-between-failures
        and mean-time-to-repair (accepts a Host or its name)."""
        return self._add("host", host, mtbf, mttr, dist, shape)

    def add_link(self, link, mtbf: float, mttr: float,
                 dist: str = "exponential", shape: float = 1.0
                 ) -> "FaultCampaign":
        """Declare a link to fail (accepts a Link/LinkImpl or its name)."""
        return self._add("link", link, mtbf, mttr, dist, shape)

    def fork(self, seed_offset: int = 0) -> "FaultCampaign":
        """A fresh campaign with the same horizon and resource specs and
        seed ``self.seed + seed_offset`` — the cheap way around the
        one-shot :meth:`schedule` contract (same seed reproduces the
        schedule bit-for-bit; distinct offsets give replicas of a fleet
        independent draws)."""
        out = FaultCampaign(seed=self.seed + int(seed_offset),
                            horizon=self.horizon)
        for (kind, name), spec in sorted(self._specs.items()):
            out._add(kind, name, spec.mtbf, spec.mttr, spec.dist,
                     spec.shape)
        return out

    # -- generation --------------------------------------------------------
    def generate(self) -> Dict[Tuple[str, str], List[Tuple[float, float]]]:
        """Generate (and cache) the event schedule: a sorted-by-resource
        dict of ``(kind, name) -> [(date, value), ...]`` with value 0.0
        for failure and 1.0 for recovery.  Identical seeds and specs give
        bit-identical schedules."""
        if self._events is not None:
            return self._events
        rng = seeded_stream(self.seed, "fault-campaign")
        events: Dict[Tuple[str, str], List[Tuple[float, float]]] = {}
        for key in sorted(self._specs):
            spec = self._specs[key]
            rng.reset_next_substream()
            points: List[Tuple[float, float]] = []
            t = 0.0
            while True:
                t += _draw(rng, spec.dist, spec.mtbf, spec.shape)
                if t >= self.horizon:
                    break
                points.append((t, 0.0))
                t += _draw(rng, spec.dist, spec.mttr, spec.shape)
                if t >= self.horizon:
                    break
                points.append((t, 1.0))
            events[key] = points
        self._events = events
        return events

    def mean_availability(self, horizon: Optional[float] = None
                          ) -> Dict[Tuple[str, str], float]:
        """Fraction of ``[0, horizon)`` each declared resource spends UP
        under the generated schedule — the fluid time-averaged capacity
        factor of the campaign.

        This is the static projection batched campaign drains consume
        (:mod:`simgrid_tpu.parallel.campaign`): a pure-drain phase
        cannot absorb mid-drain state flips, so a replica's fault
        schedule is folded into per-resource capacity multipliers
        instead.  Deterministic per seed, like the schedule itself."""
        h = self.horizon if horizon is None else float(horizon)
        if h <= 0:
            raise ValueError("horizon must be > 0")
        out: Dict[Tuple[str, str], float] = {}
        for key, points in sorted(self.generate().items()):
            down = 0.0
            fail_at: Optional[float] = None
            for date, value in points:
                if date >= h:
                    break
                if value == 0.0:
                    fail_at = date
                elif fail_at is not None:
                    down += date - fail_at
                    fail_at = None
            if fail_at is not None:
                down += h - fail_at
            out[key] = 1.0 - down / h
        return out

    def compile_tape(self, floor: float
                     ) -> List[Tuple[float, str, str, float]]:
        """Flatten the generated schedule into ONE time-sorted event
        tape: ``(date, kind, name, factor)`` entries where a failure
        degrades the resource's capacity to ``floor`` (a fully-dead
        resource would stall a pure drain, so tapes use the same
        clamped-degradation semantics as the static
        :meth:`mean_availability` projection) and a recovery restores
        ``factor = 1.0``.

        The tape is a pure projection of :meth:`generate`'s cached
        schedule — the SAME RngStream draws, in the same per-resource
        substream order — so its event dates are bit-identical to the
        Profiles :meth:`schedule` compiles for an engine.  Ties sort by
        the resource key, matching the sorted order ``schedule``
        attaches profiles in.  Batched campaign drains
        (:mod:`simgrid_tpu.parallel.campaign`) map these entries to
        constraint slots and absolute capacity values and upload them
        as per-lane device event tapes."""
        floor = float(floor)
        if not 0.0 < floor <= 1.0:
            raise ValueError("floor must be in (0, 1]")
        tape: List[Tuple[float, str, str, float]] = []
        for (kind, name), points in sorted(self.generate().items()):
            for date, value in points:
                tape.append((date, kind, name,
                             1.0 if value > 0 else floor))
        tape.sort(key=lambda e: (e[0], e[1], e[2]))
        return tape

    def tape_len(self, floor: float = 0.05) -> int:
        """Number of entries :meth:`compile_tape` would emit — the
        per-admission tape-slot count a serving fleet must reserve for
        this campaign.  Same draws as the tape (the schedule cache is
        shared), so the probe is exact and repeatable."""
        floor = float(floor)
        if not 0.0 < floor <= 1.0:
            raise ValueError("floor must be in (0, 1]")
        sched = self.generate()
        return sum(len(sched[k]) for k in sorted(sched))

    # -- compilation onto an engine ---------------------------------------
    def schedule(self, engine=None) -> Dict[Tuple[str, str],
                                            List[Tuple[float, float]]]:
        """Compile the generated schedule into state Profiles attached to
        the engine's resources (hosts' CPUs / links) and scheduled on its
        FutureEvtSet.  Returns the schedule dict.  One-shot per campaign:
        re-attaching the same event streams twice would double-fire."""
        from ..plugins._base import resolve_engine
        if self._scheduled:
            raise RuntimeError("This FaultCampaign was already scheduled; "
                               "build a new campaign (same seed for the "
                               "same schedule) to drive another engine")
        impl = resolve_engine(engine)
        assert impl is not None, "No engine: create s4u.Engine first"
        events = self.generate()
        for (kind, name), points in sorted(events.items()):
            if kind == "host":
                host = impl.hosts.get(name)
                assert host is not None, f"Host '{name}' not found"
                target = host.cpu
            else:
                target = impl.links.get(name)
                assert target is not None, f"Link '{name}' not found"
            if target.state_event is not None:
                raise RuntimeError(
                    f"{kind} '{name}' already has a state profile; "
                    "campaign events would be mistaken for its events")
            if not points:
                continue        # horizon shorter than the first failure
            profile = Profile.from_dated_values(
                f"__fault_{kind}_{name}", points)
            target.set_state_profile(profile)
        self._scheduled = True
        return events

    def schedule_degrade(self, engine=None, floor: float = 0.05
                         ) -> List[Tuple[float, str, str, float]]:
        """Compile the schedule as BANDWIDTH-degradation Profiles instead
        of on/off state flips: a failure drops each declared link to
        ``peak * floor`` and a recovery restores the full peak, exactly
        the clamped-degradation semantics :meth:`compile_tape` encodes
        for device tapes.  Links only — a degraded host has no
        engine-side analogue here, so campaigns with host specs raise.
        Shares the one-shot contract with :meth:`schedule`.  Returns the
        compiled tape."""
        from ..plugins._base import resolve_engine
        if self._scheduled:
            raise RuntimeError("This FaultCampaign was already scheduled; "
                               "build a new campaign (same seed for the "
                               "same schedule) to drive another engine")
        impl = resolve_engine(engine)
        assert impl is not None, "No engine: create s4u.Engine first"
        tape = self.compile_tape(floor)
        by_link: Dict[str, List[Tuple[float, float]]] = {}
        for date, kind, name, factor in tape:
            if kind != "link":
                raise RuntimeError(
                    f"schedule_degrade only supports links, campaign "
                    f"declares {kind} '{name}'")
            by_link.setdefault(name, []).append((date, factor))
        for name in sorted(by_link):
            target = impl.links.get(name)
            assert target is not None, f"Link '{name}' not found"
            if target.bandwidth_event is not None:
                raise RuntimeError(
                    f"link '{name}' already has a bandwidth profile; "
                    "campaign events would be mistaken for its events")
            peak = target.bandwidth_peak
            points = [(date, peak * factor)
                      for date, factor in by_link[name]]
            if not points:
                continue
            profile = Profile.from_dated_values(
                f"__fault_bw_link_{name}", points)
            target.set_bandwidth_profile(profile)
        self._scheduled = True
        return tape
