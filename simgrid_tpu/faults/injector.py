"""Programmatic fault injection with engine timers.

``Injector`` scripts point failures against a live simulation::

    inj = Injector()                       # current engine
    inj.at(10.0).host_off("Jupiter")
    inj.at(12.5).link_degrade("backbone", 0.25)
    inj.at(20.0).partition(["A", "B"], ["C", "D"], duration=5.0)
    inj.at(40.0).restore_all()

Each operation is an engine :class:`~simgrid_tpu.kernel.engine.Timer`
callback, so it fires maestro-side at a deterministic position of the
event loop; ``.now`` variants (calling the operation methods on the
injector itself) execute immediately — through a simcall when called
from an actor, inline from maestro or the main thread.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Set


def _resolve_host(impl, host):
    if hasattr(host, "actor_list"):
        return host
    resolved = impl.hosts.get(str(host))
    assert resolved is not None, f"Host '{host}' not found"
    return resolved


def _resolve_link(impl, link):
    if hasattr(link, "bandwidth_peak"):
        return link
    resolved = impl.links.get(str(link))
    assert resolved is not None, f"Link '{link}' not found"
    return resolved


class _At:
    """Operations bound to one injection date (chainable)."""

    def __init__(self, injector: "Injector", date: float):
        self._injector = injector
        self._date = date

    def _schedule(self, fn) -> "_At":
        self._injector._engine.timer_set(self._date, fn)
        return self

    def host_off(self, host) -> "_At":
        return self._schedule(lambda: self._injector.host_off(host))

    def host_on(self, host) -> "_At":
        return self._schedule(lambda: self._injector.host_on(host))

    def link_off(self, link) -> "_At":
        return self._schedule(lambda: self._injector.link_off(link))

    def link_on(self, link) -> "_At":
        return self._schedule(lambda: self._injector.link_on(link))

    def link_degrade(self, link, fraction: float) -> "_At":
        return self._schedule(
            lambda: self._injector.link_degrade(link, fraction))

    def partition(self, zone_a: Iterable, zone_b: Iterable,
                  duration: float = -1.0) -> "_At":
        return self._schedule(
            lambda: self._injector.partition(zone_a, zone_b, duration))

    def restore_all(self) -> "_At":
        return self._schedule(lambda: self._injector.restore_all())


class Injector:
    """Mid-simulation fault injection API (see module docstring)."""

    def __init__(self, engine=None):
        from ..plugins._base import resolve_engine
        self._engine = resolve_engine(engine)
        assert self._engine is not None, \
            "No engine: create s4u.Engine first"
        self._hosts_off: Set[str] = set()
        self._links_off: Set[str] = set()
        #: link name -> original bandwidth_peak, recorded at first degrade
        self._degraded: Dict[str, float] = {}

    def at(self, date: float) -> _At:
        """Bind the chained operations to an absolute simulated date."""
        return _At(self, date)

    # -- immediate operations ---------------------------------------------
    def _do(self, fn):
        """Run a state mutation kernel-side: as a simcall from an actor
        context (the mutation may kill actors — including the caller),
        inline from maestro/main (the reference routes s4u::Host::turn_off
        through kernel::actor::simcall the same way)."""
        from ..s4u.actor import _current_impl
        issuer = _current_impl()

        def handler(sc):
            fn()
            sc.issuer.simcall_answer()
        issuer.simcall("fault_inject", handler)

    def host_off(self, host) -> None:
        host = _resolve_host(self._engine, host)

        def op():
            if host.is_on():
                self._hosts_off.add(host.name)
                host.turn_off()
        self._do(op)

    def host_on(self, host) -> None:
        host = _resolve_host(self._engine, host)

        def op():
            self._hosts_off.discard(host.name)
            host.turn_on()
        self._do(op)

    def link_off(self, link) -> None:
        link = _resolve_link(self._engine, link)

        def op():
            if link.is_on():
                self._links_off.add(link.name)
                link.turn_off()
        self._do(op)

    def link_on(self, link) -> None:
        link = _resolve_link(self._engine, link)

        def op():
            self._links_off.discard(link.name)
            link.turn_on()
        self._do(op)

    def link_degrade(self, link, fraction: float) -> None:
        """Scale a link's bandwidth to ``fraction`` of its ORIGINAL
        capacity (0 parks in-flight flows, 1 restores)."""
        assert 0.0 <= fraction, "fraction must be >= 0"
        link = _resolve_link(self._engine, link)
        assert hasattr(link, "set_bandwidth"), \
            f"Link '{link.name}' does not support bandwidth changes"

        def op():
            original = self._degraded.setdefault(link.name,
                                                 link.bandwidth_peak)
            link.set_bandwidth(original * fraction)
            if fraction >= 1.0:
                self._degraded.pop(link.name, None)
        self._do(op)

    def partition(self, zone_a: Iterable, zone_b: Iterable,
                  duration: float = -1.0) -> None:
        """Cut every link on the routes between the two host groups
        (both directions); with ``duration`` >= 0 the cut heals itself
        that many simulated seconds later.  Links shared with intra-zone
        routes are cut too — a partition severs the physical medium."""
        hosts_a = [_resolve_host(self._engine, h) for h in zone_a]
        hosts_b = [_resolve_host(self._engine, h) for h in zone_b]

        def op():
            cut: List = []
            seen: Set[str] = set()
            for a in hosts_a:
                for b in hosts_b:
                    for src, dst in ((a, b), (b, a)):
                        route: List = []
                        src.route_to(dst, route)
                        for link in route:
                            if link.name not in seen:
                                seen.add(link.name)
                                cut.append(link)
            for link in sorted(cut, key=lambda l: l.name):
                if link.is_on():
                    self._links_off.add(link.name)
                    link.turn_off()
            if duration >= 0:
                names = sorted(seen)

                def heal():
                    for name in names:
                        link = self._engine.links.get(name)
                        if link is not None:
                            self._links_off.discard(name)
                            link.turn_on()
                self._engine.timer_set(self._engine.now + duration, heal)
        self._do(op)

    def restore_all(self) -> None:
        """Undo every injection this injector performed: power failed
        hosts/links back on and restore degraded bandwidths."""
        def op():
            for name in sorted(self._hosts_off):
                host = self._engine.hosts.get(name)
                if host is not None:
                    host.turn_on()
            self._hosts_off.clear()
            for name in sorted(self._links_off):
                link = self._engine.links.get(name)
                if link is not None:
                    link.turn_on()
            self._links_off.clear()
            for name in sorted(self._degraded):
                link = self._engine.links.get(name)
                if link is not None:
                    link.set_bandwidth(self._degraded[name])
            self._degraded.clear()
        self._do(op)
