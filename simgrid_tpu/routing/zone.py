"""Hierarchical netzones + global route resolution.

Semantics from the reference's src/kernel/routing/NetZoneImpl.cpp: the
platform is a tree of netzones, each owning a local routing algorithm;
a global route is resolved by finding the common ancestor of src and dst,
taking the ancestor's local route between the two child zones' gateways
and recursing toward both endpoints (NetZoneImpl.cpp:374-416), with
optional bypass routes short-circuiting the walk (265-360).
"""

from __future__ import annotations

from enum import Enum
from typing import Dict, List, Optional, Tuple

from ..utils.signal import Signal


class NetPointType(Enum):
    HOST = 0
    ROUTER = 1
    NETZONE = 2


class NetPoint:
    """A routing endpoint (reference NetPoint.cpp)."""

    on_creation = Signal()

    def __init__(self, engine, name: str, kind: NetPointType,
                 englobing_zone: Optional["NetZoneImpl"]):
        self.engine = engine
        self.name = name
        self.kind = kind
        self.englobing_zone = englobing_zone
        self.id = -1  # position inside the englobing zone's routing table
        self.coords: Optional[List[float]] = None  # vivaldi coordinates
        if englobing_zone is not None:
            self.id = englobing_zone.register_netpoint(self)
        engine.netpoints[name] = self
        NetPoint.on_creation(self)

    def is_netzone(self) -> bool:
        return self.kind == NetPointType.NETZONE

    def is_router(self) -> bool:
        return self.kind == NetPointType.ROUTER

    def __repr__(self):
        return f"<NetPoint {self.name}>"


class Route:
    """A local route (reference RouteCreationArgs)."""

    __slots__ = ("links", "gw_src", "gw_dst")

    def __init__(self, links=None, gw_src=None, gw_dst=None):
        self.links: List = links or []
        self.gw_src: Optional[NetPoint] = gw_src
        self.gw_dst: Optional[NetPoint] = gw_dst


class NetZoneImpl:
    """Base netzone (reference NetZoneImpl.cpp)."""

    on_creation = Signal()
    on_seal = Signal()

    def __init__(self, engine, father: Optional["NetZoneImpl"], name: str):
        self.engine = engine
        self.father = father
        self.name = name
        self.children: List["NetZoneImpl"] = []
        self.vertices: List[NetPoint] = []   # netpoints of this zone
        self.bypass_routes: Dict[Tuple[NetPoint, NetPoint], Route] = {}
        self.properties: Dict[str, str] = {}
        self.sealed = False
        if father is not None:
            father.children.append(self)
        else:
            engine.netzone_root = self
        self.netpoint = NetPoint(engine, name, NetPointType.NETZONE, father)
        NetZoneImpl.on_creation(self)

    def __repr__(self):
        return f"<{type(self).__name__} {self.name}>"

    def register_netpoint(self, netpoint: NetPoint) -> int:
        self.vertices.append(netpoint)
        return len(self.vertices) - 1

    def get_hosts(self) -> List:
        return [self.engine.hosts[v.name] for v in self.vertices
                if v.kind == NetPointType.HOST]

    # -- route declaration -------------------------------------------------
    def add_route(self, src: NetPoint, dst: NetPoint,
                  gw_src: Optional[NetPoint], gw_dst: Optional[NetPoint],
                  links: List, symmetrical: bool = True) -> None:
        raise NotImplementedError(
            f"NetZone {self.name} does not accept explicit routes")

    def add_bypass_route(self, src: NetPoint, dst: NetPoint,
                         gw_src: Optional[NetPoint],
                         gw_dst: Optional[NetPoint], links: List,
                         symmetrical: bool = False) -> None:
        route = Route(list(links), gw_src, gw_dst)
        self.bypass_routes[(src, dst)] = route
        if symmetrical:
            self.bypass_routes[(dst, src)] = Route(list(reversed(links)),
                                                   gw_dst, gw_src)

    def seal(self) -> None:
        self.sealed = True
        for child in self.children:
            child.seal()
        NetZoneImpl.on_seal(self)

    # -- local routing -----------------------------------------------------
    def get_local_route(self, src: NetPoint, dst: NetPoint, route: Route,
                        latency: List[float]) -> None:
        raise NotImplementedError

    def _add_link_latency(self, route_links: List, link, latency: List[float]):
        route_links.append(link)
        if latency is not None:
            latency[0] += link.get_latency()

    # -- bypass ------------------------------------------------------------
    def get_bypass_route(self, src: NetPoint, dst: NetPoint, links: List,
                         latency: List[float]) -> bool:
        # reference NetZoneImpl.cpp:265-360
        if not self.bypass_routes:
            return False
        if (src.englobing_zone is self and dst.englobing_zone is self):
            route = self.bypass_routes.get((src, dst))
            if route is not None:
                for link in route.links:
                    self._add_link_latency(links, link, latency)
                return True
            return False

        path_src = _path_to_root(src)
        path_dst = _path_to_root(dst)
        while (len(path_src) > 1 and len(path_dst) > 1
               and path_src[-1] is path_dst[-1]):
            path_src.pop()
            path_dst.pop()

        max_index_src = len(path_src) - 1
        max_index_dst = len(path_dst) - 1
        bypassed = None
        key = None
        for mx in range(max(max_index_src, max_index_dst) + 1):
            for i in range(mx):
                if i <= max_index_src and mx <= max_index_dst:
                    key = (path_src[i].netpoint, path_dst[mx].netpoint)
                    bypassed = self.bypass_routes.get(key)
                    if bypassed:
                        break
                if mx <= max_index_src and i <= max_index_dst:
                    key = (path_src[mx].netpoint, path_dst[i].netpoint)
                    bypassed = self.bypass_routes.get(key)
                    if bypassed:
                        break
            if bypassed:
                break
            if mx <= max_index_src and mx <= max_index_dst:
                key = (path_src[mx].netpoint, path_dst[mx].netpoint)
                bypassed = self.bypass_routes.get(key)
                if bypassed:
                    break
        if bypassed:
            if src is not key[0]:
                get_global_route_impl(src, bypassed.gw_src, links, latency)
            for link in bypassed.links:
                self._add_link_latency(links, link, latency)
            if key[1] is not dst:
                get_global_route_impl(bypassed.gw_dst, dst, links, latency)
            return True
        return False


def _path_to_root(netpoint: NetPoint) -> List[NetZoneImpl]:
    path = []
    current = netpoint.englobing_zone
    while current is not None:
        path.append(current)
        current = current.father
    return path


def _find_common_ancestors(src: NetPoint, dst: NetPoint):
    # reference NetZoneImpl.cpp:221-263
    path_src = _path_to_root(src)
    path_dst = _path_to_root(dst)
    father = None
    while (len(path_src) > 1 and len(path_dst) > 1
           and path_src[-1] is path_dst[-1]):
        father = path_src[-1]
        path_src.pop()
        path_dst.pop()
    src_ancestor = path_src[-1]
    dst_ancestor = path_dst[-1]
    common_ancestor = src_ancestor if src_ancestor is dst_ancestor else father
    return common_ancestor, src_ancestor, dst_ancestor


def get_global_route_impl(src: NetPoint, dst: NetPoint, links: List,
                          latency: Optional[List[float]]) -> None:
    # reference NetZoneImpl::get_global_route (NetZoneImpl.cpp:374-416)
    common_ancestor, src_ancestor, dst_ancestor = _find_common_ancestors(src, dst)

    if common_ancestor.get_bypass_route(src, dst, links, latency):
        return

    if src_ancestor is dst_ancestor:
        route = Route(links=links)
        common_ancestor.get_local_route(src, dst, route, latency)
        links[:] = route.links
        return

    route = Route()
    common_ancestor.get_local_route(src_ancestor.netpoint,
                                    dst_ancestor.netpoint, route, latency)
    assert route.gw_src is not None and route.gw_dst is not None, \
        f"Bad gateways for route from '{src.name}' to '{dst.name}'"

    if src is not route.gw_src:
        get_global_route_impl(src, route.gw_src, links, latency)
    links.extend(route.links)
    if route.gw_dst is not dst:
        get_global_route_impl(route.gw_dst, dst, links, latency)


def get_global_route(src: NetPoint, dst: NetPoint, links: List) -> float:
    """Resolve the full route; returns the accumulated latency."""
    latency = [0.0]
    get_global_route_impl(src, dst, links, latency)
    return latency[0]
