"""Cluster zones: flat crossbar/backbone clusters + the <cluster> tag.

Semantics from the reference's src/kernel/routing/ClusterZone.cpp (route =
src private up-link, optional limiter, optional backbone, dst private
down-link; loopback for self-routes) and sg_platf_new_cluster
(src/surf/sg_platf.cpp): one host + private link per radical entry, an
optional backbone, a cluster router for inter-zone traffic.  The fat-tree
/ torus / dragonfly variants subclass this and add their own interconnect
(their dedicated modules register themselves in the topology table).
"""

from __future__ import annotations

import re
from typing import Dict, List, Optional, Tuple

from ..exceptions import ParseError
from ..ops.lmm_host import SharingPolicy
from .zone import NetPoint, NetPointType, NetZoneImpl


def make_duplex_link(engine, link_id: str, bw: float, lat: float,
                     sharing: str):
    """Create one cluster link; SPLITDUPLEX makes an _UP/_DOWN pair
    (sg_platf.cpp:132-134 naming).  Returns (link_up, link_down)."""
    model = engine.network_model
    if sharing == "SPLITDUPLEX":
        up = model.create_link(f"{link_id}_UP", bw, lat, SharingPolicy.SHARED)
        down = model.create_link(f"{link_id}_DOWN", bw, lat,
                                 SharingPolicy.SHARED)
        return up, down
    policy = (SharingPolicy.FATPIPE if sharing == "FATPIPE"
              else SharingPolicy.SHARED)
    link = model.create_link(link_id, bw, lat, policy)
    return link, link


def parse_radical(radical: str) -> List[int]:
    """Expand "0-9,12,15-20" to the explicit id list (sg_platf.cpp)."""
    ids: List[int] = []
    for group in radical.split(","):
        group = group.strip()
        if not group:
            continue
        if "-" in group:
            start, end = group.split("-")
            ids.extend(range(int(start), int(end) + 1))
        else:
            ids.append(int(group))
    return ids


class ClusterZone(NetZoneImpl):
    """Flat cluster: private links + optional backbone."""

    def __init__(self, engine, father, name):
        super().__init__(engine, father, name)
        self.private_links: Dict[int, Tuple[Optional[object], Optional[object]]] = {}
        self.backbone = None
        self.router: Optional[NetPoint] = None
        self.has_loopback = False
        self.has_limiter = False
        self.limiter_bw = 0.0
        self.num_links_per_node = 1
        # netpoint.id -> 0-based rank.  The reference indexes private links
        # by raw netpoint id, which only works when the cluster is alone in
        # the platform; the explicit map keeps multi-zone platforms correct.
        self.node_rank: Dict[int, int] = {}

    # position helpers (reference ClusterZone.hpp node_pos* )
    def node_pos(self, node_id: int) -> int:
        return node_id * self.num_links_per_node

    def node_pos_with_loopback(self, node_id: int) -> int:
        return self.node_pos(node_id) + (1 if self.has_loopback else 0)

    def node_pos_with_loopback_limiter(self, node_id: int) -> int:
        return self.node_pos_with_loopback(node_id) + (1 if self.has_limiter else 0)

    def add_private_link(self, position: int, link_up, link_down) -> None:
        self.private_links[position] = (link_up, link_down)

    def create_links_for_node(self, cluster_name: str, node_id, rank: int,
                              position: int, sharing: str, bw: float,
                              lat: float) -> None:
        """Default flat-cluster node links: one private (possibly
        split-duplex) link per node (ClusterZone::create_links_for_node +
        sg_platf_new_link's _UP/_DOWN split, sg_platf.cpp:132-134)."""
        link_up, link_down = make_duplex_link(
            self.engine, f"{cluster_name}_link_{node_id}", bw, lat, sharing)
        self.add_private_link(position, link_up, link_down)

    def get_local_route(self, src: NetPoint, dst: NetPoint, route,
                        latency) -> None:
        assert self.private_links, \
            "Cluster routing: no links attached to the source node"
        if src.id == dst.id and self.has_loopback:
            if not src.is_router():
                up, _ = self.private_links[
                    self.node_pos(self.node_rank[src.id])]
                self._add_link_latency(route.links, up, latency)
            return

        if not src.is_router():
            rank = self.node_rank[src.id]
            if self.has_limiter:
                up, _ = self.private_links[self.node_pos_with_loopback(rank)]
                route.links.append(up)
            up, _ = self.private_links[
                self.node_pos_with_loopback_limiter(rank)]
            if up is not None:
                self._add_link_latency(route.links, up, latency)

        if self.backbone is not None:
            self._add_link_latency(route.links, self.backbone, latency)

        if not dst.is_router():
            rank = self.node_rank[dst.id]
            _, down = self.private_links[
                self.node_pos_with_loopback_limiter(rank)]
            if down is not None:
                self._add_link_latency(route.links, down, latency)
            if self.has_limiter:
                up, _ = self.private_links[self.node_pos_with_loopback(rank)]
                route.links.append(up)


#: topology-string parsers registered by fat_tree/torus/dragonfly modules
_TOPO_ZONES = {}


def register_topo_zone(kind: str, cls) -> None:
    _TOPO_ZONES[kind] = cls


def parse_cluster_tag(loader, elem, father) -> None:
    """Create a cluster per the <cluster> tag (sg_platf_new_cluster)."""
    from ..models.host import Host

    engine = loader.engine
    name = elem.get("id")
    prefix = elem.get("prefix", "")
    suffix = elem.get("suffix", "")
    radical = elem.get("radical")
    speeds = elem.get("speed")
    bw = elem.get("bw")
    lat = elem.get("lat")
    core = int(elem.get("core", "1"))
    topology = elem.get("topology", "FLAT").upper()
    # DTD default is SPLITDUPLEX (simgrid.dtd:173): two directed links per
    # node.  FULLDUPLEX is the deprecated alias.
    sharing_policy = elem.get("sharing_policy", "SPLITDUPLEX").upper()
    if sharing_policy == "FULLDUPLEX":
        sharing_policy = "SPLITDUPLEX"
    bb_sharing = elem.get("bb_sharing_policy", "SHARED")

    if topology == "FLAT":
        zone = ClusterZone(engine, father, name)
    elif topology in _TOPO_ZONES:
        zone = _TOPO_ZONES[topology](engine, father, name,
                                     elem.get("topo_parameters", ""))
    else:
        raise ParseError(f"Unknown cluster topology {topology}")

    from ..platform.units import (parse_bandwidth, parse_speeds, parse_time)
    speed_list = parse_speeds(speeds)
    bw_value = parse_bandwidth(bw)
    lat_value = parse_time(lat)

    loopback_bw = elem.get("loopback_bw")
    loopback_lat = elem.get("loopback_lat")
    limiter_link = elem.get("limiter_link")
    if loopback_bw or loopback_lat:
        zone.has_loopback = True
    if limiter_link:
        zone.has_limiter = True
        zone.limiter_bw = parse_bandwidth(limiter_link)
    # Topology zones preset their own per-node link count (e.g. torus:
    # one per dimension); loopback/limiter slots add to it (sg_platf.cpp:
    # 174-182 ordering).
    zone.num_links_per_node += (1 if zone.has_loopback else 0) + \
        (1 if zone.has_limiter else 0)

    # cluster-level <prop> entries are copied onto every created host
    # (sg_platf.cpp:70-78; energy_cluster.xml sets watt_per_state here)
    # AND kept on the cluster's own NetZone (the reference attaches
    # them to the zone too — platform-properties oracle reads them via
    # get_englobing_zone()->get_properties())
    cluster_props = {child.get("id"): child.get("value")
                     for child in elem if child.tag == "prop"}
    if cluster_props:
        zone.properties.update(cluster_props)

    ids = parse_radical(radical)
    for rank, node_id in enumerate(ids):
        host_name = f"{prefix}{node_id}{suffix}"
        host = Host(engine, host_name)
        host.netpoint = NetPoint(engine, host_name, NetPointType.HOST, zone)
        engine.cpu_model.create_cpu(host, speed_list, core)
        if cluster_props:
            host.properties.update(cluster_props)
        zone.node_rank[host.netpoint.id] = rank

        if zone.has_loopback:
            lb = engine.network_model.create_link(
                f"{name}_link_{node_id}_loopback",
                parse_bandwidth(loopback_bw), parse_time(loopback_lat),
                SharingPolicy.FATPIPE)
            zone.add_private_link(zone.node_pos(rank), lb, lb)

        if zone.has_limiter:
            lim = engine.network_model.create_link(
                f"{name}_link_{node_id}_limiter",
                zone.limiter_bw, 0.0, SharingPolicy.SHARED)
            zone.add_private_link(zone.node_pos_with_loopback(rank),
                                  lim, lim)

        if hasattr(zone, "add_processing_node"):
            zone.add_processing_node(host.netpoint, rank)
        zone.create_links_for_node(
            name, node_id, rank, zone.node_pos_with_loopback_limiter(rank),
            sharing_policy, bw_value, lat_value)
        # Completion signal fires last, after links/rank wiring, matching
        # the <host> tag path (platform/xml.py) so listeners observe a
        # fully-built node (sg_platf.cpp fires s4u::Host::on_creation for
        # cluster nodes too — IB model and energy plugin key off it).
        Host.on_creation(host)

    # cluster router (for inter-zone routing)
    router_name = elem.get("router_id") or f"{prefix}{name}_router{suffix}"
    zone.router = NetPoint(engine, router_name, NetPointType.ROUTER, zone)

    bb_bw = elem.get("bb_bw")
    bb_lat = elem.get("bb_lat")
    if bb_bw or bb_lat:
        zone.backbone = engine.network_model.create_link(
            f"{name}_backbone", parse_bandwidth(bb_bw), parse_time(bb_lat),
            SharingPolicy.FATPIPE if bb_sharing == "FATPIPE"
            else SharingPolicy.SHARED)

    if hasattr(zone, "build_interconnect"):
        zone.build_interconnect(bw_value, lat_value, sharing_policy)

    for child in elem:
        if child.tag == "prop":
            zone.properties[child.get("id")] = child.get("value")


def parse_cabinet_tag(loader, elem, father) -> None:
    """<cabinet>: per-host SPLITDUPLEX private links inside a Cluster
    zone (reference sg_platf_new_cabinet, sg_platf.cpp:307-332: one
    host + one link_<host>_UP/_DOWN pair per radical entry)."""
    from ..models.host import Host
    from ..platform.units import parse_bandwidth, parse_speeds, parse_time
    from .zone import NetPoint, NetPointType
    prefix = elem.get("prefix", "")
    suffix = elem.get("suffix", "")
    speeds = parse_speeds(elem.get("speed"))
    bw = parse_bandwidth(elem.get("bw"))
    lat = parse_time(elem.get("lat"))
    engine = loader.engine
    for radical in parse_radical(elem.get("radical")):
        hostname = f"{prefix}{radical}{suffix}"
        host = Host(engine, hostname)
        host.netpoint = NetPoint(engine, hostname, NetPointType.HOST,
                                 father)
        engine.cpu_model.create_cpu(host, speeds, 1)
        Host.on_creation(host)     # plugins key off this signal
        up, down = make_duplex_link(engine, f"link_{hostname}", bw, lat,
                                    "SPLITDUPLEX")
        rank = len(father.node_rank)
        father.node_rank[host.netpoint.id] = rank
        father.add_private_link(father.node_pos(rank), up, down)


def parse_peer_tag(loader, elem, father) -> None:
    """<peer>: a host with up/down private links in a Vivaldi zone
    (sg_platf_new_peer)."""
    from ..models.host import Host
    from ..platform.units import parse_bandwidth, parse_speed, parse_time

    engine = loader.engine
    name = elem.get("id")
    host = Host(engine, name)
    host.netpoint = NetPoint(engine, name, NetPointType.HOST, father)
    engine.cpu_model.create_cpu(host, [parse_speed(elem.get("speed"))], 1)
    coords = elem.get("coordinates")
    if coords:
        host.netpoint.coords = [float(x) for x in coords.split()]
    assert hasattr(father, "set_peer_link"), \
        "<peer> tag can only be used in Vivaldi netzones"
    father.set_peer_link(host.netpoint,
                         parse_bandwidth(elem.get("bw_in")),
                         parse_bandwidth(elem.get("bw_out")))
    # Fires last so listeners observe coords + peer links (see cluster path).
    Host.on_creation(host)
